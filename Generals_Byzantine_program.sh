#!/bin/sh
# Entry point, launch-compatible with the reference's launcher contract
# (one positional N; extra framework flags pass through).
exec python3 -m ba_tpu.runtime.main "$@"
