"""Campaign generator: seed-keyed sampling + mutation over the scenario
spec grammar, lowered campaign-per-instance into one batched block.

The search engine's representation decision (docs/DESIGN.md §14): a
candidate adversary campaign IS an ordinary validated
:class:`~ba_tpu.scenario.spec.Scenario` — the same plain-data grammar
the REPL replays and CI round-trips — so anything the search finds is
immediately a committable, replayable spec file.  A *population* of B
distinct candidates lowers into ONE
:class:`~ba_tpu.scenario.compile.SparseScenarioBlock` of batch B by
tagging candidate ``i``'s resolved events with ``instances=(i,)`` (the
per-instance masks the scenario engine has carried since ISSUE 5), so
evaluating B campaigns costs exactly one batched dispatch stream.

Everything here is deterministic and seed-keyed: candidate ``uid``
draws its events from ``numpy`` ``default_rng((seed, tag, uid))`` —
``SeedSequence`` spawning, stable across processes — so the same
``(seed, uid)`` always yields the same campaign, which is what makes
search-state checkpoints resumable bit-exactly and exported
reproducers self-describing (their provenance stores the pair).

Constraints are plain data (:class:`SearchSpace`) and validated
EAGERLY, ``coalesced_sweep``-style: population size, event budgets,
strategy names, and the n/f knobs (``faulty_max`` / ``kill_max``) all
raise :class:`~ba_tpu.scenario.spec.ScenarioError`-grade messages
before any array is built — a hand-edited search config fails at
``validate_space``, never mid-hunt with a shape crash.

Like ``scenario/spec.py`` this module is numpy/stdlib only (no jax):
the ``python -m ba_tpu.search`` sample/corpus subcommands and ba-lint's
BA301 host-tier scope both rely on the jax-free import.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ba_tpu.scenario.compile import SparseScenarioBlock, compile_scenario
from ba_tpu.scenario.spec import (
    EVENT_KINDS,
    ORDERS,
    STRATEGY_NAMES,
    Event,
    Scenario,
    ScenarioError,
    validate,
)

# Default event-kind menu: `revive` is excluded — on the all-alive
# initial population state a revive is a no-op until a kill lands, and
# the kill/revive same-round conflict rule would force resampling;
# spaces that want membership-flap campaigns opt it back in.
DEFAULT_KINDS = ("kill", "set_faulty", "set_strategy")

# rng stream tags: one namespace per derivation so a sampled candidate
# and a mutation of the same uid can never share a stream.
_TAG_SAMPLE = 0
_TAG_MUTATE = 1


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    """The search's constraint set, as plain data.

    - ``rounds`` / ``capacity``: every candidate campaign's length and
      cluster width (slots 1..capacity, the generator's roster);
    - ``population``: candidates per generation — B campaigns per
      batched dispatch stream;
    - ``events_min`` / ``events_max``: per-candidate event budget;
    - ``kinds``: the event-kind menu (subset of ``EVENT_KINDS``);
    - ``strategies``: the adversary-strategy menu ``set_strategy`` may
      assign (subset of ``STRATEGY_NAMES``);
    - ``faulty_max`` / ``kill_max``: n/f knobs — the most DISTINCT
      generals a single campaign may ever set faulty / kill (None = no
      cap).  ``faulty_max <= floor((capacity - 1) / 3)`` keeps the hunt
      inside the classical n > 3t bound, where a violation would
      falsify the protocol; the default (None) hunts the full space;
    - ``ids_per_event``: most generals one event may name;
    - ``order``: the campaign order every candidate runs under.
    """

    rounds: int
    capacity: int
    population: int
    events_min: int = 1
    events_max: int = 6
    kinds: tuple = DEFAULT_KINDS
    strategies: tuple = STRATEGY_NAMES
    faulty_max: int | None = None
    kill_max: int | None = None
    ids_per_event: int = 3
    order: str = "attack"


def validate_space(space: SearchSpace) -> SearchSpace:
    """Eager host-side validation; returns ``space`` for chaining.

    Everything a hand-edited config could get wrong raises HERE with a
    ScenarioError naming the field — before any candidate samples, any
    plane materializes, or any buffer donates (the
    ``coalesced_sweep``-style eager-validation discipline)."""
    for name in ("rounds", "capacity", "population"):
        v = getattr(space, name)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            raise ScenarioError(
                f"search space {name}={v!r} must be an int >= 1"
            )
    for name in ("events_min", "events_max", "ids_per_event"):
        v = getattr(space, name)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            raise ScenarioError(
                f"search space {name}={v!r} must be an int >= 0"
            )
    if space.events_min > space.events_max:
        raise ScenarioError(
            f"search space events_min={space.events_min} exceeds "
            f"events_max={space.events_max}"
        )
    if space.events_max > space.rounds * space.capacity:
        raise ScenarioError(
            f"search space events_max={space.events_max} exceeds the "
            f"campaign's {space.rounds} x {space.capacity} event cells"
        )
    if space.ids_per_event < 1 or space.ids_per_event > space.capacity:
        raise ScenarioError(
            f"search space ids_per_event={space.ids_per_event} outside "
            f"[1, capacity={space.capacity}]"
        )
    if not space.kinds or not set(space.kinds) <= set(EVENT_KINDS):
        raise ScenarioError(
            f"search space kinds={space.kinds!r} must be a non-empty "
            f"subset of {EVENT_KINDS}"
        )
    if not space.strategies or not set(space.strategies) <= set(
        STRATEGY_NAMES
    ):
        raise ScenarioError(
            f"search space strategies={space.strategies!r} must be a "
            f"non-empty subset of {STRATEGY_NAMES}"
        )
    for name in ("faulty_max", "kill_max"):
        v = getattr(space, name)
        if v is not None and (
            not isinstance(v, int) or isinstance(v, bool)
            or not 0 <= v <= space.capacity
        ):
            raise ScenarioError(
                f"search space {name}={v!r} must be None or an int in "
                f"[0, capacity={space.capacity}]"
            )
    if space.order not in ORDERS:
        raise ScenarioError(
            f"search space order={space.order!r} must be one of {ORDERS}"
        )
    return space


def space_to_dict(space: SearchSpace) -> dict:
    """The JSON form (round-trips exactly through :func:`space_from_dict`)."""
    doc = dataclasses.asdict(validate_space(space))
    doc["kinds"] = list(space.kinds)
    doc["strategies"] = list(space.strategies)
    return doc


def space_from_dict(doc: dict) -> SearchSpace:
    """Parse + validate the JSON form; strict about keys."""
    if not isinstance(doc, dict):
        raise ScenarioError(
            f"search space document must be an object, got {doc!r}"
        )
    fields = {f.name for f in dataclasses.fields(SearchSpace)}
    unknown = set(doc) - fields
    if unknown:
        raise ScenarioError(f"unknown search space keys: {sorted(unknown)}")
    missing = {"rounds", "capacity", "population"} - set(doc)
    if missing:
        raise ScenarioError(
            f"search space document missing keys: {sorted(missing)}"
        )
    kwargs = dict(doc)
    for name in ("kinds", "strategies"):
        if name in kwargs:
            if not isinstance(kwargs[name], (list, tuple)):
                raise ScenarioError(
                    f"search space {name} must be a list, "
                    f"got {kwargs[name]!r}"
                )
            kwargs[name] = tuple(kwargs[name])
    return validate_space(SearchSpace(**kwargs))


def candidate_name(seed: int, uid: int) -> str:
    """The canonical candidate name: seed + uid IS the replay recipe
    (the per-slot PRNG key derives from exactly this pair)."""
    return f"search-s{seed}-u{uid}"


def _rng(seed: int, tag: int, uid: int) -> np.random.Generator:
    """One deterministic stream per (seed, namespace, uid) — numpy's
    SeedSequence mixing, stable across processes and platforms."""
    return np.random.default_rng((seed, tag, uid))


def _draw_ids(rng, space: SearchSpace, pool: list) -> tuple:
    k = min(1 + int(rng.integers(space.ids_per_event)), len(pool))
    picked = rng.choice(len(pool), size=k, replace=False)
    return tuple(sorted(int(pool[i]) for i in picked))


def _draw_events(rng, space: SearchSpace) -> tuple:
    """Sample one candidate's event list under the space's budgets.

    Budgets are enforced DURING sampling (the faulty/kill id pools
    shrink as a campaign spends them), so every sampled candidate
    validates by construction — no rejection loop whose iteration count
    could couple distinct uids' streams."""
    n_events = int(
        rng.integers(space.events_min, space.events_max + 1)
    )
    all_ids = list(range(1, space.capacity + 1))
    faulty_pool = list(all_ids)
    kill_pool = list(all_ids)
    faulty_budget = (
        space.capacity if space.faulty_max is None else space.faulty_max
    )
    kill_budget = (
        space.capacity if space.kill_max is None else space.kill_max
    )
    killed_by_round: dict = {}
    revived_by_round: dict = {}
    events = []
    for _ in range(n_events):
        kind = space.kinds[int(rng.integers(len(space.kinds)))]
        rnd = int(rng.integers(space.rounds))
        if kind == "kill":
            # Same-round kill+revive of one general is the one grammar
            # conflict validate() rejects — exclude ids this candidate
            # already revives in this round (the mirror of the revive
            # branch's exclusion; either event may sample first).
            pool = [
                g for g in kill_pool[: max(kill_budget, 0)]
                if g not in revived_by_round.get(rnd, ())
            ]
            if not pool:
                continue
            ids = _draw_ids(rng, space, pool)
            kill_budget -= sum(1 for g in ids if g in kill_pool)
            kill_pool = [g for g in kill_pool if g not in ids]
            killed_by_round.setdefault(rnd, set()).update(ids)
            events.append(Event(round=rnd, kind="kill", ids=ids))
        elif kind == "revive":
            pool = [
                g for g in all_ids
                if g not in killed_by_round.get(rnd, ())
            ]
            if not pool:
                continue
            ids = _draw_ids(rng, space, pool)
            revived_by_round.setdefault(rnd, set()).update(ids)
            events.append(Event(round=rnd, kind="revive", ids=ids))
        elif kind == "set_faulty":
            # Bias 3:1 toward True: clearing fault flags on an honest
            # roster is mostly a no-op, and the hunt wants adversaries.
            value = bool(rng.integers(4) > 0)
            if value:
                pool = faulty_pool[: max(faulty_budget, 0)]
                if not pool:
                    continue
                ids = _draw_ids(rng, space, pool)
                faulty_budget -= sum(1 for g in ids if g in faulty_pool)
                faulty_pool = [g for g in faulty_pool if g not in ids]
            else:
                ids = _draw_ids(rng, space, all_ids)
            events.append(
                Event(round=rnd, kind="set_faulty", ids=ids, value=value)
            )
        else:  # set_strategy (validate_space rejected everything else)
            strat = space.strategies[
                int(rng.integers(len(space.strategies)))
            ]
            ids = _draw_ids(rng, space, all_ids)
            events.append(
                Event(
                    round=rnd, kind="set_strategy", ids=ids, value=strat
                )
            )
    return tuple(events)


def sample_campaign(space: SearchSpace, seed: int, uid: int) -> Scenario:
    """One deterministic candidate campaign for ``(seed, uid)``."""
    rng = _rng(seed, _TAG_SAMPLE, uid)
    return validate(
        Scenario(
            name=candidate_name(seed, uid),
            rounds=space.rounds,
            events=_draw_events(rng, space),
            order=space.order,
        )
    )


def mutate_campaign(
    parent: Scenario, space: SearchSpace, seed: int, uid: int
) -> Scenario:
    """A deterministic single-step mutation of ``parent`` — the
    coordinate-descent move over event planes.

    One of: drop an event, re-round an event (move it along the round
    axis), re-value a ``set_strategy``/``set_faulty`` event, or append
    a freshly sampled event (budget-checked by revalidating the whole
    child against the space's budgets; an over-budget or conflicting
    child falls back to a fresh sample so the move never dead-ends).
    The child is keyed by its OWN uid — resuming a checkpoint replays
    identical mutations.
    """
    rng = _rng(seed, _TAG_MUTATE, uid)
    events = list(parent.events)
    op = int(rng.integers(4))
    if op == 0 and events:
        events.pop(int(rng.integers(len(events))))
    elif op == 1 and events:
        i = int(rng.integers(len(events)))
        events[i] = dataclasses.replace(
            events[i], round=int(rng.integers(space.rounds))
        )
    elif op == 2 and events:
        i = int(rng.integers(len(events)))
        ev = events[i]
        if ev.kind == "set_strategy":
            events[i] = dataclasses.replace(
                ev,
                value=space.strategies[
                    int(rng.integers(len(space.strategies)))
                ],
            )
        elif ev.kind == "set_faulty":
            events[i] = dataclasses.replace(ev, value=not ev.value)
        # kill/revive carry no value: the no-op keeps streams aligned.
    else:
        events.extend(_draw_events(rng, space)[:1])
    child = Scenario(
        name=candidate_name(seed, uid),
        rounds=space.rounds,
        events=tuple(events),
        order=space.order,
    )
    try:
        validate(child)
        _check_budgets(child, space)
    except ScenarioError:
        # A conflicting / over-budget mutation re-rolls as a fresh
        # sample under the SAME uid — still deterministic.
        return sample_campaign(space, seed, uid)
    return child


def _check_budgets(campaign: Scenario, space: SearchSpace) -> None:
    """Re-check a campaign against the space's budget knobs (mutations
    compose events, so per-event sampling discipline is not enough)."""
    if len(campaign.events) > space.events_max:
        raise ScenarioError(
            f"campaign {campaign.name!r} has {len(campaign.events)} "
            f"events, budget is {space.events_max}"
        )
    if space.faulty_max is not None:
        made_faulty = {
            g
            for ev in campaign.events
            if ev.kind == "set_faulty" and ev.value
            for g in ev.ids
        }
        if len(made_faulty) > space.faulty_max:
            raise ScenarioError(
                f"campaign {campaign.name!r} sets {len(made_faulty)} "
                f"generals faulty, faulty_max is {space.faulty_max}"
            )
    if space.kill_max is not None:
        killed = {
            g
            for ev in campaign.events
            if ev.kind == "kill"
            for g in ev.ids
        }
        if len(killed) > space.kill_max:
            raise ScenarioError(
                f"campaign {campaign.name!r} kills {len(killed)} "
                f"generals, kill_max is {space.kill_max}"
            )


def sample_population(
    space: SearchSpace, seed: int, first_uid: int = 0
) -> tuple:
    """``population`` fresh candidates with uids ``first_uid..``."""
    validate_space(space)
    return tuple(
        sample_campaign(space, seed, first_uid + i)
        for i in range(space.population)
    )


def lower_population(
    campaigns, capacity: int, rounds: int
) -> SparseScenarioBlock:
    """Lower B candidate campaigns into ONE sparse block of batch B —
    campaign ``i`` confined to instance ``i`` via the per-instance mask.

    Each candidate lowers through the ordinary public compiler at
    batch 1 (one resolution implementation — the search cannot drift
    from what a standalone replay of the same spec lowers to), then its
    resolved events are re-tagged with ``instances=(i,)`` and the merged
    event list builds the population block, re-validated by
    ``SparseScenarioBlock.__post_init__``.  The block feeds
    ``coalesced_sweep(scenario=...)`` directly.
    """
    campaigns = tuple(campaigns)
    if not campaigns:
        raise ScenarioError("lower_population needs at least one campaign")
    merged = []
    for i, campaign in enumerate(campaigns):
        if campaign.rounds != rounds:
            raise ScenarioError(
                f"campaign {campaign.name!r} covers {campaign.rounds} "
                f"round(s), population wants {rounds}"
            )
        single = compile_scenario(
            campaign, batch=1, capacity=capacity, sparse=True
        )
        for r, kind, rows, slots, value in single.events:
            if rows not in (None, (0,)):
                raise ScenarioError(
                    f"campaign {campaign.name!r} carries instance masks "
                    f"{rows!r}; population candidates must be "
                    f"single-instance specs"
                )
            merged.append((r, kind, (i,), slots, value))
    # Spec order within a candidate is preserved; candidates write
    # disjoint instance rows, so the merge order across candidates
    # cannot change any plane cell.
    return SparseScenarioBlock(
        rounds=rounds,
        batch=len(campaigns),
        capacity=capacity,
        events=tuple(merged),
    )


def campaign_fingerprint(campaign: Scenario) -> tuple:
    """Content identity for dedup: everything but the name/provenance
    (two uids that sampled the same events are ONE discovery)."""
    return (
        campaign.rounds,
        campaign.order,
        tuple(
            (ev.round, ev.kind, ev.ids, ev.value) for ev in campaign.events
        ),
    )
