"""ba_tpu.search — the adversary search engine (ISSUE 15).

The scenario engine made campaigns plain data and IC1/IC2/quorum
verdicts on-device counters; this package turns "find the campaign
that breaks agreement" into the throughput problem the repo is built
to brute-force.  Four layers, mirroring the scenario package's
jax-free-at-import discipline (docs/DESIGN.md §14):

- **generator** (``search/generate.py``): a deterministic seed-keyed
  campaign sampler + mutator over the scenario spec grammar, with
  constraints as plain data (:class:`~ba_tpu.search.generate.SearchSpace`,
  eagerly validated) and a population lowering that packs B distinct
  candidate campaigns into ONE batched block — campaign-per-instance
  via the per-instance event masks.
- **objective** (``search/objective.py``): scores over the per-slot
  scenario counter blocks the coalesced engine already drains inside
  its depth-delayed retire fetches — scoring adds zero new syncs.
- **search loop** (``search/loop.py``): random sweep → elite selection
  → mutation, B campaigns per dispatch stream, per-candidate PRNG keys
  (``fold_in(key(seed), uid)`` — population/shard/standalone all draw
  the same stream), versioned search-state checkpoints
  (``utils/snapshot``) for bit-exact resume, ``mesh=`` per-shard
  populations, and the ``search_*`` obs record/gauge family under a
  deterministic run_id.
- **minimizer + corpus** (``search/minimize.py``, ``search/corpus.py``):
  ddmin shrink to a 1-minimal violating event set, re-validated by the
  alone-vs-in-population bit-exact replay oracle (the serving parity
  pin as ground truth), exported as ordinary provenance-stamped
  scenario JSON specs into ``examples/scenarios/found/``.

Import discipline: this ``__init__`` eagerly imports only the jax-free
layers (``python -m ba_tpu.search`` validates corpora and samples
populations without an accelerator stack; ba-lint BA301 pins the
host-tier contract); :func:`hunt` — the engine — loads on attribute
access.
"""

from ba_tpu.search.corpus import (
    FOUND_DIR,
    check_reproducer,
    export_found,
    load_corpus,
)
from ba_tpu.search.generate import (
    SearchSpace,
    campaign_fingerprint,
    candidate_name,
    lower_population,
    mutate_campaign,
    sample_campaign,
    sample_population,
    space_from_dict,
    space_to_dict,
    validate_space,
)
from ba_tpu.search.objective import (
    OBJECTIVES,
    Objective,
    get_objective,
    score_rows,
    violation_rows,
)

__all__ = [
    "FOUND_DIR",
    "OBJECTIVES",
    "Objective",
    "SearchSpace",
    "campaign_fingerprint",
    "candidate_name",
    "check_reproducer",
    "export_found",
    "get_objective",
    "hunt",
    "load_corpus",
    "lower_population",
    "mutate_campaign",
    "sample_campaign",
    "sample_population",
    "score_rows",
    "space_from_dict",
    "space_to_dict",
    "validate_space",
    "violation_rows",
]


def __getattr__(name):
    # Lazy: `hunt` pulls the whole parallel engine (and jax) — it must
    # not ride the jax-free CLI / CI validation import path.
    if name == "hunt":
        from ba_tpu.search.loop import hunt

        return hunt
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
