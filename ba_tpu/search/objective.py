"""Search objectives: scores over the per-slot scenario counter blocks.

The engine's per-candidate signal is the ``[B, C]`` final per-slot
counter block a scenario ``coalesced_sweep`` already drains inside its
depth-delayed retire fetches (``slot_counter_delta`` — row ``b`` is
bit-identical to candidate ``b``'s own B=1 run).  Scoring therefore
adds ZERO new synchronizations: this module is pure host arithmetic
over numpy rows the engine fetched anyway, and the objective table is
plain data.

Column semantics come from
``ba_tpu.parallel.pipeline.SCENARIO_COUNTER_NAMES``; the engine hands
the name list back per run (``result["counter_names"]``) and every
score resolves columns BY NAME, so a counter-table reorder can never
silently re-weight an objective.  ``unanimous_rounds`` is excluded from
every objective: per slot it is the constant B=1 value (one instance
always decides unanimously), carrying no signal.

numpy/stdlib only (no jax) — the jax-free CLI prints the objective
table, and ba-lint's BA301 host-tier scope covers the module.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ba_tpu.scenario.spec import ScenarioError


@dataclasses.dataclass(frozen=True)
class Objective:
    """One scoring rule: integer weights over counter columns, plus the
    subset whose any-nonzero verdict defines a *violation* (what the
    hunt collects, shrinks and exports)."""

    name: str
    weights: tuple  # ((counter_name, int_weight), ...)
    violation_counters: tuple  # counter names whose > 0 is a finding


# The objective table (docs/DESIGN.md §14).  ``ic`` is the default
# hunt: IC1/IC2 are the paper's agreement conditions, so a nonzero
# count IS a broken-agreement campaign.  ``havoc`` weights the IC
# verdicts above the softer quorum/equivocation signals so coordinate
# descent can climb toward violations through campaigns that merely
# disturb quorum first.
OBJECTIVES = {
    "ic1": Objective(
        "ic1", (("ic1_violations", 1),), ("ic1_violations",)
    ),
    "ic2": Objective(
        "ic2", (("ic2_violations", 1),), ("ic2_violations",)
    ),
    "ic": Objective(
        "ic",
        (("ic1_violations", 1), ("ic2_violations", 1)),
        ("ic1_violations", "ic2_violations"),
    ),
    "quorum": Objective(
        "quorum", (("quorum_failures", 1),), ("quorum_failures",)
    ),
    "havoc": Objective(
        "havoc",
        (
            ("ic1_violations", 8),
            ("ic2_violations", 8),
            ("quorum_failures", 2),
            ("equivocation_observed", 1),
        ),
        ("ic1_violations", "ic2_violations"),
    ),
}


def get_objective(name) -> Objective:
    """Name -> :class:`Objective`; eager ScenarioError on unknowns (the
    hand-edited-config rule: fail before any array is built)."""
    if isinstance(name, Objective):
        return name
    try:
        return OBJECTIVES[name]
    except (KeyError, TypeError):
        raise ScenarioError(
            f"unknown search objective {name!r}; one of "
            f"{sorted(OBJECTIVES)}"
        ) from None


def _columns(counter_names, wanted, objective_name: str) -> list:
    idx = []
    for name in wanted:
        try:
            idx.append(list(counter_names).index(name))
        except ValueError:
            raise ScenarioError(
                f"objective {objective_name!r} reads counter {name!r} "
                f"which is not in the run's table {list(counter_names)}"
            ) from None
    return idx


def score_rows(rows, counter_names, objective) -> np.ndarray:
    """``[B, C]`` per-slot counter rows -> ``[B]`` int64 scores."""
    obj = get_objective(objective)
    rows = np.asarray(rows)
    if rows.ndim != 2 or rows.shape[1] != len(tuple(counter_names)):
        raise ScenarioError(
            f"counter rows are {rows.shape}, expected "
            f"[B, {len(tuple(counter_names))}]"
        )
    names = [n for n, _ in obj.weights]
    cols = _columns(counter_names, names, obj.name)
    weights = np.array([w for _, w in obj.weights], np.int64)
    return rows[:, cols].astype(np.int64) @ weights


def violation_rows(rows, counter_names, objective) -> np.ndarray:
    """``[B, C]`` rows -> ``[B]`` bool: which slots broke the objective's
    violation counters (any nonzero)."""
    obj = get_objective(objective)
    rows = np.asarray(rows)
    cols = _columns(counter_names, obj.violation_counters, obj.name)
    return (rows[:, cols] > 0).any(axis=1)


def counters_dict(row, counter_names) -> dict:
    """One ``[C]`` per-slot row as ``{name: int}`` — the provenance /
    record form."""
    return {
        name: int(v) for name, v in zip(tuple(counter_names), row)
    }
