"""Minimal-reproducer mining: delta-debugging shrink + the parity oracle.

A hunt's raw finding is whatever event soup the sampler landed on; the
committable artifact is the MINIMAL event set that still breaks the
objective.  :func:`shrink` is classic ddmin over the campaign's event
list — remove chunks at doubling granularity, then a 1-minimal pass —
where each trial replays the candidate ALONE at B=1 under its own
``(seed, uid)`` key (``loop.evaluate_alone``) and keeps the removal iff
the violation survives.  Every surviving event is therefore
load-bearing: removing any single one loses the violation.

:func:`verify_minimized` is the correctness oracle the export gate
runs: the shrunk spec replayed alone must be BIT-EXACT
(decisions/leaders/counters) with the same spec evaluated inside a
co-population (slot 0 of a padded batch) — the serving parity pin
(``coalesced_sweep``: slot b ≡ its own B=1 run) reused as the search's
ground truth.  A reproducer that passes replays identically wherever
it runs: standalone, in a population, or from its exported JSON via
``scenario_sweep``.

jax-free at import (ba-lint BA301 host-tier): the evaluation engine
loads lazily through ``ba_tpu.search.loop``'s function-body imports.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ba_tpu.scenario.spec import Scenario, ScenarioError, validate
from ba_tpu.search import objective as _objective


def _violates(result: dict, objective) -> bool:
    rows = np.asarray(result["counters"])[None, :]
    return bool(
        _objective.violation_rows(
            rows, result["counter_names"], objective
        )[0]
    )


def _with_events(campaign: Scenario, events) -> Scenario:
    return validate(
        dataclasses.replace(campaign, events=tuple(events))
    )


def shrink(
    campaign: Scenario,
    *,
    seed: int,
    uid: int,
    capacity: int,
    objective="ic",
    depth: int = 2,
    rounds_per_dispatch: int = 8,
    engine: str | None = None,
    evaluate=None,
):
    """ddmin the campaign's event list to a 1-minimal violating set.

    ``evaluate`` (injectable for tests) maps a candidate
    :class:`Scenario` to ``loop.evaluate_alone``'s result dict; the
    default replays at B=1 under the candidate's own ``(seed, uid)``
    key.  Raises :class:`ScenarioError` if ``campaign`` itself does not
    violate — shrinking a non-finding would "converge" to the empty
    campaign and export garbage.

    Returns ``(shrunk_campaign, info)`` with ``info`` =
    ``{"events_before", "events_after", "evals"}``.
    """
    obj = _objective.get_objective(objective)
    if evaluate is None:
        from ba_tpu.search.loop import evaluate_alone

        def evaluate(c):
            return evaluate_alone(
                c, seed=seed, uid=uid, capacity=capacity, depth=depth,
                rounds_per_dispatch=rounds_per_dispatch, engine=engine,
            )

    evals = 0

    def still_violates(events) -> bool:
        nonlocal evals
        evals += 1
        return _violates(evaluate(_with_events(campaign, events)), obj)

    events = list(campaign.events)
    if not still_violates(events):
        raise ScenarioError(
            f"campaign {campaign.name!r} does not violate objective "
            f"{obj.name!r} — nothing to shrink"
        )
    # ddmin: try dropping complement chunks at doubling granularity.
    n = 2
    while len(events) >= 2:
        chunk = max(1, len(events) // n)
        reduced = False
        i = 0
        while i < len(events):
            trial = events[:i] + events[i + chunk:]
            if trial and still_violates(trial):
                events = trial
                n = max(n - 1, 2)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if chunk == 1:
                break
            n = min(n * 2, len(events))
    # 1-minimal pass: every surviving event is individually load-bearing.
    i = 0
    while i < len(events) and len(events) > 1:
        trial = events[:i] + events[i + 1:]
        if still_violates(trial):
            events = trial
        else:
            i += 1
    shrunk = _with_events(campaign, events)
    return shrunk, {
        "events_before": len(campaign.events),
        "events_after": len(events),
        "evals": evals,
    }


def verify_minimized(
    campaign: Scenario,
    *,
    seed: int,
    uid: int,
    capacity: int,
    objective="ic",
    pad: int = 3,
    depth: int = 2,
    rounds_per_dispatch: int = 8,
    engine: str | None = None,
):
    """The export gate: replay ``campaign`` alone AND at slot 0 of a
    ``1 + pad`` co-population (pad slots run the empty campaign under
    :data:`~ba_tpu.search.loop.PAD_UID_BASE` keys), and compare the
    candidate's decisions/leaders/counters bit-exactly.

    Returns ``{"bit_exact": bool, "violates": bool, "score": int,
    "counters": {name: int}}`` — ``bit_exact`` is the parity-oracle
    verdict, ``violates``/``score`` read the ALONE run (the one the
    exported spec's provenance describes).
    """
    from ba_tpu.search import generate as _generate
    from ba_tpu.search.loop import (
        PAD_UID_BASE,
        candidate_keys,
        evaluate_alone,
        evaluate_population,
        population_state,
    )

    obj = _objective.get_objective(objective)
    alone = evaluate_alone(
        campaign, seed=seed, uid=uid, capacity=capacity, depth=depth,
        rounds_per_dispatch=rounds_per_dispatch, engine=engine,
    )
    pads = [
        Scenario(
            name=f"pad-{j}",
            rounds=campaign.rounds,
            events=(),
            order=campaign.order,
        )
        for j in range(pad)
    ]
    block = _generate.lower_population(
        [campaign] + pads, capacity, campaign.rounds
    )
    keys = candidate_keys(
        seed, [uid] + [PAD_UID_BASE + j for j in range(pad)]
    )
    state = population_state(1 + pad, capacity, campaign.order)
    pop = evaluate_population(
        keys, state, block,
        rounds=campaign.rounds, depth=depth,
        rounds_per_dispatch=rounds_per_dispatch, engine=engine,
    )
    bit_exact = (
        np.array_equal(alone["decisions"], pop["decisions"][:, 0])
        and np.array_equal(alone["leaders"], pop["leaders"][:, 0])
        and np.array_equal(alone["counters"], pop["counters"][0])
        and list(alone["counter_names"]) == list(pop["counter_names"])
    )
    rows = np.asarray(alone["counters"])[None, :]
    return {
        "bit_exact": bool(bit_exact),
        "violates": _violates(alone, obj),
        "score": int(
            _objective.score_rows(rows, alone["counter_names"], obj)[0]
        ),
        "counters": _objective.counters_dict(
            alone["counters"], alone["counter_names"]
        ),
    }
