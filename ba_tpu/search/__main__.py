"""``python -m ba_tpu.search <command> ...`` — the search CLI.

Three subcommands; ``sample`` and ``corpus`` are jax-free by
construction (spec grammar + generator + corpus are numpy/stdlib only
— the subprocess pin in tests/test_search.py proves no jax import),
so they cost milliseconds in CI; ``hunt`` drives the engine and is the
one subcommand that loads jax.

- ``sample <space.json> [--seed N] [--count K]`` — print K sampled
  candidate campaigns (their ordinary spec-JSON docs) for a search
  space, deterministically.  The dry-run view of what a hunt would
  sweep.
- ``corpus <dir>`` — validate a found-reproducer corpus: every spec
  loads, validates, round-trips byte-stably, and carries the
  ``provenance.search`` replay recipe.  Exits non-zero naming the
  first offender.
- ``hunt <space.json> [--seed N] [--generations G] [--objective NAME]
  [--export DIR] [--checkpoint PATH] [--resume PATH]
  [--stop-after N]`` — run a hunt and print one JSON summary line
  (found/minimized/exported counts, best score, run_id).

Search-space JSON is :func:`ba_tpu.search.generate.space_from_dict`'s
grammar: ``{"rounds": R, "capacity": n, "population": B, ...}``.
"""

from __future__ import annotations

import argparse
import json
import sys

from ba_tpu.scenario.spec import ScenarioError, to_dict
from ba_tpu.search.corpus import load_corpus
from ba_tpu.search.generate import sample_campaign, space_from_dict


def _load_space(path: str):
    with open(path) as fh:
        try:
            doc = json.load(fh)
        except json.JSONDecodeError as e:
            raise ScenarioError(f"{path}: not valid JSON ({e})") from None
    return space_from_dict(doc)


def _cmd_sample(args) -> int:
    space = _load_space(args.space)
    for i in range(args.count):
        campaign = sample_campaign(space, args.seed, i)
        print(json.dumps(to_dict(campaign)))
    return 0


def _cmd_corpus(args) -> int:
    specs = load_corpus(args.dir)
    for spec in specs:
        search = spec.provenance["search"]
        print(
            f"{spec.name}: OK — {len(spec.events)} event(s), "
            f"objective {search['objective']!r} score {search['score']} "
            f"(seed {search['seed']}, uid {search['uid']}, "
            f"gen {search['generation']})"
        )
    print(f"corpus OK ({len(specs)} reproducer(s))")
    return 0


def _cmd_hunt(args) -> int:
    # The ONE jax-loading subcommand: resolve lazily so sample/corpus
    # stay importable (and fast) on accelerator-free hosts.
    from ba_tpu.search.loop import hunt

    kwargs = dict(
        seed=args.seed,
        generations=args.generations,
        objective=args.objective,
        stop_after=args.stop_after,
        export_dir=args.export,
        checkpoint_path=args.checkpoint,
    )
    # A space file given alongside --resume passes through so hunt()'s
    # space-conflict guard engages (the checkpoint's space governs; a
    # DIFFERENT file must refuse loudly, never be silently dropped).
    space = _load_space(args.space) if args.space else None
    out = hunt(space, resume=args.resume, **kwargs)
    print(
        json.dumps(
            {
                "found": out["stats"]["found"],
                "minimized": out["stats"]["minimized"],
                "exported": out["exported"],
                "best_score": out["stats"]["best_score"],
                "campaigns": out["stats"]["campaigns"],
                "generations": out["stats"]["generations_run"],
                "run_id": out["stats"]["run_id"],
            }
        )
    )
    return 0


def main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ba_tpu.search", description=__doc__
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("sample", help="print sampled candidate campaigns")
    p.add_argument("space")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--count", type=int, default=4)
    p.set_defaults(fn=_cmd_sample)

    p = sub.add_parser("corpus", help="validate a found-reproducer corpus")
    p.add_argument("dir")
    p.set_defaults(fn=_cmd_corpus)

    p = sub.add_parser("hunt", help="run an adversary hunt (loads jax)")
    p.add_argument("space", nargs="?", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--generations", type=int, default=4)
    p.add_argument("--objective", default="ic")
    p.add_argument("--stop-after", type=int, default=None)
    p.add_argument("--export", default=None)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--resume", default=None)
    p.set_defaults(fn=_cmd_hunt)

    args = parser.parse_args(argv)
    if args.command == "hunt" and not args.space and not args.resume:
        print("hunt needs a space file or --resume", file=sys.stderr)
        return 2
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:  # ScenarioError is a ValueError
        print(f"FAIL — {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
