"""The found-reproducer corpus: export, load and validate search finds.

A minimized finding exports as an ORDINARY scenario JSON spec — the
same grammar the REPL ``scenario`` command replays and ``python -m
ba_tpu.scenario`` CI-validates — into ``examples/scenarios/found/``,
with a ``provenance`` header (the spec grammar's optional metadata key,
ISSUE 15) recording the complete replay recipe:

    "provenance": {"search": {
        "seed": 7, "uid": 123, "generation": 2, "objective": "ic",
        "capacity": 8, "score": 5, "counters": {...},
        "events_before": 6}}

``(seed, uid)`` pins the candidate's PRNG key
(``fold_in(key(seed), uid)``) and ``capacity`` the padded width its
coin streams depend on, so any process can re-run the exact hunt-time
evaluation (``loop.evaluate_alone``) and check the stored counters
bit-for-bit — tests/test_search.py does exactly that for the committed
corpus.

jax-free (stdlib + the scenario spec layer): the ``python -m
ba_tpu.search corpus`` CI stage validates a corpus directory without
an accelerator stack.
"""

from __future__ import annotations

import os

from ba_tpu.scenario.spec import (
    Scenario,
    ScenarioError,
    from_dict,
    load,
    save,
    to_dict,
)

FOUND_DIR = os.path.join("examples", "scenarios", "found")

# The provenance keys every exported reproducer must carry — the
# replay recipe (seed/uid), the discovery coordinates
# (generation/objective) and the expected outcome (score/counters).
PROVENANCE_KEYS = (
    "seed", "uid", "generation", "objective", "capacity", "score",
    "counters",
)


def provenance(
    entry: dict, seed: int, objective: str, capacity: int
) -> dict:
    """The ``provenance`` header for one minimized-finding entry (the
    dict shape ``loop.hunt`` builds)."""
    return {
        "search": {
            "seed": seed,
            "uid": entry["uid"],
            "generation": entry["generation"],
            "objective": objective,
            "capacity": capacity,
            "score": entry["score"],
            "counters": dict(entry["counters"]),
            "events_before": entry.get(
                "events_before", len(entry["doc"].get("events", ()))
            ),
        }
    }


def reproducer_path(dirpath: str, spec: Scenario) -> str:
    return os.path.join(dirpath, f"{spec.name}.json")


def export_found(
    entries, dirpath: str, *, seed: int, objective: str, capacity: int
):
    """Write minimized-finding entries as provenance-stamped spec files.

    Entries whose parity oracle failed (``bit_exact`` False) are
    REFUSED — an exported reproducer that replays differently alone vs
    batched is exactly the artifact this corpus must never contain.
    Returns the written paths (sorted, deterministic).
    """
    os.makedirs(dirpath, exist_ok=True)
    paths = []
    for entry in entries:
        if not entry.get("bit_exact", False):
            raise ScenarioError(
                f"finding uid={entry.get('uid')} failed the "
                f"alone-vs-in-population parity oracle — refusing to "
                f"export a non-reproducing spec"
            )
        spec = from_dict(entry["doc"])
        stamped = from_dict(
            {
                **to_dict(spec),
                "provenance": provenance(entry, seed, objective, capacity),
            }
        )
        path = reproducer_path(dirpath, stamped)
        save(path, stamped)
        paths.append(path)
    return sorted(paths)


def check_reproducer(spec: Scenario) -> Scenario:
    """Validate the corpus contract on one loaded spec: a well-formed
    ``provenance.search`` header with every replay-recipe key."""
    prov = spec.provenance or {}
    search = prov.get("search")
    if not isinstance(search, dict):
        raise ScenarioError(
            f"reproducer {spec.name!r} has no provenance.search header"
        )
    missing = [k for k in PROVENANCE_KEYS if k not in search]
    if missing:
        raise ScenarioError(
            f"reproducer {spec.name!r} provenance missing {missing}"
        )
    for key in ("seed", "uid", "generation", "capacity", "score"):
        if not isinstance(search[key], int) or isinstance(
            search[key], bool
        ):
            raise ScenarioError(
                f"reproducer {spec.name!r} provenance {key}="
                f"{search[key]!r} must be an int"
            )
    if not isinstance(search["counters"], dict) or not search["counters"]:
        raise ScenarioError(
            f"reproducer {spec.name!r} provenance counters must be a "
            f"non-empty object"
        )
    return spec


def load_corpus(dirpath: str):
    """Load + contract-check every ``*.json`` reproducer in ``dirpath``
    (sorted for determinism).  Returns a list of validated specs."""
    if not os.path.isdir(dirpath):
        raise ScenarioError(f"corpus directory {dirpath!r} does not exist")
    specs = []
    for name in sorted(os.listdir(dirpath)):
        if name.endswith(".json"):
            specs.append(
                check_reproducer(load(os.path.join(dirpath, name)))
            )
    if not specs:
        raise ScenarioError(
            f"corpus directory {dirpath!r} holds no .json reproducers"
        )
    return specs
