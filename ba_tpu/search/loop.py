"""The adversary hunt loop: random sweep -> elite selection -> mutation,
batched campaign-per-instance through the coalesced engine.

The execution shape (docs/DESIGN.md §14): one generation = one
population of B candidate campaigns lowered into a single batch
(``generate.lower_population``) and evaluated by ONE
``coalesced_sweep(scenario=...)`` stream — per-slot key schedules, so
candidate ``b``'s decisions/leaders/counters are bit-identical to its
own B=1 run (the serving parity pin, reused here as the search's
correctness oracle), and per-slot scenario counter blocks, so scoring
(``objective.score_rows``) reads ONLY what the engine's depth-delayed
retire fetches already brought back — the hunt adds zero device
synchronizations beyond the engine's own (the no-blocking
dispatch-count proof re-runs with the harness live,
tests/test_search.py).

Candidate ``uid`` draws its per-slot PRNG key as
``fold_in(key(seed), uid)`` — slot-position-free, which is what makes
population membership, mesh shard assignment and standalone replay all
bit-exact with each other, and an exported reproducer's
``(seed, uid)`` provenance a complete replay recipe.

Search state is plain JSON data checkpointed through
``utils/snapshot.write_search_checkpoint`` (versioned header, content
digest, atomic write): a killed day-long hunt resumes bit-exactly —
every sample and mutation is keyed by ``(seed, uid)`` and the uid
cursor rides the checkpoint — and the resumed process re-derives the
same run_id, joining its predecessor's flight ledger exactly like a
supervised campaign's successor does.

``mesh=`` shards a generation into per-device sub-populations (one
evaluation thread per device, the engine's async dispatch overlapping
across chips); slot keys make shard assignment layout-only, so a
sharded hunt is bit-exact with the single-device hunt at any device
count.

This module is HOST-TIER at import (ba-lint BA301: jax loads lazily
from function bodies) and lives in the BA101 hot-path scope — the
generation loop must never block on the device outside the engine's
own retire discipline.
"""

from __future__ import annotations

import dataclasses
import json
import time

from ba_tpu import obs
from ba_tpu.scenario.spec import Scenario, ScenarioError, from_dict, to_dict
from ba_tpu.search import generate as _generate
from ba_tpu.search import objective as _objective
from ba_tpu.utils import metrics as _metrics

# NOT imported at module level: `ba_tpu.search.minimize` (its lazy
# loop-import closure reaches the engine) and `ba_tpu.utils.snapshot`
# (whose state loader reaches core) — both load from function bodies,
# the BA301 host-tier lazy seam.

# Pad slots (minimizer verification co-population) fold uids from here
# up — far above any hunt's candidate cursor, so a pad key can never
# collide with a real candidate's stream.
PAD_UID_BASE = 0x7F000000


def candidate_keys(seed: int, uids):
    """One typed PRNG key per candidate: ``fold_in(key(seed), uid)``.

    Slot-position-free by construction — the per-slot schedule folds
    instance 0 whatever slot the candidate lands in — so the SAME key
    drives the candidate in any population, any mesh shard, and its
    standalone replay (threefry derivation is backend-independent).
    """
    import jax.random as jr

    base = jr.key(seed)
    return [jr.fold_in(base, uid) for uid in uids]


def population_state(batch: int, capacity: int, order: str):
    """The canonical all-honest initial state every candidate starts
    from: all ``capacity`` slots alive, nobody faulty, leader slot 0,
    ids 1..capacity — the campaign's events ARE the whole adversary, so
    a candidate is a pure function of (events, seed, uid)."""
    import jax.numpy as jnp

    from ba_tpu.core.state import SimState
    from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT

    code = {"attack": ATTACK, "retreat": RETREAT}[order]
    return SimState(
        order=jnp.full((batch,), code, COMMAND_DTYPE),
        leader=jnp.zeros((batch,), jnp.int32),
        faulty=jnp.zeros((batch, capacity), bool),
        alive=jnp.ones((batch, capacity), bool),
        ids=jnp.broadcast_to(
            jnp.arange(1, capacity + 1, dtype=jnp.int32),
            (batch, capacity),
        ),
    )


def evaluate_population(  # ba-lint: donates(state)
    slot_keys,
    state,
    block,
    *,
    rounds: int,
    depth: int = 2,
    rounds_per_dispatch: int = 8,
    unroll: int = 1,
    engine: str | None = None,
    exec_seam=None,
):
    """Evaluate one population block through the coalesced engine.

    A thin named seam over ``coalesced_sweep(scenario=block)`` so the
    hunt, the minimizer and the tests share one evaluation path.
    DONATION: ``state`` is consumed by the first dispatch (the engine's
    contract) — callers stage a fresh :func:`population_state` per
    call.  Returns the coalesced result dict: ``decisions``
    [rounds, B], ``leaders`` [rounds, B], ``counters`` [B, C] per-slot
    final blocks + ``counter_names``, ``stats``.
    """
    from ba_tpu.parallel.pipeline import coalesced_sweep

    return coalesced_sweep(
        slot_keys,
        state,
        rounds,
        scenario=block,
        depth=depth,
        rounds_per_dispatch=rounds_per_dispatch,
        unroll=unroll,
        engine=engine,
        exec_seam=exec_seam,
    )


def _mesh_devices(mesh) -> list:
    """Flatten a Mesh (or any device sequence) into the shard list."""
    devices = getattr(mesh, "devices", mesh)
    flat = getattr(devices, "flat", None)
    return list(flat) if flat is not None else list(devices)


def _evaluate_candidates(
    candidates, uids, space, *, seed, depth, rounds_per_dispatch,
    unroll, engine, exec_seam, mesh=None,
):
    """Lower + evaluate a candidate list; with ``mesh`` the population
    splits into per-device sub-populations evaluated concurrently (one
    thread per device — dispatch is async, so device compute overlaps
    while each thread runs its own depth-k retire loop).  Returns
    ``(counters [B, C], counter_names, decisions [R, B],
    leaders [R, B], stats)`` in candidate order — bit-identical at any
    shard count (per-slot keys make placement layout-only)."""
    import jax
    import numpy as np  # host assembly of already-host retire blocks

    def run_shard(cands, cand_uids, device=None):
        block = _generate.lower_population(
            cands, space.capacity, space.rounds
        )
        keys = candidate_keys(seed, cand_uids)

        def call():
            state = population_state(
                len(cands), space.capacity, space.order
            )
            return evaluate_population(
                keys, state, block,
                rounds=space.rounds, depth=depth,
                rounds_per_dispatch=rounds_per_dispatch, unroll=unroll,
                engine=engine, exec_seam=exec_seam,
            )

        if device is None:
            return call()
        with jax.default_device(device):
            return call()

    if mesh is None:
        res = run_shard(candidates, uids)
        return (
            res["counters"], res["counter_names"], res["decisions"],
            res["leaders"], [res["stats"]],
        )
    devices = _mesh_devices(mesh)
    d = len(devices)
    if d < 1 or len(candidates) % d:
        raise ScenarioError(
            f"population {len(candidates)} does not divide over "
            f"{d} mesh device(s) — per-shard populations must be equal"
        )
    per = len(candidates) // d
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(max_workers=d) as pool:
        futures = [
            pool.submit(
                run_shard,
                candidates[k * per:(k + 1) * per],
                uids[k * per:(k + 1) * per],
                devices[k],
            )
            for k in range(d)
        ]
        shards = [f.result() for f in futures]
    return (
        np.concatenate([s["counters"] for s in shards], axis=0),
        shards[0]["counter_names"],
        np.concatenate([s["decisions"] for s in shards], axis=1),
        np.concatenate([s["leaders"] for s in shards], axis=1),
        [s["stats"] for s in shards],
    )


def evaluate_alone(
    campaign: Scenario,
    *,
    seed: int,
    uid: int,
    capacity: int,
    depth: int = 2,
    rounds_per_dispatch: int = 8,
    unroll: int = 1,
    engine: str | None = None,
):
    """One candidate, alone at B=1 — the standalone replay leg of the
    parity oracle (same key, same padded capacity as its population
    run).  Returns ``{counters [C], counter_names, decisions [R],
    leaders [R]}``."""
    block = _generate.lower_population([campaign], capacity, campaign.rounds)
    state = population_state(1, capacity, campaign.order)
    res = evaluate_population(
        candidate_keys(seed, [uid]), state, block,
        rounds=campaign.rounds, depth=depth,
        rounds_per_dispatch=rounds_per_dispatch, unroll=unroll,
        engine=engine,
    )
    return {
        "counters": res["counters"][0],
        "counter_names": res["counter_names"],
        "decisions": res["decisions"][:, 0],
        "leaders": res["leaders"][:, 0],
    }


@dataclasses.dataclass
class SearchState:
    """The hunt's resumable cursor — plain JSON data, nothing else.

    ``generation`` is the NEXT generation to run and ``next_uid`` the
    next candidate uid to assign; together with the seed-keyed
    generator they determine every future sample and mutation, which
    is the whole resume-bit-exactness argument.
    """

    seed: int
    objective: str
    space_doc: dict
    generation: int = 0
    next_uid: int = 0
    elites: list = dataclasses.field(default_factory=list)
    found: list = dataclasses.field(default_factory=list)
    campaigns: int = 0
    best_score: int = 0

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "SearchState":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - fields
        if unknown:
            raise ScenarioError(
                f"unknown search state keys: {sorted(unknown)}"
            )
        return cls(**doc)


def _compose_population(state: SearchState, space, elites: int):
    """The next generation's candidates, deterministically: surviving
    elites spawn mutants for half the population, fresh samples fill
    the rest (generation 0, or an elite-less hunt, is the pure random
    sweep).  Assigns uids from the state's cursor."""
    parents = [
        from_dict(e["doc"]) for e in state.elites[:elites]
    ]
    candidates, uids = [], []

    def add(campaign):
        candidates.append(campaign)
        uids.append(state.next_uid)
        state.next_uid += 1

    n_mutants = space.population // 2 if parents else 0
    for j in range(n_mutants):
        add(
            _generate.mutate_campaign(
                parents[j % len(parents)], space, state.seed,
                state.next_uid,
            )
        )
    while len(candidates) < space.population:
        add(_generate.sample_campaign(space, state.seed, state.next_uid))
    return candidates, uids


def _write_checkpoint(path, state: SearchState, run_id) -> str:
    from ba_tpu.utils import snapshot as _snapshot

    written = path.replace("{generation}", str(state.generation))
    _snapshot.write_search_checkpoint(
        written, state.to_doc(), run_id=run_id
    )
    _metrics.emit(
        {
            "event": "search_checkpoint",
            "v": _metrics.SCHEMA_VERSION,
            "generation": state.generation,
            "path": written,
            "found": len(state.found),
        }
    )
    obs.default_registry().counter("search_checkpoints_total").inc()
    return written


def hunt(
    space=None,
    *,
    seed: int = 0,
    generations: int = 4,
    objective="ic",
    elites: int = 4,
    depth: int = 2,
    rounds_per_dispatch: int = 8,
    unroll: int = 1,
    mesh=None,
    engine: str | None = None,
    exec_seam=None,
    checkpoint_path: str | None = None,
    checkpoint_every: int | None = None,
    resume=None,
    stop_after: int | None = None,
    minimize: bool = True,
    minimize_max: int = 4,
    export_dir: str | None = None,
    on_generation=None,
):
    """Run an adversary hunt: ``generations`` rounds of sample →
    evaluate → select → mutate over ``space``, collecting every
    campaign that breaks the ``objective``'s violation counters.

    ``space`` is a :class:`~ba_tpu.search.generate.SearchSpace` (or its
    dict form); every dial is validated EAGERLY before any array is
    built.  ``checkpoint_path`` (+ ``checkpoint_every`` generations,
    default 1) serializes the search state after each due generation —
    a literal ``{generation}`` in the path keeps a family;
    ``resume=`` (a path or a state doc) continues a hunt bit-exactly
    (``space``/``seed``/``objective`` ride the checkpoint; passing a
    conflicting ``space`` raises).  ``stop_after=N`` ends the
    generation loop early once N distinct violations are on file.

    ``minimize=True`` delta-debugs up to ``minimize_max`` findings to
    minimal event sets (``search/minimize.py``), each re-validated by
    the alone-vs-in-population bit-exact replay oracle;
    ``export_dir`` then writes the minimized reproducers as ordinary
    provenance-stamped scenario JSON specs (``search/corpus.py``).

    The whole hunt runs inside a flight-recorder run scope: a
    deterministic run_id (derived from seed/space/objective, or
    inherited from the resume checkpoint so a restarted hunt joins its
    predecessor's ledger) stamps every ``search_*`` record, gauge
    snapshot and checkpoint header.

    Returns a dict: ``found`` (violation entries: spec doc, uid,
    generation, score, per-slot counters), ``minimized`` (shrunk
    entries incl. the ``bit_exact`` oracle verdict), ``elites``,
    ``exported`` (paths, when ``export_dir``), ``state`` (the final
    resumable doc) and ``stats``.
    """
    obj = _objective.get_objective(objective)
    if generations < 1:
        raise ScenarioError(f"generations={generations} must be >= 1")
    if elites < 0:
        raise ScenarioError(f"elites={elites} must be >= 0")
    if depth < 1 or rounds_per_dispatch < 1 or unroll < 1:
        raise ScenarioError(
            f"depth={depth} / rounds_per_dispatch={rounds_per_dispatch} "
            f"/ unroll={unroll} must all be >= 1"
        )
    if stop_after is not None and stop_after < 1:
        raise ScenarioError(f"stop_after={stop_after} must be >= 1")
    if minimize_max < 0:
        raise ScenarioError(f"minimize_max={minimize_max} must be >= 0")
    if checkpoint_every is not None and checkpoint_every < 1:
        raise ScenarioError(
            f"checkpoint_every={checkpoint_every} must be >= 1"
        )
    if checkpoint_every is not None and checkpoint_path is None:
        # The pipeline engine's rule: a checkpoint cadence with no sink
        # would leave an empty disk at resume time.
        raise ScenarioError("checkpoint_every needs checkpoint_path")
    if checkpoint_path is not None and checkpoint_every is None:
        checkpoint_every = 1

    if resume is not None:
        if isinstance(resume, str):
            from ba_tpu.utils import snapshot as _snapshot

            meta, state_doc = _snapshot.read_search_checkpoint(resume)
            inherited_rid = meta.get("run_id")
        else:
            state_doc, inherited_rid = dict(resume), None
        state = SearchState.from_doc(state_doc)
        resumed_space = _generate.space_from_dict(state.space_doc)
        if space is not None:
            given = (
                _generate.space_to_dict(space)
                if isinstance(space, _generate.SearchSpace)
                else _generate.space_to_dict(
                    _generate.space_from_dict(space)
                )
            )
            if given != state.space_doc:
                raise ScenarioError(
                    "resume checkpoint was written for a different "
                    "search space — pass space=None (the checkpoint "
                    "carries it) or the identical space"
                )
        space = resumed_space
        seed = state.seed
        obj = _objective.get_objective(state.objective)
        if not state.generation < generations:
            raise ScenarioError(
                f"resume cursor {state.generation} outside hunt "
                f"[0, {generations}) — pass a larger generations= to "
                f"extend the hunt"
            )
    else:
        if space is None:
            raise ScenarioError("hunt needs a search space (or resume=)")
        if not isinstance(space, _generate.SearchSpace):
            space = _generate.space_from_dict(space)
        _generate.validate_space(space)
        state = SearchState(
            seed=seed,
            objective=obj.name,
            space_doc=_generate.space_to_dict(space),
        )
        inherited_rid = None
    if mesh is not None:
        d = len(_mesh_devices(mesh))
        if d < 1 or space.population % d:
            raise ScenarioError(
                f"population {space.population} does not divide over "
                f"{d} mesh device(s) — per-shard populations must be "
                f"equal"
            )

    rid = obs.flight.resolve_run_id(
        inherited=inherited_rid,
        material_fn=lambda: [
            "search",
            seed,
            json.dumps(state.space_doc, sort_keys=True),
            obj.name,
            generations,
        ],
    )
    reg = obs.default_registry()
    seen = {
        _generate.campaign_fingerprint(from_dict(e["doc"]))
        for e in state.found
    }
    n_checkpoints = 0
    shard_stats = []
    t_hunt = time.perf_counter()
    with obs.flight.run_scope(rid) as scope:
        obs.instant(
            "search_start",
            generations=generations,
            population=space.population,
            objective=obj.name,
            resume=state.generation,
        )
        while state.generation < generations:
            if stop_after is not None and len(state.found) >= stop_after:
                break
            g = state.generation
            t0 = time.perf_counter()
            candidates, uids = _compose_population(state, space, elites)
            rows, names, decisions, leaders, stats = _evaluate_candidates(
                candidates, uids, space,
                seed=seed, depth=depth,
                rounds_per_dispatch=rounds_per_dispatch, unroll=unroll,
                engine=engine, exec_seam=exec_seam, mesh=mesh,
            )
            shard_stats = stats
            scores = _objective.score_rows(rows, names, obj)
            violations = _objective.violation_rows(rows, names, obj)
            new_found = 0
            for i, campaign in enumerate(candidates):
                if not violations[i]:
                    continue
                fp = _generate.campaign_fingerprint(campaign)
                if fp in seen:
                    continue
                seen.add(fp)
                new_found += 1
                entry = {
                    "doc": to_dict(campaign),
                    "uid": uids[i],
                    "generation": g,
                    "score": int(scores[i]),
                    "counters": _objective.counters_dict(rows[i], names),
                }
                state.found.append(entry)
                _metrics.emit(
                    {
                        "event": "search_found",
                        "v": _metrics.SCHEMA_VERSION,
                        "name": campaign.name,
                        "uid": uids[i],
                        "generation": g,
                        "score": entry["score"],
                        "events": len(campaign.events),
                        "counters": entry["counters"],
                        "objective": obj.name,
                    }
                )
            pool = state.elites[:elites] + [
                {
                    "doc": to_dict(c),
                    "uid": uids[i],
                    "score": int(scores[i]),
                }
                for i, c in enumerate(candidates)
            ]
            pool.sort(key=lambda e: (-e["score"], e["uid"]))
            state.elites = pool[: max(elites, 1)]
            state.campaigns += len(candidates)
            state.best_score = max(
                state.best_score, int(scores.max()) if len(scores) else 0
            )
            state.generation = g + 1
            reg.counter("search_generations_total").inc()
            reg.counter("search_campaigns_total").inc(len(candidates))
            if new_found:
                reg.counter("search_found_total").inc(new_found)
            reg.gauge("search_best_score").set(state.best_score)
            gen_wall = time.perf_counter() - t0
            _metrics.emit(
                {
                    "event": "search_generation",
                    "v": _metrics.SCHEMA_VERSION,
                    "generation": g,
                    "campaigns": len(candidates),
                    "best_score": state.best_score,
                    "new_found": new_found,
                    "found_total": len(state.found),
                    "objective": obj.name,
                    "wall_s": round(gen_wall, 6),
                }
            )
            if on_generation is not None:
                on_generation(
                    g,
                    {
                        "scores": scores,
                        "new_found": new_found,
                        "found_total": len(state.found),
                    },
                )
            if (
                checkpoint_path is not None
                and (state.generation % checkpoint_every == 0
                     or state.generation == generations)
            ):
                _write_checkpoint(checkpoint_path, state, scope.run_id)
                n_checkpoints += 1

        minimized = []
        if minimize:
            from ba_tpu.search import minimize as _minimize

            for entry in state.found[:minimize_max]:
                campaign = from_dict(entry["doc"])
                shrunk, info = _minimize.shrink(
                    campaign,
                    seed=seed,
                    uid=entry["uid"],
                    capacity=space.capacity,
                    objective=obj,
                    depth=depth,
                    rounds_per_dispatch=rounds_per_dispatch,
                    engine=engine,
                )
                verdict = _minimize.verify_minimized(
                    shrunk,
                    seed=seed,
                    uid=entry["uid"],
                    capacity=space.capacity,
                    objective=obj,
                    depth=depth,
                    rounds_per_dispatch=rounds_per_dispatch,
                    engine=engine,
                )
                minimized.append(
                    {
                        "doc": to_dict(shrunk),
                        "uid": entry["uid"],
                        "generation": entry["generation"],
                        "events_before": info["events_before"],
                        "events_after": info["events_after"],
                        "evals": info["evals"],
                        "score": verdict["score"],
                        "counters": verdict["counters"],
                        "bit_exact": verdict["bit_exact"],
                    }
                )
                _metrics.emit(
                    {
                        "event": "search_minimized",
                        "v": _metrics.SCHEMA_VERSION,
                        "name": shrunk.name,
                        "uid": entry["uid"],
                        "generation": entry["generation"],
                        "events_before": info["events_before"],
                        "events_after": info["events_after"],
                        "evals": info["evals"],
                        "score": verdict["score"],
                        "bit_exact": verdict["bit_exact"],
                        "objective": obj.name,
                    }
                )

        exported = []
        if export_dir is not None and minimized:
            from ba_tpu.search import corpus as _corpus

            exported = _corpus.export_found(
                minimized, export_dir, seed=seed, objective=obj.name,
                capacity=space.capacity,
            )
        reg.gauge("search_corpus_size").set(len(exported))
        obs.instant(
            "search_drain",
            generations=state.generation,
            found=len(state.found),
            best_score=state.best_score,
        )
        result = {
            "found": list(state.found),
            "minimized": minimized,
            "elites": list(state.elites),
            "exported": exported,
            "state": state.to_doc(),
            "stats": {
                "run_id": scope.run_id,
                "seed": seed,
                "objective": obj.name,
                "generations_run": state.generation,
                "population": space.population,
                "campaigns": state.campaigns,
                "found": len(state.found),
                "minimized": len(minimized),
                "best_score": state.best_score,
                "checkpoints": n_checkpoints,
                "shards": (
                    len(_mesh_devices(mesh)) if mesh is not None else 1
                ),
                "engine": (
                    shard_stats[0].get("engine") if shard_stats else None
                ),
                "wall_s": round(time.perf_counter() - t_hunt, 6),
            },
        }
        if scope.owner:
            obs.flight.emit_flight_summary(run_id=scope.run_id)
    return result
