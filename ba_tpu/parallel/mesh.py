"""Mesh construction helpers + the shared compiled-program cache."""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax
from jax.sharding import Mesh

# One bounded LRU for every node-sharded protocol's jitted shard_map
# program (om1/sm/eig): rebuilding the closure per call would re-trace and
# recompile every round (~2 s each on the 8-device CPU mesh), while an
# unbounded per-module dict leaks compiled executables in long-lived
# processes that churn meshes/shapes (VERDICT r2 weak #6).  64 programs is
# far beyond any real working set; eviction merely falls back to a re-jit.
_COMPILED: OrderedDict = OrderedDict()
_COMPILED_CAP = 64


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions (single compat seam).

    Newer jax exposes it as public ``jax.shard_map`` with a ``check_vma``
    flag; 0.4.x ships ``jax.experimental.shard_map.shard_map`` where the
    same knob is ``check_rep``.  Every node-sharded protocol and the
    fused sweep kernel route through here so the version split lives in
    exactly one place.
    """
    if check_vma is None:
        kw = {}
    elif hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
    else:
        kw = {"check_rep": check_vma}
    if hasattr(jax, "shard_map"):
        sm = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as sm
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def cached_jit(key, build):
    """jax.jit(build()) memoized under ``key`` in the shared bounded LRU.

    ``key`` must carry the caller's identity (e.g. start it with the
    protocol name) plus everything the traced program shape depends on —
    typically (mesh, n, m, flags...).  ``build`` is only called on a miss.
    """
    try:
        fn = _COMPILED[key]
        _COMPILED.move_to_end(key)
        return fn
    except KeyError:
        fn = jax.jit(build())
        _COMPILED[key] = fn
        while len(_COMPILED) > _COMPILED_CAP:
            _COMPILED.popitem(last=False)
        return fn


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("data", "node"),
    devices=None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default: all devices on the "data" (instance) axis and a trivial "node"
    axis — the right layout for fault-pattern sweeps, where instances are
    independent and ICI bandwidth goes entirely to the batch.  Pass an
    explicit ``shape`` (e.g. ``(2, 4)``) to give the node axis real chips
    for large-n single-cluster runs.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if len(shape) != len(axis_names):
        raise ValueError(
            f"mesh shape {shape} names {len(shape)} axis(es) but "
            f"axis_names {axis_names} has {len(axis_names)}"
        )
    n_dev = int(np.prod(shape))
    if n_dev < 1:
        raise ValueError(f"mesh shape {shape} must be all-positive")
    if n_dev > len(devices):
        # Without this, the oversized request dies inside Mesh with an
        # opaque reshape error; name the numbers so the caller (or the
        # REPL/bench one-line error paths) can act on them.
        raise ValueError(
            f"mesh shape {shape} needs {n_dev} device(s) but only "
            f"{len(devices)} are available — shrink the shape or force "
            f"more virtual devices (--xla_force_host_platform_"
            f"device_count)"
        )
    devs = np.asarray(devices[:n_dev]).reshape(shape)
    return Mesh(devs, axis_names)
