"""Mesh construction helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("data", "node"),
    devices=None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default: all devices on the "data" (instance) axis and a trivial "node"
    axis — the right layout for fault-pattern sweeps, where instances are
    independent and ICI bandwidth goes entirely to the batch.  Pass an
    explicit ``shape`` (e.g. ``(2, 4)``) to give the node axis real chips
    for large-n single-cluster runs.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n_dev = int(np.prod(shape))
    devs = np.asarray(devices[:n_dev]).reshape(shape)
    return Mesh(devs, axis_names)
