"""Sign-ahead host lane: per-round signature tables prepared while the
pipelined signed megastep is in flight (ISSUE 14 tentpole).

The signed SM(m) protocol's host obligation is signing, and signing is
what kept it off every fast path: ``runtime/backends._run_signed`` had
to host-sign BETWEEN the round-1 broadcast and the relay rounds, so
every round paid sign + verify + dispatch + fetch strictly in series.
The dissolving observation: a round's signatures cover the commander's
(at most V) DISTINCT round-bound claims — "commander of instance b says
v in round r" (``crypto.signed.round_message``) — not the realized
broadcast, so round r's whole table is known before round r runs.  The
lane exploits exactly the machinery ``crypto/signed.py`` proved in its
chunked setup overlap (``setup_signed_tables_overlapped``): sign a
window of rounds on host, dispatch the chunked device verification
without fetching, and hand the per-round ``[B, V]`` verdict planes to
the scan as consumed ``xs``.  ``pipeline_sweep(signed=True)`` stages
window d+1 through :meth:`SignAheadLane.stage` in the SAME host_work
overlap slot that stages scenario planes, while dispatches d-depth..d
occupy the device — host signing leaves the critical path entirely.

Nothing here ever fetches: signing is host numpy work, verification an
async device dispatch (or, on the CPU backend, the native C++ batch
verifier — host work in the host lane, overlapping the XLA compute
threads).  The no-blocking dispatch-count proof runs with the lane
live (tests/test_signed_pipeline.py).

:func:`sequential_signed_sweep` is the blocking per-round reference
driver — the ``_run_signed`` shape generalized to a sweep — whose
outputs the pipelined lane must reproduce BIT-EXACTLY under the same
key schedule and round tables (decisions, histograms, counters, final
majorities).  Its counter derivation is independent host numpy, so the
parity test cross-checks the in-scan verdict formulas too.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu import obs
from ba_tpu.crypto.signed import (
    _verify_received_exact,
    commander_keys,
    sign_round_tables,
)
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.utils import metrics as _metrics


class SignAheadLane:
    """The host lane: one commander key-set, per-round table staging.

    Keygen happens ONCE at construction (the per-key-set cost the
    signed setup always paid); :meth:`stage` then prepares any window
    of rounds — host-sign each round's per-(instance, value) table
    (``sign_round_tables``: messages bind instance, ROUND and value),
    dispatch one chunked verification over the whole window, and
    return the ``[hi-lo, B, V]`` verdict planes as a device array the
    signed megastep consumes as scan ``xs``.

    ``stage`` is re-entrant per window and never fetches; cumulative
    wall time lands in :attr:`sign_ahead_s` (the engine mirrors it
    into the ``host_sign_ahead_s`` gauge and ``stats["sign_ahead_s"]``
    — the committed overlap-efficiency reading), and each window emits
    one ``{"event": "sign_ahead", "v": 1}`` record when the sink is
    live.
    """

    def __init__(self, batch: int, seed: int = 0, n_values: int = 2):
        if batch < 1:
            raise ValueError(f"batch={batch} must be >= 1")
        if n_values < 1:
            raise ValueError(f"n_values={n_values} must be >= 1")
        self.batch = batch
        self.seed = seed
        self.n_values = n_values
        with obs.span("sign_ahead_keys", batch=batch):
            self.sks, self.pks = commander_keys(batch, seed)
        self.sign_ahead_s = 0.0
        self.windows = 0
        self.rounds_signed = 0

    def round_tables(self, round_index: int):
        """One round's (msgs, sigs) tables — host numpy, the unit the
        window staging loops over; also the piece a last-round
        majority recompute (``runtime/backends``) needs alone."""
        return sign_round_tables(
            self.sks, self.pks, round_index, self.n_values
        )

    def stage(self, lo: int, hi: int):
        """Sign + dispatch-verify rounds ``[lo, hi)`` -> device bool
        ``[hi-lo, B, V]`` verdict planes.  Never fetches."""
        if not 0 <= lo < hi:
            raise ValueError(f"bad sign-ahead window [{lo}, {hi})")
        t0 = time.perf_counter()
        nr = hi - lo
        parts = [self.round_tables(r) for r in range(lo, hi)]
        msgs = np.concatenate([m for m, _ in parts])  # [nr*B, V, LEN]
        sigs = np.concatenate([s for _, s in parts])
        pks_w = np.tile(self.pks, (nr, 1))
        # The EXACT per-signature verifier, deliberately sidestepping
        # the BA_TPU_VERIFY_RLC knob: the RLC wrapper's accept/fallback
        # decision is a BLOCKING fetch (it would serialize this lane
        # against the in-flight dispatches it exists to overlap), and
        # its cofactored verdict is batch-dependent — per-round table
        # verdicts feed the sig_rejections counter, so they must be
        # per-signature semantics whatever window they were batched in.
        # The exact path dispatches the chunked device program (or the
        # native batch verifier on CPU backends) and returns WITHOUT
        # fetching; the reshape is a lazy device view.
        ok = _verify_received_exact(pks_w, msgs, sigs).reshape(
            nr, self.batch, self.n_values
        )
        wall = time.perf_counter() - t0
        self.sign_ahead_s += wall
        self.windows += 1
        self.rounds_signed += nr
        reg = obs.default_registry()
        reg.counter("pipeline_sign_ahead_windows_total").inc()
        reg.counter("pipeline_sign_ahead_rounds_total").inc(nr)
        if _metrics.default_sink().enabled:
            _metrics.emit(
                {
                    "event": "sign_ahead",
                    "v": _metrics.SCHEMA_VERSION,
                    "lo": lo,
                    "hi": hi,
                    "batch": self.batch,
                    "values": self.n_values,
                    "wall_s": round(wall, 6),
                    "table_bytes": int(msgs.nbytes + sigs.nbytes),
                }
            )
        return ok


@functools.partial(jax.jit, static_argnums=2)
def _keys_at(key, round_index, batch: int):
    """Round ``round_index``'s per-instance keys under the engine's
    schedule: ``fold_in(fold_in(base, r), i)`` — the exact
    ``pipeline.round_keys`` derivation, jitted once for the sequential
    driver's per-round loop."""
    kr = jr.fold_in(key, round_index)
    idx = jnp.arange(batch, dtype=jnp.uint32)
    return jax.vmap(jr.fold_in, in_axes=(None, 0))(kr, idx)


def _host_signed_counter_delta(
    decision, majorities, received, ok, alive, faulty, leader
):
    """One round's SIGNED_COUNTER_NAMES increments derived ON HOST in
    numpy from the fetched streams — deliberately independent of the
    in-scan ``signed_counter_delta`` formulas, so the bit-match test
    cross-checks them (the PR 4 host-derivation discipline)."""
    B, n = majorities.shape
    idx = np.arange(n)[None, :]
    lieutenants = alive & (idx != leader[:, None])
    quorum_failures = int((decision == UNDEFINED).sum())
    counts = [
        int((decision == RETREAT).sum()),
        int((decision == ATTACK).sum()),
        int((decision == UNDEFINED).sum()),
    ]
    unanimous = int(max(counts) == B)
    big = np.int64(127)
    maj = majorities.astype(np.int64)
    mmax = np.where(lieutenants, maj, -big).max(axis=1)
    mmin = np.where(lieutenants, maj, big).min(axis=1)
    disagree = (mmax != mmin) & lieutenants.any(axis=1)
    traitor_present = (faulty & alive).any(axis=1)
    equivocation = int((disagree & traitor_present).sum())
    sig_rej = int((~ok).any(axis=1).sum())
    got_a = ((received == ATTACK) & lieutenants).any(axis=1)
    got_r = ((received == RETREAT) & lieutenants).any(axis=1)
    rows = np.arange(B)
    leader_faulty = faulty[rows, leader]
    leader_alive = alive[rows, leader]
    cmd_equiv = int((got_a & got_r & leader_faulty & leader_alive).sum())
    return np.array(
        [quorum_failures, unanimous, equivocation, sig_rej, cmd_equiv],
        np.int64,
    )


def sequential_signed_sweep(
    key,
    state,
    rounds: int,
    *,
    m: int = 1,
    collapsed: bool = False,
    sign_seed: int = 0,
    collect_decisions: bool = True,
    lane: SignAheadLane | None = None,
):
    """The BLOCKING per-round signed driver: the reference behavior the
    sign-ahead lane must reproduce bit-exactly, and the bench A/B's
    baseline leg.

    Per round, strictly in series (the ``backends._run_signed`` shape
    generalized to a sweep): host-sign the round's tables, verify and
    FETCH the verdicts, dispatch one jitted signed round, FETCH its
    outputs.  Keys derive from the same schedule the engine threads
    (``fold_in(fold_in(base, r), i)``), tables from the same lane
    grammar — so ``pipeline_sweep(signed=True)`` under the same
    ``key``/``sign_seed`` is bit-identical in decisions, histograms,
    counters and final-round majorities (the parity tests pin it).

    Returns a dict: ``histograms`` [R, 3], ``decisions`` [R, B] (when
    ``collect_decisions``), ``counters`` ({name: int} over
    SIGNED_COUNTER_NAMES, derived on HOST — see
    ``_host_signed_counter_delta``), ``majorities`` [B, n] (last
    round), and ``timings`` (cumulative ``sign_s`` / ``verify_s`` /
    ``step_s`` — the serial cost structure the bench reports).
    """
    from ba_tpu.parallel.pipeline import SIGNED_COUNTER_NAMES
    from ba_tpu.parallel.sweep import signed_agreement_step

    B, n = state.faulty.shape
    if lane is None:
        lane = SignAheadLane(B, seed=sign_seed)
    step = jax.jit(
        signed_agreement_step, static_argnames=("m", "collapsed")
    )
    alive = np.asarray(state.alive)
    faulty = np.asarray(state.faulty)
    leader = np.asarray(state.leader)
    hists = np.zeros((rounds, 3), np.int64)
    decisions = np.zeros((rounds, B), np.int64)
    counters = np.zeros(len(SIGNED_COUNTER_NAMES), np.int64)
    majorities = None
    sign_s = verify_s = step_s = 0.0
    for r in range(rounds):
        t0 = time.perf_counter()
        msgs, sigs = lane.round_tables(r)
        t1 = time.perf_counter()
        # The exact per-signature path, like the lane (same verdict
        # semantics on both legs is part of the parity contract); the
        # np.asarray is the BLOCKING per-round fetch this driver is the
        # baseline for.
        ok = np.asarray(_verify_received_exact(lane.pks, msgs, sigs))
        t2 = time.perf_counter()
        keys = _keys_at(key, jnp.asarray(r, jnp.int32), B)
        out = step(
            keys, state, jnp.asarray(ok), m=m, collapsed=collapsed
        )
        # The blocking fetch the pipelined engine exists to remove:
        # every stream comes back to host before the next round may
        # even be signed.
        decision = np.asarray(out["decision"])
        maj = np.asarray(out["majorities"])
        received = np.asarray(out["received"])
        hists[r] = np.asarray(out["histogram"])
        t3 = time.perf_counter()
        decisions[r] = decision
        majorities = maj
        counters += _host_signed_counter_delta(
            decision, maj, received, ok, alive, faulty, leader
        )
        sign_s += t1 - t0
        verify_s += t2 - t1
        step_s += t3 - t2
    result = {
        "histograms": hists,
        "majorities": majorities,
        "counters": {
            name: int(v) for name, v in zip(SIGNED_COUNTER_NAMES, counters)
        },
        "timings": {
            "sign_s": round(sign_s, 6),
            "verify_s": round(verify_s, 6),
            "step_s": round(step_s, 6),
        },
    }
    if collect_decisions:
        result["decisions"] = decisions
    return result
