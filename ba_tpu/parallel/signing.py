"""Sign-ahead host lane: per-round signature tables prepared while the
pipelined signed megastep is in flight (ISSUE 14 tentpole).

The signed SM(m) protocol's host obligation is signing, and signing is
what kept it off every fast path: ``runtime/backends._run_signed`` had
to host-sign BETWEEN the round-1 broadcast and the relay rounds, so
every round paid sign + verify + dispatch + fetch strictly in series.
The dissolving observation: a round's signatures cover the commander's
(at most V) DISTINCT round-bound claims — "commander of instance b says
v in round r" (``crypto.signed.round_message``) — not the realized
broadcast, so round r's whole table is known before round r runs.  The
lane exploits exactly the machinery ``crypto/signed.py`` proved in its
chunked setup overlap (``setup_signed_tables_overlapped``): sign a
window of rounds on host, dispatch the chunked device verification
without fetching, and hand the per-round ``[B, V]`` verdict planes to
the scan as consumed ``xs``.  ``pipeline_sweep(signed=True)`` stages
window d+1 through :meth:`SignAheadLane.stage` in the SAME host_work
overlap slot that stages scenario planes, while dispatches d-depth..d
occupy the device — host signing leaves the critical path entirely.

Nothing here ever fetches: signing is host numpy work, verification an
async device dispatch (or, on the CPU backend, the native C++ batch
verifier — host work in the host lane, overlapping the XLA compute
threads).  The no-blocking dispatch-count proof runs with the lane
live (tests/test_signed_pipeline.py).

:func:`sequential_signed_sweep` is the blocking per-round reference
driver — the ``_run_signed`` shape generalized to a sweep — whose
outputs the pipelined lane must reproduce BIT-EXACTLY under the same
key schedule and round tables (decisions, histograms, counters, final
majorities).  Its counter derivation is independent host numpy, so the
parity test cross-checks the in-scan verdict formulas too.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu import obs
from ba_tpu.crypto import pool as _pool_mod
from ba_tpu.crypto.signed import (
    _round_table_msgs,
    _verify_received_exact,
    commander_keys,
    host_verify_route,
    key_table_arrays,
    sign_round_tables,
    sign_table_msgs_arrays,
    verify_host_exact,
)
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.utils import metrics as _metrics


class SignAheadLane:
    """The host lane: one commander key-set, per-round table staging.

    Keygen happens ONCE at construction (the per-key-set cost the
    signed setup always paid); :meth:`stage` then prepares any window
    of rounds — host-sign each round's per-(instance, value) table
    (``sign_round_tables``: messages bind instance, ROUND and value),
    dispatch one chunked verification over the whole window, and
    return the ``[hi-lo, B, V]`` verdict planes as a device array the
    signed megastep consumes as scan ``xs``.

    ``stage`` is re-entrant per window and never fetches; cumulative
    wall time lands in :attr:`sign_ahead_s` (the engine mirrors it
    into the ``host_sign_ahead_s`` gauge and ``stats["sign_ahead_s"]``
    — the committed overlap-efficiency reading), and each window emits
    one ``{"event": "sign_ahead", "v": 1}`` record when the sink is
    live.
    """

    def __init__(
        self,
        batch: int,
        seed: int = 0,
        n_values: int = 2,
        pool: _pool_mod.SignPool | None = None,
        cache: _pool_mod.SigTableCache | None = None,
    ):
        if batch < 1:
            raise ValueError(f"batch={batch} must be >= 1")
        if n_values < 1:
            raise ValueError(f"n_values={n_values} must be >= 1")
        self.batch = batch
        self.seed = seed
        self.n_values = n_values
        with obs.span("sign_ahead_keys", batch=batch):
            self.sks, self.pks = commander_keys(batch, seed)
        # ISSUE 16 small fix: the per-signature-row key arrays are
        # INVARIANT for the lane's key-set — hoisted here once instead
        # of re-stacked from the sk byte strings inside every window's
        # signing call (pinned no-behavior-change by
        # tests/test_sign_pool.py).
        self._sk_rep, self._pk_rep = key_table_arrays(
            self.sks, self.pks, n_values
        )
        # ``pool``/``cache``: an explicit object wins; None takes the
        # process default (``BA_TPU_SIGN_POOL`` / ``BA_TPU_SIGN_CACHE``
        # — the serving front-end owns the default pool's lifecycle);
        # 0/False forces the in-process, uncached path.  (isinstance,
        # not truthiness: an EMPTY SigTableCache is len()-falsy.)
        if pool is None:
            self.pool = _pool_mod.default_pool()
        else:
            self.pool = pool if isinstance(pool, _pool_mod.SignPool) else None
        if cache is None:
            self.cache = _pool_mod.default_cache()
        else:
            self.cache = (
                cache if isinstance(cache, _pool_mod.SigTableCache) else None
            )
        self.sign_ahead_s = 0.0
        self.windows = 0
        self.rounds_signed = 0
        # ISSUE 16 accounting: per-lane splits the engine's stats and
        # the sign_pool record family read.
        self.sign_s = 0.0
        self.verify_s = 0.0
        self.pool_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self.sigs_signed = 0
        self.sigs_verified = 0
        self._run_id = obs.flight.derive_run_id(
            "sign-pool", seed, batch, n_values
        )

    @property
    def pool_workers(self) -> int:
        return self.pool.workers if self.pool is not None else 0

    def round_tables(self, round_index: int):
        """One round's (msgs, sigs) tables — host numpy, the unit the
        window staging loops over; also the piece a last-round
        majority recompute (``runtime/backends``) needs alone."""
        return sign_round_tables(
            self.sks, self.pks, round_index, self.n_values
        )

    def stage(self, lo: int, hi: int):
        """Sign + dispatch-verify rounds ``[lo, hi)`` -> device bool
        ``[hi-lo, B, V]`` verdict planes.  Never fetches."""
        return self.stage_windows([(lo, hi)])[0]

    def _sign_inprocess(self, rounds: list[int]) -> np.ndarray:
        """In-process signing body over the hoisted key arrays: ONE
        native batch call for the whole coalesced group -> sigs
        [len(rounds), B, V, 64].  Also the pool's degradation fallback
        — per-row Ed25519 determinism makes every route byte-equal."""
        k = len(rounds)
        msgs = np.concatenate(
            [
                _round_table_msgs(self.batch, r, self.n_values, 0)
                for r in rounds
            ]
        )
        return sign_table_msgs_arrays(
            np.tile(self._sk_rep, (k, 1)),
            np.tile(self._pk_rep, (k, 1)),
            msgs,
        ).reshape(k, self.batch, self.n_values, 64)

    def stage_windows(self, windows):
        """Sign + verify a GROUP of round windows ``[(lo, hi), ...]`` in
        one coalesced pass -> one device bool ``[hi-lo, B, V]`` verdict
        plane per window.  Never fetches.

        The ISSUE 16 tentpole lives here, behind the PR 14 window
        grammar:

        - **cache** — each round's table is probed in the bytes-keyed
          LRU first; a hit skips sign AND (host-route) verify,
          bit-exactly by Ed25519 determinism.
        - **pool** — cache-miss rounds shard across the worker
          processes (contiguous round ranges, reassembled by index);
          verify rows shard the same way.  A dead worker degrades that
          shard in-process, counted, never wedging.
        - **amortization** — misses across ALL the group's windows
          sign in one batch call and verify in ONE coalesced
          ``verify_host_exact`` / ``_verify_received_exact`` call (the
          native C++ verifier sees the coalesced size), instead of one
          call per window.

        Verdicts use the EXACT per-signature verifier, deliberately
        sidestepping the BA_TPU_VERIFY_RLC knob: the RLC wrapper's
        accept/fallback decision is a BLOCKING fetch (it would
        serialize this lane against the in-flight dispatches it exists
        to overlap), and its cofactored verdict is batch-dependent —
        per-round table verdicts feed the sig_rejections counter, so
        they must be per-signature semantics whatever group they were
        batched in.  On the host route (pool live, or the CPU backend's
        native verifier) verdicts are host numpy wrapped into device
        arrays without a sync; on device platforms the chunked verify
        program dispatches WITHOUT fetching and verdict planes stay
        lazy device views (the cache then holds signatures only).
        """
        if not windows:
            raise ValueError("stage_windows needs at least one window")
        for lo, hi in windows:
            if not 0 <= lo < hi:
                raise ValueError(f"bad sign-ahead window [{lo}, {hi})")
        t0 = time.perf_counter()
        # Staging span (ISSUE 19): one causal position for the whole
        # coalesced pass, a child of the ambient context (the engine's
        # campaign/batch scope).  Its traceparent rides the pool task
        # tuples so worker pool_task spans parent under it; the
        # sign_ahead / sign_pool records below carry it explicitly.
        stage_ctx = (
            obs.trace.child_context()
            if obs.trace.current() is not None
            else None
        )
        stage_tp = (
            None
            if stage_ctx is None
            else _metrics.format_traceparent(stage_ctx[0], stage_ctx[1])
        )
        B, V = self.batch, self.n_values
        rounds = [r for lo, hi in windows for r in range(lo, hi)]
        msgs_by_r = {
            r: _round_table_msgs(B, r, V, 0) for r in rounds
        }
        # Host-verdict route: the pool verifies on host by contract;
        # otherwise mirror _verify_received_exact's own routing (native
        # on CPU backends) so the host-kept verdicts are the SAME bytes
        # that path would wrap.
        pool_live = self.pool is not None and self.pool.workers > 0
        host_route = pool_live or host_verify_route()
        sigs_by_r: dict = {}
        ok_by_r: dict = {}
        keys_by_r: dict = {}
        hits = misses = 0
        if self.cache is not None:
            for r in rounds:
                key_r = _pool_mod.SigTableCache.round_key(
                    self.pks, msgs_by_r[r]
                )
                keys_by_r[r] = key_r
                entry = self.cache.get(key_r)
                if entry is None:
                    misses += 1
                else:
                    sigs_by_r[r], ok_by_r[r] = entry
                    hits += 1
        miss_rounds = [r for r in rounds if r not in sigs_by_r]
        # -- sign (cache misses only) ---------------------------------
        t_sign = time.perf_counter()
        pool_s0 = 0.0
        if miss_rounds:
            if pool_live:
                p0 = time.perf_counter()
                signed_block = self.pool.sign_rounds(
                    self.seed, B, V, 0, miss_rounds, self._sign_inprocess,
                    traceparent=stage_tp,
                )
                pool_s0 += time.perf_counter() - p0
            else:
                signed_block = self._sign_inprocess(miss_rounds)
            for i, r in enumerate(miss_rounds):
                sigs_by_r[r] = signed_block[i]
        sign_wall = time.perf_counter() - t_sign
        # -- verify (coalesced across the whole group) ------------------
        t_verify = time.perf_counter()
        need = [r for r in rounds if ok_by_r.get(r) is None]
        n_verified = len(need) * B * V
        if host_route:
            if need:
                msgs_cat = np.concatenate([msgs_by_r[r] for r in need])
                sigs_cat = np.concatenate([sigs_by_r[r] for r in need])
                pks_w = np.tile(self.pks, (len(need), 1))
                if pool_live:
                    p0 = time.perf_counter()
                    ok_cat = self.pool.verify_rows(
                        pks_w, msgs_cat, sigs_cat, traceparent=stage_tp
                    )
                    pool_s0 += time.perf_counter() - p0
                else:
                    # ONE native C++ batch call at the coalesced size.
                    ok_cat = verify_host_exact(pks_w, msgs_cat, sigs_cat)
                ok_cat = np.asarray(ok_cat, np.bool_).reshape(
                    len(need), B, V
                )
                for i, r in enumerate(need):
                    ok_by_r[r] = ok_cat[i]
            if self.cache is not None:
                for r in miss_rounds:
                    self.cache.put(keys_by_r[r], sigs_by_r[r], ok_by_r[r])
            planes = [
                jnp.asarray(
                    np.stack([ok_by_r[r] for r in range(lo, hi)])
                )
                for lo, hi in windows
            ]
        else:
            # Device-verify platform: signatures cache (ok=None rider),
            # verdicts stay a lazy device view of ONE coalesced chunked
            # dispatch — no fetch, no host verdict copy.
            if self.cache is not None:
                for r in miss_rounds:
                    self.cache.put(keys_by_r[r], sigs_by_r[r], None)
            msgs_cat = np.concatenate([msgs_by_r[r] for r in rounds])
            sigs_cat = np.concatenate([sigs_by_r[r] for r in rounds])
            pks_w = np.tile(self.pks, (len(rounds), 1))
            n_verified = len(rounds) * B * V
            ok_all = _verify_received_exact(
                pks_w, msgs_cat, sigs_cat
            ).reshape(len(rounds), B, V)
            planes, cursor = [], 0
            for lo, hi in windows:
                planes.append(ok_all[cursor : cursor + (hi - lo)])
                cursor += hi - lo
        verify_wall = time.perf_counter() - t_verify
        wall = time.perf_counter() - t0

        # -- accounting + records --------------------------------------
        n_rounds = len(rounds)
        self.sign_ahead_s += wall
        self.rounds_signed += n_rounds
        self.sign_s += sign_wall
        self.verify_s += verify_wall
        self.pool_s += pool_s0
        self.cache_hits += hits
        self.cache_misses += misses
        self.sigs_signed += len(miss_rounds) * B * V
        self.sigs_verified += n_verified
        reg = obs.default_registry()
        reg.counter("pipeline_sign_ahead_rounds_total").inc(n_rounds)
        if self.sign_s > 0 and self.sigs_signed:
            reg.gauge("host_sign_throughput_sigs_per_s").set(
                round(self.sigs_signed / self.sign_s, 1)
            )
        if self.verify_s > 0 and self.sigs_verified:
            reg.gauge("host_verify_throughput_sigs_per_s").set(
                round(self.sigs_verified / self.verify_s, 1)
            )
        if self.cache is not None:
            reg.counter("sign_cache_hits_total").inc(hits)
            reg.counter("sign_cache_misses_total").inc(misses)
        sink_live = _metrics.default_sink().enabled
        # Explicit stamping (like _emit_flight_span's ctx): the staging
        # span is the node these records describe — the ambient scope on
        # this thread is its PARENT, so setdefault stamping would hang
        # the pool workers' spans one level too high.
        stamp = {}
        if stage_ctx is not None:
            stamp = {"trace_id": stage_ctx[0], "span_id": stage_ctx[1]}
            if stage_ctx[2] is not None:
                stamp["parent_id"] = stage_ctx[2]
        for lo, hi in windows:
            nr = hi - lo
            self.windows += 1
            reg.counter("pipeline_sign_ahead_windows_total").inc()
            if sink_live:
                _metrics.emit(
                    {
                        "event": "sign_ahead",
                        "v": _metrics.SCHEMA_VERSION,
                        "lo": lo,
                        "hi": hi,
                        "batch": B,
                        "values": V,
                        "t_perf": round(t0, 6),
                        **stamp,
                        # The group's wall, attributed by round share
                        # (the group is ONE coalesced pass; per-window
                        # walls no longer exist as measurements).
                        "wall_s": round(wall * nr / n_rounds, 6),
                        # msgs (MSG_LEN) + sigs (64) per table cell —
                        # same arithmetic the pre-coalescing stage()
                        # read off its window's concatenated arrays.
                        "table_bytes": int(nr * B * V * (16 + 64)),
                    }
                )
        if sink_live and self.pool is not None:
            _metrics.emit(
                {
                    "event": "sign_pool",
                    "v": _metrics.SCHEMA_VERSION,
                    "t_perf": round(t0, 6),
                    **stamp,
                    "run_id": _metrics.active_run_id() or self._run_id,
                    "workers": self.pool.workers,
                    "requested": self.pool.requested,
                    "degraded": self.pool.degraded,
                    "rounds": n_rounds,
                    "cache_hits": hits,
                    "cache_misses": misses,
                    "sign_s": round(sign_wall, 6),
                    "verify_s": round(verify_wall, 6),
                    "pool_s": round(pool_s0, 6),
                }
            )
        return planes


@functools.partial(jax.jit, static_argnums=2)
def _keys_at(key, round_index, batch: int):
    """Round ``round_index``'s per-instance keys under the engine's
    schedule: ``fold_in(fold_in(base, r), i)`` — the exact
    ``pipeline.round_keys`` derivation, jitted once for the sequential
    driver's per-round loop."""
    kr = jr.fold_in(key, round_index)
    idx = jnp.arange(batch, dtype=jnp.uint32)
    return jax.vmap(jr.fold_in, in_axes=(None, 0))(kr, idx)


def _host_signed_counter_delta(
    decision, majorities, received, ok, alive, faulty, leader
):
    """One round's SIGNED_COUNTER_NAMES increments derived ON HOST in
    numpy from the fetched streams — deliberately independent of the
    in-scan ``signed_counter_delta`` formulas, so the bit-match test
    cross-checks them (the PR 4 host-derivation discipline)."""
    B, n = majorities.shape
    idx = np.arange(n)[None, :]
    lieutenants = alive & (idx != leader[:, None])
    quorum_failures = int((decision == UNDEFINED).sum())
    counts = [
        int((decision == RETREAT).sum()),
        int((decision == ATTACK).sum()),
        int((decision == UNDEFINED).sum()),
    ]
    unanimous = int(max(counts) == B)
    big = np.int64(127)
    maj = majorities.astype(np.int64)
    mmax = np.where(lieutenants, maj, -big).max(axis=1)
    mmin = np.where(lieutenants, maj, big).min(axis=1)
    disagree = (mmax != mmin) & lieutenants.any(axis=1)
    traitor_present = (faulty & alive).any(axis=1)
    equivocation = int((disagree & traitor_present).sum())
    sig_rej = int((~ok).any(axis=1).sum())
    got_a = ((received == ATTACK) & lieutenants).any(axis=1)
    got_r = ((received == RETREAT) & lieutenants).any(axis=1)
    rows = np.arange(B)
    leader_faulty = faulty[rows, leader]
    leader_alive = alive[rows, leader]
    cmd_equiv = int((got_a & got_r & leader_faulty & leader_alive).sum())
    return np.array(
        [quorum_failures, unanimous, equivocation, sig_rej, cmd_equiv],
        np.int64,
    )


def sequential_signed_sweep(
    key,
    state,
    rounds: int,
    *,
    m: int = 1,
    collapsed: bool = False,
    sign_seed: int = 0,
    collect_decisions: bool = True,
    lane: SignAheadLane | None = None,
):
    """The BLOCKING per-round signed driver: the reference behavior the
    sign-ahead lane must reproduce bit-exactly, and the bench A/B's
    baseline leg.

    Per round, strictly in series (the ``backends._run_signed`` shape
    generalized to a sweep): host-sign the round's tables, verify and
    FETCH the verdicts, dispatch one jitted signed round, FETCH its
    outputs.  Keys derive from the same schedule the engine threads
    (``fold_in(fold_in(base, r), i)``), tables from the same lane
    grammar — so ``pipeline_sweep(signed=True)`` under the same
    ``key``/``sign_seed`` is bit-identical in decisions, histograms,
    counters and final-round majorities (the parity tests pin it).

    Returns a dict: ``histograms`` [R, 3], ``decisions`` [R, B] (when
    ``collect_decisions``), ``counters`` ({name: int} over
    SIGNED_COUNTER_NAMES, derived on HOST — see
    ``_host_signed_counter_delta``), ``majorities`` [B, n] (last
    round), and ``timings`` (cumulative ``sign_s`` / ``verify_s`` /
    ``step_s`` — the serial cost structure the bench reports).
    """
    from ba_tpu.parallel.pipeline import SIGNED_COUNTER_NAMES
    from ba_tpu.parallel.sweep import signed_agreement_step

    B, n = state.faulty.shape
    if lane is None:
        lane = SignAheadLane(B, seed=sign_seed)
    step = jax.jit(
        signed_agreement_step, static_argnames=("m", "collapsed")
    )
    alive = np.asarray(state.alive)
    faulty = np.asarray(state.faulty)
    leader = np.asarray(state.leader)
    hists = np.zeros((rounds, 3), np.int64)
    decisions = np.zeros((rounds, B), np.int64)
    counters = np.zeros(len(SIGNED_COUNTER_NAMES), np.int64)
    majorities = None
    sign_s = verify_s = step_s = 0.0
    for r in range(rounds):
        t0 = time.perf_counter()
        msgs, sigs = lane.round_tables(r)
        t1 = time.perf_counter()
        # The exact per-signature path, like the lane (same verdict
        # semantics on both legs is part of the parity contract); the
        # np.asarray is the BLOCKING per-round fetch this driver is the
        # baseline for.
        ok = np.asarray(_verify_received_exact(lane.pks, msgs, sigs))
        t2 = time.perf_counter()
        keys = _keys_at(key, jnp.asarray(r, jnp.int32), B)
        out = step(
            keys, state, jnp.asarray(ok), m=m, collapsed=collapsed
        )
        # The blocking fetch the pipelined engine exists to remove:
        # every stream comes back to host before the next round may
        # even be signed.
        decision = np.asarray(out["decision"])
        maj = np.asarray(out["majorities"])
        received = np.asarray(out["received"])
        hists[r] = np.asarray(out["histogram"])
        t3 = time.perf_counter()
        decisions[r] = decision
        majorities = maj
        counters += _host_signed_counter_delta(
            decision, maj, received, ok, alive, faulty, leader
        )
        sign_s += t1 - t0
        verify_s += t2 - t1
        step_s += t3 - t2
    result = {
        "histograms": hists,
        "majorities": majorities,
        "counters": {
            name: int(v) for name, v in zip(SIGNED_COUNTER_NAMES, counters)
        },
        "timings": {
            "sign_s": round(sign_s, 6),
            "verify_s": round(verify_s, 6),
            "step_s": round(step_s, 6),
        },
    }
    if collect_decisions:
        result["decisions"] = decisions
    return result
