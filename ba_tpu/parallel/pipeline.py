"""Pipelined multi-round sweep engine: donated buffers, on-device key
schedule, depth-k host/device overlap.

The blocking per-round driver this replaces (bench.py's inherited form of
the reference's disease, ba.py:287-301) pays three host costs every round:

1. a host-side per-round key split to derive the round's per-instance
   keys (the key upload rides every dispatch);
2. fresh allocations for every round's state/key buffers;
3. a blocking fetch (host-get or a block-until-ready sync) before the
   next round may even be *dispatched*, so host work and device compute
   strictly alternate.

This engine removes all three:

- **On-device key schedule** (:class:`KeySchedule`): the sweep carries one
  base key (raw uint32 data) plus an int32 round counter ON DEVICE.  Each
  round derives its per-instance keys inside the compiled program —
  ``fold_in(base, counter)`` then a vmapped ``fold_in`` over the instance
  index — so the host never touches PRNG state after launch.  The
  schedule is deterministic and host-reproducible: round ``r``,
  instance ``i`` draws from exactly ``fold_in(fold_in(base, r), i)``
  (threefry derivation is backend-independent), which is what the
  bit-exact equivalence tests pin.
- **Donated buffers**: the round megastep is jitted with
  ``donate_argnums`` on the :class:`SimState` and the key schedule, and
  returns both (state unchanged, counter advanced), so XLA aliases every
  steady-state buffer in place — rounds allocate only their small
  per-round outputs (decision row + 3-bin histogram).  DONATION CONTRACT:
  the state and schedule passed to a dispatch are CONSUMED — callers must
  thread the returned ones and never touch the donated inputs again
  (JAX deletes them; use-after-donate raises, and the tests prove it).
- **Depth-k in-flight dispatch**: the host loop keeps up to ``depth``
  megastep dispatches in flight with NO intermediate sync — JAX dispatch
  is async, and the only blocking operation is *retiring* the oldest
  in-flight dispatch's outputs once the window is full (a fetch of the
  tiny histogram block, which waits on round ``d - depth`` while rounds
  through ``d`` are already queued).  Host work — signing-table prep,
  metrics emission (``utils/metrics.py``) — runs in the ``host_work``
  callback between dispatches, overlapping device compute.
- **``lax.scan`` megastep** with configurable ``unroll``: each dispatch
  covers ``rounds_per_dispatch`` rounds in one compiled scan, the
  whole-sweep generalization of the fused-K idea from the Pallas kernel
  (ops/sweep_step.py) — per-dispatch overhead divides by K with compile
  cost O(unroll), not O(K).

Mesh composition: ``sharded_sweep``'s layout applies unchanged — pass a
mesh and the state shards on its "data" axis while the schedule
replicates; the compiled megastep is the same program, sharding is
propagated by the compiler.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import json
import os.path
import threading
import time

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu import obs
from ba_tpu.core.election import elect_lowest_id
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT, UNDEFINED
from ba_tpu.parallel.multihost import put_global
from ba_tpu.parallel.sweep import agreement_step, signed_agreement_step
from ba_tpu.utils import metrics as _metrics
from ba_tpu.utils import snapshot as _snapshot

# On-device agreement counters (ISSUE 4): one int32 per name, riding the
# donated scan carry as pure data — folded in-scan, drained only at the
# engine's existing depth-delayed retire fetch (counter rows piggyback
# the histogram block), so BA101 and the no-blocking test stay clean.
COUNTER_NAMES = ("quorum_failures", "unanimous_rounds", "equivocation_observed")

# -- engine selection (ISSUE 13) ----------------------------------------------
#
# Two multi-round engines run the same round semantics: the XLA scan
# cores in this module ("xla") and the fused Pallas megastep kernel
# (ops/scenario_step.py — "pallas" compiles through Mosaic on TPU,
# "interpret" runs the same kernel as jnp ops anywhere; both bit-exact
# vs the scan cores incl. the threefry coin streams, which
# tests/test_megastep.py pins).  `engine=` on the sweep entry points
# selects per call; None reads BA_TPU_ENGINE (default "xla").  "auto"
# prefers the Mosaic kernel where it is supported AND the platform is a
# real TPU, silently-but-countedly falling back to the scan core
# otherwise (stats["engine_fallback"] + the
# pipeline_engine_fallback_total counter); an EXPLICIT "pallas"/
# "interpret" on an unsupported combination raises eagerly, before any
# buffer is donated.  The resolved value joins the compile-signature
# axes, so an engine flip reads `"engine": ["xla", "pallas"]` in
# recompile records and the cross-run ledger, and lands in the
# `pipeline_engine` gauge as its ENGINE_IDS index.

ENGINE_ENV = "BA_TPU_ENGINE"
ENGINES = ("xla", "pallas", "interpret")
ENGINE_IDS = {name: i for i, name in enumerate(ENGINES)}
_ENGINE_REQUESTS = ENGINES + ("auto",)


def engine_support(m: int = 1, n_shards: int = 1,
                   signed: bool = False,
                   meshed: bool = False) -> str | None:
    """None when the Pallas megastep kernel covers this combination,
    else the human-readable reason it cannot (the fallback table:
    OM(1) only, no mesh, oral messages).  ``meshed`` covers the
    mesh-with-data=1 case: EVERY mesh dispatch runs the
    shard_map-wrapped XLA scan core, so a kernel request there would
    otherwise record an engine that never ran."""
    if signed:
        return ("signed=True (the signed lane runs the XLA signed "
                "megastep; the fused kernel covers oral OM(1) only)")
    if m != 1:
        return f"m={m} (the dense EIG tree stays on the XLA scan core)"
    if n_shards != 1 or meshed:
        return (f"mesh data={n_shards} (every mesh dispatch runs the "
                f"shard_map-wrapped XLA scan core; the kernel is "
                f"single-device)")
    return None


def resolve_engine(engine: str | None, *, m: int = 1, n_shards: int = 1,
                   signed: bool = False, meshed: bool = False):
    """``(resolved, fallback_reason)`` for one sweep's engine request.

    ``engine`` None reads ``BA_TPU_ENGINE`` (default ``"xla"``).
    A CALL-SITE ``"pallas"``/``"interpret"`` raises eagerly on
    unsupported combinations — the caller has not donated anything
    yet.  The same token sourced from the ENV is a process-wide
    preference, not a per-call demand: it falls back to ``"xla"`` with
    the reason returned (counted, like ``"auto"``), so exporting
    ``BA_TPU_ENGINE=pallas`` cannot break the mesh/EIG/signed paths it
    never covered.  ``"pallas"`` off-TPU resolves to ``"interpret"``
    (the house interpret= pattern: same kernel, jnp semantics), so the
    RECORDED engine axis always names what actually ran.
    """
    explicit = engine is not None
    requested = engine or os.environ.get(ENGINE_ENV) or "xla"
    if requested not in _ENGINE_REQUESTS:
        raise ValueError(
            f"engine={requested!r} unknown (choose from "
            f"{_ENGINE_REQUESTS}; None reads {ENGINE_ENV})"
        )
    if requested == "xla":
        return "xla", None
    reason = engine_support(m, n_shards, signed, meshed)
    if requested == "auto":
        if reason is not None:
            return "xla", reason
        platform = jax.devices()[0].platform
        if platform != "tpu":
            return "xla", (
                f"platform={platform} (the Mosaic kernel engine is "
                f"TPU-codegen; engine='interpret' forces the "
                f"interpreter)"
            )
        return "pallas", None
    if reason is not None:
        if explicit:
            raise ValueError(
                f"engine={requested!r} unsupported: {reason}"
            )
        return "xla", reason  # env preference: counted fallback
    if requested == "interpret":
        return "interpret", None
    if jax.devices()[0].platform == "tpu":
        return "pallas", None
    return "interpret", None


def _engine_megasteps(engine: str):
    """The (scenario_fn, plain_fn, coalesced_fn, extra_kwargs) tuple for
    a RESOLVED engine — the one seam the dispatch loops swap callables
    through.  Lazy kernel import: the XLA path must not pay for (or
    depend on) the Pallas toolchain."""
    if engine == "xla":
        return scenario_megastep, pipeline_megastep, coalesced_megastep, {}
    from ba_tpu.ops import scenario_step as _ss

    return (
        _ss.pallas_scenario_megastep,
        _ss.pallas_pipeline_megastep,
        _ss.pallas_coalesced_megastep,
        {"interpret": engine == "interpret"},
    )


def _record_engine(reg, engine: str, fallback: str | None) -> None:
    """One spelling of the engine bookkeeping: the `pipeline_engine`
    gauge holds the ENGINE_IDS index of what actually ran (gauges are
    numeric; the mapping is this module's ENGINES tuple, documented in
    DESIGN.md), and a counted auto-fallback increments
    `pipeline_engine_fallback_total`.  Set BEFORE the first dispatch so
    a mid-campaign health sample reads THIS sweep's engine."""
    reg.gauge("pipeline_engine").set(ENGINE_IDS[engine])
    if fallback is not None:
        reg.counter("pipeline_engine_fallback_total").inc()


# Scenario campaigns (ISSUE 5) extend the block with per-round IC1/IC2
# property verdicts — the Interactive Consistency conditions of the
# Byzantine Generals paper, checked on device every round and drained at
# the same retire points: IC1 = all honest alive lieutenants of an
# instance agree; IC2 = under an honest commander they agree on ITS
# order.  The first len(COUNTER_NAMES) entries are bit-identical to the
# PR 4 block (the counters stay protocol-agnostic: everything reads
# ``agreement_step`` outputs + the state, never the protocol's RNG).
SCENARIO_COUNTER_NAMES = COUNTER_NAMES + ("ic1_violations", "ic2_violations")

# Signed campaigns (ISSUE 14) extend the block with the SIGNED verdicts
# instead: ``sig_rejections`` counts instances whose round table carried
# at least one INVALID commander signature (the device verifier's
# reject, surfaced as a counter — honest tables keep it 0, which the
# property tests assert), and ``commander_equivocations`` counts
# instances whose faulty alive commander PROVABLY equivocated this
# round — both contradictory claims reached alive lieutenants, i.e.
# both honestly-signed messages exist, exactly the paper's
# faulty-commander power in SM(m).  The first len(COUNTER_NAMES)
# entries stay bit-identical to the PR 4 block.
SIGNED_COUNTER_NAMES = COUNTER_NAMES + (
    "sig_rejections", "commander_equivocations"
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class KeySchedule:
    """Device-resident PRNG schedule: base key data + rounds consumed.

    ``key_data`` is the raw uint32 form of one typed base key (raw so it
    donates/shards like any other buffer and crosses process meshes the
    way ``sharded_sweep`` already ships keys); ``counter`` is a scalar
    int32 advanced by the compiled step itself.  Round ``counter``'s
    instance-``i`` key is ``fold_in(fold_in(base, counter), i)`` —
    derived entirely on device, never uploaded.
    """

    key_data: jax.Array
    counter: jax.Array


def fresh_copy(tree):
    """A live copy of a pytree of arrays (SimState, KeySchedule, ...).

    The one sanctioned way to keep a usable handle on buffers about to
    enter the engine's donation thread: dispatches CONSUME their inputs,
    so a caller that needs the pre-run state afterwards copies it first.

    Also the sanctioned way to LAUNDER host-staged arrays into the
    donation thread: ``jnp.asarray(numpy)`` may ZERO-COPY on CPU, and
    donating a buffer that aliases live host memory makes the returned
    aliased carry nondeterministically wrong — copy first when the
    pytree was built from numpy (runtime/backends.run_scenario learned
    this the hard way).
    """
    return jax.tree.map(lambda x: x.copy(), tree)


def make_key_schedule(key: jax.Array, counter: int = 0) -> KeySchedule:
    """Stage a :class:`KeySchedule` for ``key`` starting at round ``counter``.

    The key data is COPIED: the schedule enters the donation thread (the
    engine's dispatches consume and re-emit it), and the caller's ``key``
    must survive that — only the state and the schedule itself are part of
    the donation contract.
    """
    return KeySchedule(
        key_data=jnp.array(jr.key_data(key), copy=True),
        counter=jnp.asarray(counter, jnp.int32),
    )


def round_keys(
    sched: KeySchedule, batch: int, index_base=None
) -> jax.Array:
    """The current round's per-instance typed keys, derived on device.

    Trace-time only (call under jit): one ``fold_in`` of the carried
    counter, then one vmapped ``fold_in`` over the instance index — the
    device-side replacement for the blocking driver's host-side per-round
    key split.  Same threefry derivation strength, and the instance-index
    fold keeps this module free of the banned host-split idiom ba-lint's
    BA102 rule (ba_tpu/analysis, run by scripts/ci.sh) checks for — this
    ``fold_in`` is sanctioned because it sits outside any host loop.

    ``index_base`` (ISSUE 8) offsets the instance index: a mesh shard
    holding instances ``[base, base + batch)`` of the global batch folds
    by its GLOBAL indices, so the sharded engine draws bit-identical
    per-instance streams to the single-device run — sharding is layout
    only, never a different key schedule.
    """
    base = jr.wrap_key_data(sched.key_data)
    kr = jr.fold_in(base, sched.counter)
    idx = jnp.arange(batch, dtype=jnp.uint32)
    if index_base is not None:
        idx = idx + jnp.asarray(index_base, jnp.uint32)
    return jax.vmap(jr.fold_in, in_axes=(None, 0))(kr, idx)


def agreement_counters_init() -> jax.Array:
    """A zeroed on-device counter block (one int32 per COUNTER_NAMES)."""
    return jnp.zeros((len(COUNTER_NAMES),), jnp.int32)


def agreement_counter_delta(
    out: dict, state: SimState, axis_name: str | None = None
) -> jax.Array:
    """One round's counter increments, derived ON DEVICE (trace-time,
    called inside the compiled scan body) from ``agreement_step``'s
    outputs — the paper's agreement semantics as values, not emissions:

    - ``quorum_failures``: instances whose quorum decision this round is
      UNDEFINED (no side reached the majority-of-majorities threshold);
    - ``unanimous_rounds``: 1 when every instance in the batch decided
      alike (the histogram concentrates in one bin);
    - ``equivocation_observed``: instances containing at least one live
      traitor whose alive lieutenants' majorities DISAGREE — the visible
      footprint of per-recipient equivocation (a faulty responder
      answering different queriers differently; honest-only instances
      always tally unanimously under an honest leader).

    Every count is host-reproducible from the decisions/majorities
    streams (tests/test_pipeline.py pins the bit-match).

    ``axis_name`` (ISSUE 8) is the mesh shard axis when the scan runs
    inside ``shard_map``: the per-instance counts stay shard-local (the
    per-shard blocks SUM to the single-device block — that is the
    retire-time tree-reduction contract), but unanimity is a GLOBAL
    property of the round, so the 3-bin histogram is psummed (the only
    cross-shard traffic in the whole scan, 3 ints per round) and the
    verdict — globally unanimous iff one bin holds the whole summed
    batch — is credited to shard 0 alone so the shard sum still equals
    the single-device count.
    """
    decision = out["decision"]
    maj = out["majorities"]
    quorum_failures = jnp.sum(decision == UNDEFINED, dtype=jnp.int32)
    if axis_name is None:
        unanimous = (
            out["histogram"].max() == decision.shape[0]
        ).astype(jnp.int32)
    else:
        hist = jax.lax.psum(out["histogram"], axis_name)
        # The bins partition the global batch (every instance decides
        # exactly one way), so max == sum is "one bin holds everyone".
        unanimous = (hist.max() == hist.sum()).astype(jnp.int32)
        unanimous = jnp.where(
            jax.lax.axis_index(axis_name) == 0, unanimous, 0
        )
    idx = jnp.arange(state.faulty.shape[1])[None, :]
    lieutenants = state.alive & (idx != state.leader[:, None])
    big = jnp.asarray(127, maj.dtype)
    mmax = jnp.max(jnp.where(lieutenants, maj, -big), axis=1)
    mmin = jnp.min(jnp.where(lieutenants, maj, big), axis=1)
    disagree = (mmax != mmin) & lieutenants.any(axis=1)
    traitor_present = (state.faulty & state.alive).any(axis=1)
    equivocation = jnp.sum(disagree & traitor_present, dtype=jnp.int32)
    return jnp.stack([quorum_failures, unanimous, equivocation])


def _pipeline_scan(
    state: SimState,
    sched: KeySchedule,
    counters: jax.Array | None,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    index_base=None,
    axis_name: str | None = None,
):
    """The plain (non-mutating) scan core (trace-time; shared verbatim by
    the donated :func:`pipeline_megastep` and the mesh-sharded
    ``parallel.shard.sharded_pipeline_megastep``, so the single- and
    multi-chip engines run exactly ONE implementation of the round).

    ``index_base``/``axis_name`` are the sharding seam (ISSUE 8): a
    shard folds per-instance keys by its GLOBAL instance indices and
    the counter delta psums the 3-bin histogram for the global
    unanimity verdict (see :func:`agreement_counter_delta`).  With the
    defaults the trace is bit-identical to the pre-mesh engine.

    Returns ``(carry, ys)`` with carry ``(state, sched[, counters])``
    and ys ``(histograms[, decisions][, counter_rows])``.
    """
    with_counters = counters is not None

    def body(carry, _):
        if with_counters:
            st, sc, ctr = carry
        else:
            st, sc = carry
        keys = round_keys(sc, st.batch, index_base)
        out = agreement_step(keys, st, m=m, max_liars=max_liars)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        ys = (out["histogram"],)
        if collect_decisions:
            ys += (out["decision"],)
        if with_counters:
            ctr = ctr + agreement_counter_delta(out, st, axis_name)
            return (st, nxt, ctr), ys + (ctr,)
        return (st, nxt), ys

    init = (state, sched, counters) if with_counters else (state, sched)
    return jax.lax.scan(body, init, None, length=rounds, unroll=unroll)


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "max_liars", "unroll", "collect_decisions"),
    donate_argnums=(0, 1),
)
def pipeline_megastep(
    state: SimState,
    sched: KeySchedule,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    counters: jax.Array | None = None,
):
    """``rounds`` agreement rounds in one donated ``lax.scan`` dispatch.

    Returns ``(state, sched, histograms[, decisions][, counter_rounds])``:
    the state rides through unchanged and the schedule advances by
    ``rounds``, both aliased onto the donated inputs so steady-state
    dispatches allocate nothing but the outputs (``histograms``
    [rounds, 3] int32 and, when ``collect_decisions``, ``decisions``
    [rounds, B] int8).

    ``counters`` (a block from :func:`agreement_counters_init`, or the
    previous dispatch's last ``counter_rounds`` row) enables the
    on-device agreement counters: the block rides the scan carry,
    :func:`agreement_counter_delta` folds each round's increments in,
    and ``counter_rounds`` [rounds, len(COUNTER_NAMES)] holds the
    CUMULATIVE block after every round — its last row both continues the
    counter thread into the next dispatch and reaches the host for free
    inside the existing retire fetch.  Counters are pure data in the
    compiled program: no host emission, no added synchronization.

    Bit-compat contract: round ``sched.counter + r`` computes exactly
    ``agreement_step(round_keys(<schedule at counter + r>, B), state)`` —
    the round-by-round blocking driver under the same key schedule
    produces identical decisions and histograms (tests/test_pipeline.py),
    with or without the counter block (counters read the step's outputs,
    never its RNG).
    """
    carry, ys = _pipeline_scan(
        state,
        sched,
        counters,
        rounds=rounds,
        m=m,
        max_liars=max_liars,
        unroll=unroll,
        collect_decisions=collect_decisions,
    )
    return (carry[0], carry[1], *ys)


def scenario_counters_init() -> jax.Array:
    """A zeroed scenario counter block (one int32 per
    SCENARIO_COUNTER_NAMES: the PR 4 agreement counters + the IC1/IC2
    verdict tallies)."""
    return jnp.zeros((len(SCENARIO_COUNTER_NAMES),), jnp.int32)


def scenario_counter_delta(
    out: dict, state: SimState, axis_name: str | None = None
) -> jax.Array:
    """One round's scenario counter increments (trace-time, in-scan).

    The PR 4 agreement deltas (:func:`agreement_counter_delta`, first
    three entries — bit-identical to the non-scenario path) followed by
    the per-round IC1/IC2 property verdicts:

    - ``ic1_violations``: instances whose honest ALIVE lieutenants'
      majorities disagree — Interactive Consistency condition 1 broken
      this round (with t too large or a coordinated adversary this is
      reachable; under the classical n > 3t bound it must stay 0, which
      the property tests assert);
    - ``ic2_violations``: instances whose commander is honest yet some
      honest alive lieutenant's majority differs from the commander's
      order — IC2 broken.

    Protocol-agnostic like the base block: reads ``agreement_step``
    outputs and the (post-mutation) state only, never the round's RNG —
    and host-reproducible from the majorities stream, which the
    kill-mid-campaign bit-match test pins.

    ``axis_name`` (ISSUE 8) threads the mesh shard axis into the base
    delta exactly as :func:`agreement_counter_delta` documents; the
    IC1/IC2 verdicts are per-instance sums and stay shard-local.
    """
    base = agreement_counter_delta(out, state, axis_name)
    maj = out["majorities"]
    idx = jnp.arange(state.faulty.shape[1])[None, :]
    honest_lt = (
        state.alive & ~state.faulty & (idx != state.leader[:, None])
    )
    big = jnp.asarray(127, maj.dtype)
    mmax = jnp.max(jnp.where(honest_lt, maj, -big), axis=1)
    mmin = jnp.min(jnp.where(honest_lt, maj, big), axis=1)
    ic1 = jnp.sum(
        (mmax != mmin) & honest_lt.any(axis=1), dtype=jnp.int32
    )
    leader_faulty = jnp.take_along_axis(
        state.faulty, state.leader[:, None], axis=1
    )[:, 0]
    disobey = (honest_lt & (maj != state.order[:, None])).any(axis=1)
    ic2 = jnp.sum(~leader_faulty & disobey, dtype=jnp.int32)
    return jnp.concatenate([base, jnp.stack([ic1, ic2])])


def _scenario_scan(
    state: SimState,
    sched: KeySchedule,
    strategy: jax.Array,
    counters: jax.Array,
    events: dict,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    index_base=None,
    axis_name: str | None = None,
):
    """The mutating-round scan core (trace-time; shared verbatim by the
    donated :func:`scenario_megastep`, the jittable
    ``parallel.sweep.failover_sweep`` wrapper, and the mesh-sharded
    ``parallel.shard.sharded_scenario_megastep``, so there is exactly
    ONE implementation of the kill → re-elect → agree transition — the
    sharded engine inherits it through ``index_base``/``axis_name``
    (global-instance key folding + the psummed unanimity verdict,
    see :func:`_pipeline_scan`).

    ``events`` is a dict of ``[rounds, B, n]`` planes (a
    ``ScenarioBlock.chunk``): ``kill``/``revive`` bool alive-mask
    deltas, ``set_faulty``/``set_strategy`` int8 tri-states (-1 keep).
    Per round, in REPL order (commands land between rounds,
    ba.py:354-445):

    1. membership + fault-flag + strategy mutations apply;
    2. instances whose leader died re-elect by lowest alive id
       (ba.py:126-157); a living leader is never displaced — "election
       is for life" (ba.py:124-125), so a revived lower id waits;
    3. the strategy-aware agreement round runs
       (``agreement_step(strategies=...)``) and the scenario counter
       block folds the round's deltas (incl. IC1/IC2 verdicts).

    Returns ``(carry, ys)`` with carry ``(state, sched, strategy,
    counters)`` and ys ``(histograms, leaders, counter_rows[,
    decisions])`` — leaders are post-election, counter rows cumulative.
    """

    def body(carry, ev):
        st, sc, strat, ctr = carry
        kill, revive, fset, sset = ev
        alive = (st.alive & ~kill) | revive
        faulty = jnp.where(fset >= 0, fset > 0, st.faulty)
        strat = jnp.where(sset >= 0, sset, strat)
        leader_alive = jnp.take_along_axis(
            alive, st.leader[:, None], axis=1
        )[:, 0]
        leader = jnp.where(
            leader_alive, st.leader, elect_lowest_id(st.ids, alive)
        )
        st = SimState(st.order, leader, faulty, alive, st.ids)
        keys = round_keys(sc, st.batch, index_base)
        out = agreement_step(
            keys, st, m=m, max_liars=max_liars, strategies=strat
        )
        ctr = ctr + scenario_counter_delta(out, st, axis_name)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        ys = (out["histogram"], leader, ctr)
        if collect_decisions:
            ys += (out["decision"],)
        return (st, nxt, strat, ctr), ys

    xs = (
        events["kill"],
        events["revive"],
        events["set_faulty"],
        events["set_strategy"],
    )
    return jax.lax.scan(
        body, (state, sched, strategy, counters), xs,
        length=rounds, unroll=unroll,
    )


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "max_liars", "unroll", "collect_decisions"),
    donate_argnums=(0, 1, 2),
)
def scenario_megastep(
    state: SimState,
    sched: KeySchedule,
    strategy: jax.Array,
    counters: jax.Array,
    events: dict,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
):
    """``rounds`` MUTATING agreement rounds in one donated dispatch: the
    scenario engine's megastep (ISSUE 5 tentpole).

    The mutating scenario state — the :class:`SimState` (alive/faulty/
    leader now change in-scan), the key schedule, and the live
    per-general strategy plane — rides the donated carry next to PR 4's
    counter slots, so every steady-state buffer aliases in place and a
    campaign dispatch allocates only its small outputs.  The per-round
    event planes enter as the scan's consumed ``xs``; like the counter
    block they are NOT donate-annotated — none of the outputs matches
    their shapes, so XLA could alias nothing (the counter thread
    continues through the stacked rows, the PR 4 pattern).

    DONATION CONTRACT: ``state``, ``sched`` and ``strategy`` are
    CONSUMED — thread the returned ``(state, sched, strategy, ...)``
    and never touch the donated inputs again
    (``pipeline_sweep(scenario=...)`` is the driver that does this for
    you).

    Returns ``(state, sched, strategy, histograms, leaders,
    counter_rounds[, decisions])``: histograms ``[rounds, 3]``, leaders
    ``[rounds, B]`` (post-election, the ``failover_sweep`` output
    generalized), counter_rounds ``[rounds, len(SCENARIO_COUNTER_NAMES)]``
    cumulative rows whose last row continues the counter thread — all
    reaching the host inside the engine's existing depth-delayed retire
    fetch, zero added synchronization.

    Bit-compat contract: with the all-RANDOM strategy plane and no-op
    event planes, round ``sched.counter + r`` is bit-identical to
    :func:`pipeline_megastep`'s round (the empty-scenario parity test);
    with kill planes only it is bit-identical to ``failover_sweep``
    (same scan core, same schedule).
    """
    carry, ys = _scenario_scan(
        state,
        sched,
        strategy,
        counters,
        events,
        rounds=rounds,
        m=m,
        max_liars=max_liars,
        unroll=unroll,
        collect_decisions=collect_decisions,
    )
    return (carry[0], carry[1], carry[2], *ys)


# -- the signed megastep (ISSUE 14) ------------------------------------------
#
# The signed SM(m) protocol was the last reference behavior excluded
# from every fast path: host Ed25519 signing sat BETWEEN the round-1
# broadcast and the relay rounds, so ``runtime/backends._run_signed``
# ran one blocking host-sign + device-verify + dispatch + fetch cycle
# per round.  The sign-ahead lane (``parallel/signing.py``) dissolves
# that order: each round's signatures cover the commander's (at most V)
# DISTINCT round-bound claims — not the realized broadcast — so the
# tables for rounds d+1..d+depth can be signed on host and their
# verification dispatched while dispatches d-depth..d are still in
# flight, and the per-round [B, V] verdicts enter the scan as consumed
# ``xs`` exactly like scenario event planes.  In-scan, the broadcast's
# values gather their verdicts by a select (``signed_agreement_step``),
# which is the dedup-verify identity ``sig_valid_from_tables`` pins.


def signed_counters_init() -> jax.Array:
    """A zeroed signed counter block (one int32 per
    SIGNED_COUNTER_NAMES: the PR 4 agreement counters + the signature /
    equivocation verdicts)."""
    return jnp.zeros((len(SIGNED_COUNTER_NAMES),), jnp.int32)


def signed_counter_delta(
    out: dict, state: SimState, ok: jax.Array
) -> jax.Array:
    """One signed round's counter increments (trace-time, in-scan).

    The PR 4 agreement deltas (first three entries, bit-identical)
    followed by the signed verdicts:

    - ``sig_rejections``: instances whose round table held at least one
      invalid commander signature (``ok`` [B, V] is the device
      verifier's per-claim verdict row);
    - ``commander_equivocations``: instances whose commander is faulty
      and alive AND whose alive lieutenants received BOTH orders this
      round — two honestly-signed contradictory claims in flight, the
      provable equivocation SM(m)'s V-set rule exists to catch.

    Host-reproducible from the fetched ``received`` stream, which the
    sequential-driver bit-match test derives independently in numpy.
    """
    base = agreement_counter_delta(out, state)
    received = out["received"]
    sig_rej = jnp.sum(jnp.any(~ok, axis=-1), dtype=jnp.int32)
    idx = jnp.arange(state.faulty.shape[1])[None, :]
    lieutenants = state.alive & (idx != state.leader[:, None])
    got_a = ((received == ATTACK) & lieutenants).any(axis=1)
    got_r = ((received == RETREAT) & lieutenants).any(axis=1)
    leader_faulty = jnp.take_along_axis(
        state.faulty, state.leader[:, None], axis=1
    )[:, 0]
    leader_alive = jnp.take_along_axis(
        state.alive, state.leader[:, None], axis=1
    )[:, 0]
    equiv = jnp.sum(
        got_a & got_r & leader_faulty & leader_alive, dtype=jnp.int32
    )
    return jnp.concatenate([base, jnp.stack([sig_rej, equiv])])


def _signed_scan(
    state: SimState,
    sched: KeySchedule,
    counters: jax.Array,
    ok_planes: jax.Array,
    *,
    rounds: int,
    m: int = 1,
    collapsed: bool = False,
    unroll: int = 1,
    collect_decisions: bool = False,
):
    """The signed scan core (trace-time; shared by the donated
    :func:`signed_megastep` and the sequential reference driver's
    single-round calls through ``signed_agreement_step``).

    ``ok_planes`` [rounds, B, V] bool — the sign-ahead lane's per-round
    table verdicts — are the scan's consumed ``xs``.  Returns
    ``(carry, ys)`` with carry ``(state, sched, counters)`` and ys
    ``(histograms[, decisions], counter_rows)`` — the exact layout of
    the plain counter-threaded scan, so the engine's retire/assembly
    path serves both protocols verbatim.
    """
    def body(carry, ok):
        st, sc, ctr = carry
        keys = round_keys(sc, st.batch)
        out = signed_agreement_step(keys, st, ok, m=m, collapsed=collapsed)
        ctr = ctr + signed_counter_delta(out, st, ok)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        ys = (out["histogram"],)
        if collect_decisions:
            ys += (out["decision"],)
        return (st, nxt, ctr), ys + (ctr,)

    return jax.lax.scan(
        body, (state, sched, counters), ok_planes,
        length=rounds, unroll=unroll,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "rounds", "m", "collapsed", "unroll", "collect_decisions"
    ),
    donate_argnums=(0, 1),
)
def signed_megastep(  # ba-lint: donates(state, sched)
    state: SimState,
    sched: KeySchedule,
    counters: jax.Array,
    ok_planes: jax.Array,
    *,
    rounds: int,
    m: int = 1,
    collapsed: bool = False,
    unroll: int = 1,
    collect_decisions: bool = False,
):
    """``rounds`` SIGNED SM(m) rounds in one donated dispatch (ISSUE 14
    tentpole): round-1 equivocation broadcast, table-signature gating,
    m relay rounds and the quorum layer, per round, all inside one
    ``lax.scan``.

    Mirrors the existing megasteps' signature/donation/return contract
    exactly: ``state`` and ``sched`` are CONSUMED (thread the returned
    ones), the counter block (SIGNED_COUNTER_NAMES) rides the carry
    with its cumulative rows stacked into the outputs (the PR 4
    pattern — the last row continues the thread and reaches the host
    inside the existing depth-delayed retire fetch), and the sign-ahead
    verdict planes enter as consumed ``xs`` (NOT donated — no output
    aliases their shape, like scenario event planes).

    Bit-compat contract: round ``sched.counter + r`` computes exactly
    ``signed_agreement_step(round_keys(<schedule at counter + r>, B),
    state, ok_planes[r])`` — the blocking sequential signed driver
    (``parallel.signing.sequential_signed_sweep``) under the same key
    schedule and the same round tables produces identical decisions,
    histograms and counters (tests/test_signed_pipeline.py).
    """
    carry, ys = _signed_scan(
        state,
        sched,
        counters,
        ok_planes,
        rounds=rounds,
        m=m,
        collapsed=collapsed,
        unroll=unroll,
        collect_decisions=collect_decisions,
    )
    return (carry[0], carry[1], *ys)


def slot_signed_counter_delta(
    out: dict, state: SimState, ok: jax.Array
) -> jax.Array:
    """One signed round's PER-SLOT counter increments ([B, C] — the
    coalesced serving twin of :func:`signed_counter_delta`, exactly as
    :func:`slot_counter_delta` relates to the batch deltas): row ``b``
    is bit-identical to the delta slot ``b``'s own B=1 signed run would
    fold, with the batch reductions dropped and unanimity fixed at its
    B=1 value."""
    base = slot_counter_delta(out, state, scenario=False)
    received = out["received"]
    sig_rej = jnp.any(~ok, axis=-1).astype(jnp.int32)
    idx = jnp.arange(state.faulty.shape[1])[None, :]
    lieutenants = state.alive & (idx != state.leader[:, None])
    got_a = ((received == ATTACK) & lieutenants).any(axis=1)
    got_r = ((received == RETREAT) & lieutenants).any(axis=1)
    leader_faulty = jnp.take_along_axis(
        state.faulty, state.leader[:, None], axis=1
    )[:, 0]
    leader_alive = jnp.take_along_axis(
        state.alive, state.leader[:, None], axis=1
    )[:, 0]
    equiv = (got_a & got_r & leader_faulty & leader_alive).astype(jnp.int32)
    return jnp.concatenate(
        [base, jnp.stack([sig_rej, equiv], axis=-1)], axis=-1
    )


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "collapsed", "unroll"),
    donate_argnums=(0, 1),
)
def coalesced_signed_megastep(  # ba-lint: donates(state, sched)
    state: SimState,
    sched: KeySchedule,
    slot_counters: jax.Array,
    ok_planes: jax.Array,
    *,
    rounds: int,
    m: int = 1,
    collapsed: bool = False,
    unroll: int = 1,
):
    """``rounds`` SIGNED rounds of a COALESCED serving batch in one
    donated dispatch: every slot an independent signed request.

    ``sched`` is a slot schedule (one base key per slot, folding
    instance 0 — :func:`slot_round_keys`), so slot ``b`` is bit-exact
    with its own B=1 ``pipeline_sweep(signed=True)`` run at equal
    padded capacity — the serving parity pin extended verbatim to
    signed cohorts.  Returns ``(state, sched, None, last_majorities,
    decisions, counter_rows)`` — the unsigned coalesced tuple with the
    (absent) strategy slot pinned to None, so the dispatch loop's
    unpacking serves both protocols verbatim.
    """

    def body(carry, ok):
        st, sc, ctr, _maj = carry
        keys = slot_round_keys(sc)
        out = signed_agreement_step(keys, st, ok, m=m, collapsed=collapsed)
        ctr = ctr + slot_signed_counter_delta(out, st, ok)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        return (st, nxt, ctr, out["majorities"]), (out["decision"], ctr)

    B, n = state.faulty.shape
    maj0 = jnp.full((B, n), UNDEFINED, COMMAND_DTYPE)
    carry, ys = jax.lax.scan(
        body, (state, sched, slot_counters, maj0), ok_planes,
        length=rounds, unroll=unroll,
    )
    return (carry[0], carry[1], None, carry[3], *ys)


@dataclasses.dataclass(frozen=True)
class CarryCheckpoint:
    """A resumable snapshot of the engine's donated carry (ISSUE 6).

    Everything a dispatch thread needs to continue bit-exactly:
    the :class:`SimState`, the :class:`KeySchedule` (whose counter IS
    the campaign's round cursor — threefry derivation is
    backend-independent, so the resumed key stream matches the
    uninterrupted one on any process/backend), the cumulative counter
    block, the live strategy plane (scenario campaigns), and ``round``.
    ``counters``/``strategy`` are ``None`` on carries that never had
    them (a plain sweep without ``with_counters``).

    Shard layout (ISSUE 8): a checkpoint is DEVICE-COUNT-FREE.  A mesh
    campaign's per-shard counter blocks gather (sum) to the canonical
    single-device block at write time, state/strategy planes fetch to
    their full global shapes, and ``shard_layout`` records the writing
    mesh's axis sizes (``{"data": 1}`` for single-device) as
    provenance — so a campaign checkpointed on d devices resumes
    bit-exactly on d' (``pipeline_sweep(resume=..., mesh=...)``
    re-splits on read; subprocess-pinned in tests/test_scenario.py).

    Serialized via :func:`save_carry_checkpoint` to the repo's single
    checkpoint format (``utils/snapshot.py``: one versioned ``.npz``
    with a JSON ``__meta__`` header, atomic write); the engine writes
    the same format from inside its retire fetch when
    ``checkpoint_every`` is set, and ``pipeline_sweep(resume=...)``
    restores it.
    """

    state: SimState
    schedule: KeySchedule
    counters: jax.Array | None
    strategy: jax.Array | None
    round: int
    shard_layout: dict | None = None
    # Signed campaigns (ISSUE 14): the counter block is the SIGNED
    # table (SIGNED_COUNTER_NAMES) and a resume must re-enter the
    # signed lane — the flag is what lets load/resume refuse a
    # cross-protocol splice positionally.
    signed: bool = False
    # Flight-recorder correlation (ISSUE 9): the run_id of the campaign
    # that wrote this checkpoint, so a resume CONTINUES the same run's
    # ledger (a killed process's successor joins its predecessor's
    # records).  None on pre-recorder checkpoints.
    run_id: str | None = None


def _carry_arrays(host_state, host_sched, host_counters, host_strategy):
    """Flatten a fetched (host numpy) carry into the checkpoint's named
    array dict — one layout, shared by the engine's in-retire writer and
    the public :func:`save_carry_checkpoint`."""
    arrays = {
        "order": host_state.order,
        "leader": host_state.leader,
        "faulty": host_state.faulty,
        "alive": host_state.alive,
        "ids": host_state.ids,
        "key_data": host_sched.key_data,
        "counter": host_sched.counter,
    }
    if host_counters is not None:
        arrays["counters"] = host_counters
    if host_strategy is not None:
        arrays["strategy"] = host_strategy
    return arrays


# Carry-header fields the engine/writer stamp themselves: ONE literal
# shared by _carry_meta's clash check and pipeline_sweep's eager
# checkpoint_meta validation, so a future header field cannot be added
# to one and forgotten in the other (a caller's meta key silently
# colliding with an engine field is the misclassified-checkpoint
# hazard both checks exist to prevent).
RESERVED_CARRY_META_KEYS = frozenset(
    {"format", "v", "round", "scenario", "signed", "counter_names",
     "sha256", "rounds_total", "shard_layout", "run_id", "traceparent"}
)


def _carry_meta(
    round_cursor: int, counters, strategy, shard_layout=None, run_id=None,
    signed=False, **extra
) -> dict:
    clash = (RESERVED_CARRY_META_KEYS - {"rounds_total"}) & set(extra)
    if clash:
        # Silently overriding a header field would write a checkpoint
        # every reader rejects (or worse, misclassifies): catch it at
        # write time, where the caller can still fix the kwarg.
        raise ValueError(
            f"checkpoint meta key(s) {sorted(clash)} are reserved for "
            f"the carry header"
        )
    names = None
    if counters is not None:
        # The strategy plane is what makes a carry a scenario carry —
        # select the name table on it (then the signed flag), never on
        # block length (the tables' lengths are not a contract).
        names = list(
            SCENARIO_COUNTER_NAMES
            if strategy is not None
            else SIGNED_COUNTER_NAMES if signed else COUNTER_NAMES
        )
    return {
        "round": int(round_cursor),
        "scenario": strategy is not None,
        "signed": bool(signed),
        "counter_names": names,
        # Provenance, not a resume constraint: the stored arrays are
        # canonical (gather-on-write), so any device count reads them.
        "shard_layout": shard_layout or {"data": 1},
        # Run correlation (ISSUE 9): which campaign run wrote this
        # carry; a resume adopts it so the ledger stays one run.
        "run_id": run_id,
        # Causal continuity (ISSUE 19): the writer's trace position at
        # write time rides the header, so a resumed campaign's spans
        # parent under the pre-crash span (the supervisor reads it back
        # into an inject_scope at both resume sites).  None untraced.
        "traceparent": obs.trace.current_traceparent(),
        **extra,
    }


def save_carry_checkpoint(path: str, ckpt: CarryCheckpoint, **extra) -> int:
    """Serialize a live carry to ``path`` (atomic, versioned).

    Fetches the carry to host first — callers on the engine's donation
    thread must pass a carry they own (``fresh_copy`` the live one; the
    engine's ``checkpoint_every`` path does this for you at its existing
    retire sync, so prefer it inside sweeps).  ``extra`` keys ride the
    JSON meta header (campaign name, total rounds, ...).  Returns the
    total array bytes written (the engine's ``scenario_checkpoint``
    JSONL record reports it).

    A per-shard counter block ([d, C], a live mesh carry) gathers to
    the canonical single-device block here (gather-on-write: the sum is
    the invariant), so the written file is device-count-free whatever
    carry the caller held.  This is the ONE implementation of that
    rule — the engine's in-retire writer routes through here.
    """
    host = list(
        jax.device_get(
            (ckpt.state, ckpt.schedule, ckpt.counters, ckpt.strategy)
        )
    )
    layout = ckpt.shard_layout
    if host[2] is not None and host[2].ndim == 2:
        if layout is None:
            layout = {"data": int(host[2].shape[0])}
        host[2] = host[2].sum(axis=0, dtype=host[2].dtype)
    arrays = _carry_arrays(*host)
    _snapshot.write_carry_checkpoint(
        path,
        arrays,
        _carry_meta(
            ckpt.round, host[2], host[3], shard_layout=layout,
            run_id=ckpt.run_id or _metrics.active_run_id(),
            signed=ckpt.signed, **extra
        ),
    )
    return sum(v.nbytes for v in arrays.values())


def load_carry_checkpoint(path: str) -> CarryCheckpoint:
    """Read + schema-check a carry checkpoint into live device arrays.

    Every array is COPIED onto the device (``jnp.array`` never aliases
    the numpy backing store), so the restored carry is safe to hand
    straight to the engine's donation thread — the fresh_copy hazard
    cannot reach a resumed campaign.
    """
    meta, arrays = _snapshot.read_carry_checkpoint(path)
    if "counters" in arrays:
        live = (
            SCENARIO_COUNTER_NAMES
            if meta.get("scenario")
            else SIGNED_COUNTER_NAMES
            if meta.get("signed")
            else COUNTER_NAMES
        )
        stored = meta.get("counter_names")
        if stored is not None and tuple(stored) != tuple(live):
            # The block is positional: a renamed/reordered table between
            # the writing build and this one would silently attribute
            # resumed totals to the wrong counters.  The names ride the
            # meta header exactly so this check can refuse.
            raise ValueError(
                f"checkpoint counter table {list(stored)} does not match "
                f"this build's {list(live)} — refusing to resume totals "
                f"positionally"
            )
    state = SimState(
        order=jnp.array(arrays["order"]),
        leader=jnp.array(arrays["leader"]),
        faulty=jnp.array(arrays["faulty"]),
        alive=jnp.array(arrays["alive"]),
        ids=jnp.array(arrays["ids"]),
    )
    sched = KeySchedule(
        key_data=jnp.array(arrays["key_data"]),
        counter=jnp.array(arrays["counter"]),
    )
    counters = (
        jnp.array(arrays["counters"]) if "counters" in arrays else None
    )
    strategy = (
        jnp.array(arrays["strategy"]) if "strategy" in arrays else None
    )
    return CarryCheckpoint(
        state=state,
        schedule=sched,
        counters=counters,
        strategy=strategy,
        round=meta["round"],
        shard_layout=meta.get("shard_layout"),
        signed=bool(meta.get("signed", False)),
        run_id=meta.get("run_id"),
    )


# -- coalesced serving batches (ISSUE 10) -------------------------------------
#
# The serving front-end (``runtime/serve.py``) coalesces concurrent
# interactive requests into ONE padded batch dimension.  The contract
# that makes coalescing safe to offer at all is slot independence:
# every batched result must be BIT-EXACT with the same request run
# alone at equal padded capacity.  Two things deliver it:
#
# 1. **Per-slot key schedules.**  The plain engine derives round r's
#    instance-i key as ``fold_in(fold_in(base, r), i)`` — so a request
#    sharing a batch at slot 3 would draw a different stream than the
#    same request alone at slot 0.  A coalesced batch instead carries
#    one base key PER SLOT (``key_data`` [B, ...]) and every slot folds
#    instance index 0: slot b draws exactly the stream its own B=1 run
#    would (:func:`slot_round_keys`).
# 2. **Per-slot counter blocks.**  The engine's counter block sums over
#    the batch (and "unanimous" is a batch-global verdict), which would
#    entangle cohabiting requests.  :func:`slot_counter_delta` keeps
#    the same formulas per slot, exactly as a B=1 batch reduces them —
#    a one-instance round is always unanimous, so that column is a
#    constant 1 per round, which is precisely what the alone run's
#    ``histogram.max() == 1`` computes.


def make_slot_key_schedule(slot_keys, counter: int = 0) -> KeySchedule:
    """A :class:`KeySchedule` carrying one base key PER SLOT.

    ``slot_keys`` is a sequence of typed keys (one per batch slot); the
    stacked raw data is COPIED (``jnp.stack`` allocates), so the
    callers' keys survive the schedule entering the donation thread —
    same contract as :func:`make_key_schedule`.
    """
    data = jnp.stack([jnp.asarray(jr.key_data(k)) for k in slot_keys])
    return KeySchedule(
        key_data=data, counter=jnp.asarray(counter, jnp.int32)
    )


def slot_round_keys(sched: KeySchedule) -> jax.Array:
    """The current round's per-slot keys from a slot schedule
    (trace-time, like :func:`round_keys`).

    Slot ``b`` derives ``fold_in(fold_in(base_b, counter), 0)`` — the
    exact key its own B=1 run's :func:`round_keys` derives for instance
    0, which is the whole coalescing bit-exactness contract.  The
    ``fold_in`` here is the sanctioned on-device derivation (ba-lint
    BA102 bans only host-loop splits).
    """

    def one(kd):
        base = jr.wrap_key_data(kd)
        return jr.fold_in(
            jr.fold_in(base, sched.counter), jnp.uint32(0)
        )

    return jax.vmap(one)(sched.key_data)


def slot_counter_delta(
    out: dict, state: SimState, scenario: bool
) -> jax.Array:
    """One round's PER-SLOT counter increments (trace-time, in-scan):
    ``[B, C]`` where row ``b`` is bit-identical to the delta a B=1 run
    of slot ``b`` alone would fold into its (scenario) counter block —
    the same formulas as :func:`agreement_counter_delta` /
    :func:`scenario_counter_delta` with the batch reductions dropped
    and the unanimity verdict fixed at its B=1 value (one instance
    always decides unanimously)."""
    decision = out["decision"]
    maj = out["majorities"]
    idx = jnp.arange(state.faulty.shape[1])[None, :]
    lieutenants = state.alive & (idx != state.leader[:, None])
    big = jnp.asarray(127, maj.dtype)
    mmax = jnp.max(jnp.where(lieutenants, maj, -big), axis=1)
    mmin = jnp.min(jnp.where(lieutenants, maj, big), axis=1)
    disagree = (mmax != mmin) & lieutenants.any(axis=1)
    traitor = (state.faulty & state.alive).any(axis=1)
    cols = [
        (decision == UNDEFINED).astype(jnp.int32),
        jnp.ones_like(decision, dtype=jnp.int32),
        (disagree & traitor).astype(jnp.int32),
    ]
    if scenario:
        honest_lt = lieutenants & ~state.faulty
        hmax = jnp.max(jnp.where(honest_lt, maj, -big), axis=1)
        hmin = jnp.min(jnp.where(honest_lt, maj, big), axis=1)
        ic1 = (hmax != hmin) & honest_lt.any(axis=1)
        leader_faulty = jnp.take_along_axis(
            state.faulty, state.leader[:, None], axis=1
        )[:, 0]
        disobey = (honest_lt & (maj != state.order[:, None])).any(axis=1)
        ic2 = ~leader_faulty & disobey
        cols += [ic1.astype(jnp.int32), ic2.astype(jnp.int32)]
    return jnp.stack(cols, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "max_liars", "unroll", "scenario"),
    donate_argnums=(0, 1, 2),
)
def coalesced_megastep(  # ba-lint: donates(state, sched, strategy)
    state: SimState,
    sched: KeySchedule,
    strategy: jax.Array | None,
    slot_counters: jax.Array,
    events: dict | None,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    scenario: bool = False,
):
    """``rounds`` rounds of a COALESCED serving batch in one donated
    dispatch (ISSUE 10): every slot is an independent request.

    ``sched`` is a slot schedule (:func:`make_slot_key_schedule` — one
    base key per slot, all folding instance index 0), so slot ``b``'s
    decisions/majorities/counters are bit-identical to its own B=1 run
    at equal padded capacity.  ``scenario=True`` additionally applies
    per-round event planes (``events``: the dict a
    ``ScenarioBlock.chunk`` yields, each slot's campaign concatenated
    along the batch axis) with the same kill → re-elect → agree
    transition as :func:`scenario_megastep`, per slot.

    DONATION CONTRACT: ``state``, ``sched`` and ``strategy`` are
    CONSUMED — thread the returned ones.  ``slot_counters`` rides the
    cumulative ``counter_rows`` output instead (the PR 4 pattern: no
    output aliases its shape).

    Returns ``(state, sched, strategy, last_majorities, decisions,
    counter_rows[, leaders])``: ``last_majorities`` [B, n] is the FINAL
    round's per-general block (carried, overwritten each round — the
    interactive ``actual-order`` output without a second dispatch),
    ``decisions`` [rounds, B], ``counter_rows`` [rounds, B, C]
    cumulative per-slot blocks (last row continues the thread),
    ``leaders`` [rounds, B] post-election (scenario only).
    """

    def body(carry, ev):
        st, sc, strat, ctr, _maj = carry
        if scenario:
            kill, revive, fset, sset = ev
            alive = (st.alive & ~kill) | revive
            faulty = jnp.where(fset >= 0, fset > 0, st.faulty)
            strat = jnp.where(sset >= 0, sset, strat)
            leader_alive = jnp.take_along_axis(
                alive, st.leader[:, None], axis=1
            )[:, 0]
            leader = jnp.where(
                leader_alive, st.leader, elect_lowest_id(st.ids, alive)
            )
            st = SimState(st.order, leader, faulty, alive, st.ids)
        keys = slot_round_keys(sc)
        out = agreement_step(
            keys, st, m=m, max_liars=max_liars,
            strategies=strat if scenario else None,
        )
        ctr = ctr + slot_counter_delta(out, st, scenario)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        ys = (out["decision"], ctr)
        if scenario:
            ys += (st.leader,)
        return (st, nxt, strat, ctr, out["majorities"]), ys

    B, n = state.faulty.shape
    maj0 = jnp.full((B, n), UNDEFINED, COMMAND_DTYPE)
    xs = None
    if scenario:
        xs = (
            events["kill"],
            events["revive"],
            events["set_faulty"],
            events["set_strategy"],
        )
    carry, ys = jax.lax.scan(
        body, (state, sched, strategy, slot_counters, maj0), xs,
        length=rounds, unroll=unroll,
    )
    return (carry[0], carry[1], carry[2], carry[4], *ys)


# -- AOT specialization specs (ISSUE 11) --------------------------------------
#
# The executable cache (``obs/aotcache.py``) compiles megastep
# specializations OFF the request path, keyed by the SAME named-axes
# signature the dispatch loops build for the recompile explainer.  These
# builders are the axes -> abstract-signature inverse: given one axes
# dict, reconstruct the exact (jitted, args, kwargs) lowering call the
# engine's dispatch of that signature performs — so a warmup-compiled
# executable is THE executable the jit path would have compiled, and the
# engine can dispatch it interchangeably (the warm-vs-cold bit-exactness
# tests pin it).  They live HERE, not in the obs tier: building abstract
# SimStates needs the jitted trees, which obs modules must never import
# (ba-lint BA301).


def _abstract_state(batch: int, capacity: int) -> SimState:
    S = jax.ShapeDtypeStruct
    return SimState(
        order=S((batch,), COMMAND_DTYPE),
        leader=S((batch,), jnp.int32),
        faulty=S((batch, capacity), jnp.bool_),
        alive=S((batch, capacity), jnp.bool_),
        ids=S((batch, capacity), jnp.int32),
    )


def _key_data_spec():
    """Shape/dtype of one typed key's raw data under the ACTIVE rng
    implementation (threefry: ``(2,) uint32``) — executables specialize
    on it, so the spec must be read live, never hard-coded."""
    kd = jr.key_data(jr.key(0))
    return tuple(kd.shape), kd.dtype


def _event_plane_specs(rounds: int, batch: int, capacity: int) -> dict:
    S = jax.ShapeDtypeStruct
    shape = (rounds, batch, capacity)
    # Dtypes mirror scenario.compile._fresh_planes — the one definition
    # of a staged chunk's layout.
    return {
        "kill": S(shape, jnp.bool_),
        "revive": S(shape, jnp.bool_),
        "set_faulty": S(shape, jnp.int8),
        "set_strategy": S(shape, jnp.int8),
    }


def _engine_axis_kwargs(axes: dict, which: str) -> tuple:
    """``(fn_override | None, extra kwargs)`` for one AOT spec's engine
    axis (ISSUE 13): rows without the axis are pre-engine ledger rows —
    the XLA core; kernel-engine rows lower the Pallas twin with its
    interpret static, so a warm pallas cohort's executable is THE
    executable the dispatch loop would have jit-compiled."""
    engine = axes.get("engine", "xla")
    if engine == "xla":
        return None, {}
    if engine not in ENGINES:
        raise ValueError(f"unknown engine axis {engine!r} in AOT spec")
    from ba_tpu.ops import scenario_step as _ss

    fn = {
        "coalesced": _ss.pallas_coalesced_megastep,
        "pipeline": _ss.pallas_pipeline_megastep,
        "scenario": _ss.pallas_scenario_megastep,
    }[which]
    return fn, {"interpret": engine == "interpret"}


def coalesced_aot_spec(axes: dict):
    """``(jitted, args, kwargs)`` lowering one :func:`coalesced_megastep`
    specialization from its named axes signature (the serving
    dispatcher's dict: batch/capacity/rounds/m/max_liars/unroll/
    scenario/engine)."""
    S = jax.ShapeDtypeStruct
    B, n, nr = axes["batch"], axes["capacity"], axes["rounds"]
    scenario = bool(axes["scenario"])
    kshape, kdtype = _key_data_spec()
    sched = KeySchedule(
        key_data=S((B,) + kshape, kdtype), counter=S((), jnp.int32)
    )
    if axes.get("signed"):
        # The signed coalesced twin (ISSUE 14): per-slot SIGNED counter
        # blocks, per-round table-verdict planes as xs, always the XLA
        # core (the kernel never covers signed — resolve_engine pins it).
        counters = S((B, len(SIGNED_COUNTER_NAMES)), jnp.int32)
        ok = S((nr, B, 2), jnp.bool_)
        return (
            coalesced_signed_megastep,
            (_abstract_state(B, n), sched, counters, ok),
            dict(
                rounds=nr,
                m=axes["m"],
                collapsed=bool(axes.get("collapsed", False)),
                unroll=axes["unroll"],
            ),
        )
    strategy = S((B, n), jnp.int8) if scenario else None
    names = SCENARIO_COUNTER_NAMES if scenario else COUNTER_NAMES
    counters = S((B, len(names)), jnp.int32)
    events = _event_plane_specs(nr, B, n) if scenario else None
    fn, extra = _engine_axis_kwargs(axes, "coalesced")
    return (
        fn or coalesced_megastep,
        (_abstract_state(B, n), sched, strategy, counters, events),
        dict(
            rounds=nr,
            m=axes["m"],
            max_liars=axes["max_liars"],
            unroll=axes["unroll"],
            scenario=scenario,
            **extra,
        ),
    )


def pipeline_aot_spec(axes: dict):
    """``(jitted, args, kwargs)`` for one :func:`pipeline_megastep`
    specialization (campaign axes: batch/capacity/rounds/m/max_liars/
    unroll/collect_decisions/counters/data — single-device only; a
    sharded signature, ``data > 1``, has no portable serialized form)."""
    if axes.get("data", 1) != 1:
        raise ValueError(
            f"cannot AOT-cache a sharded specialization (data="
            f"{axes.get('data')})"
        )
    S = jax.ShapeDtypeStruct
    B, n, nr = axes["batch"], axes["capacity"], axes["rounds"]
    kshape, kdtype = _key_data_spec()
    sched = KeySchedule(key_data=S(kshape, kdtype), counter=S((), jnp.int32))
    counters = (
        S((len(COUNTER_NAMES),), jnp.int32) if axes["counters"] else None
    )
    fn, extra = _engine_axis_kwargs(axes, "pipeline")
    return (
        fn or pipeline_megastep,
        (_abstract_state(B, n), sched),
        dict(
            rounds=nr,
            m=axes["m"],
            max_liars=axes["max_liars"],
            unroll=axes["unroll"],
            collect_decisions=axes["collect_decisions"],
            counters=counters,
            **extra,
        ),
    )


def scenario_aot_spec(axes: dict):
    """``(jitted, args, kwargs)`` for one :func:`scenario_megastep`
    specialization (single-device, like :func:`pipeline_aot_spec`)."""
    if axes.get("data", 1) != 1:
        raise ValueError(
            f"cannot AOT-cache a sharded specialization (data="
            f"{axes.get('data')})"
        )
    S = jax.ShapeDtypeStruct
    B, n, nr = axes["batch"], axes["capacity"], axes["rounds"]
    kshape, kdtype = _key_data_spec()
    sched = KeySchedule(key_data=S(kshape, kdtype), counter=S((), jnp.int32))
    fn, extra = _engine_axis_kwargs(axes, "scenario")
    return (
        fn or scenario_megastep,
        (
            _abstract_state(B, n),
            sched,
            S((B, n), jnp.int8),
            S((len(SCENARIO_COUNTER_NAMES),), jnp.int32),
            _event_plane_specs(nr, B, n),
        ),
        dict(
            rounds=nr,
            m=axes["m"],
            max_liars=axes["max_liars"],
            unroll=axes["unroll"],
            collect_decisions=axes["collect_decisions"],
            **extra,
        ),
    )


def signed_aot_spec(axes: dict):
    """``(jitted, args, kwargs)`` for one :func:`signed_megastep`
    specialization (ISSUE 14; single-device by construction — the
    signed lane never meshes)."""
    S = jax.ShapeDtypeStruct
    B, n, nr = axes["batch"], axes["capacity"], axes["rounds"]
    kshape, kdtype = _key_data_spec()
    sched = KeySchedule(key_data=S(kshape, kdtype), counter=S((), jnp.int32))
    return (
        signed_megastep,
        (
            _abstract_state(B, n),
            sched,
            S((len(SIGNED_COUNTER_NAMES),), jnp.int32),
            S((nr, B, 2), jnp.bool_),
        ),
        dict(
            rounds=nr,
            m=axes["m"],
            collapsed=bool(axes.get("collapsed", False)),
            unroll=axes["unroll"],
            collect_decisions=axes["collect_decisions"],
        ),
    )


# fn name -> builder; the names ARE the compile-signature/ledger fn
# names, so the warmup pass can map ledger rows straight onto builders.
AOT_SPECS = {
    "coalesced_megastep": coalesced_aot_spec,
    "pipeline_megastep": pipeline_aot_spec,
    "scenario_megastep": scenario_aot_spec,
    "signed_megastep": signed_aot_spec,
}


@contextlib.contextmanager
def _dispatch_span(fn: str, axes: dict, warm: bool, **attrs):
    """The dispatch site's span, in both temperatures (ISSUE 11).

    A WARM dispatch (precompiled executable) is a plain ``dispatch``
    span with ``warm=True`` — it deliberately never touches the jit
    first-call classifier: an AOT executable does not populate jit's
    cache, so marking the signature seen would make a LATER cache-less
    jit dispatch of the same shape read as a cached ``dispatch`` while
    paying a real, uncounted compile.  A cold dispatch classifies
    through ``compile_or_dispatch_span`` exactly as before.  Yields the
    phase name either way.
    """
    if warm:
        with obs.default_tracer().span("dispatch", warm=True, **attrs):
            yield "dispatch"
    else:
        with obs.compile_or_dispatch_span(fn, axes=axes, **attrs) as phase:
            yield phase


def _warm_call(exe_call, jit_call, executables, fn, axes, fell_back):
    """Wrap a warm dispatch with its jit-path fallback: if the
    precompiled executable ITSELF raises at call time, evict the entry
    (quarantining its disk bytes for post-mortem) and run the jit path
    — the cache's load-time degradation ladder extended to call time,
    so one unusable entry costs one compile, never a bricked signature.

    The fallback is safe exactly when the executable raised BEFORE
    consuming the donated carry (argument-structure mismatches do —
    they fail at host-side flattening); a post-donation device failure
    makes the jit retry raise use-after-donate, which propagates as the
    fault it is.  ``fell_back`` is a mutable list cell — the caller
    counts a fallback as a request-path compile, not a warm dispatch.
    """

    def call():
        try:
            return exe_call()
        except Exception:
            executables.evict(fn, axes)
            fell_back.append(fn)
            return jit_call()

    return call


def _pipeline_instruments(reg):
    """The dispatch/retire discipline's instrument block — ONE creation
    site shared by the campaign loop and the coalesced serving loop
    (ISSUE 10), so a renamed histogram or changed bucket shape cannot
    drift between the two traffic types: the health sampler's
    depth-occupancy / retire-lag signals (the serving front-end's
    admission inputs) must read both identically."""
    return {
        "lat": reg.histogram("pipeline_dispatch_latency_s"),
        "lag": reg.histogram("pipeline_retire_lag_s"),
        "occ": reg.histogram(
            "pipeline_depth_occupancy", base=1.0, n_buckets=16
        ),
        "disp": reg.counter("pipeline_dispatches_total"),
        "ret": reg.counter("pipeline_retires_total"),
        "rounds": reg.counter("pipeline_rounds_total"),
    }


def _emit_flight_span(d, lo, hi, latency_s, lag_s, run_id=None, ctx=None,
                      t_perf=None):
    """One ``flight_span`` record per retired round window — the ONE
    spelling of the record shape (campaign loop and coalesced loop
    both emit through here).  ``run_id`` stamps the id EXPLICITLY
    (serving batches, which never activate the process-global scope);
    None leaves stamping to the sink's scope-based setdefault.

    ``ctx`` (ISSUE 19) is the dispatch's own trace position — stamped
    explicitly for the same reason run_id is: the retire fetch runs on
    the driving thread, whose AMBIENT context is the whole batch/
    campaign, not this window.  ``t_perf`` (perf_counter seconds at
    submit) lets obs/fleet place the window on the cross-process axis
    via the shard's clock anchor."""
    if not _metrics.default_sink().enabled:
        return
    rec = {
        "event": "flight_span",
        "v": _metrics.SCHEMA_VERSION,
        "phase": "retire",
        "dispatch": d,
        "lo": lo,
        "hi": hi,
        "latency_s": round(latency_s, 6),
        "lag_s": round(lag_s, 6),
    }
    if run_id is not None:
        rec["run_id"] = run_id
    if t_perf is not None:
        rec["t_perf"] = round(t_perf, 6)
    if ctx is not None:
        rec["trace_id"], rec["span_id"] = ctx[0], ctx[1]
        if ctx[2] is not None:
            rec["parent_id"] = ctx[2]
    _metrics.emit(rec)


def coalesced_sweep(  # ba-lint: donates(state)
    slot_keys,
    state: SimState,
    rounds: int,
    *,
    m: int = 1,
    max_liars: int | None = None,
    depth: int = 2,
    rounds_per_dispatch: int = 8,
    unroll: int = 1,
    scenario=None,
    initial_strategy: jax.Array | None = None,
    signed: bool = False,
    collapsed: bool = False,
    sign_seed: int = 0,
    exec_seam=None,
    on_retire=None,
    executables=None,
    engine: str | None = None,
):
    """Run a coalesced serving batch through the depth-k pipelined loop
    (ISSUE 10): B independent requests, one padded batch, bit-exact
    slot results.

    ``slot_keys`` is one typed key per slot; slot ``b``'s outputs are
    bit-identical to ``pipeline_sweep(slot_keys[b], <its B=1 state>,
    rounds)`` (or ``scenario_sweep`` with its own [R, 1, n] planes) at
    equal padded capacity — the coalesced-batch parity test pins it.
    ``scenario`` is a :class:`ba_tpu.scenario.compile.ScenarioBlock`
    (or a plane dict) whose batch axis concatenates the slots'
    campaigns.  ``exec_seam(call, phase, dispatch, lo, hi)`` is the
    same injectable seam the main engine exposes — the serving
    front-end composes chaos injection and transient retry there, and
    a cohort whose retries exhaust fails as ONE unit (per-cohort fault
    isolation; nothing outside this call is touched).
    ``on_retire(dispatch, lo, hi, host_ys)`` delivers each retire
    fetch's host block — the slot→request mapping hook: the service
    streams per-request rows out as windows retire instead of waiting
    for the drain.

    ``signed=True`` (ISSUE 14) runs the batch through the SIGNED
    coalesced megastep: per-slot keys as above, per-slot SIGNED counter
    blocks, and the sign-ahead lane's per-round table verdicts staged
    up front (every slot's alone-run binds instance 0 under
    ``sign_seed``, so the per-slot tables coincide and the lane signs
    each distinct round-bound claim once).  Slot ``b`` stays bit-exact
    with its own B=1 ``pipeline_sweep(signed=True)`` run at equal
    padded capacity — the parity pin, extended verbatim.  ``collapsed``
    selects the O(n) fair-coin relay; incompatible with ``scenario``
    (the signed megastep has no mutating-round form).

    ``executables`` (ISSUE 11) is an ``obs.aotcache.ExecutableCache``
    (anything with ``.get(fn, axes)``): the loop consults it BEFORE each
    dispatch and, on a hit, dispatches the precompiled executable
    instead of the jit path — bit-identical results (the AOT lowering is
    the same program), zero compile on the request path.  A miss falls
    back to the jit path exactly as before (compile-on-miss), counted in
    ``stats["request_path_compiles"]``; warm dispatches count in
    ``stats["warm_dispatches"]``.

    The batch gets a run_id (``BA_TPU_RUN_ID`` pin, else derived from
    the slot keys + rounds + event-plane content) carried EXPLICITLY on
    its ``flight_span`` records and ``stats["run_id"]`` — it
    deliberately does NOT activate the process-global run scope: the
    serving dispatcher is its own thread, and taking the single-slot
    scope there would make a concurrent main-thread campaign inherit a
    transient cohort's id (or lose its own mid-run) in the documented
    one-process roster+service mode.  It also emits NO
    ``flight_summary``: serving batches are high-frequency, and the
    assembler's rescan of the shared JSONL stream per batch would make
    a long-lived service's sink quadratic — the per-request ``request``
    records carry the run_id for correlation instead.

    DONATION: ``state`` (and ``initial_strategy``'s staged copy) are
    consumed by the first dispatch — serving callers stage fresh device
    copies per batch (``fresh_copy`` numpy-staged states: the zero-copy
    donation hazard applies here exactly as in ``runtime/backends``).

    Returns a dict: ``decisions`` [rounds, B] host int8, ``majorities``
    [B, n] host (final round's per-general block), ``counters`` [B, C]
    host int32 per-slot final blocks + ``counter_names``, ``leaders``
    [rounds, B] (scenario only), and ``stats`` (dispatches, depth,
    slots, run_id, ...).
    """
    if rounds < 1:
        raise ValueError(f"rounds={rounds} must be >= 1")
    if depth < 1:
        raise ValueError(f"depth={depth} must be >= 1")
    if rounds_per_dispatch < 1:
        raise ValueError(
            f"rounds_per_dispatch={rounds_per_dispatch} must be >= 1"
        )
    # Engine resolution (ISSUE 13/14): eager like the campaign path — an
    # explicit kernel request that cannot serve this cohort raises
    # before anything stages or donates; serving cohorts are always
    # single-device, so only the m and signed dials can exclude the
    # kernel.
    engine_resolved, engine_fallback = resolve_engine(
        engine, m=m, signed=signed
    )
    if signed and scenario is not None:
        raise ValueError(
            "signed cohorts cannot carry scenario planes (the signed "
            "megastep has no mutating-round form)"
        )
    if collapsed and not signed:
        # Same eager rejection as the campaign path: silently ignoring
        # the dial would hand back exact-relay results to a caller who
        # asked for the O(n) collapsed relay.
        raise ValueError("collapsed= is the signed relay dial; it needs "
                         "signed=True")
    B, n = state.faulty.shape
    if len(slot_keys) != B:
        raise ValueError(
            f"{len(slot_keys)} slot key(s) for a batch of {B}"
        )
    sched = make_slot_key_schedule(slot_keys)
    is_scenario = scenario is not None
    strategy = None
    ev_planes = None
    if is_scenario:
        ev_planes = (
            scenario if isinstance(scenario, dict)
            else scenario.chunk(0, rounds)
            if hasattr(scenario, "chunk")
            else None
        )
        if ev_planes is None or set(ev_planes) != {
            "kill", "revive", "set_faulty", "set_strategy"
        }:
            raise ValueError(
                "scenario must be a ScenarioBlock or a plane dict"
            )
        got = tuple(jnp.shape(ev_planes["kill"]))
        if got != (rounds, B, n):
            raise ValueError(
                f"scenario planes are {got}, batch wants {(rounds, B, n)}"
            )
        if initial_strategy is None:
            strategy = jnp.zeros((B, n), jnp.int8)
        else:
            strategy = jnp.asarray(initial_strategy, jnp.int8).copy()
    elif initial_strategy is not None:
        raise ValueError("initial_strategy needs a scenario block")
    names = (
        SCENARIO_COUNTER_NAMES
        if is_scenario
        else SIGNED_COUNTER_NAMES if signed else COUNTER_NAMES
    )
    counters = jnp.zeros((B, len(names)), jnp.int32)

    chunks = [rounds_per_dispatch] * (rounds // rounds_per_dispatch)
    if rounds % rounds_per_dispatch:
        chunks.append(rounds % rounds_per_dispatch)

    # Signed cohorts (ISSUE 14): every slot's alone run binds instance 0
    # under the shared sign seed, so the per-slot round tables COINCIDE
    # — the lane signs each distinct round-bound claim once (the dedup
    # the tables exist for) and the [R, 1, V] verdict planes broadcast
    # over the batch at staging.  One lane, one verify dispatch, staged
    # up front (serving batches are short; the campaign engine owns the
    # true windowed sign-ahead).
    ok_planes = None
    sign_lane = None
    if signed:
        from ba_tpu.parallel import signing as _signing

        # Default pool/cache ride along (ISSUE 16): repeated signed
        # cohorts re-stage IDENTICAL per-round tables under the shared
        # sign seed, so the process-wide signature-table cache turns
        # every cohort after the first into pure lookups — the serving
        # front-end's warm path even pre-populates it.
        sign_lane = _signing.SignAheadLane(1, seed=sign_seed)
        ok_planes = sign_lane.stage(0, rounds)

    def _identity_material():
        material = [
            "coalesced", rounds, B,
            jax.device_get(sched.key_data).tobytes(),
        ]
        if signed:
            # Protocol joins the identity: a signed cohort under the
            # same keys/rounds is a different flight than its oral twin.
            material.append(f"signed:m={m}:collapsed={collapsed}")
        if ev_planes is not None:
            # Event-plane CONTENT joins the identity (the PR 9
            # hardening, upheld here): two scenario cohorts with equal
            # keys/rounds but different campaigns must not share a
            # run_id, or their records would merge into one flight.
            for name in ("kill", "revive", "set_faulty", "set_strategy"):
                material.append(
                    jax.device_get(ev_planes[name]).tobytes()
                )
        return material

    # Env pin > derivation — NEVER an active scope's id (unlike
    # resolve_run_id): a cohort inheriting a concurrent campaign's id
    # is exactly the cross-thread merging this path must not do.
    env = os.environ.get(obs.flight.RUN_ID_ENV)
    if env:
        if not obs.flight.valid_run_id(env):
            raise ValueError(
                f"{obs.flight.RUN_ID_ENV}={env!r} is not a valid run id"
            )
        rid = env
    else:
        rid = obs.flight.derive_run_id(*_identity_material())
    # Causal entry (ISSUE 19): the serve dispatcher's batch scope is
    # already active on this thread and wins; a direct caller may
    # inject via BA_TPU_TRACE_CONTEXT; untraced stays untraced.  On
    # adoption the minted root materializes as a "campaign" record so
    # the window spans below never merge unparented.
    with obs.trace.inject_scope(mark="campaign"):
        out = _coalesced_loop(
            state, sched, strategy, counters, ev_planes, chunks,
            m=m, max_liars=max_liars, depth=depth, unroll=unroll,
            is_scenario=is_scenario, exec_seam=exec_seam,
            on_retire=on_retire, run_id=rid, executables=executables,
            engine_resolved=engine_resolved,
            engine_fallback=engine_fallback,
            signed=signed, collapsed=collapsed, ok_planes=ok_planes,
        )
    out["counter_names"] = list(names)
    out["stats"]["run_id"] = rid
    out["stats"]["engine"] = engine_resolved
    out["stats"]["engine_fallback"] = engine_fallback
    if sign_lane is not None:
        out["stats"]["sign_ahead_s"] = round(sign_lane.sign_ahead_s, 6)
        out["stats"]["sign_pool_workers"] = sign_lane.pool_workers
        out["stats"]["sign_pool_s"] = round(sign_lane.pool_s, 6)
        out["stats"]["sign_cache_hits"] = sign_lane.cache_hits
    return out


def _coalesced_loop(
    state, sched, strategy, counters, ev_planes, chunks, *,
    m, max_liars, depth, unroll, is_scenario, exec_seam, on_retire,
    run_id=None, executables=None, engine_resolved="xla",
    engine_fallback=None, signed=False, collapsed=False, ok_planes=None,
):
    """The coalesced driver's dispatch loop: the main engine's depth-k
    retire discipline, without scenario staging/checkpoint machinery
    (serving batches are short) — instrumentation feeds the SAME
    pipeline_* instruments (``_pipeline_instruments``), so the health
    sampler's depth-occupancy and retire-lag signals (the service's
    admission inputs) see serving traffic exactly like campaign
    traffic."""
    tracer = obs.default_tracer()
    reg = obs.default_registry()
    inst = _pipeline_instruments(reg)
    lat_h, lag_h, occ_h = inst["lat"], inst["lag"], inst["occ"]
    disp_c, ret_c, rounds_c = inst["disp"], inst["ret"], inst["rounds"]
    _record_engine(reg, engine_resolved, engine_fallback)
    _, _, coalesced_fn, engine_extra = _engine_megasteps(engine_resolved)

    inflight: collections.deque = collections.deque()
    retired = []
    max_in_flight = 0
    warm_dispatches = 0
    request_path_compiles = 0
    # Engine-side phase walls for the SLO attribution join (ISSUE 17):
    # the service subtracts these from its dispatched→retired span so a
    # request's dispatch_s is pure device/dispatch time, with compiles
    # and retire fetches attributed to their own phases.
    compile_s = 0.0
    retire_fetch_s = 0.0

    def retire():
        nonlocal retire_fetch_s
        d, ys, t_sub, lo, hi, d_ctx = inflight.popleft()
        with obs.timed_span("retire", lag_h, dispatch=d) as lag_box:
            with obs.xla.annotate("coalesced_retire", dispatch=d):
                fetch = functools.partial(jax.device_get, ys)
                if exec_seam is None:
                    host_ys = fetch()
                else:
                    host_ys = exec_seam(fetch, "retire", d, lo, hi)
                retired.append(host_ys)
        retire_fetch_s += lag_box.elapsed_s or 0.0
        latency_s = (time.perf_counter_ns() - t_sub) / 1e9
        lat_h.record(latency_s)
        ret_c.inc()
        rounds_c.inc(hi - lo)
        _emit_flight_span(
            d, lo, hi, latency_s, lag_box.elapsed_s or 0.0, run_id=run_id,
            ctx=d_ctx, t_perf=t_sub / 1e9,
        )
        if on_retire is not None:
            on_retire(d, lo, hi, host_ys)

    round_base = 0
    majorities = None
    B_slots = state.faulty.shape[0]
    for d, nr in enumerate(chunks):
        lo, hi = round_base, round_base + nr
        axes = {
            "batch": B_slots,
            "capacity": state.faulty.shape[1],
            "rounds": nr,
            "m": m,
            "max_liars": max_liars,
            "unroll": min(unroll, nr),
            "scenario": is_scenario,
            # ISSUE 14: ONE fn name for both protocols of the serving
            # megastep with the protocol as a named axis — a signed
            # cohort after an oral one at equal shapes reads
            # `"signed": [false, true]` in the recompile record, an
            # EXPLAINED recompile rather than a mystery second compile.
            "signed": signed,
            "collapsed": collapsed if signed else False,
            "engine": engine_resolved,
        }
        ev = None
        if is_scenario:
            with tracer.span("stage_planes", lo=lo, hi=hi):
                # Async upload of this dispatch's plane slice; it
                # queues behind the in-flight dispatches.
                ev = {k: jnp.asarray(v[lo:hi]) for k, v in ev_planes.items()}
        elif signed:
            with tracer.span("stage_planes", lo=lo, hi=hi, signed=True):
                # The lane's [nr, 1, V] verdict slice broadcasts over
                # the slots (every slot's alone-run table coincides —
                # coalesced_sweep documents the dedup); a lazy device
                # view, no fetch.
                ev = jnp.broadcast_to(
                    ok_planes[lo:hi], (nr, B_slots, ok_planes.shape[-1])
                )
        # Executable-cache consult (ISSUE 11): a hit dispatches the
        # precompiled executable under a plain warm `dispatch` span
        # (_dispatch_span documents why it skips the classifier); a
        # miss is the jit path exactly as before.
        exe = (
            executables.get("coalesced_megastep", axes)
            if executables is not None
            else None
        )
        fell_back: list = []
        t_disp = time.perf_counter()
        with _dispatch_span(
            "coalesced_megastep", axes, exe is not None,
            dispatch=d, rounds=nr,
        ) as phase:
            with obs.xla.annotate("coalesced_dispatch", dispatch=d):
                if signed:
                    jit_call = functools.partial(
                        coalesced_signed_megastep,
                        state, sched, counters, ev,
                        rounds=nr, m=m, collapsed=collapsed,
                        unroll=min(unroll, nr),
                    )
                    exe_args = (state, sched, counters, ev)
                else:
                    jit_call = functools.partial(
                        coalesced_fn,
                        state, sched, strategy, counters, ev,
                        rounds=nr, m=m, max_liars=max_liars,
                        unroll=min(unroll, nr), scenario=is_scenario,
                        **engine_extra,
                    )
                    exe_args = (state, sched, strategy, counters, ev)
                if exe is not None:
                    # The executable's call takes only the traced
                    # arguments (statics baked at lowering); a call-time
                    # failure evicts + falls back to jit_call.
                    call = _warm_call(
                        functools.partial(exe, *exe_args),
                        jit_call, executables,
                        "coalesced_megastep", axes, fell_back,
                    )
                else:
                    call = jit_call
                if exec_seam is None:
                    out = call()
                else:
                    out = exec_seam(call, "dispatch", d, lo, hi)
        if exe is not None and not fell_back:
            warm_dispatches += 1
        elif phase == "compile" or fell_back:
            request_path_compiles += 1
            # A cold dispatch's block wall is dominated by tracing +
            # XLA compile (the async dispatch itself returns in µs) —
            # attribute the whole block to the compile phase.
            compile_s += time.perf_counter() - t_disp
        round_base = hi
        t_sub = time.perf_counter_ns()
        disp_c.inc()
        state, sched, strategy, majorities = out[0], out[1], out[2], out[3]
        ys = out[4:]
        counters = ys[1][-1]  # cumulative rows' last row continues
        # Each in-flight window is its own span (ISSUE 19), a child of
        # the ambient context (the serve batch's fan-in node) minted at
        # submit and stamped at retire — id derivation only, no sync.
        d_ctx = (
            obs.trace.child_context()
            if obs.trace.current() is not None
            else None
        )
        inflight.append((d, ys, t_sub, lo, hi, d_ctx))
        max_in_flight = max(max_in_flight, len(inflight))
        occ_h.record(len(inflight))
        while len(inflight) > depth:
            retire()
    while inflight:
        retire()

    import numpy as _host_np

    # Everything below concatenates host blocks the retire fetches
    # already brought back; the one extra fetch is the final carry's
    # majorities/counters, which the drained queue has already waited
    # on (no dispatch is still running).
    result = {
        "decisions": _host_np.concatenate([ys[0] for ys in retired]),
        "counters": jax.device_get(counters),
        "majorities": jax.device_get(majorities),
        "stats": {
            "rounds": round_base,
            "slots": state.faulty.shape[0],
            "dispatches": len(chunks),
            "depth": depth,
            "max_in_flight": max_in_flight,
            "warm_dispatches": warm_dispatches,
            "request_path_compiles": request_path_compiles,
            # SLO attribution inputs (ISSUE 17): engine-side phase
            # walls for this batch, both 6-dp rounded like the records
            # they feed.
            "compile_s": round(compile_s, 6),
            "retire_fetch_s": round(retire_fetch_s, 6),
        },
    }
    if is_scenario:
        result["leaders"] = _host_np.concatenate(
            [ys[2] for ys in retired]
        )
    return result


def pipeline_sweep(  # ba-lint: donates(state)
    key: jax.Array,
    state: SimState,
    rounds: int,
    *,
    scenario=None,
    resume=None,
    **engine_kwargs,
):
    """Run ``rounds`` sweep rounds through the depth-k pipelined engine,
    inside a flight-recorder run scope (ISSUE 9).

    The thin public layer over :func:`_pipeline_sweep_impl` (which
    documents every engine dial — depth, rounds_per_dispatch, scenario
    mode, mesh sharding, checkpointing, the resilience seams, and the
    new ``health_every``): before the first dispatch it resolves the
    campaign's **run_id** (``BA_TPU_RUN_ID`` > an already-active scope >
    the resume checkpoint's stored id > a sha256 derived from the key
    material/rounds/scenario — deterministic, so a killed process's
    successor joins the same ledger) and activates it for the whole
    sweep.  While active, every JSONL record, span, checkpoint header
    and compile-ledger row carries the id; the scope OWNER (the
    outermost caller — a supervised campaign's id wins over its
    attempts') assembles the sink's stream into ONE versioned
    ``flight_summary`` record at the end (``obs/flight.py``).  The
    resolved id also lands in ``stats["run_id"]``.

    Recording costs clock reads and (when the sink is live) one small
    JSONL line per retire — never a device synchronization: the
    no-blocking dispatch-count proof re-runs with the recorder and the
    health sampler live (tests/test_flight.py).

    DONATION: ``state`` is consumed exactly as the engine documents —
    thread the returned ``final_state``.
    """
    if isinstance(resume, str):
        # Load here (not in the impl) so the run_id the checkpoint
        # header carries can seed the scope the impl runs under.
        resume = load_carry_checkpoint(resume)
    def _identity_material():
        # Deferred (flight.resolve_run_id calls this only when env /
        # active scope / resume header yield nothing): the key fetch
        # and scenario-content hashing are wasted work on every
        # supervised retry attempt, whose derivation always loses to
        # the supervisor's active scope.
        material = [rounds]
        if engine_kwargs.get("signed"):
            # Protocol joins the identity (ISSUE 14): a signed campaign
            # under the same key/rounds is a different flight than its
            # oral twin — merged records would collide on the round
            # grid.
            material.append(
                f"signed:m={engine_kwargs.get('m', 1)}:"
                f"collapsed={engine_kwargs.get('collapsed', False)}"
            )
        if key is not None:
            material.append(jax.device_get(jr.key_data(key)).tobytes())
        elif resume is not None:
            material.append(
                jax.device_get(resume.schedule.key_data).tobytes()
            )
        if scenario is not None:
            doc = getattr(scenario, "to_doc", None)
            if doc is not None:
                material.append(json.dumps(doc(), sort_keys=True))
            else:
                # Dense blocks have no document form: hash the event
                # plane CONTENT (same identity the supervisor's
                # campaign fingerprint uses) — two campaigns differing
                # only in events must not share a run_id, or the
                # assembler would silently merge their flights on the
                # round grid.
                for name in (
                    "kill", "revive", "set_faulty", "set_strategy"
                ):
                    material.append(
                        jax.device_get(getattr(scenario, name)).tobytes()
                    )
        return material

    rid = obs.flight.resolve_run_id(
        inherited=resume.run_id if resume is not None else None,
        material_fn=_identity_material,
    )
    # Causal entry (ISSUE 19): adopt an externally injected traceparent
    # (BA_TPU_TRACE_CONTEXT) when no context is already active — an
    # already-active scope (the supervisor's resume scope, a serve
    # batch) always wins.  Untraced stays untraced: zero per-dispatch
    # context work in that case.  On adoption the minted root
    # materializes immediately ("campaign" record) so a SIGKILL
    # mid-flight still leaves the root its window spans parent under.
    with obs.trace.inject_scope(mark="campaign"), \
            obs.flight.run_scope(rid) as scope:
        out = _pipeline_sweep_impl(
            key, state, rounds, scenario=scenario, resume=resume,
            **engine_kwargs,
        )
        out["stats"]["run_id"] = scope.run_id
        if scope.owner:
            # One flight_summary per run, appended to the sink's own
            # stream (a disabled / stderr sink has nothing to join and
            # costs nothing).
            obs.flight.emit_flight_summary(run_id=scope.run_id)
    return out


def _pipeline_sweep_impl(  # ba-lint: donates(state)
    key: jax.Array,
    state: SimState,
    rounds: int,
    *,
    m: int = 1,
    max_liars: int | None = None,
    depth: int = 2,
    rounds_per_dispatch: int = 1,
    unroll: int = 1,
    collect_decisions: bool = False,
    with_counters: bool = False,
    host_work=None,
    mesh: Mesh | None = None,
    on_event=None,
    scenario=None,
    initial_strategy: jax.Array | None = None,
    signed: bool = False,
    collapsed: bool = False,
    sign_seed: int = 0,
    checkpoint_every: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_keep_last: int | None = None,
    checkpoint_meta: dict | None = None,
    on_checkpoint=None,
    resume=None,
    exec_seam=None,
    retire_timeout_s: float | None = None,
    on_stall=None,
    on_rows=None,
    health_every: int | None = None,
    executables=None,
    engine: str | None = None,
):
    """Run ``rounds`` sweep rounds through the depth-k pipelined engine.

    ENGINE SELECTION (ISSUE 13): ``engine`` picks the megastep
    implementation per the module-level table (``resolve_engine``):
    ``"xla"`` (default via ``BA_TPU_ENGINE``) is the scan cores,
    ``"pallas"``/``"interpret"`` the fused Pallas kernel
    (``ops/scenario_step.py`` — bit-exact vs the scan cores incl. the
    threefry coin streams), ``"auto"`` prefers the kernel on supported
    combinations on real TPU and falls back silently-but-counted.
    Explicit kernel requests on unsupported combinations (mesh
    ``data > 1``, ``m >= 2``) raise eagerly, BEFORE any buffer is
    donated.  The resolved value rides the compile-signature axes, the
    ``pipeline_engine`` gauge and ``stats["engine"]``; everything else
    — donation, depth-k retires, counters, checkpoints, resume — is
    engine-agnostic (a campaign may resume under a different engine).

    Dispatches ``ceil(rounds / rounds_per_dispatch)`` donated megasteps
    (the last one sized to the remainder), keeping ``depth`` of them
    un-retired between loop iterations — so immediately after a new
    dispatch (and before its retire check) up to ``depth + 1`` are
    momentarily in flight, which is what ``stats["max_in_flight"]``
    reports.  Between a dispatch and the retire check the
    ``host_work(dispatch_index)`` callback runs host-side work overlapped
    with device compute.  ``on_event(kind, index)`` (kinds ``"dispatch"``
    / ``"retire"``) instruments the schedule for the dispatch-count tests.

    DONATION: ``state`` is consumed by the first dispatch — use the
    returned ``final_state``.

    MESH MODE (ISSUE 8): with ``mesh`` set the engine lays the batch
    out on the mesh's "data" axis (``sharded_sweep``'s placement,
    multi-process safe via ``put_global``) and every dispatch runs the
    ``shard_map`` megasteps from ``parallel/shard.py`` — the SAME scan
    cores, batch-sharded, donation recycling the sharded copies, so
    per-device peak carry/plane bytes are the single-device figure
    divided by the device count.  Bit-exactness with the single-device
    run at equal shapes is the contract (per-instance keys fold by
    GLOBAL instance index; sharding is layout only).  Counter blocks
    and per-round histogram contributions stay PER-SHARD on device and
    the host tree-reduces them inside the existing depth-delayed
    retire fetch — no new synchronization (the no-blocking
    dispatch-count proof runs on a live mesh); the one in-scan
    collective is a 3-int histogram psum per round for the global
    unanimity verdict, and only when counters are on.  The batch must
    divide the data-axis size (eagerly validated); ``final_counters``
    comes back as the live per-shard ``[d, C]`` block (any later
    resume/checkpoint collapses it — the sum is the invariant).
    Checkpoints are DEVICE-COUNT-FREE: per-shard blocks gather at
    write, ``shard_layout`` records provenance, and a campaign
    checkpointed on d devices resumes bit-exactly on d' (pass the new
    ``mesh=`` — or none — with ``resume=``).

    Returns a dict:

    - ``histograms`` [rounds, 3] host int32 — per-round [retreat, attack,
      undefined] decision counts (fetched at retire time, never earlier);
    - ``decisions`` [rounds, B] host int8 when ``collect_decisions``;
    - with ``with_counters``: ``counters`` — a ``{name: int}`` dict of
      the final on-device agreement counter block (COUNTER_NAMES),
      ``counters_per_round`` [rounds, len(COUNTER_NAMES)] host int32
      cumulative rows, and ``final_counters`` — the live device block
      continuing the counter thread.  Counter rows piggyback the
      existing retire fetch (they ride ``ys``), so enabling them adds
      ZERO host synchronization; the final values also land in registry
      gauges ``agreement_<name>``;
    - ``final_state`` / ``final_schedule`` — the live (un-donated) pair,
      ready to continue the sweep;
    - ``stats`` — dispatch bookkeeping: ``dispatches``, ``depth``,
      ``rounds_per_dispatch``, ``max_in_flight``, and
      ``retires_before_drain`` (how many retires the steady-state loop
      performed; the rest drained at the end).

    SCENARIO MODE (ISSUE 5): pass ``scenario`` (a compiled
    ``ba_tpu.scenario.compile.ScenarioBlock`` whose ``rounds``/shape
    match) and every dispatch runs :func:`scenario_megastep` instead —
    kills, revivals, fault-flag flips, strategy reassignment, and
    lowest-alive-id leader re-election all ride the same donated scan,
    with the per-general strategy plane (``initial_strategy``, default
    all-RANDOM) as an extra donated carry slot.  Counters are always on
    (the block grows the IC1/IC2 verdict entries —
    ``SCENARIO_COUNTER_NAMES``) and the result additionally carries:

    - ``leaders`` [rounds, B] host int32 — each round's post-election
      leader (``failover_sweep``'s output, pipelined);
    - ``final_strategy`` — the live strategy plane continuing the
      campaign.

    The per-dispatch event chunks are staged DOUBLE-BUFFERED (ISSUE 6):
    chunk d+1 is host-materialized and its async upload enqueued in the
    ``host_work`` overlap slot while dispatches d-depth..d are still in
    flight, so plane staging never serializes with the scan — the same
    depth-delay trick as the retire fetch, and the no-blocking test
    runs with a live SPARSE block to pin it.  A sparse block
    (``ba_tpu.scenario.compile.SparseScenarioBlock``) keeps host plane
    memory O(chunk) instead of O(R); all-empty chunks reuse ONE staged
    zero chunk per chunk length (nothing re-uploads across a
    pure-agreement stretch).  An empty scenario is bit-exact with the
    plain engine under the same key.

    CHECKPOINTED CARRIES (ISSUE 6): with ``checkpoint_every=k``, every k
    rounds (aligned up to the next dispatch boundary) the engine
    ``fresh_copy``\\ s the live carry — an async device-side copy, no
    host sync — and serializes it INSIDE the existing depth-delayed
    retire fetch of the dispatch that produced it (the copy is
    necessarily ready when that fetch returns, so checkpointing adds
    bytes to an existing sync, never a new one).  ``checkpoint_path``
    names the ``.npz`` target (a literal ``{round}`` substitutes the
    round cursor; without it the latest checkpoint wins the path —
    note the campaign-FINAL checkpoint, cursor == rounds, wins last,
    and it can only seed a longer campaign, so keep ``{round}`` in the
    path when mid-campaign resumability is the point);
    ``on_checkpoint(round, path)`` fires after each write.  Each
    checkpoint also emits a ``scenario_checkpoint`` JSONL record.

    ``resume=`` (a :class:`CarryCheckpoint` or a path) continues a
    campaign from its cursor: pass ``key=None, state=None`` — the
    checkpoint IS the carry — and the same ``rounds``/``scenario`` the
    original run had.  The resumed rounds are bit-exact with the
    uninterrupted run's tail (same key schedule, same counters, same
    strategy plane), which the resume tests pin mid-campaign and across
    a process boundary.  ``checkpoint_keep_last=N`` (ISSUE 7) prunes a
    ``{round}``-templated family to its N newest members after every
    write (``utils/snapshot.prune_checkpoints``; companion sidecars go
    with them).  ``checkpoint_meta`` (JSON-able dict) rides every
    checkpoint's ``__meta__`` header next to the engine's own fields —
    the supervisor stamps its campaign fingerprint here; reserved header
    keys are rejected at write time.

    RESILIENCE SEAMS (ISSUE 7; all host-side, zero added device
    synchronization — the no-blocking test re-runs with every seam
    live):

    - ``exec_seam(call, phase, dispatch, lo, hi)`` — the injectable
      execution seam.  When set, every megastep invocation (``phase ==
      "dispatch"``) and every retire fetch (``phase == "retire"``) runs
      as ``exec_seam(call, ...)`` where ``call`` is the zero-arg real
      operation and ``[lo, hi)`` the dispatch's round window.  The
      execution supervisor (``runtime/supervisor.py``) composes fault
      injection and transient-retry here; a seam that simply returns
      ``call()`` is the identity.  Retrying ``call`` at the retire
      phase is always safe (the fetched outputs are not donated);
      retrying at the dispatch phase is safe exactly when the previous
      attempt raised BEFORE the jitted call consumed the donated carry
      (an injected fault; a real post-donation failure raises
      use-after-donate on retry and escalates).
    - ``retire_timeout_s`` + ``on_stall(dispatch, timeout_s)`` — the
      wall-clock watchdog on the depth-delayed retire: a
      ``threading.Timer`` armed around each retire fetch declares the
      dispatch STALLED if the fetch runs past the timeout (a
      ``dispatch_stalled`` instant, the ``pipeline_stalls_total``
      counter, and the callback — fired from the timer thread, which
      can only observe: an in-process hung fetch is not interruptible,
      recovery by process replacement + checkpoint resume is the
      supervisor's job).  The fetch itself is untouched — detection
      adds a timer arm/cancel, never a sync.
    - ``on_rows(dispatch, lo, hi, host_ys)`` — per-retire delivery of
      the host-fetched output block, BEFORE any checkpoint write of the
      same retire: a supervisor can persist campaign history alongside
      each checkpoint and stitch a full bit-exact result across
      recoveries.

    WARM EXECUTABLES (ISSUE 11, opt-in): ``executables`` (an
    ``obs.aotcache.ExecutableCache``) is consulted before every
    single-device dispatch; a precompiled specialization dispatches
    without the jit path's first-call compile (bit-identical program —
    the AOT lowering is the same trace).  Mesh dispatches ignore it (a
    sharded executable has no portable serialized form).
    ``stats["warm_dispatches"]`` / ``stats["request_path_compiles"]``
    report the split.

    HEALTH SAMPLING (ISSUE 9): ``health_every=N`` takes one
    ``obs.health.HealthSampler`` sample every N dispatches, from the
    SAME host-side slot ``host_work`` runs in (between a dispatch and
    its retire check, overlapping device compute).  A sample is
    lock-free registry reads + a ``health_*`` gauge write-back + (with
    a live sink) one ``health_snapshot`` JSONL record — zero added
    device synchronization, pinned by the no-blocking proof running
    with the sampler live.  ``stats["health_samples"]`` counts them.
    """
    if rounds < 1:
        raise ValueError(f"rounds={rounds} must be >= 1")
    if depth < 1:
        raise ValueError(f"depth={depth} must be >= 1")
    if rounds_per_dispatch < 1:
        raise ValueError(
            f"rounds_per_dispatch={rounds_per_dispatch} must be >= 1"
        )
    if unroll < 1:
        raise ValueError(f"unroll={unroll} must be >= 1")
    if checkpoint_every is not None and checkpoint_every < 1:
        raise ValueError(f"checkpoint_every={checkpoint_every} must be >= 1")
    if (checkpoint_path or on_checkpoint) and checkpoint_every is None:
        raise ValueError(
            "checkpoint_path/on_checkpoint need checkpoint_every"
        )
    if checkpoint_every is not None and checkpoint_path is None:
        # Without a path every checkpoint would be captured, fetched and
        # discarded — the caller believes the campaign is durable and
        # finds an empty disk at resume time.  on_checkpoint alone is no
        # sink either: the hook receives (round, path), not the carry.
        raise ValueError("checkpoint_every needs checkpoint_path")
    if checkpoint_keep_last is not None:
        if checkpoint_keep_last < 1:
            raise ValueError(
                f"checkpoint_keep_last={checkpoint_keep_last} must be >= 1"
            )
        if checkpoint_every is None:
            raise ValueError("checkpoint_keep_last needs checkpoint_every")
        if "{round}" in os.path.dirname(checkpoint_path):
            # snapshot.checkpoint_paths would reject this from inside
            # the first mid-campaign prune — the exact late failure the
            # eager checks here exist to prevent.
            raise ValueError(
                "checkpoint_path cannot carry the {round} slot in its "
                "directory component (retention scans one directory)"
            )
        if "{round}" not in os.path.basename(checkpoint_path):
            # A non-templated path IS a keep-last-1 family already;
            # asking for retention on it means the caller expected a
            # history that will never exist.  Basename, not the whole
            # path: a {round} slot in the DIRECTORY component would
            # pass here only to blow up snapshot.checkpoint_paths at
            # the first mid-campaign prune.
            raise ValueError(
                "checkpoint_keep_last needs a {round}-templated "
                "checkpoint FILENAME (the directory component cannot "
                "carry the slot)"
            )
    if checkpoint_meta is not None:
        if checkpoint_every is None:
            raise ValueError("checkpoint_meta needs checkpoint_every")
        reserved = RESERVED_CARRY_META_KEYS & set(checkpoint_meta)
        if reserved:
            # Eagerly, not at the first mid-campaign write: the engine
            # stamps these itself, and _carry_meta's own clash check
            # would only fire after checkpoint_every rounds of device
            # work.
            raise ValueError(
                f"checkpoint_meta key(s) {sorted(reserved)} are "
                f"reserved for the carry header"
            )
    if retire_timeout_s is not None and retire_timeout_s <= 0:
        raise ValueError(f"retire_timeout_s={retire_timeout_s} must be > 0")
    if on_stall is not None and retire_timeout_s is None:
        raise ValueError("on_stall needs retire_timeout_s")
    if health_every is not None and health_every < 1:
        raise ValueError(f"health_every={health_every} must be >= 1")
    if signed:
        # SIGNED MODE (ISSUE 14): the sign-ahead lane prepares per-round
        # signature-table verdicts in the host_work overlap slot and the
        # scan consumes them as xs (``signed_megastep``).  Single-device
        # XLA only — the fused kernel and the mesh scan cores never
        # covered the SM relay, and both exclusions are EAGER (nothing
        # donated yet).  Counters are always on: the signed verdicts
        # are the campaign's product and they ride the existing retire
        # fetch for free, exactly like scenario mode.
        if scenario is not None:
            raise ValueError(
                "signed=True cannot take a scenario block (the signed "
                "megastep has no mutating-round form)"
            )
        if mesh is not None:
            raise ValueError(
                "signed=True is single-device (mesh signed combos are "
                "unsupported; shard by running independent sweeps)"
            )
        with_counters = True
    elif collapsed:
        raise ValueError("collapsed= is the signed relay dial; it needs "
                         "signed=True")

    if resume is not None:
        if isinstance(resume, str):
            resume = load_carry_checkpoint(resume)
        if bool(getattr(resume, "signed", False)) != signed:
            # A cross-protocol splice would resume the wrong counter
            # table positionally AND re-enter the wrong megastep under
            # the checkpoint's key schedule — refuse loudly.
            raise ValueError(
                f"resume checkpoint signed={resume.signed} but this "
                f"sweep has signed={signed} — a carry never crosses "
                f"protocols"
            )
        if key is not None or state is not None:
            raise ValueError(
                "resume= supplies the carry: pass key=None, state=None"
            )
        if initial_strategy is not None:
            raise ValueError(
                "resume= supplies the strategy plane; initial_strategy "
                "must be None"
            )
        if not 0 <= resume.round < rounds:
            done = (
                " — the checkpoint is from a COMPLETED campaign; pass a "
                "larger rounds=/scenario= to extend it"
                if resume.round == rounds
                else ""
            )
            raise ValueError(
                f"resume cursor {resume.round} outside campaign "
                f"[0, {rounds}){done}"
            )
        start = resume.round
        # The checkpoint's donated pieces (state, schedule, strategy —
        # donate_argnums 0..2) are COPIED before entering the donation
        # thread: a resume=path carry is already fresh off the reader,
        # but an in-memory CarryCheckpoint stays usable after the run
        # (second resume, save_carry_checkpoint), and a caller-built one
        # whose arrays zero-copied numpy never donates live host memory
        # (the fresh_copy hazard).
        state = fresh_copy(resume.state)
    else:
        start = 0

    strategy = None
    if scenario is not None:
        if scenario.rounds != rounds:
            raise ValueError(
                f"scenario block covers {scenario.rounds} round(s), "
                f"sweep asked for {rounds}"
            )
        B, n = state.faulty.shape
        if (scenario.batch, scenario.n) != (B, n):
            raise ValueError(
                f"scenario block is [{scenario.batch}, {scenario.n}] "
                f"per round, state is [{B}, {n}]"
            )
        # Scenario campaigns always thread the (extended) counter block:
        # the IC1/IC2 verdicts ARE the campaign's product, and they ride
        # the existing retire fetch for free.
        with_counters = True
        if resume is not None:
            if resume.strategy is None or resume.counters is None:
                raise ValueError(
                    "resume checkpoint has no strategy/counter planes — "
                    "it was not taken from a scenario campaign"
                )
            strategy = fresh_copy(resume.strategy)
        elif initial_strategy is None:
            strategy = jnp.zeros((B, n), jnp.int8)  # everyone RANDOM
        else:
            strategy = jnp.asarray(initial_strategy, jnp.int8)
            if strategy.shape != (B, n):
                raise ValueError(
                    f"initial_strategy shape {strategy.shape} != {(B, n)}"
                )
            # The plane joins the donated carry, but initial_strategy is
            # NOT part of the documented donation contract (only state
            # is) — and jnp.asarray zero-copies both device arrays and
            # int8 numpy, so without this copy the first dispatch would
            # consume the CALLER's buffer (or worse, donate live host
            # memory — the fresh_copy hazard).
            strategy = strategy.copy()
    elif initial_strategy is not None:
        raise ValueError("initial_strategy needs a scenario block")
    elif resume is not None and resume.strategy is not None:
        raise ValueError(
            "resume checkpoint carries a strategy plane but no scenario "
            "block was passed"
        )

    if resume is not None:
        sched = fresh_copy(resume.schedule)
        if scenario is not None:
            counters = resume.counters
        else:
            # Mismatches raise like the scenario branch above: silently
            # zero-initializing would make the resumed totals look like
            # cumulative campaign totals, and silently dropping would
            # lose counts the original run paid for.
            if with_counters and resume.counters is None:
                raise ValueError(
                    "resume checkpoint has no counter block — the "
                    "original run had with_counters=False"
                )
            if not with_counters and resume.counters is not None:
                raise ValueError(
                    "resume checkpoint carries a counter block; pass "
                    "with_counters=True so the totals keep accumulating"
                )
            counters = resume.counters if with_counters else None
    else:
        sched = make_key_schedule(key)
        if scenario is not None:
            counters = scenario_counters_init()
        elif signed:
            counters = signed_counters_init()
        else:
            counters = agreement_counters_init() if with_counters else None
    n_shards = 1
    if mesh is not None:
        # The mesh scan core (ISSUE 8): shard_map over the "data" axis,
        # per-shard counter blocks, retire-time host tree-reduction.
        # Lazy import — shard.py imports this module's scan cores.
        from ba_tpu.parallel import shard as _shard

        n_shards = _shard.validate_mesh(mesh, state.faulty.shape[0])
        state = jax.tree.map(
            lambda x: put_global(
                mesh, x, P("data", *([None] * (x.ndim - 1)))
            ),
            state,
        )
        sched = jax.tree.map(
            lambda x: put_global(mesh, x, P(*([None] * x.ndim))), sched
        )
        if counters is not None:
            # Per-shard blocks [d, C] (reshard-on-read when resuming a
            # canonical checkpoint block): each shard folds only its
            # local deltas and the host sums the fetched rows at retire
            # — the counter thread never rides a collective.
            counters = _shard.expand_counters(mesh, counters)
        if strategy is not None:
            # The strategy plane shards with the batch it describes.
            strategy = put_global(mesh, strategy, P("data", None))
    elif counters is not None and counters.ndim == 2:
        # A live per-shard block resumed WITHOUT a mesh (d -> 1):
        # collapse to the canonical block — the sum is the invariant.
        counters = counters.sum(axis=0)

    # Engine resolution (ISSUE 13): eager — an explicit kernel request
    # on an unsupported combination must raise HERE, with nothing
    # donated yet; an auto fallback resolves to the scan core and is
    # counted below once stats exists.
    engine_resolved, engine_fallback = resolve_engine(
        engine, m=m, n_shards=n_shards, signed=signed,
        meshed=mesh is not None,
    )
    scen_fn, plain_fn, _, engine_extra = _engine_megasteps(engine_resolved)

    span = rounds - start
    chunks = [rounds_per_dispatch] * (span // rounds_per_dispatch)
    if span % rounds_per_dispatch:
        chunks.append(span % rounds_per_dispatch)

    inflight: collections.deque = collections.deque()
    retired = []  # (histograms, decisions|None) host tuples, dispatch order
    max_in_flight = 0
    retires_before_drain = 0
    warm_dispatches = 0
    request_path_compiles = 0
    n_checkpoints = 0
    n_stalls = 0
    plane_peak_bytes = 0
    plane_shard_peak = 0
    stage_s = 0.0
    sign_ahead_s = 0.0

    # Observability (ISSUE 2): spans + registry feed off the engine's
    # existing dispatch/retire/host_work structure and add NO
    # synchronization — only perf_counter reads (the no-blocking test
    # runs with instrumentation enabled to pin that).  Spans no-op when
    # the tracer is disabled; registry updates are in-memory scalar ops.
    tracer = obs.default_tracer()
    reg = obs.default_registry()
    # Shared with the coalesced serving loop (ISSUE 10): one creation
    # site for the dispatch/retire instrument block, incl. the
    # retired-round counter (ISSUE 9) — the health sampler's rounds/s
    # numerator, exact per-window deltas rather than retire counts
    # times a dial that may degrade mid-campaign.
    inst = _pipeline_instruments(reg)
    lat_h, lag_h, occ_h = inst["lat"], inst["lag"], inst["occ"]
    disp_c, ret_c, rounds_c = inst["disp"], inst["ret"], inst["rounds"]
    sampler = (
        obs.health.HealthSampler(reg, timeout_s=retire_timeout_s)
        if health_every is not None
        else None
    )
    if sampler is not None:
        # Baseline the window on THIS campaign's start: the registry is
        # process-global, and an unprimed first sample would read every
        # earlier sweep's lifetime totals as one giant first window.
        sampler.prime()
    # Shard gauges set UP FRONT, not only at drain (ISSUE 9): a live
    # health sample taken mid-campaign must read THIS sweep's device
    # count and per-device carry share, not the previous sweep's.  The
    # carry's shapes are constant for the whole sweep, so the staged
    # buffers already carry the steady-state figures; the drain-time
    # set below recomputes on the final carry (same values).
    reg.gauge("pipeline_shards").set(n_shards)
    _record_engine(reg, engine_resolved, engine_fallback)
    carry0 = (state, sched, counters, strategy)
    if mesh is not None:
        reg.gauge("pipeline_carry_bytes_per_shard").set(
            _shard.per_shard_nbytes(carry0)
        )
        shares0 = _shard.per_shard_nbytes_all(carry0)
        if shares0:
            mean0 = sum(shares0) / len(shares0)
            reg.gauge("pipeline_carry_imbalance").set(
                round(shares0[0] / mean0, 4) if mean0 else 1.0
            )
    else:
        reg.gauge("pipeline_carry_bytes_per_shard").set(
            sum(x.nbytes for x in jax.tree.leaves(carry0))
        )
    del carry0
    if scenario is not None:
        # Scenario-phase instants + scenario_* counters (ISSUE 5 obs
        # wiring): clock reads and in-memory scalar ops only — the
        # no-blocking test runs with a live scenario block to pin it.
        obs.instant(
            "scenario_start",
            rounds=span,
            batch=state.faulty.shape[0],
            capacity=state.faulty.shape[1],
        )
        reg.counter("scenario_campaigns_total").inc()
        reg.counter("scenario_rounds_total").inc(span)

    # Plane staging (ISSUE 6): one host materialize + async upload per
    # chunk, double-buffered — chunk d+1 stages in the host_work overlap
    # slot while dispatches d-depth..d are in flight.  A chunk with no
    # events reuses ONE staged zero chunk per chunk length (sparse
    # blocks report emptiness in O(log events); across a pure-agreement
    # stretch nothing materializes and nothing uploads).  The staged
    # event arrays are scan `xs`, never donated, so reuse is safe.
    zero_staged: dict = {}  # chunk length -> staged device event dict

    def stage_chunk(lo, hi):
        nonlocal plane_peak_bytes, plane_shard_peak, stage_s
        t0 = time.perf_counter()
        nr = hi - lo
        empty = scenario.chunk_is_empty(lo, hi)
        staged = zero_staged.get(nr) if empty else None
        nbytes = 0
        if staged is None:
            with tracer.span("stage_planes", lo=lo, hi=hi, empty=empty):
                host = scenario.chunk(lo, hi)
                if mesh is None:
                    # Host-array -> jnp.asarray is an ASYNC upload; it
                    # queues behind the in-flight dispatches without
                    # waiting on them.
                    staged = {k: jnp.asarray(v) for k, v in host.items()}
                else:
                    # put_global slices the HOST chunk straight onto the
                    # mesh: each device receives only its [nr, B/d, n]
                    # slice, so peak per-device plane bytes are the
                    # single-device figure divided by the shard count —
                    # the full chunk never lands on one device first.
                    staged = {
                        k: put_global(mesh, v, P(None, "data", None))
                        for k, v in host.items()
                    }
                nbytes = sum(v.nbytes for v in host.values())
            if empty:
                zero_staged[nr] = staged
        plane_peak_bytes = max(plane_peak_bytes, nbytes)
        # Live plane gauges (ISSUE 9): update per STAGE, not only at
        # drain, so a mid-campaign health sample reads THIS sweep's
        # staging — and the imbalance is MEASURED per-device shares of
        # the staged chunk (max/mean via addressable-shard metadata),
        # not a total/shards identity that could never read skewed.
        # In-memory scalar ops + metadata walks; no fetch, no sync.
        reg.gauge("scenario_plane_bytes").set(plane_peak_bytes)
        if mesh is not None:
            shares = _shard.per_shard_nbytes_all(staged)
            if shares:
                # PEAK share, like the non-mesh reading and the drain
                # set: one gauge name must mean one thing at any point
                # in the campaign (a current-chunk reading would make
                # the live value incomparable with the drain value).
                plane_shard_peak = max(plane_shard_peak, shares[0])
                reg.gauge("scenario_plane_bytes_per_shard").set(
                    plane_shard_peak
                )
                mean = sum(shares) / len(shares)
                reg.gauge("scenario_plane_imbalance").set(
                    round(shares[0] / mean, 4) if mean else 1.0
                )
        else:
            reg.gauge("scenario_plane_bytes_per_shard").set(
                plane_peak_bytes
            )
            reg.gauge("scenario_plane_imbalance").set(1.0)
        stage_s += time.perf_counter() - t0
        return staged

    # The sign-ahead host lane (ISSUE 14): per-round signature tables
    # for the NEXT dispatch window are signed on host and their device
    # verification dispatched in the same overlap slot plane staging
    # uses — while dispatches d-depth..d are in flight — and the
    # per-round [B, V] verdicts enter the scan as consumed xs.  Signing
    # is host numpy work, verification an async dispatch (or, on the
    # CPU backend, the native batch verifier — still the host lane):
    # neither ever fetches, so the no-blocking dispatch-count proof
    # runs with the lane live.
    sign_lane = None
    if signed:
        from ba_tpu.parallel import signing as _signing

        sign_lane = _signing.SignAheadLane(
            state.faulty.shape[0], seed=sign_seed
        )

    # Cross-window batch amortization (ISSUE 16): staging chunk i may
    # coalesce the next BA_TPU_SIGN_COALESCE-1 windows into the SAME
    # sign + verify pass (one native batch call at the coalesced size
    # instead of one per window); the extra planes wait host-side in
    # `signed_pending` and later refills pop them for free.  Still the
    # overlap slot, still zero fetches — only the call granularity of
    # the host crypto changes, never a byte of any verdict.
    sign_coalesce = max(
        1, int(os.environ.get("BA_TPU_SIGN_COALESCE", "2"))
    )
    signed_pending: dict = {}

    def stage_signed(chunk_idx, bounds):
        nonlocal sign_ahead_s
        want = bounds[chunk_idx]
        if want not in signed_pending:
            group = [
                bounds[i]
                for i in range(
                    chunk_idx, min(chunk_idx + sign_coalesce, len(bounds))
                )
                if bounds[i] not in signed_pending
            ]
            with tracer.span(
                "sign_ahead", lo=group[0][0], hi=group[-1][1]
            ):
                planes = sign_lane.stage_windows(group)
            signed_pending.update(zip(group, planes))
        staged = signed_pending.pop(want)
        sign_ahead_s = sign_lane.sign_ahead_s
        # Live overlap gauge (the go/no-go reading): cumulative wall
        # the host lane spent signing + dispatching verifies inside
        # the overlap slot.  In-memory scalar ops, no fetch, no sync.
        reg.gauge("host_sign_ahead_s").set(round(sign_ahead_s, 6))
        return staged

    # Carry checkpointing (ISSUE 6): `pending` is (round cursor, a
    # fresh_copy of the live carry — an async device-side copy, not a
    # sync) attached to the dispatch that produced it; the write happens
    # inside that dispatch's retire fetch, where the copy is necessarily
    # ready, so checkpoints ride an EXISTING sync point.
    next_ckpt = start + checkpoint_every if checkpoint_every else None

    def write_checkpoint(round_cursor, carry):
        nonlocal n_checkpoints
        carry_state, carry_sched, carry_counters, carry_strategy = carry
        # Gather-on-write (ISSUE 8) — per-shard counter collapse and
        # layout provenance — lives in ONE place: save_carry_checkpoint
        # (its device_get is this retire's existing sync; the carry copy
        # is necessarily ready here).
        layout = _shard.shard_layout(mesh) if mesh is not None else None
        # checkpoint_path is always set here: the up-front validation
        # rejects checkpoint_every without it.
        written = checkpoint_path.replace("{round}", str(round_cursor))
        nbytes = save_carry_checkpoint(
            written,
            CarryCheckpoint(
                state=carry_state,
                schedule=carry_sched,
                counters=carry_counters,
                strategy=carry_strategy,
                round=round_cursor,
                shard_layout=layout,
                signed=signed,
            ),
            rounds_total=rounds,
            **(checkpoint_meta or {}),
        )
        n_checkpoints += 1
        obs.instant("scenario_checkpoint", round=round_cursor, path=written)
        reg.counter("scenario_checkpoints_total").inc()
        _metrics.emit(
            {
                "event": "scenario_checkpoint",
                "v": _metrics.SCHEMA_VERSION,
                "round": round_cursor,
                "rounds": rounds,
                "scenario": scenario is not None,
                "path": written,
                "bytes": nbytes,
                "shard_layout": layout or {"data": 1},
            }
        )
        if checkpoint_keep_last is not None:
            # Retention is hygiene: prune never raises into the retire.
            _snapshot.prune_checkpoints(checkpoint_path, checkpoint_keep_last)
        if on_checkpoint is not None:
            on_checkpoint(round_cursor, written)

    def declare_stalled(d, lo, hi):
        # Timer-thread path (ISSUE 7 watchdog): the retire fetch for
        # dispatch d has run past retire_timeout_s.  Observe and report
        # only — an in-process hung fetch cannot be interrupted, so
        # recovery (process replacement + checkpoint resume) belongs to
        # the supervisor reading these signals.
        nonlocal n_stalls
        n_stalls += 1
        obs.instant(
            "dispatch_stalled", dispatch=d, lo=lo, hi=hi,
            timeout_s=retire_timeout_s,
        )
        reg.counter("pipeline_stalls_total").inc()
        if on_stall is not None:
            try:
                on_stall(d, retire_timeout_s)
            except Exception:
                # A watchdog reporter must never take down the fetch it
                # is watching (the timer thread would only print a
                # traceback, but the noise reads as a second fault).
                pass

    def retire():
        # t_sub rides the in-flight tuple (perf_counter_ns at submit).
        d, ys, t_sub, pending, lo, hi, d_ctx = inflight.popleft()
        with obs.timed_span("retire", lag_h, dispatch=d) as lag_box:
            # The ONLY blocking operation in the engine: fetch dispatch
            # d's outputs, which waits on a dispatch `depth` behind the
            # queue head while later rounds keep the device busy.  (The
            # xla.annotate marker aligns this host phase with the device
            # timeline when a BA_TPU_XPROF capture is running.)
            with obs.xla.annotate("megastep_retire", dispatch=d):
                watchdog = None
                if retire_timeout_s is not None:
                    watchdog = threading.Timer(
                        retire_timeout_s, declare_stalled, args=(d, lo, hi)
                    )
                    watchdog.daemon = True
                    watchdog.start()
                try:
                    fetch = functools.partial(jax.device_get, ys)
                    if exec_seam is None:
                        host_ys = fetch()
                    else:
                        host_ys = exec_seam(fetch, "retire", d, lo, hi)
                finally:
                    if watchdog is not None:
                        watchdog.cancel()
                if mesh is not None:
                    # Retire-time tree-reduction (ISSUE 8): sum the
                    # fetched per-shard histogram/counter contributions
                    # to the canonical single-device shapes — host
                    # arithmetic on the fetch that just returned, never
                    # a new sync.  on_rows/checkpoint consumers below
                    # therefore see byte-identical blocks at any device
                    # count.
                    host_ys = _shard.reduce_host_ys(
                        host_ys,
                        scenario=scenario is not None,
                        collect_decisions=collect_decisions,
                        with_counters=with_counters,
                    )
                retired.append(host_ys)
        # Latency records BEFORE the checkpoint write: the histogram
        # measures submit->retire of the dispatch itself, and folding a
        # slow disk target's serialization time in would skew the
        # distribution the engine's overlap analysis is built on.
        latency_s = (time.perf_counter_ns() - t_sub) / 1e9
        lat_h.record(latency_s)
        ret_c.inc()
        rounds_c.inc(hi - lo)
        # Flight recorder (ISSUE 9): one line per retired round window
        # — the dispatch→retire leg of the run's timeline, keyed by
        # ROUNDS so replayed windows after a recovery land on the same
        # grid and the assembler dedups them.  A host emit on the fetch
        # that just returned, never a new sync; run_id stamps via the
        # active scope.
        _emit_flight_span(
            d, lo, hi, latency_s, lag_box.elapsed_s or 0.0,
            ctx=d_ctx, t_perf=t_sub / 1e9,
        )
        if on_rows is not None:
            # Before the checkpoint write on purpose: a supervisor
            # persisting campaign history next to each checkpoint needs
            # this dispatch's rows already delivered when on_checkpoint
            # fires for the same retire.
            on_rows(d, lo, hi, host_ys)
        if pending is not None:
            # The checkpoint copy was made right after this dispatch's
            # outputs; the fetch above already waited for them, so this
            # fetch returns without further blocking.
            write_checkpoint(*pending)
        if on_event is not None:
            on_event("retire", d)

    round_base = start
    staged_ev = None
    if scenario is not None and chunks:
        # Chunk 0 stages before the loop (nothing is in flight yet to
        # overlap with); every later chunk stages in the overlap slot.
        staged_ev = stage_chunk(start, start + chunks[0])
    signed_bounds = []
    if signed and chunks:
        # The chunk schedule as round windows, computed once: the
        # coalescing groups in stage_signed address windows by chunk
        # index, ahead of the dispatch cursor.
        cursor = start
        for nr_c in chunks:
            signed_bounds.append((cursor, cursor + nr_c))
            cursor += nr_c
        # Same discipline for the sign-ahead lane: window 0's tables
        # sign before the loop, every later window signs in the slot.
        staged_ev = stage_signed(0, signed_bounds)
    for d, nr in enumerate(chunks):
        # The round window this dispatch covers — threaded through the
        # execution seam and the in-flight tuple so fault injection,
        # stall reports and row delivery all speak in ROUNDS (stable
        # across supervised restarts), never dispatch indices (which
        # reset to 0 on every resume).
        lo, hi = round_base, round_base + nr
        # First dispatch of a fresh static specialization pays trace +
        # compile (or a persistent-cache load) synchronously before the
        # async dispatch; later ones are cached dispatches — the span is
        # named accordingly, and the NAMED axes signature feeds the
        # recompile explainer (a later re-specialization emits a
        # `recompile` record diffing exactly these axes).  The mesh
        # data-axis SIZE rides the axes (ISSUE 8): a sharded input
        # forces a fresh specialization even at equal shapes/statics,
        # and a device-count change now reads as `"data": [1, 8]` in
        # the recompile record — and in the cross-run compile ledger's
        # signature — instead of an unexplained recompile.
        if signed:
            # The signed megastep's own named-axes signature (ISSUE 14):
            # `signed` rides every megastep's axes so a protocol flip is
            # an explained recompile and the cross-run ledger / warmup
            # lattice can address signed specializations.
            axes = {
                "batch": state.faulty.shape[0],
                "capacity": state.faulty.shape[1],
                "rounds": nr,
                "m": m,
                "collapsed": collapsed,
                "unroll": min(unroll, nr),
                "collect_decisions": collect_decisions,
                "signed": True,
                "engine": engine_resolved,
            }
        else:
            axes = {
                "batch": state.faulty.shape[0],
                "capacity": state.faulty.shape[1],
                "rounds": nr,
                "m": m,
                "max_liars": max_liars,
                "unroll": min(unroll, nr),
                "collect_decisions": collect_decisions,
                "counters": with_counters,
                "data": n_shards,
                "scenario": scenario is not None,
                "signed": False,
                # ISSUE 13: an engine flip at equal shapes is an
                # EXPLAINED recompile — `"engine": ["xla", "pallas"]`
                # in the record.
                "engine": engine_resolved,
            }
        # Executable-cache consult (ISSUE 11, single-device only): a hit
        # dispatches the precompiled executable under a plain warm
        # `dispatch` span (_dispatch_span documents why it skips the
        # classifier); a call-time failure evicts + falls back to jit.
        exe = None
        fell_back: list = []
        if executables is not None and mesh is None:
            exe = executables.get(
                "signed_megastep" if signed
                else "scenario_megastep" if scenario is not None
                else "pipeline_megastep",
                axes,
            )
        if scenario is not None:
            # This dispatch's event planes were staged one loop
            # iteration ago (chunk 0 before the loop): the upload is
            # already queued — or finished — behind the in-flight
            # dispatches, never on this dispatch's critical path.
            ev = staged_ev
            kwargs = dict(
                rounds=nr,
                m=m,
                max_liars=max_liars,
                unroll=min(unroll, nr),
                collect_decisions=collect_decisions,
            )
            with _dispatch_span(
                "scenario_megastep", axes, exe is not None,
                dispatch=d, rounds=nr,
            ) as phase:
                with obs.xla.annotate("megastep_dispatch", dispatch=d):
                    # functools.partial (not a lambda) binds the carry
                    # NOW: the seam may retry the zero-arg call, and the
                    # names `state`/`sched`/... rebind right below.
                    if exe is not None:
                        # Statics were baked at AOT lowering: the
                        # executable takes only the traced arguments;
                        # a call-time failure evicts + falls back.
                        call = _warm_call(
                            functools.partial(
                                exe, state, sched, strategy, counters, ev
                            ),
                            functools.partial(
                                scen_fn,
                                state, sched, strategy, counters, ev,
                                **kwargs, **engine_extra,
                            ),
                            executables, "scenario_megastep", axes,
                            fell_back,
                        )
                    elif mesh is None:
                        call = functools.partial(
                            scen_fn,
                            state, sched, strategy, counters, ev,
                            **kwargs, **engine_extra,
                        )
                    else:
                        call = functools.partial(
                            _shard.sharded_scenario_megastep,
                            state, sched, strategy, counters, ev,
                            mesh=mesh, **kwargs,
                        )
                    if exec_seam is None:
                        out = call()
                    else:
                        out = exec_seam(call, "dispatch", d, lo, hi)
            if (
                phase == "compile" and obs.xla.enabled() and mesh is None
                and engine_resolved == "xla"
            ):
                # Donated args keep their shape/dtype metadata after the
                # dispatch consumes them, which is all abstractify reads
                # (same contract the plain path relies on for kwargs).
                # Kernel engines skip introspection: XLA's cost
                # analysis reads a pallas_call as one opaque custom
                # call, and the harvested numbers would be noise.
                obs.xla.introspect(
                    scenario_megastep,
                    "scenario_megastep",
                    obs.xla.abstractify(
                        (out[0], out[1], out[2], counters, ev)
                    ),
                    obs.xla.abstractify(kwargs),
                    axes=axes,
                )
        elif signed:
            # This window's verdict planes were staged one loop
            # iteration ago (window 0 before the loop): the signing
            # already happened in the overlap slot, the verify dispatch
            # is queued — or done — behind the in-flight megasteps.
            ev = staged_ev
            kwargs = dict(
                rounds=nr,
                m=m,
                collapsed=collapsed,
                unroll=min(unroll, nr),
                collect_decisions=collect_decisions,
            )
            with _dispatch_span(
                "signed_megastep", axes, exe is not None,
                dispatch=d, rounds=nr,
            ) as phase:
                with obs.xla.annotate("megastep_dispatch", dispatch=d):
                    if exe is not None:
                        # Statics baked at AOT lowering; a call-time
                        # failure evicts + falls back.
                        call = _warm_call(
                            functools.partial(
                                exe, state, sched, counters, ev
                            ),
                            functools.partial(
                                signed_megastep,
                                state, sched, counters, ev, **kwargs,
                            ),
                            executables, "signed_megastep", axes,
                            fell_back,
                        )
                    else:
                        call = functools.partial(
                            signed_megastep,
                            state, sched, counters, ev, **kwargs,
                        )
                    if exec_seam is None:
                        out = call()
                    else:
                        out = exec_seam(call, "dispatch", d, lo, hi)
            if phase == "compile" and obs.xla.enabled():
                # Device-tier artifact (the scenario-path pattern): the
                # returned carry's signature equals the donated inputs'.
                obs.xla.introspect(
                    signed_megastep,
                    "signed_megastep",
                    obs.xla.abstractify((out[0], out[1], counters, ev)),
                    obs.xla.abstractify(kwargs),
                    axes=axes,
                )
        else:
            kwargs = dict(
                rounds=nr,
                m=m,
                max_liars=max_liars,
                unroll=min(unroll, nr),
                collect_decisions=collect_decisions,
                counters=counters,
            )
            with _dispatch_span(
                "pipeline_megastep", axes, exe is not None,
                dispatch=d, rounds=nr,
            ) as phase:
                with obs.xla.annotate("megastep_dispatch", dispatch=d):
                    if exe is not None:
                        # Only `counters` of the kwargs is a traced
                        # argument; the statics were baked at lowering.
                        # A call-time failure evicts + falls back.
                        call = _warm_call(
                            functools.partial(
                                exe, state, sched, counters=counters
                            ),
                            functools.partial(
                                plain_fn, state, sched, **kwargs,
                                **engine_extra,
                            ),
                            executables, "pipeline_megastep", axes,
                            fell_back,
                        )
                    elif mesh is None:
                        call = functools.partial(
                            plain_fn, state, sched, **kwargs,
                            **engine_extra,
                        )
                    else:
                        call = functools.partial(
                            _shard.sharded_pipeline_megastep,
                            state, sched, mesh=mesh, **kwargs,
                        )
                    if exec_seam is None:
                        out = call()
                    else:
                        out = exec_seam(call, "dispatch", d, lo, hi)
            if (
                phase == "compile" and obs.xla.enabled() and mesh is None
                and engine_resolved == "xla"
            ):
                # Device-tier artifact: AOT-harvest this specialization's
                # cost/memory analysis (flops, bytes, donation-alias
                # evidence).  The abstract signature is read off the
                # RETURNED carry — the megastep threads state/sched
                # through at unchanged shapes/dtypes, so the outputs'
                # signature equals the consumed (donated) inputs' — and
                # is built only on the one-or-two compile dispatches per
                # sweep, keeping the steady-state loop free of tree
                # walks.  After the span and before t_sub, so the extra
                # AOT compile inflates neither compile_time_s nor
                # dispatch latency (it has its own xla_introspect_s
                # histogram).
                obs.xla.introspect(
                    pipeline_megastep,
                    "pipeline_megastep",
                    obs.xla.abstractify((out[0], out[1])),
                    obs.xla.abstractify(kwargs),
                    axes=axes,
                )
        if exe is not None and not fell_back:
            warm_dispatches += 1
        elif phase == "compile" or fell_back:
            request_path_compiles += 1
        round_base = hi
        t_sub = time.perf_counter_ns()
        disp_c.inc()
        if scenario is not None:
            state, sched, strategy = out[0], out[1], out[2]
            ys = out[3:]
            # Cumulative counter rows sit at ys[2] on the scenario path
            # (histograms, leaders, counter_rows[, decisions]); the last
            # row continues the thread — a lazy device slice, not a
            # fetch.
            counters = ys[2][-1]
        else:
            state, sched = out[0], out[1]
            ys = out[2:]
            if with_counters:
                # The stacked cumulative rows' last row continues the
                # counter thread into the next dispatch — a lazy device
                # slice, not a fetch.
                counters = ys[-1][-1]
        pending = None
        if next_ckpt is not None and round_base >= next_ckpt:
            # fresh_copy enqueues device-side copies of the live carry —
            # async like the dispatch itself; the copies serialize to
            # disk inside THIS dispatch's retire fetch.
            pending = (
                round_base,
                fresh_copy((state, sched, counters, strategy)),
            )
            next_ckpt = round_base + checkpoint_every
        if on_event is not None:
            on_event("dispatch", d)
        # Per-window trace position (ISSUE 19): child of the campaign's
        # ambient context, minted at submit, stamped at retire.
        d_ctx = (
            obs.trace.child_context()
            if obs.trace.current() is not None
            else None
        )
        inflight.append((d, ys, t_sub, pending, lo, hi, d_ctx))
        max_in_flight = max(max_in_flight, len(inflight))
        occ_h.record(len(inflight))
        if scenario is not None and d + 1 < len(chunks):
            # The double-buffer refill: materialize + enqueue chunk
            # d+1's upload NOW, while dispatches d-depth..d occupy the
            # device — the host_work overlap slot, extended to plane
            # staging.
            staged_ev = stage_chunk(round_base, round_base + chunks[d + 1])
        elif signed and d + 1 < len(chunks):
            # The sign-ahead refill (ISSUE 14): window d+1's tables sign
            # on host and their verification dispatches NOW, while
            # dispatches d-depth..d occupy the device — host signing
            # leaves the critical path exactly as the chunked
            # setup-overlap machinery in crypto/signed.py proved it
            # could.  With coalescing (ISSUE 16) the window is often
            # already waiting host-side from an earlier group, making
            # this refill a dict pop.
            staged_ev = stage_signed(d + 1, signed_bounds)
        if host_work is not None:
            with tracer.span("host_work", dispatch=d):
                host_work(d)  # overlaps the rounds still executing on device
        if sampler is not None and (d + 1) % health_every == 0:
            # Health sampling (ISSUE 9): same overlap slot as host_work
            # — the device is busy with dispatches d-depth..d while the
            # host takes lock-free registry reads, writes the health_*
            # gauges and (sink live) emits one health_snapshot record.
            with tracer.span("health_sample", dispatch=d):
                sampler.sample(emit=True, dispatch=d)
        while len(inflight) > depth:
            retire()
            retires_before_drain += 1
    while inflight:
        retire()

    # Assemble per-round outputs on the host.  The per-dispatch blocks are
    # already host arrays (fetched at retire), so this is host-side
    # concatenation, not a device sync.
    import numpy as _host_np

    # Shard-labeled gauges (ISSUE 8): the per-device denominators the
    # weak-scaling artifact reads — device count, one device's share of
    # the live carry (addressable-shard bytes: sharded leaves by their
    # local slice, replicated leaves in full).  In-memory scalar ops on
    # live handles; no fetch, no sync.
    carry = (state, sched, counters, strategy)
    if mesh is not None:
        carry_bytes_per_shard = _shard.per_shard_nbytes(carry)
        # Per-device imbalance (ISSUE 9 health view): max device share
        # over the mean — 1.0 when the batch split is even; a skewed
        # mesh layout reads > 1.0.  Metadata walk only, no fetch.
        shares = _shard.per_shard_nbytes_all(carry)
        if shares:
            mean = sum(shares) / len(shares)
            reg.gauge("pipeline_carry_imbalance").set(
                round(shares[0] / mean, 4) if mean else 1.0
            )
    else:
        carry_bytes_per_shard = sum(
            x.nbytes for x in jax.tree.leaves(carry)
        )
    reg.gauge("pipeline_shards").set(n_shards)
    reg.gauge("pipeline_carry_bytes_per_shard").set(carry_bytes_per_shard)

    histograms = _host_np.concatenate([ys[0] for ys in retired])
    result = {
        "histograms": histograms,
        "final_state": state,
        "final_schedule": sched,
        "stats": {
            "rounds": span,
            "start_round": start,
            "dispatches": len(chunks),
            "depth": depth,
            "rounds_per_dispatch": rounds_per_dispatch,
            "max_in_flight": max_in_flight,
            "retires_before_drain": retires_before_drain,
            "warm_dispatches": warm_dispatches,
            "request_path_compiles": request_path_compiles,
            "checkpoints": n_checkpoints,
            "stalls": n_stalls,
            "plane_peak_bytes": plane_peak_bytes,
            "plane_peak_bytes_per_shard": plane_peak_bytes // n_shards,
            "stage_s": round(stage_s, 6),
            "shards": n_shards,
            "carry_bytes_per_shard": carry_bytes_per_shard,
            "health_samples": sampler.samples if sampler is not None else 0,
            "engine": engine_resolved,
            "engine_fallback": engine_fallback,
            "signed": signed,
            "sign_ahead_s": round(sign_ahead_s, 6),
            # Host-crypto pool/cache readings (ISSUE 16): live worker
            # count, wall spent inside pool round-trips, and the
            # signature-table cache's hit tally — the committed
            # bench's per-leg host-crypto story, as engine stats.
            "sign_pool_workers": (
                sign_lane.pool_workers if sign_lane is not None else 0
            ),
            "sign_pool_s": round(
                sign_lane.pool_s if sign_lane is not None else 0.0, 6
            ),
            "sign_cache_hits": (
                sign_lane.cache_hits if sign_lane is not None else 0
            ),
            "host_sign_s": round(
                sign_lane.sign_s if sign_lane is not None else 0.0, 6
            ),
            "host_verify_s": round(
                sign_lane.verify_s if sign_lane is not None else 0.0, 6
            ),
        },
    }
    if scenario is not None:
        # Streaming-staging gauges (ISSUE 6): peak host bytes one chunk
        # materialized (the O(chunk)-not-O(R) claim, as a number) and
        # the total wall time staging spent in the overlap slot.
        reg.gauge("scenario_plane_bytes").set(plane_peak_bytes)
        reg.gauge("scenario_plane_bytes_per_shard").set(
            plane_peak_bytes // n_shards
        )
        reg.gauge("scenario_stage_overlap_s").set(round(stage_s, 6))
        # Everything below is host arithmetic over blocks the retire
        # fetches already brought back — the campaign "drain" adds no
        # synchronization (the no-blocking test runs a live block).
        result["leaders"] = _host_np.concatenate([ys[1] for ys in retired])
        counter_rows = _host_np.concatenate([ys[2] for ys in retired])
        final = {
            name: int(v)
            for name, v in zip(SCENARIO_COUNTER_NAMES, counter_rows[-1])
        }
        result["counters"] = final
        result["counters_per_round"] = counter_rows
        result["final_counters"] = counters
        result["final_strategy"] = strategy
        if collect_decisions:
            result["decisions"] = _host_np.concatenate(
                [ys[3] for ys in retired]
            )
        for name, value in final.items():
            reg.gauge(f"scenario_{name}").set(value)
        obs.instant("scenario_drain", rounds=span, **final)
        return result
    if collect_decisions:
        result["decisions"] = _host_np.concatenate([ys[1] for ys in retired])
    if with_counters:
        # Counter rows were already fetched inside the retire fetches
        # (they ride ys), so everything below is host arithmetic — the
        # "drain" adds no synchronization.  Signed sweeps carry the
        # SIGNED verdict table (the name table is positional — the
        # checkpoint reader pins the same selection).
        counter_rows = _host_np.concatenate([ys[-1] for ys in retired])
        names_table = SIGNED_COUNTER_NAMES if signed else COUNTER_NAMES
        final = {
            name: int(v) for name, v in zip(names_table, counter_rows[-1])
        }
        result["counters"] = final
        result["counters_per_round"] = counter_rows
        result["final_counters"] = counters
        for name, value in final.items():
            reg.gauge(f"agreement_{name}").set(value)
    return result


def scenario_sweep(  # ba-lint: donates(state)
    key: jax.Array,
    state: SimState,
    scenario,
    **kwargs,
):
    """Run a compiled scenario campaign through the pipelined engine.

    The named front door of scenario mode — literally
    ``pipeline_sweep(..., scenario=block)`` with the round count read
    off the block, so every engine dial (``depth``,
    ``rounds_per_dispatch``, ``unroll``, ``mesh``, ``host_work``,
    ``initial_strategy``, ``checkpoint_every``, ``resume``,
    ``engine``, ...) passes
    through unchanged (resuming: ``scenario_sweep(None, None, block,
    resume=ckpt)``).  DONATION: ``state`` is consumed exactly as in
    ``pipeline_sweep`` — thread the returned ``final_state``.
    """
    return pipeline_sweep(key, state, scenario.rounds, scenario=scenario,
                          **kwargs)
