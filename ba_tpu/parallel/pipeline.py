"""Pipelined multi-round sweep engine: donated buffers, on-device key
schedule, depth-k host/device overlap.

The blocking per-round driver this replaces (bench.py's inherited form of
the reference's disease, ba.py:287-301) pays three host costs every round:

1. a host-side per-round key split to derive the round's per-instance
   keys (the key upload rides every dispatch);
2. fresh allocations for every round's state/key buffers;
3. a blocking fetch (host-get or a block-until-ready sync) before the
   next round may even be *dispatched*, so host work and device compute
   strictly alternate.

This engine removes all three:

- **On-device key schedule** (:class:`KeySchedule`): the sweep carries one
  base key (raw uint32 data) plus an int32 round counter ON DEVICE.  Each
  round derives its per-instance keys inside the compiled program —
  ``fold_in(base, counter)`` then a vmapped ``fold_in`` over the instance
  index — so the host never touches PRNG state after launch.  The
  schedule is deterministic and host-reproducible: round ``r``,
  instance ``i`` draws from exactly ``fold_in(fold_in(base, r), i)``
  (threefry derivation is backend-independent), which is what the
  bit-exact equivalence tests pin.
- **Donated buffers**: the round megastep is jitted with
  ``donate_argnums`` on the :class:`SimState` and the key schedule, and
  returns both (state unchanged, counter advanced), so XLA aliases every
  steady-state buffer in place — rounds allocate only their small
  per-round outputs (decision row + 3-bin histogram).  DONATION CONTRACT:
  the state and schedule passed to a dispatch are CONSUMED — callers must
  thread the returned ones and never touch the donated inputs again
  (JAX deletes them; use-after-donate raises, and the tests prove it).
- **Depth-k in-flight dispatch**: the host loop keeps up to ``depth``
  megastep dispatches in flight with NO intermediate sync — JAX dispatch
  is async, and the only blocking operation is *retiring* the oldest
  in-flight dispatch's outputs once the window is full (a fetch of the
  tiny histogram block, which waits on round ``d - depth`` while rounds
  through ``d`` are already queued).  Host work — signing-table prep,
  metrics emission (``utils/metrics.py``) — runs in the ``host_work``
  callback between dispatches, overlapping device compute.
- **``lax.scan`` megastep** with configurable ``unroll``: each dispatch
  covers ``rounds_per_dispatch`` rounds in one compiled scan, the
  whole-sweep generalization of the fused-K idea from the Pallas kernel
  (ops/sweep_step.py) — per-dispatch overhead divides by K with compile
  cost O(unroll), not O(K).

Mesh composition: ``sharded_sweep``'s layout applies unchanged — pass a
mesh and the state shards on its "data" axis while the schedule
replicates; the compiled megastep is the same program, sharding is
propagated by the compiler.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu import obs
from ba_tpu.core.state import SimState
from ba_tpu.core.types import UNDEFINED
from ba_tpu.parallel.multihost import put_global
from ba_tpu.parallel.sweep import agreement_step

# On-device agreement counters (ISSUE 4): one int32 per name, riding the
# donated scan carry as pure data — folded in-scan, drained only at the
# engine's existing depth-delayed retire fetch (counter rows piggyback
# the histogram block), so BA101 and the no-blocking test stay clean.
COUNTER_NAMES = ("quorum_failures", "unanimous_rounds", "equivocation_observed")


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class KeySchedule:
    """Device-resident PRNG schedule: base key data + rounds consumed.

    ``key_data`` is the raw uint32 form of one typed base key (raw so it
    donates/shards like any other buffer and crosses process meshes the
    way ``sharded_sweep`` already ships keys); ``counter`` is a scalar
    int32 advanced by the compiled step itself.  Round ``counter``'s
    instance-``i`` key is ``fold_in(fold_in(base, counter), i)`` —
    derived entirely on device, never uploaded.
    """

    key_data: jax.Array
    counter: jax.Array


def fresh_copy(tree):
    """A live copy of a pytree of arrays (SimState, KeySchedule, ...).

    The one sanctioned way to keep a usable handle on buffers about to
    enter the engine's donation thread: dispatches CONSUME their inputs,
    so a caller that needs the pre-run state afterwards copies it first.
    """
    return jax.tree.map(lambda x: x.copy(), tree)


def make_key_schedule(key: jax.Array, counter: int = 0) -> KeySchedule:
    """Stage a :class:`KeySchedule` for ``key`` starting at round ``counter``.

    The key data is COPIED: the schedule enters the donation thread (the
    engine's dispatches consume and re-emit it), and the caller's ``key``
    must survive that — only the state and the schedule itself are part of
    the donation contract.
    """
    return KeySchedule(
        key_data=jnp.array(jr.key_data(key), copy=True),
        counter=jnp.asarray(counter, jnp.int32),
    )


def round_keys(sched: KeySchedule, batch: int) -> jax.Array:
    """The current round's per-instance typed keys, derived on device.

    Trace-time only (call under jit): one ``fold_in`` of the carried
    counter, then one vmapped ``fold_in`` over the instance index — the
    device-side replacement for the blocking driver's host-side per-round
    key split.  Same threefry derivation strength, and the instance-index
    fold keeps this module free of the banned host-split idiom ba-lint's
    BA102 rule (ba_tpu/analysis, run by scripts/ci.sh) checks for — this
    ``fold_in`` is sanctioned because it sits outside any host loop.
    """
    base = jr.wrap_key_data(sched.key_data)
    kr = jr.fold_in(base, sched.counter)
    return jax.vmap(jr.fold_in, in_axes=(None, 0))(
        kr, jnp.arange(batch, dtype=jnp.uint32)
    )


def agreement_counters_init() -> jax.Array:
    """A zeroed on-device counter block (one int32 per COUNTER_NAMES)."""
    return jnp.zeros((len(COUNTER_NAMES),), jnp.int32)


def agreement_counter_delta(out: dict, state: SimState) -> jax.Array:
    """One round's counter increments, derived ON DEVICE (trace-time,
    called inside the compiled scan body) from ``agreement_step``'s
    outputs — the paper's agreement semantics as values, not emissions:

    - ``quorum_failures``: instances whose quorum decision this round is
      UNDEFINED (no side reached the majority-of-majorities threshold);
    - ``unanimous_rounds``: 1 when every instance in the batch decided
      alike (the histogram concentrates in one bin);
    - ``equivocation_observed``: instances containing at least one live
      traitor whose alive lieutenants' majorities DISAGREE — the visible
      footprint of per-recipient equivocation (a faulty responder
      answering different queriers differently; honest-only instances
      always tally unanimously under an honest leader).

    Every count is host-reproducible from the decisions/majorities
    streams (tests/test_pipeline.py pins the bit-match).
    """
    decision = out["decision"]
    maj = out["majorities"]
    quorum_failures = jnp.sum(decision == UNDEFINED, dtype=jnp.int32)
    unanimous = (out["histogram"].max() == decision.shape[0]).astype(jnp.int32)
    idx = jnp.arange(state.faulty.shape[1])[None, :]
    lieutenants = state.alive & (idx != state.leader[:, None])
    big = jnp.asarray(127, maj.dtype)
    mmax = jnp.max(jnp.where(lieutenants, maj, -big), axis=1)
    mmin = jnp.min(jnp.where(lieutenants, maj, big), axis=1)
    disagree = (mmax != mmin) & lieutenants.any(axis=1)
    traitor_present = (state.faulty & state.alive).any(axis=1)
    equivocation = jnp.sum(disagree & traitor_present, dtype=jnp.int32)
    return jnp.stack([quorum_failures, unanimous, equivocation])


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "max_liars", "unroll", "collect_decisions"),
    donate_argnums=(0, 1),
)
def pipeline_megastep(
    state: SimState,
    sched: KeySchedule,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    counters: jax.Array | None = None,
):
    """``rounds`` agreement rounds in one donated ``lax.scan`` dispatch.

    Returns ``(state, sched, histograms[, decisions][, counter_rounds])``:
    the state rides through unchanged and the schedule advances by
    ``rounds``, both aliased onto the donated inputs so steady-state
    dispatches allocate nothing but the outputs (``histograms``
    [rounds, 3] int32 and, when ``collect_decisions``, ``decisions``
    [rounds, B] int8).

    ``counters`` (a block from :func:`agreement_counters_init`, or the
    previous dispatch's last ``counter_rounds`` row) enables the
    on-device agreement counters: the block rides the scan carry,
    :func:`agreement_counter_delta` folds each round's increments in,
    and ``counter_rounds`` [rounds, len(COUNTER_NAMES)] holds the
    CUMULATIVE block after every round — its last row both continues the
    counter thread into the next dispatch and reaches the host for free
    inside the existing retire fetch.  Counters are pure data in the
    compiled program: no host emission, no added synchronization.

    Bit-compat contract: round ``sched.counter + r`` computes exactly
    ``agreement_step(round_keys(<schedule at counter + r>, B), state)`` —
    the round-by-round blocking driver under the same key schedule
    produces identical decisions and histograms (tests/test_pipeline.py),
    with or without the counter block (counters read the step's outputs,
    never its RNG).
    """
    with_counters = counters is not None

    def body(carry, _):
        if with_counters:
            st, sc, ctr = carry
        else:
            st, sc = carry
        keys = round_keys(sc, st.batch)
        out = agreement_step(keys, st, m=m, max_liars=max_liars)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        ys = (out["histogram"],)
        if collect_decisions:
            ys += (out["decision"],)
        if with_counters:
            ctr = ctr + agreement_counter_delta(out, st)
            return (st, nxt, ctr), ys + (ctr,)
        return (st, nxt), ys

    init = (state, sched, counters) if with_counters else (state, sched)
    carry, ys = jax.lax.scan(body, init, None, length=rounds, unroll=unroll)
    return (carry[0], carry[1], *ys)


def pipeline_sweep(
    key: jax.Array,
    state: SimState,
    rounds: int,
    *,
    m: int = 1,
    max_liars: int | None = None,
    depth: int = 2,
    rounds_per_dispatch: int = 1,
    unroll: int = 1,
    collect_decisions: bool = False,
    with_counters: bool = False,
    host_work=None,
    mesh: Mesh | None = None,
    on_event=None,
):
    """Run ``rounds`` sweep rounds through the depth-k pipelined engine.

    Dispatches ``ceil(rounds / rounds_per_dispatch)`` donated megasteps
    (the last one sized to the remainder), keeping ``depth`` of them
    un-retired between loop iterations — so immediately after a new
    dispatch (and before its retire check) up to ``depth + 1`` are
    momentarily in flight, which is what ``stats["max_in_flight"]``
    reports.  Between a dispatch and the retire check the
    ``host_work(dispatch_index)`` callback runs host-side work overlapped
    with device compute.  ``on_event(kind, index)`` (kinds ``"dispatch"``
    / ``"retire"``) instruments the schedule for the dispatch-count tests.

    DONATION: ``state`` is consumed by the first dispatch — use the
    returned ``final_state``.  With ``mesh`` set the engine first lays the
    batch out on the mesh's "data" axis (``sharded_sweep``'s placement,
    multi-process safe via ``put_global``) and donation recycles the
    sharded copies instead.

    Returns a dict:

    - ``histograms`` [rounds, 3] host int32 — per-round [retreat, attack,
      undefined] decision counts (fetched at retire time, never earlier);
    - ``decisions`` [rounds, B] host int8 when ``collect_decisions``;
    - with ``with_counters``: ``counters`` — a ``{name: int}`` dict of
      the final on-device agreement counter block (COUNTER_NAMES),
      ``counters_per_round`` [rounds, len(COUNTER_NAMES)] host int32
      cumulative rows, and ``final_counters`` — the live device block
      continuing the counter thread.  Counter rows piggyback the
      existing retire fetch (they ride ``ys``), so enabling them adds
      ZERO host synchronization; the final values also land in registry
      gauges ``agreement_<name>``;
    - ``final_state`` / ``final_schedule`` — the live (un-donated) pair,
      ready to continue the sweep;
    - ``stats`` — dispatch bookkeeping: ``dispatches``, ``depth``,
      ``rounds_per_dispatch``, ``max_in_flight``, and
      ``retires_before_drain`` (how many retires the steady-state loop
      performed; the rest drained at the end).
    """
    if rounds < 1:
        raise ValueError(f"rounds={rounds} must be >= 1")
    if depth < 1:
        raise ValueError(f"depth={depth} must be >= 1")
    if rounds_per_dispatch < 1:
        raise ValueError(
            f"rounds_per_dispatch={rounds_per_dispatch} must be >= 1"
        )
    if unroll < 1:
        raise ValueError(f"unroll={unroll} must be >= 1")

    sched = make_key_schedule(key)
    counters = agreement_counters_init() if with_counters else None
    if mesh is not None:
        state = jax.tree.map(
            lambda x: put_global(
                mesh, x, P("data", *([None] * (x.ndim - 1)))
            ),
            state,
        )
        sched = jax.tree.map(
            lambda x: put_global(mesh, x, P(*([None] * x.ndim))), sched
        )
        if counters is not None:
            # Replicated like the schedule: every shard folds the same
            # global deltas (agreement_counter_delta reduces over the
            # full batch, which XLA turns into the histogram's psum).
            counters = put_global(mesh, counters, P(None))

    chunks = [rounds_per_dispatch] * (rounds // rounds_per_dispatch)
    if rounds % rounds_per_dispatch:
        chunks.append(rounds % rounds_per_dispatch)

    inflight: collections.deque = collections.deque()
    retired = []  # (histograms, decisions|None) host tuples, dispatch order
    max_in_flight = 0
    retires_before_drain = 0

    # Observability (ISSUE 2): spans + registry feed off the engine's
    # existing dispatch/retire/host_work structure and add NO
    # synchronization — only perf_counter reads (the no-blocking test
    # runs with instrumentation enabled to pin that).  Spans no-op when
    # the tracer is disabled; registry updates are in-memory scalar ops.
    tracer = obs.default_tracer()
    reg = obs.default_registry()
    lat_h = reg.histogram("pipeline_dispatch_latency_s")
    lag_h = reg.histogram("pipeline_retire_lag_s")
    occ_h = reg.histogram("pipeline_depth_occupancy", base=1.0, n_buckets=16)
    disp_c = reg.counter("pipeline_dispatches_total")
    ret_c = reg.counter("pipeline_retires_total")

    def retire():
        # t_sub rides the in-flight tuple (perf_counter_ns at submit).
        d, ys, t_sub = inflight.popleft()
        with obs.timed_span("retire", lag_h, dispatch=d):
            # The ONLY blocking operation in the engine: fetch dispatch
            # d's outputs, which waits on a dispatch `depth` behind the
            # queue head while later rounds keep the device busy.  (The
            # xla.annotate marker aligns this host phase with the device
            # timeline when a BA_TPU_XPROF capture is running.)
            with obs.xla.annotate("megastep_retire", dispatch=d):
                retired.append(jax.device_get(ys))
        lat_h.record((time.perf_counter_ns() - t_sub) / 1e9)
        ret_c.inc()
        if on_event is not None:
            on_event("retire", d)

    for d, nr in enumerate(chunks):
        # First dispatch of a fresh static specialization pays trace +
        # compile (or a persistent-cache load) synchronously before the
        # async dispatch; later ones are cached dispatches — the span is
        # named accordingly, and the NAMED axes signature feeds the
        # recompile explainer (a later re-specialization emits a
        # `recompile` record diffing exactly these axes).  "meshed"
        # rides the axes because sharded inputs force a fresh
        # specialization even at equal shapes/statics.
        kwargs = dict(
            rounds=nr,
            m=m,
            max_liars=max_liars,
            unroll=min(unroll, nr),
            collect_decisions=collect_decisions,
            counters=counters,
        )
        axes = {
            "batch": state.faulty.shape[0],
            "capacity": state.faulty.shape[1],
            "rounds": nr,
            "m": m,
            "max_liars": max_liars,
            "unroll": min(unroll, nr),
            "collect_decisions": collect_decisions,
            "counters": with_counters,
            "meshed": mesh is not None,
        }
        with obs.compile_or_dispatch_span(
            "pipeline_megastep", axes=axes, dispatch=d, rounds=nr
        ) as phase:
            with obs.xla.annotate("megastep_dispatch", dispatch=d):
                out = pipeline_megastep(state, sched, **kwargs)
        if phase == "compile" and obs.xla.enabled():
            # Device-tier artifact: AOT-harvest this specialization's
            # cost/memory analysis (flops, bytes, donation-alias
            # evidence).  The abstract signature is read off the
            # RETURNED carry — the megastep threads state/sched through
            # at unchanged shapes/dtypes, so the outputs' signature
            # equals the consumed (donated) inputs' — and is built only
            # on the one-or-two compile dispatches per sweep, keeping
            # the steady-state loop free of tree walks.  After the span
            # and before t_sub, so the extra AOT compile inflates
            # neither compile_time_s nor dispatch latency (it has its
            # own xla_introspect_s histogram).
            obs.xla.introspect(
                pipeline_megastep,
                "pipeline_megastep",
                obs.xla.abstractify((out[0], out[1])),
                obs.xla.abstractify(kwargs),
                axes=axes,
            )
        t_sub = time.perf_counter_ns()
        disp_c.inc()
        state, sched = out[0], out[1]
        ys = out[2:]
        if with_counters:
            # The stacked cumulative rows' last row continues the
            # counter thread into the next dispatch — a lazy device
            # slice, not a fetch.
            counters = ys[-1][-1]
        if on_event is not None:
            on_event("dispatch", d)
        inflight.append((d, ys, t_sub))
        max_in_flight = max(max_in_flight, len(inflight))
        occ_h.record(len(inflight))
        if host_work is not None:
            with tracer.span("host_work", dispatch=d):
                host_work(d)  # overlaps the rounds still executing on device
        while len(inflight) > depth:
            retire()
            retires_before_drain += 1
    while inflight:
        retire()

    # Assemble per-round outputs on the host.  The per-dispatch blocks are
    # already host arrays (fetched at retire), so this is host-side
    # concatenation, not a device sync.
    import numpy as _host_np

    histograms = _host_np.concatenate([ys[0] for ys in retired])
    result = {
        "histograms": histograms,
        "final_state": state,
        "final_schedule": sched,
        "stats": {
            "rounds": rounds,
            "dispatches": len(chunks),
            "depth": depth,
            "rounds_per_dispatch": rounds_per_dispatch,
            "max_in_flight": max_in_flight,
            "retires_before_drain": retires_before_drain,
        },
    }
    if collect_decisions:
        result["decisions"] = _host_np.concatenate([ys[1] for ys in retired])
    if with_counters:
        # Counter rows were already fetched inside the retire fetches
        # (they ride ys), so everything below is host arithmetic — the
        # "drain" adds no synchronization.
        counter_rows = _host_np.concatenate([ys[-1] for ys in retired])
        final = {
            name: int(v) for name, v in zip(COUNTER_NAMES, counter_rows[-1])
        }
        result["counters"] = final
        result["counters_per_round"] = counter_rows
        result["final_counters"] = counters
        for name, value in final.items():
            reg.gauge(f"agreement_{name}").set(value)
    return result
