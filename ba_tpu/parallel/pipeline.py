"""Pipelined multi-round sweep engine: donated buffers, on-device key
schedule, depth-k host/device overlap.

The blocking per-round driver this replaces (bench.py's inherited form of
the reference's disease, ba.py:287-301) pays three host costs every round:

1. a host-side per-round key split to derive the round's per-instance
   keys (the key upload rides every dispatch);
2. fresh allocations for every round's state/key buffers;
3. a blocking fetch (host-get or a block-until-ready sync) before the
   next round may even be *dispatched*, so host work and device compute
   strictly alternate.

This engine removes all three:

- **On-device key schedule** (:class:`KeySchedule`): the sweep carries one
  base key (raw uint32 data) plus an int32 round counter ON DEVICE.  Each
  round derives its per-instance keys inside the compiled program —
  ``fold_in(base, counter)`` then a vmapped ``fold_in`` over the instance
  index — so the host never touches PRNG state after launch.  The
  schedule is deterministic and host-reproducible: round ``r``,
  instance ``i`` draws from exactly ``fold_in(fold_in(base, r), i)``
  (threefry derivation is backend-independent), which is what the
  bit-exact equivalence tests pin.
- **Donated buffers**: the round megastep is jitted with
  ``donate_argnums`` on the :class:`SimState` and the key schedule, and
  returns both (state unchanged, counter advanced), so XLA aliases every
  steady-state buffer in place — rounds allocate only their small
  per-round outputs (decision row + 3-bin histogram).  DONATION CONTRACT:
  the state and schedule passed to a dispatch are CONSUMED — callers must
  thread the returned ones and never touch the donated inputs again
  (JAX deletes them; use-after-donate raises, and the tests prove it).
- **Depth-k in-flight dispatch**: the host loop keeps up to ``depth``
  megastep dispatches in flight with NO intermediate sync — JAX dispatch
  is async, and the only blocking operation is *retiring* the oldest
  in-flight dispatch's outputs once the window is full (a fetch of the
  tiny histogram block, which waits on round ``d - depth`` while rounds
  through ``d`` are already queued).  Host work — signing-table prep,
  metrics emission (``utils/metrics.py``) — runs in the ``host_work``
  callback between dispatches, overlapping device compute.
- **``lax.scan`` megastep** with configurable ``unroll``: each dispatch
  covers ``rounds_per_dispatch`` rounds in one compiled scan, the
  whole-sweep generalization of the fused-K idea from the Pallas kernel
  (ops/sweep_step.py) — per-dispatch overhead divides by K with compile
  cost O(unroll), not O(K).

Mesh composition: ``sharded_sweep``'s layout applies unchanged — pass a
mesh and the state shards on its "data" axis while the schedule
replicates; the compiled megastep is the same program, sharding is
propagated by the compiler.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu import obs
from ba_tpu.core.state import SimState
from ba_tpu.parallel.multihost import put_global
from ba_tpu.parallel.sweep import agreement_step


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class KeySchedule:
    """Device-resident PRNG schedule: base key data + rounds consumed.

    ``key_data`` is the raw uint32 form of one typed base key (raw so it
    donates/shards like any other buffer and crosses process meshes the
    way ``sharded_sweep`` already ships keys); ``counter`` is a scalar
    int32 advanced by the compiled step itself.  Round ``counter``'s
    instance-``i`` key is ``fold_in(fold_in(base, counter), i)`` —
    derived entirely on device, never uploaded.
    """

    key_data: jax.Array
    counter: jax.Array


def fresh_copy(tree):
    """A live copy of a pytree of arrays (SimState, KeySchedule, ...).

    The one sanctioned way to keep a usable handle on buffers about to
    enter the engine's donation thread: dispatches CONSUME their inputs,
    so a caller that needs the pre-run state afterwards copies it first.
    """
    return jax.tree.map(lambda x: x.copy(), tree)


def make_key_schedule(key: jax.Array, counter: int = 0) -> KeySchedule:
    """Stage a :class:`KeySchedule` for ``key`` starting at round ``counter``.

    The key data is COPIED: the schedule enters the donation thread (the
    engine's dispatches consume and re-emit it), and the caller's ``key``
    must survive that — only the state and the schedule itself are part of
    the donation contract.
    """
    return KeySchedule(
        key_data=jnp.array(jr.key_data(key), copy=True),
        counter=jnp.asarray(counter, jnp.int32),
    )


def round_keys(sched: KeySchedule, batch: int) -> jax.Array:
    """The current round's per-instance typed keys, derived on device.

    Trace-time only (call under jit): one ``fold_in`` of the carried
    counter, then one vmapped ``fold_in`` over the instance index — the
    device-side replacement for the blocking driver's host-side per-round
    key split.  Same threefry derivation strength, and the instance-index
    fold keeps this module free of the banned host-split idiom ba-lint's
    BA102 rule (ba_tpu/analysis, run by scripts/ci.sh) checks for — this
    ``fold_in`` is sanctioned because it sits outside any host loop.
    """
    base = jr.wrap_key_data(sched.key_data)
    kr = jr.fold_in(base, sched.counter)
    return jax.vmap(jr.fold_in, in_axes=(None, 0))(
        kr, jnp.arange(batch, dtype=jnp.uint32)
    )


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "max_liars", "unroll", "collect_decisions"),
    donate_argnums=(0, 1),
)
def pipeline_megastep(
    state: SimState,
    sched: KeySchedule,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
):
    """``rounds`` agreement rounds in one donated ``lax.scan`` dispatch.

    Returns ``(state, sched, histograms[, decisions])``: the state rides
    through unchanged and the schedule advances by ``rounds``, both
    aliased onto the donated inputs so steady-state dispatches allocate
    nothing but the outputs (``histograms`` [rounds, 3] int32 and, when
    ``collect_decisions``, ``decisions`` [rounds, B] int8).

    Bit-compat contract: round ``sched.counter + r`` computes exactly
    ``agreement_step(round_keys(<schedule at counter + r>, B), state)`` —
    the round-by-round blocking driver under the same key schedule
    produces identical decisions and histograms (tests/test_pipeline.py).
    """

    def body(carry, _):
        st, sc = carry
        keys = round_keys(sc, st.batch)
        out = agreement_step(keys, st, m=m, max_liars=max_liars)
        nxt = KeySchedule(sc.key_data, sc.counter + 1)
        ys = (
            (out["histogram"], out["decision"])
            if collect_decisions
            else out["histogram"]
        )
        return (st, nxt), ys

    (state, sched), ys = jax.lax.scan(
        body, (state, sched), None, length=rounds, unroll=unroll
    )
    if collect_decisions:
        return state, sched, ys[0], ys[1]
    return state, sched, ys


def pipeline_sweep(
    key: jax.Array,
    state: SimState,
    rounds: int,
    *,
    m: int = 1,
    max_liars: int | None = None,
    depth: int = 2,
    rounds_per_dispatch: int = 1,
    unroll: int = 1,
    collect_decisions: bool = False,
    host_work=None,
    mesh: Mesh | None = None,
    on_event=None,
):
    """Run ``rounds`` sweep rounds through the depth-k pipelined engine.

    Dispatches ``ceil(rounds / rounds_per_dispatch)`` donated megasteps
    (the last one sized to the remainder), keeping ``depth`` of them
    un-retired between loop iterations — so immediately after a new
    dispatch (and before its retire check) up to ``depth + 1`` are
    momentarily in flight, which is what ``stats["max_in_flight"]``
    reports.  Between a dispatch and the retire check the
    ``host_work(dispatch_index)`` callback runs host-side work overlapped
    with device compute.  ``on_event(kind, index)`` (kinds ``"dispatch"``
    / ``"retire"``) instruments the schedule for the dispatch-count tests.

    DONATION: ``state`` is consumed by the first dispatch — use the
    returned ``final_state``.  With ``mesh`` set the engine first lays the
    batch out on the mesh's "data" axis (``sharded_sweep``'s placement,
    multi-process safe via ``put_global``) and donation recycles the
    sharded copies instead.

    Returns a dict:

    - ``histograms`` [rounds, 3] host int32 — per-round [retreat, attack,
      undefined] decision counts (fetched at retire time, never earlier);
    - ``decisions`` [rounds, B] host int8 when ``collect_decisions``;
    - ``final_state`` / ``final_schedule`` — the live (un-donated) pair,
      ready to continue the sweep;
    - ``stats`` — dispatch bookkeeping: ``dispatches``, ``depth``,
      ``rounds_per_dispatch``, ``max_in_flight``, and
      ``retires_before_drain`` (how many retires the steady-state loop
      performed; the rest drained at the end).
    """
    if rounds < 1:
        raise ValueError(f"rounds={rounds} must be >= 1")
    if depth < 1:
        raise ValueError(f"depth={depth} must be >= 1")
    if rounds_per_dispatch < 1:
        raise ValueError(
            f"rounds_per_dispatch={rounds_per_dispatch} must be >= 1"
        )
    if unroll < 1:
        raise ValueError(f"unroll={unroll} must be >= 1")

    sched = make_key_schedule(key)
    if mesh is not None:
        state = jax.tree.map(
            lambda x: put_global(
                mesh, x, P("data", *([None] * (x.ndim - 1)))
            ),
            state,
        )
        sched = jax.tree.map(
            lambda x: put_global(mesh, x, P(*([None] * x.ndim))), sched
        )

    chunks = [rounds_per_dispatch] * (rounds // rounds_per_dispatch)
    if rounds % rounds_per_dispatch:
        chunks.append(rounds % rounds_per_dispatch)

    inflight: collections.deque = collections.deque()
    retired = []  # (histograms, decisions|None) host tuples, dispatch order
    max_in_flight = 0
    retires_before_drain = 0

    # Observability (ISSUE 2): spans + registry feed off the engine's
    # existing dispatch/retire/host_work structure and add NO
    # synchronization — only perf_counter reads (the no-blocking test
    # runs with instrumentation enabled to pin that).  Spans no-op when
    # the tracer is disabled; registry updates are in-memory scalar ops.
    tracer = obs.default_tracer()
    reg = obs.default_registry()
    lat_h = reg.histogram("pipeline_dispatch_latency_s")
    lag_h = reg.histogram("pipeline_retire_lag_s")
    occ_h = reg.histogram("pipeline_depth_occupancy", base=1.0, n_buckets=16)
    disp_c = reg.counter("pipeline_dispatches_total")
    ret_c = reg.counter("pipeline_retires_total")

    def retire():
        # t_sub rides the in-flight tuple (perf_counter_ns at submit).
        d, ys, t_sub = inflight.popleft()
        with obs.timed_span("retire", lag_h, dispatch=d):
            # The ONLY blocking operation in the engine: fetch dispatch
            # d's outputs, which waits on a dispatch `depth` behind the
            # queue head while later rounds keep the device busy.
            retired.append(jax.device_get(ys))
        lat_h.record((time.perf_counter_ns() - t_sub) / 1e9)
        ret_c.inc()
        if on_event is not None:
            on_event("retire", d)

    for d, nr in enumerate(chunks):
        # First dispatch of a fresh static specialization pays trace +
        # compile (or a persistent-cache load) synchronously before the
        # async dispatch; later ones are cached dispatches — the span is
        # named accordingly (obs.compile_or_dispatch_span).
        ckey = (
            "pipeline_megastep",
            state.faulty.shape,
            nr,
            m,
            max_liars,
            min(unroll, nr),
            collect_decisions,
            # Sharded inputs force a fresh specialization even at equal
            # shapes/statics — key on it so the meshed first call still
            # classifies as "compile".
            mesh is not None,
        )
        with obs.compile_or_dispatch_span(ckey, dispatch=d, rounds=nr):
            out = pipeline_megastep(
                state,
                sched,
                rounds=nr,
                m=m,
                max_liars=max_liars,
                unroll=min(unroll, nr),
                collect_decisions=collect_decisions,
            )
        t_sub = time.perf_counter_ns()
        disp_c.inc()
        state, sched = out[0], out[1]
        ys = out[2:]
        if on_event is not None:
            on_event("dispatch", d)
        inflight.append((d, ys, t_sub))
        max_in_flight = max(max_in_flight, len(inflight))
        occ_h.record(len(inflight))
        if host_work is not None:
            with tracer.span("host_work", dispatch=d):
                host_work(d)  # overlaps the rounds still executing on device
        while len(inflight) > depth:
            retire()
            retires_before_drain += 1
    while inflight:
        retire()

    # Assemble per-round outputs on the host.  The per-dispatch blocks are
    # already host arrays (fetched at retire), so this is host-side
    # concatenation, not a device sync.
    import numpy as _host_np

    histograms = _host_np.concatenate([ys[0] for ys in retired])
    result = {
        "histograms": histograms,
        "final_state": state,
        "final_schedule": sched,
        "stats": {
            "rounds": rounds,
            "dispatches": len(chunks),
            "depth": depth,
            "rounds_per_dispatch": rounds_per_dispatch,
            "max_in_flight": max_in_flight,
            "retires_before_drain": retires_before_drain,
        },
    }
    if collect_decisions:
        result["decisions"] = _host_np.concatenate([ys[1] for ys in retired])
    return result
