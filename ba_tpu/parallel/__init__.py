"""Device-mesh parallelism: the TPU-native communication backend.

The reference's distributed backend is RPyC point-to-point TCP with Python
``for``-loops as broadcast/gather (SURVEY.md section 2.3, ba.py:159-223).
Here the same roles are played by XLA collectives over ICI/DCN on a
``jax.sharding.Mesh``:

- instance axis ("data"): embarrassingly-parallel consensus instances —
  the 10k-instance sweep of BASELINE.json config #5 (``sweep``), plus the
  multi-round ``failover_sweep`` with on-device leader re-election;
- node axis ("node"): generals of ONE large cluster sharded across chips,
  with ``all_gather``/``psum`` replacing the O(n^2) RPC mesh — the
  sequence-parallelism analogue for n=1024-scale clusters, covering all
  three protocols: OM(1) (``node_parallel``), the recursive OM(m) EIG
  tree (``eig_parallel``), and SM(m) signed messages (``sm_parallel``).

Multi-host: every path here is plain ``shard_map``/``NamedSharding`` over
whatever mesh the caller builds, so scaling past one host is the standard
JAX recipe, packaged in ``multihost``: ``init_distributed()`` (the join
protocol) then ``make_global_mesh()`` — the "data" axis spans hosts (its
per-round traffic is a 3-int psum, DCN-tolerant) and "node" stays inside
a slice (its all_gathers want ICI bandwidth).
"""

from ba_tpu.parallel.mesh import make_mesh
from ba_tpu.parallel.multihost import init_distributed, make_global_mesh, put_global
from ba_tpu.parallel.pipeline import (
    COUNTER_NAMES,
    ENGINES,
    SCENARIO_COUNTER_NAMES,
    SIGNED_COUNTER_NAMES,
    CarryCheckpoint,
    KeySchedule,
    agreement_counters_init,
    engine_support,
    fresh_copy,
    load_carry_checkpoint,
    make_key_schedule,
    pipeline_megastep,
    pipeline_sweep,
    resolve_engine,
    round_keys,
    save_carry_checkpoint,
    scenario_counters_init,
    scenario_megastep,
    scenario_sweep,
    signed_counters_init,
    signed_megastep,
)
from ba_tpu.parallel.sweep import (
    bucketed_sweep_states,
    failover_sweep,
    make_sweep_state,
    sharded_sweep,
)
from ba_tpu.parallel.node_parallel import om1_node_sharded
from ba_tpu.parallel.eig_parallel import eig_node_sharded
from ba_tpu.parallel.sm_parallel import sm_node_sharded

__all__ = [
    "make_mesh",
    "init_distributed",
    "make_global_mesh",
    "put_global",
    "COUNTER_NAMES",
    "ENGINES",
    "SCENARIO_COUNTER_NAMES",
    "SIGNED_COUNTER_NAMES",
    "CarryCheckpoint",
    "KeySchedule",
    "agreement_counters_init",
    "fresh_copy",
    "load_carry_checkpoint",
    "make_key_schedule",
    "save_carry_checkpoint",
    "engine_support",
    "pipeline_megastep",
    "pipeline_sweep",
    "resolve_engine",
    "round_keys",
    "scenario_counters_init",
    "scenario_megastep",
    "scenario_sweep",
    "signed_counters_init",
    "signed_megastep",
    "failover_sweep",
    "sharded_sweep",
    "make_sweep_state",
    "bucketed_sweep_states",
    "om1_node_sharded",
    "eig_node_sharded",
    "sm_node_sharded",
]
