"""Node-axis parallelism: one huge cluster sharded across chips.

The long-context story of this framework (SURVEY.md section 6): the scaling
axis is n = generals, and OM(1)'s round-2 answer cube is O(B * n^2) — at
n=1024 that is the object that must be sharded, exactly like a sequence-
parallel attention matrix.  Layout:

- receivers (the asker axis i) shard across the mesh's "node" axis;
- the round-1 ``received`` row [B, n] is *recomputed replicated*: every
  node shard derives the identical row from a shared per-data-shard PRNG
  key, so no cross-chip broadcast is needed at all (the reference's O(n^2)
  get_order() RPC mesh, ba.py:169-186, becomes a local masked select —
  every chip answers for its own receivers);
- quorum counts come back with a single ``psum`` over "node"
  (the majority-of-majorities gather, ba.py:197-223).

Per-chip memory is O(B * n * n/devices); ICI traffic is O(B * n) — the
all-to-all never materialises across chips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu.core.rng import coin_bits
from ba_tpu.core.quorum import quorum_decision, strict_majority
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.parallel.mesh import cached_jit, shard_map
from ba_tpu.parallel.multihost import put_global


def om1_node_sharded(mesh: Mesh, key: jax.Array, state: SimState):
    """OM(1) agreement with generals sharded over the "node" mesh axis.

    state: SimState with batch B (sharded over "data") and n divisible by
    the node-axis size.  Returns the ``om1_agreement``-style dict with
    ``majorities`` sharded [B, n] and replicated quorum outputs.
    """
    B, n = state.faulty.shape
    n_node = mesh.shape["node"]
    assert n % n_node == 0, f"node axis {n_node} must divide n={n}"

    def shard_fn(key_raw, order, leader, faulty, alive):
        # Shapes in here are per-shard: order/leader [b], faulty/alive
        # [b, n] (replicated node axis), receivers i owned: n_local.
        key = jr.wrap_key_data(key_raw)
        node_idx = jax.lax.axis_index("node")
        data_idx = jax.lax.axis_index("data")
        b = order.shape[0]
        n_local = n // n_node
        i_global = node_idx * n_local + jnp.arange(n_local)  # [n_local]

        # Round 1 (replicated): same key on every node shard -> every chip
        # derives the identical received row, no broadcast needed beyond
        # the scalar order. Coins keyed per data shard only.
        k_r1 = jr.fold_in(key, data_idx)
        coins1 = coin_bits(k_r1, (b, n))
        leader_faulty = jnp.take_along_axis(faulty, leader[:, None], axis=1)
        received = jnp.where(leader_faulty, coins1, order[:, None])
        is_leader_j = jnp.arange(n)[None, :] == leader[:, None]  # [b, n]
        received = jnp.where(is_leader_j, order[:, None], received)

        # Round 2 (sharded): this chip answers only for its receivers.
        # Fresh coins per (receiver, responder) pair, keyed per (data,
        # node) shard so draws are distinct across chips.
        k_r2 = jr.fold_in(jr.fold_in(key, node_idx + 1000), data_idx)
        coins2 = coin_bits(k_r2, (b, n_local, n))
        answers = jnp.where(faulty[:, None, :], coins2, received[:, None, :])
        own = i_global[None, :, None] == jnp.arange(n)[None, None, :]
        answers = jnp.where(own, received[:, None, :], answers)

        weight = alive[:, None, :] & ~is_leader_j[:, None, :]
        n_att = jnp.sum((answers == ATTACK) & weight, axis=-1)
        n_ret = jnp.sum((answers == RETREAT) & weight, axis=-1)
        maj = strict_majority(n_att, n_ret)
        is_leader_local = i_global[None, :] == leader[:, None]
        maj = jnp.where(is_leader_local, order[:, None], maj)

        # Quorum: local partial counts, then one psum over the node axis —
        # the majority-of-majorities gather (ba.py:197-223) on ICI.
        alive_local = jnp.take(alive, i_global, axis=1)
        att = jnp.sum((maj == ATTACK) & alive_local, axis=-1)
        ret = jnp.sum((maj == RETREAT) & alive_local, axis=-1)
        und = jnp.sum((maj == UNDEFINED) & alive_local, axis=-1)
        att, ret, und = jax.lax.psum((att, ret, und), "node")
        decision, needed, total = quorum_decision(att, ret, und)
        return maj, decision, needed, total, att, ret, und

    fn = cached_jit(
        ("om1", mesh, n),
        lambda: shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                P(),  # key (replicated)
                P("data"),  # order
                P("data"),  # leader
                P("data", None),  # faulty: node axis replicated
                P("data", None),  # alive
            ),
            out_specs=(
                P("data", "node"),  # majorities
                P("data"),
                P("data"),
                P("data"),
                P("data"),
                P("data"),
                P("data"),
            ),
        ),
    )
    # Raw replicated key data crosses any mesh (incl. multi-process);
    # re-wrapped inside the shard body.  Same mechanism in sm_/eig_parallel.
    key_raw = put_global(mesh, jr.key_data(key), P())
    maj, decision, needed, total, att, ret, und = fn(
        key_raw, state.order, state.leader, state.faulty, state.alive
    )
    return {
        "majorities": maj,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": att,
        "n_retreat": ret,
        "n_undefined": und,
    }
