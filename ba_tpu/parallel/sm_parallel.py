"""Node-axis sharded SM(m): one huge signed cluster across chips.

The large-n execution path (BASELINE config #4: n=1024, m=32).  The dense
EIG tree is O(n^m) and cannot reach that point (ba_tpu/core/eig.py); SM(m)
is O(n^2) per relay round — and O(n) per round in the collapsed fair-coin
model (``sm_relay_rounds_collapsed``) — so n=1024 generals shard across the
mesh's "node" axis the way om1_node_sharded shards OM(1):

- generals (holders *and* receivers of signed values) shard over "node";
  each chip keeps only its generals' V-sets ``seen[b, n_local, 2]``;
- collapsed relay round: the only cross-chip state is the [b, 2]
  honest-holder / traitor-holder counts — one tiny ``psum`` over "node"
  per round, O(b) ICI bytes (vs the reference's O(n^2) RPC mesh,
  ba.py:159-186);
- exact relay round (explicit adversaries): each chip re-assembles the
  global V-sets with one ``all_gather`` ([b, n, 2] bool, O(b*n) ICI bytes)
  and draws per-(receiver, sender) coins only for its own receivers —
  per-chip memory O(b * n * n_local), never the full cube;
- the quorum layer is the same single ``psum`` as om1_node_sharded
  (the majority-of-majorities gather, ba.py:197-223).

Round-1 broadcast runs unsharded (it is O(B*n), off the hot path) via the
shared ``round1_broadcast`` and enters the shard_map replicated along
"node" — the same contract the signed pipeline (ba_tpu.crypto.signed) uses
when it pins ``received`` so its host signer sees the values the device
relays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu.core.quorum import quorum_decision
from ba_tpu.core.sm import choice_from_seen
from ba_tpu.core.rng import coin_bits, or_coin_threshold8, uniform_u8
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.parallel.mesh import cached_jit, shard_map
from ba_tpu.parallel.multihost import put_global, round1_jit


def sm_node_sharded(
    mesh: Mesh,
    key: jax.Array,
    state: SimState,
    m: int,
    *,
    received: jnp.ndarray | None = None,
    sig_valid: jnp.ndarray | None = None,
    withhold: jnp.ndarray | None = None,
    collapsed: bool = True,
):
    """SM(m) agreement with generals sharded over the "node" mesh axis.

    state: SimState with batch B (sharded over "data") and n divisible by
    the node-axis size.  ``received``/``sig_valid`` (optional [B, n]) pin
    the round-1 values and their Ed25519 validity mask, exactly as in
    ``sm_round``.  ``collapsed`` selects the O(n)-per-round fair-coin relay;
    ``collapsed=False`` runs the exact per-(receiver, sender) coin model,
    optionally under a pinned adversary schedule ``withhold``
    ([m, B, n, n, 2] bool, receiver axis sharded over "node" — same
    semantics as ``sm_relay_rounds``).
    Returns the ``om1_agreement``-style dict with ``majorities`` sharded
    [B, n] and replicated quorum outputs.
    """
    B, n = state.faulty.shape
    n_node = mesh.shape["node"]
    assert n % n_node == 0, f"node axis {n_node} must divide n={n}"
    if withhold is not None and collapsed:
        raise ValueError("collapsed relay cannot honor a withhold schedule")
    if received is None:
        # Round 1 under jit, node-replicated (O(B*n), not worth sharding):
        # jit (not eager) so global multi-process state arrays are legal
        # inputs (multihost.round1_jit, shared with eig_parallel).
        k1, key = jr.split(key)
        received = round1_jit(put_global(mesh, jr.key_data(k1), P()), state)
    has_sig = sig_valid is not None
    has_withhold = withhold is not None

    def shard_fn(key_raw, order, leader, faulty, alive, rcv, *extra):
        key = jr.wrap_key_data(key_raw)
        node_idx = jax.lax.axis_index("node")
        data_idx = jax.lax.axis_index("data")
        b = order.shape[0]
        n_local = n // n_node
        i_global = node_idx * n_local + jnp.arange(n_local)  # [n_local]
        local = lambda x: jnp.take(x, i_global, axis=1)

        honest = alive & ~faulty
        traitor = faulty & alive
        t = jnp.sum(traitor, axis=-1)  # [b] coalition size
        alive_l = local(alive)
        honest_l = local(honest)
        traitor_l = local(traitor)
        rcv_l = local(rcv)

        # This chip's generals' V-sets after the signed round-1 push.
        seen_l = jnp.stack([rcv_l == RETREAT, rcv_l == ATTACK], axis=-1)
        seen_l = seen_l & alive_l[..., None]
        extra = list(extra)
        if has_sig:
            seen_l = seen_l & local(extra.pop(0))[..., None]
        wh_l = extra.pop(0) if has_withhold else None  # [m, b, n_local, n, 2]

        # Relay coins: distinct stream per (data, node) shard, disjoint from
        # the round-1 stream (which folds in data_idx alone).
        k_relay = jr.fold_in(key, 1000 + node_idx + n_node * data_idx)

        if collapsed:

            def one_round(seen_l, r):
                held = jnp.sum(seen_l & honest_l[..., None], axis=1)  # [b, 2]
                k_cnt = jnp.sum(seen_l & traitor_l[..., None], axis=1)
                held, k_cnt = jax.lax.psum((held, k_cnt), "node")
                held_honest = held > 0
                chain_ok = (r < t)[:, None] | held_honest
                thresh = or_coin_threshold8(k_cnt, chain_ok)  # [b, 2]
                u = uniform_u8(jr.fold_in(k_relay, r), (b, n_local, 2))
                incoming = (u < thresh[:, None, :]) | held_honest[:, None, :]
                return (seen_l | incoming) & alive_l[..., None], None

            seen_l, _ = jax.lax.scan(
                one_round, seen_l, jnp.arange(1, m + 1),
                unroll=max(m, 1) if m <= 4 else 1,  # same policy as core/sm.py
            )
        else:
            for r in range(1, m + 1):
                # Global V-sets: one [b, n, 2]-bool all_gather per round.
                seen_g = jax.lax.all_gather(seen_l, "node", axis=1, tiled=True)
                held_honest = jnp.any(seen_g & honest[..., None], axis=1)
                chain_ok = (r < t)[:, None] | held_honest  # [b, 2]
                if wh_l is not None:
                    coins = ~wh_l[r - 1]
                else:
                    coins = coin_bits(
                        jr.fold_in(k_relay, r), (b, n_local, n, 2), bool
                    )
                faulty_sends = (
                    seen_g[:, None, :, :]
                    & coins
                    & faulty[:, None, :, None]
                    & chain_ok[:, None, None, :]
                )
                honest_sends = seen_g[:, None, :, :] & honest[:, None, :, None]
                sends = (faulty_sends | honest_sends) & alive[:, None, :, None]
                incoming = jnp.any(sends, axis=2)  # [b, n_local, 2]
                seen_l = (seen_l | incoming) & alive_l[..., None]

        # choice(V) for this chip's generals (sm_choice semantics; the
        # leader override needs i_global so only that part is local).
        choice = choice_from_seen(seen_l)
        is_leader_l = i_global[None, :] == leader[:, None]
        maj = jnp.where(is_leader_l, order[:, None], choice)

        # Quorum: local counts, one psum over "node" (ba.py:197-223).
        att = jnp.sum((maj == ATTACK) & alive_l, axis=-1)
        ret = jnp.sum((maj == RETREAT) & alive_l, axis=-1)
        und = jnp.sum((maj == UNDEFINED) & alive_l, axis=-1)
        att, ret, und = jax.lax.psum((att, ret, und), "node")
        decision, needed, total = quorum_decision(att, ret, und)
        return maj, decision, needed, total, att, ret, und

    def build():
        in_specs = [
            P(),  # key (replicated)
            P("data"),  # order
            P("data"),  # leader
            P("data", None),  # faulty: node axis replicated
            P("data", None),  # alive
            P("data", None),  # received
        ]
        if has_sig:
            in_specs.append(P("data", None))
        if has_withhold:
            # [m, B, receiver, sender, value]: receivers shard with their
            # owning chips, senders/values replicated.
            in_specs.append(P(None, "data", "node", None, None))
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(
                P("data", "node"),  # majorities
                P("data"),
                P("data"),
                P("data"),
                P("data"),
                P("data"),
                P("data"),
            ),
        )

    fn = cached_jit(("sm", mesh, n, m, collapsed, has_sig, has_withhold), build)
    # The key rides in as raw uint32 data, globalized over the mesh, and is
    # re-wrapped inside the shard body: a locally-committed typed key can't
    # cross a multi-process mesh, raw replicated data can (put_global).
    key_raw = put_global(mesh, jr.key_data(key), P())
    args = [key_raw, state.order, state.leader, state.faulty, state.alive, received]
    if has_sig:
        args.append(sig_valid)
    if has_withhold:
        args.append(withhold)
    maj, decision, needed, total, att, ret, und = fn(*args)
    return {
        "majorities": maj,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": att,
        "n_retreat": ret,
        "n_undefined": und,
    }
