"""Instance-axis data parallelism: massively-batched fault-pattern sweeps.

BASELINE.json config #5: "10k-instance sweep over (n in [16,1024], m <= n/3)
across a TPU slice".  Consensus instances are independent, so the instance
axis shards across every chip with zero cross-chip traffic during the round
— ICI is touched only by the final decision histogram (one tiny psum XLA
inserts automatically when the replicated summary is requested).

The reference runs ONE cluster per OS process (ba.py:354-363); this module
is the "many independent clusters" scale-out it has no analogue for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu.core.eig import eig_round
from ba_tpu.core.om import om1_round, om1_round_from_coins, round1_broadcast
from ba_tpu.core.rng import coin_bits, coin_words, unpack_coin_words
import ba_tpu.scenario.strategies as _strategies
from ba_tpu.core.quorum import majority_counts, quorum_decision
from ba_tpu.core.state import SimState
from ba_tpu.parallel.multihost import put_global
from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT, UNDEFINED


def make_sweep_state(
    key: jax.Array,
    batch: int,
    capacity: int,
    *,
    min_n: int | None = None,
    max_n: int | None = None,
    max_traitor_frac: float = 1 / 3,
    order=ATTACK,
) -> SimState:
    """Sample a batch of random (n, fault-pattern) cluster configurations.

    Per instance: cluster size n uniform in [min_n, max_n (default:
    capacity)] (alive = the
    first n slots, mirroring ascending spawn order ba.py:344-351), then an
    independent traitor count in [0, floor(n * max_traitor_frac)] assigned to
    uniformly-random lieutenants.  The leader (slot 0) stays honest so that
    sweep decisions have a ground truth to validate against; flip extra bits
    in ``faulty`` for adversarial-leader studies.

    Guarantee note: with an honest leader, OM(m) validity holds when
    n > 2t + m (a strict honest majority among eligible relays at every
    resolve level).  The default 1/3 fraction satisfies this for OM(1) with
    min_n >= 4; pass a tighter ``max_traitor_frac`` for deeper recursions.
    """
    if min_n is None:
        min_n = min(4, capacity)
    if max_n is None:
        max_n = capacity
    if not min_n <= max_n <= capacity:
        raise ValueError(f"need min_n <= max_n <= capacity, got "
                         f"{min_n}/{max_n}/{capacity}")
    k_n, k_m, k_perm = jr.split(key, 3)
    idx = jnp.arange(capacity)[None, :]
    n = jr.randint(k_n, (batch,), min_n, max_n + 1)
    alive = idx < n[:, None]
    max_traitors = (n * max_traitor_frac).astype(jnp.int32)
    n_traitors = jr.randint(k_m, (batch,), 0, max_traitors + 1)
    # Rank lieutenants by random scores; the lowest n_traitors ranks lie.
    scores = jr.uniform(k_perm, (batch, capacity))
    scores = jnp.where(alive & (idx > 0), scores, jnp.inf)
    order_ids = jnp.argsort(scores, axis=-1)
    ranks = jnp.argsort(order_ids, axis=-1)
    faulty = ranks < n_traitors[:, None]
    return SimState(
        order=jnp.broadcast_to(jnp.asarray(order, COMMAND_DTYPE), (batch,)),
        leader=jnp.zeros((batch,), jnp.int32),
        faulty=faulty,
        alive=alive,
        ids=jnp.broadcast_to(
            jnp.arange(1, capacity + 1, dtype=jnp.int32), (batch, capacity)
        ),
    )


def bucketed_sweep_states(
    key: jax.Array,
    batch: int,
    capacity: int,
    n_buckets: int = 2,
    *,
    min_n: int = 4,
    max_traitor_frac: float = 1 / 3,
    order=ATTACK,
) -> list[SimState]:
    """Equal-count, equal-width cluster-size buckets: ragged batching.

    ``make_sweep_state`` pads every instance to ``capacity``, so a sweep
    whose sizes are uniform on [min_n, capacity] burns ~half its lanes on
    dead padding (mean n ~ capacity/2 — the relay's elementwise cost
    scales with the PADDED width).  Splitting the size range into
    ``n_buckets`` equal-width sub-ranges, each padded only to its own
    upper edge, cuts the mean padded width to ~3/4 (2 buckets) or ~5/8
    (4 buckets) of ``capacity`` while sampling approximately the same
    distribution: equal instance counts over equal-width uniform
    sub-ranges compose to the uniform mixture over [min_n, capacity] up
    to the integer edges where ranges abut (sub-range widths in integers
    can differ by one size value, e.g. 509 vs 512 at capacity 1024, so
    sizes near an edge are represented at slightly different rates than
    in the flat batch).  Remainder instances go to the last (widest)
    bucket, biasing toward MORE work, never less.

    Returns one SimState per bucket (padded widths capacity/n_buckets *
    (k+1), rounded up to a multiple of 128 so the lane axis stays
    TPU-tile-aligned — capped at ``capacity`` itself when that is smaller,
    e.g. tiny test capacities); consensus semantics are unchanged — each
    bucket is
    just a smaller independent sweep, so decisions compose by
    concatenation and histograms by summation.
    """
    if n_buckets < 1 or n_buckets > capacity:
        raise ValueError(f"n_buckets={n_buckets} out of range")
    if capacity // n_buckets < min_n:
        raise ValueError(
            f"capacity/n_buckets = {capacity}/{n_buckets} puts the first "
            f"bucket's upper edge below min_n={min_n}; use fewer buckets"
        )
    per = batch // n_buckets
    states = []
    lo = min_n
    for k in range(n_buckets):
        hi = capacity * (k + 1) // n_buckets
        cap_k = -(-hi // 128) * 128
        bk = per if k < n_buckets - 1 else batch - per * (n_buckets - 1)
        states.append(
            make_sweep_state(
                jr.fold_in(key, k),
                bk,
                min(cap_k, capacity),
                min_n=lo,
                max_n=hi,
                max_traitor_frac=max_traitor_frac,
                order=order,
            )
        )
        lo = hi + 1
    return states


def decision_histogram(decision: jnp.ndarray) -> jnp.ndarray:
    """[B] decisions -> 3-bin [retreat, attack, undefined] counts."""
    return jnp.stack(
        [
            jnp.sum(decision == RETREAT),
            jnp.sum(decision == ATTACK),
            jnp.sum(decision == UNDEFINED),
        ]
    )


def agreement_step(
    keys: jax.Array,
    state: SimState,
    m: int = 1,
    max_liars: int | None = None,
    strategies: jax.Array | None = None,
):
    """One agreement round per instance with per-instance PRNG keys.

    The jittable heart of the sweep (and of bench.py): vmapped over the
    batch so each instance draws independent fault coins — the vectorised
    analogue of "fresh randomness per RPC call" (ba.py:44-49).
    ``max_liars`` (known traitor cap) shrinks the fused deepest EIG
    level's popcount draw for m >= 2 — derive it from the CONCRETE state
    before jitting (it cannot be computed from a tracer); None is always
    safe (n-1 words).

    ``strategies`` ([B, n] int8, ``ba_tpu.scenario.strategies`` ids)
    selects each faulty general's adversary behaviour; ``None`` keeps the
    historical coin-only path bit-for-bit, and the all-RANDOM plane is
    bit-exact with it under the same keys (for m >= 2 a strategies plane
    forces the dense EIG path — see ``eig_round``).
    """

    def one(k, order, leader, faulty, alive, ids, strat):
        st = SimState(order[None], leader[None], faulty[None], alive[None], ids[None])
        sb = None if strat is None else strat[None]
        maj = (
            om1_round(k, st, sb)
            if m == 1
            else eig_round(k, st, m, max_liars, sb)
        )
        return maj[0]

    if m == 1 and not _strategies._impl_chain:
        # OM(1) takes the COIN-INJECTED path (ISSUE 13): only the tiny
        # per-instance draws run under vmap — split + the coin streams,
        # exactly what the per-instance B=1 round would draw — and the
        # round math runs BATCHED (om1_round_from_coins).  Bit-identical
        # to vmapping the whole round (pinned), but the strategy lie
        # selects under vmap were the measured ~2.3x-of-the-round
        # XLA-CPU pathology the ROADMAP carried since ISSUE 5
        # (megastep_ab's A/B legs re-measure both formulations).  On
        # the strategies path the coins additionally unpack by GATHER
        # (unpack_coin_words): coin_bits's transposing unpack, fused
        # into the lie table's select tree, was most of that cost —
        # same bits, row-major layout.  The legacy formulation stays
        # reachable through strategies.chain_impl() (trace-time flag)
        # as the A/B baseline.
        n = state.faulty.shape[1]

        if strategies is None:

            def draw(k):
                k1, k2 = jr.split(k)
                return (
                    coin_bits(k1, (1, n))[0],
                    coin_bits(k2, (1, n, n))[0],
                )

            coins1, coins2 = jax.vmap(draw)(keys)
        else:

            def draw(k):
                k1, k2 = jr.split(k)
                return coin_words(k1, n), coin_words(k2, n * n)

            w1, w2 = jax.vmap(draw)(keys)
            coins1 = unpack_coin_words(w1, (n,))
            coins2 = unpack_coin_words(w2, (n, n))
        majorities = om1_round_from_coins(state, coins1, coins2, strategies)
    elif strategies is None:
        majorities = jax.vmap(
            lambda k, o, l, f, a, i: one(k, o, l, f, a, i, None)
        )(
            keys, state.order, state.leader, state.faulty, state.alive,
            state.ids,
        )
    else:
        majorities = jax.vmap(one)(
            keys, state.order, state.leader, state.faulty, state.alive,
            state.ids, strategies,
        )
    n_attack, n_retreat, n_undefined = majority_counts(majorities, state.alive)
    decision, needed, total = quorum_decision(n_attack, n_retreat, n_undefined)
    histogram = decision_histogram(decision)
    return {
        "majorities": majorities,
        "decision": decision,
        "needed": needed,
        "total": total,
        "histogram": histogram,
    }


def failover_sweep(
    key: jax.Array,
    state: SimState,
    kill_schedule: jnp.ndarray,
    m: int = 1,
    max_liars: int | None = None,
):
    """Multi-round sweep with on-device leader failover: the tensor-scale
    detect -> elect -> continue loop of the reference's run thread
    (ba.py:306-314, ping failure -> elect -> next round).

    Since ISSUE 5 this is a THIN WRAPPER over the scenario engine's scan
    core (``parallel.pipeline._scenario_scan``) driven by a kill-only
    campaign: per scan step it applies the kills, re-elects dead leaders
    by lowest alive id (``elect_lowest_id``, the argmin form of
    ba.py:126-157; "election is for life", ba.py:124-125), and runs the
    strategy-aware agreement round with every strategy at RANDOM — the
    reference adversary, bit-exact with the pre-scenario coin path.  One
    transition implementation now serves interactive failover studies,
    this jittable single-dispatch form, and the pipelined mutating
    campaigns (``pipeline_sweep(scenario=...)``), and the kill-only
    parity test (tests/test_scenario.py) pins all of them together.

    Keys derive from the engine's on-device :class:`KeySchedule`
    (``fold_in(fold_in(base, r), i)``) — the same schedule the pipelined
    engine threads, which is what makes the parity bit-exact.

    ``kill_schedule`` [R, B, n] bool: who dies before each of the R rounds
    (crash faults, the batched ``g-kill`` ba.py:415-425).  Returns dict
    with ``leaders`` [R, B] (leader after each round's election),
    ``decisions`` [R, B] int8, ``histograms`` [R, 3], and the final
    SimState.  Jittable; shard the batch axis for multi-chip use
    (sharded_sweep's layout applies unchanged).
    """
    # Runtime import: pipeline.py imports this module at load time (for
    # agreement_step), so the back-edge must resolve lazily.
    from ba_tpu.parallel import pipeline as _pipeline

    R = kill_schedule.shape[0]
    B, n = state.faulty.shape
    events = {
        "kill": kill_schedule,
        "revive": jnp.zeros((R, B, n), bool),
        "set_faulty": jnp.full((R, B, n), -1, jnp.int8),
        "set_strategy": jnp.full((R, B, n), -1, jnp.int8),
    }
    carry, ys = _pipeline._scenario_scan(
        state,
        _pipeline.make_key_schedule(key),
        jnp.zeros((B, n), jnp.int8),  # every general starts RANDOM
        _pipeline.scenario_counters_init(),
        events,
        rounds=R,
        m=m,
        max_liars=max_liars,
        unroll=1,
        collect_decisions=True,
    )
    return {
        "leaders": ys[1],
        "decisions": ys[3],
        "histograms": ys[0],
        "final_state": carry[0],
    }


def signed_agreement_step(
    keys: jax.Array,
    state: SimState,
    ok: jax.Array,
    m: int = 1,
    collapsed: bool = False,
):
    """One SIGNED SM(m) round per instance with per-instance PRNG keys
    (the sign-ahead lane's in-scan round, ISSUE 14).

    The signed twin of :func:`agreement_step`: per instance, split the
    round key, run the commander's round-1 equivocation broadcast, gate
    each received value on its TABLE signature verdict (``ok`` [B, V]
    bool — the per-(instance, value) verdicts the sign-ahead host lane
    verified for this round, gathered to the [B, n] validity mask by
    ``sig_valid_from_tables``'s select), then the m SM relay rounds and
    the quorum layer.  ``collapsed`` selects the O(B*n) fair-coin relay
    (the sweep10k production path); False keeps the exact
    per-(receiver, sender) cube — bit-identical per instance to
    ``sm_round(sig_valid=..., received=...)`` under the same key, which
    is the sequential-driver parity contract.

    Returns the :func:`agreement_step` dict plus ``received`` [B, n]
    (the round-1 broadcast — the signed counter verdicts read it).
    """
    from ba_tpu.core.sm import sm_round

    def one(k, order, leader, faulty, alive, ids, ok_row):
        st = SimState(
            order[None], leader[None], faulty[None], alive[None], ids[None]
        )
        k1, k2 = jr.split(k)
        received = round1_broadcast(k1, st)
        # V=2 tables: the broadcast select of sig_valid_from_tables,
        # inlined (the gather form serializes on TPU — its docstring).
        sig_valid = jnp.where(
            received == 1, ok_row[None, 1:2], ok_row[None, 0:1]
        )
        maj = sm_round(
            k2, st, m, sig_valid=sig_valid, received=received,
            collapsed=collapsed,
        )
        return maj[0], received[0]

    majorities, received = jax.vmap(one)(
        keys, state.order, state.leader, state.faulty, state.alive,
        state.ids, ok,
    )
    n_attack, n_retreat, n_undefined = majority_counts(majorities, state.alive)
    decision, needed, total = quorum_decision(n_attack, n_retreat, n_undefined)
    return {
        "majorities": majorities,
        "decision": decision,
        "needed": needed,
        "total": total,
        "histogram": decision_histogram(decision),
        "received": received,
    }


def _agreement_step_raw(keys_raw: jax.Array, state: SimState, m: int = 1):
    """agreement_step with the per-instance keys as raw uint32 data."""
    return agreement_step(jr.wrap_key_data(keys_raw), state, m=m)


def sharded_sweep(mesh: Mesh, key: jax.Array, state: SimState, m: int = 1):
    """Run one agreement round per instance, instances sharded over ``mesh``.

    The state's batch axis is laid out on the mesh's "data" axis; every
    per-instance output stays sharded, and only the 3-bin decision histogram
    is replicated (the lone collective).  Ingestion goes through
    ``put_global`` (and the split keys ride as raw uint32 data, re-wrapped
    under jit), so the same call works on a mesh spanning processes — the
    multi-host sweep is literally this function on a
    ``make_global_mesh()`` mesh (tests/test_multihost.py).
    """
    state = jax.tree.map(
        lambda x: put_global(mesh, x, P("data", *([None] * (x.ndim - 1)))),
        state,
    )
    keys_raw = put_global(
        mesh, jr.key_data(jr.split(key, state.batch)), P("data", None)
    )
    return jax.jit(_agreement_step_raw, static_argnames="m")(keys_raw, state, m=m)
