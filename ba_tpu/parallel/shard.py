"""Mesh-sharded megasteps: the pipelined scan cores under ``shard_map``
(ISSUE 8 tentpole) — one campaign drives every chip.

``parallel/pipeline.py`` owns the scan cores (``_pipeline_scan`` /
``_scenario_scan``); this module wraps them in ``shard_map`` over a
mesh's "data" axis so the batch — and with it every steady-state carry
buffer and every staged event plane — splits across devices:

- **Sharding is layout-only.**  Instances are independent, and the
  ``KeySchedule`` folds per-instance keys by GLOBAL instance index
  (``round_keys(..., index_base=shard_base)``), so the sharded engine
  draws bit-identical streams to the single-device run — decisions,
  leaders, histograms and every counter match bit-for-bit at equal
  shapes (the mesh parity tests pin it).
- **Per-shard outputs, retire-time tree-reduction.**  Each shard folds
  its own counter block ([d, C] global, ``P("data", None)``) and emits
  its own per-round histogram contribution ([R, d, 3]); the host SUMS
  them inside the engine's existing depth-delayed retire fetch
  (:func:`reduce_host_ys`) — no collective rides the scan for them, and
  no new synchronization point exists anywhere (the no-blocking
  dispatch-count proof re-runs on a live mesh).  The ONE cross-shard
  collective in the compiled program is a 3-int histogram psum per
  round, and only when counters are on: global unanimity is a property
  of the whole batch, not of any shard
  (``pipeline.agreement_counter_delta``).
- **Donation is unchanged.**  The sharded megasteps donate the same
  carry slots as their single-device twins, so steady-state buffers
  alias in place per device — peak per-device carry bytes are the
  single-device figure divided by the shard count.

Checkpoints stay device-count-free: the engine gathers per-shard
counter blocks to the canonical single-device block at write time
(gather-on-write) and re-splits on resume (:func:`expand_counters`,
reshard-on-read), so a campaign checkpointed on d devices resumes
bit-exactly on d' — subprocess-pinned in tests/test_scenario.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu.core.state import SimState
from ba_tpu.parallel import pipeline as _pipeline
from ba_tpu.parallel.mesh import shard_map
from ba_tpu.parallel.multihost import put_global

# The engine's shard axis: batched consensus instances are independent,
# so the batch dimension is the one that scales with chips ("data" in
# every mesh this repo builds — sharded_sweep, make_global_mesh).
DATA_AXIS = "data"

# Spec pytrees for the carry (the dataclasses double as spec containers:
# a registered-dataclass pytree of PartitionSpecs is a valid shard_map
# spec tree).  State planes shard on the batch axis; the key schedule
# replicates — it is 3 ints, and every shard derives its own slice of
# the key stream from the global indices.
STATE_SPECS = SimState(
    order=P(DATA_AXIS),
    leader=P(DATA_AXIS),
    faulty=P(DATA_AXIS, None),
    alive=P(DATA_AXIS, None),
    ids=P(DATA_AXIS, None),
)
SCHED_SPECS = _pipeline.KeySchedule(key_data=P(None), counter=P())
COUNTER_SPECS = P(DATA_AXIS, None)  # [d, C] per-shard blocks
STRATEGY_SPECS = P(DATA_AXIS, None)
EVENT_SPECS = P(None, DATA_AXIS, None)  # [R, B, n] planes
# Stacked per-round outputs: per-shard contributions keep the shard
# axis ([R, d, 3] histograms / [R, d, C] counter rows — host-reduced at
# retire); per-instance rows ([R, B] decisions/leaders) gather to the
# canonical global shape at the same fetch.
ROWS_SPECS = P(None, DATA_AXIS, None)
INSTANCE_SPECS = P(None, DATA_AXIS)


def validate_mesh(mesh: Mesh, batch: int) -> int:
    """The mesh's data-axis size, after the eager layout checks.

    Raises ``ValueError`` naming the problem (missing "data" axis, or a
    batch the axis cannot split evenly) BEFORE any buffer enters the
    donation thread — a shape error surfacing from inside a donated
    dispatch would leave the caller with consumed inputs and an opaque
    XLA message.
    """
    if DATA_AXIS not in mesh.shape:
        raise ValueError(
            f"mesh axes {tuple(mesh.shape)} carry no {DATA_AXIS!r} axis — "
            f"the engine shards the batch on it (make_mesh's default "
            f"layout)"
        )
    d = int(mesh.shape[DATA_AXIS])
    if batch % d:
        raise ValueError(
            f"batch {batch} is not divisible by the mesh's {DATA_AXIS!r} "
            f"axis ({d} device(s)) — pad the batch or shrink the mesh"
        )
    return d


def shard_layout(mesh: Mesh) -> dict:
    """The mesh's axis sizes as a JSON-able ``{axis: size}`` dict — the
    layout provenance recorded in carry-checkpoint headers and
    ``scenario_checkpoint`` records (the stored ARRAYS are canonical /
    device-count-free; the layout says what wrote them)."""
    return {name: int(size) for name, size in mesh.shape.items()}


def expand_counters(mesh: Mesh, counters: jax.Array) -> jax.Array:
    """A canonical counter block -> per-shard blocks on ``mesh``
    (reshard-on-read).

    Shard 0 receives the whole prior total and every other shard starts
    at zero: only the SUM of the per-shard blocks is ever observed (the
    retire-time reduction and the checkpoint gather both sum), so any
    decomposition preserving it is bit-exact — this one needs no
    arithmetic.  A 2-D block (a live per-shard carry resumed in memory,
    possibly from a different device count) is collapsed to canonical
    first.
    """
    if counters.ndim == 2:
        counters = counters.sum(axis=0)
    d = int(mesh.shape[DATA_AXIS])
    block = jnp.zeros((d,) + counters.shape, counters.dtype)
    block = block.at[0].set(counters)
    return put_global(mesh, block, COUNTER_SPECS)


def per_shard_nbytes(tree) -> int:
    """Bytes ONE device holds for a pytree of (possibly sharded) arrays
    — the per-device peak-memory denominator the weak-scaling artifact
    reports (replicated leaves count in full, sharded leaves by their
    local shard)."""
    total = 0
    for x in jax.tree.leaves(tree):
        shards = getattr(x, "addressable_shards", None)
        if shards:
            total += shards[0].data.nbytes
        else:
            total += x.nbytes
    return total


def per_shard_nbytes_all(tree) -> list:
    """Per-DEVICE byte totals for a pytree of (possibly sharded) arrays,
    sorted descending — the health sampler's imbalance numerator/mean
    (ISSUE 9): ``max / mean`` is 1.0 when every device holds the same
    share and grows as one device holds more than its split.  Replicated
    leaves count in full on every device (they really are resident
    everywhere); host-side leaves count nowhere.  In-memory metadata
    walks only — no fetch, no sync."""
    per: dict = {}
    for x in jax.tree.leaves(tree):
        shards = getattr(x, "addressable_shards", None)
        if shards:
            for s in shards:
                per[s.device] = per.get(s.device, 0) + s.data.nbytes
    return sorted(per.values(), reverse=True)


def reduce_host_ys(
    host_ys: tuple,
    *,
    scenario: bool,
    collect_decisions: bool,
    with_counters: bool,
) -> tuple:
    """One retire's fetched per-shard blocks -> canonical single-device
    shapes (the retire-time tree-reduction).

    Runs on HOST numpy the retire fetch already brought back — pure
    arithmetic on an existing sync, never a new one.  Histograms
    [R, d, 3] and cumulative counter rows [R, d, C] sum over the shard
    axis (each shard's rows are cumulative for its partials, so the sum
    is the cumulative global row); decisions/leaders arrive already
    gathered to [R, B] by the fetch.  Downstream consumers — the result
    assembly, ``on_rows`` history sidecars, checkpoint-adjacent row
    delivery — therefore see byte-identical blocks at any device count.
    """
    ys = list(host_ys)
    ys[0] = ys[0].sum(axis=1, dtype=ys[0].dtype)
    if scenario:
        ys[2] = ys[2].sum(axis=1, dtype=ys[2].dtype)
    elif with_counters:
        ys[-1] = ys[-1].sum(axis=1, dtype=ys[-1].dtype)
    return tuple(ys)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "rounds", "m", "max_liars", "unroll", "collect_decisions",
    ),
    donate_argnums=(0, 1),
)
def sharded_pipeline_megastep(  # ba-lint: donates(state, sched)
    state: SimState,
    sched: _pipeline.KeySchedule,
    *,
    mesh: Mesh,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    counters: jax.Array | None = None,
):
    """:func:`ba_tpu.parallel.pipeline.pipeline_megastep`, batch-sharded
    over ``mesh``'s "data" axis via ``shard_map`` — same scan core, same
    donation contract (``state``/``sched`` are CONSUMED), same return
    tuple, except histograms come back per-shard ``[rounds, d, 3]`` and
    counter rows ``[rounds, d, C]`` for the host to tree-reduce at
    retire (``counters`` is a per-shard ``[d, C]`` block from
    :func:`expand_counters`).
    """
    with_counters = counters is not None

    def run(st, sc, *rest):
        ctr = rest[0] if rest else None
        base = jax.lax.axis_index(DATA_AXIS) * st.faulty.shape[0]
        carry, ys = _pipeline._pipeline_scan(
            st,
            sc,
            ctr,
            rounds=rounds,
            m=m,
            max_liars=max_liars,
            unroll=unroll,
            collect_decisions=collect_decisions,
            index_base=base,
            axis_name=DATA_AXIS if with_counters else None,
        )
        # Local [rounds, 3] histogram -> [rounds, 1, 3]: the singleton
        # axis is this shard's slot in the stacked [rounds, d, 3]
        # contribution block (counter rows are [rounds, 1, C] already —
        # the carried block's local view is [1, C]).
        out_ys = (ys[0][:, None, :],) + ys[1:]
        return (carry[0], carry[1], *out_ys)

    in_specs = (STATE_SPECS, SCHED_SPECS)
    out_specs = (STATE_SPECS, SCHED_SPECS, ROWS_SPECS)
    if collect_decisions:
        out_specs += (INSTANCE_SPECS,)
    if with_counters:
        in_specs += (COUNTER_SPECS,)
        out_specs += (ROWS_SPECS,)
    args = (state, sched) + ((counters,) if with_counters else ())
    # check_vma=False: the replication checker predates axis_index-mixed
    # scan carries; correctness is pinned by the bit-exact parity tests.
    return shard_map(
        run, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )(*args)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "rounds", "m", "max_liars", "unroll", "collect_decisions",
    ),
    donate_argnums=(0, 1, 2),
)
def sharded_scenario_megastep(  # ba-lint: donates(state, sched, strategy)
    state: SimState,
    sched: _pipeline.KeySchedule,
    strategy: jax.Array,
    counters: jax.Array,
    events: dict,
    *,
    mesh: Mesh,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
):
    """:func:`ba_tpu.parallel.pipeline.scenario_megastep`, batch-sharded
    over ``mesh``'s "data" axis — the mutating scan core under
    ``shard_map``.  Kills, revivals, strategy flips and lowest-alive-id
    re-election are all per-instance, so every event plane slices on the
    batch axis and the whole mutating round is shard-local; the one
    collective is the counter delta's 3-int histogram psum.  Donation
    contract as the single-device twin (``state``/``sched``/``strategy``
    CONSUMED); histograms/counter rows return per-shard for the
    retire-time reduction, leaders/decisions gather to ``[rounds, B]``.
    """

    def run(st, sc, strat, ctr, ev):
        base = jax.lax.axis_index(DATA_AXIS) * st.faulty.shape[0]
        carry, ys = _pipeline._scenario_scan(
            st,
            sc,
            strat,
            ctr,
            ev,
            rounds=rounds,
            m=m,
            max_liars=max_liars,
            unroll=unroll,
            collect_decisions=collect_decisions,
            index_base=base,
            axis_name=DATA_AXIS,
        )
        # ys = (histograms, leaders, counter_rows[, decisions]); the
        # histogram gains its per-shard slot, the counter rows carry it
        # already ([rounds, 1, C] — the carried block's local view).
        out_ys = (ys[0][:, None, :],) + ys[1:]
        return (carry[0], carry[1], carry[2], *out_ys)

    event_specs = {k: EVENT_SPECS for k in events}
    out_specs = (
        STATE_SPECS, SCHED_SPECS, STRATEGY_SPECS,
        ROWS_SPECS, INSTANCE_SPECS, ROWS_SPECS,
    )
    if collect_decisions:
        out_specs += (INSTANCE_SPECS,)
    return shard_map(
        run,
        mesh=mesh,
        in_specs=(
            STATE_SPECS, SCHED_SPECS, STRATEGY_SPECS, COUNTER_SPECS,
            event_specs,
        ),
        out_specs=out_specs,
        check_vma=False,
    )(state, sched, strategy, counters, events)


__all__ = [
    "DATA_AXIS",
    "expand_counters",
    "per_shard_nbytes",
    "reduce_host_ys",
    "shard_layout",
    "sharded_pipeline_megastep",
    "sharded_scenario_megastep",
    "validate_mesh",
]
