"""Node-axis sharded OM(m)/EIG: the dense message tree across chips.

Completes the node-parallel family (OM(1): node_parallel, SM(m):
sm_parallel) for the recursive oral-message protocol.  The EIG tree's
biggest object — level m, [B, n, n^m] int8 (ba_tpu/core/eig.py) — shards
its *receiver* axis over the mesh's "node" axis, so per-chip memory is
O(B * n^(m+1) / n_node + B * n^m):

- send phase: each relay level needs every general's previous-level copies
  (receiver i hears "j said V_l[j, p]"), so each of the m levels re-
  assembles the previous level with one ``all_gather`` over "node" —
  O(B * n^l) ICI bytes, a factor n smaller than the level being built;
- resolve phase: path majorities are per-receiver independent (the
  eligibility masks are replicated), so the whole bottom-up fold is local;
- quorum: the usual single ``psum`` (ba.py:197-223).

Faulty-relay semantics match core/eig.py exactly: an independent coin per
(receiver, path) message, self-messages stay honest, ties -> UNDEFINED,
empty electorates fall back to the stored copy (OM(0) base case).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr
from jax.sharding import Mesh, PartitionSpec as P

from ba_tpu.core.eig import _in_path_mask
from ba_tpu.core.quorum import quorum_decision, strict_majority
from ba_tpu.core.rng import coin_bits
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.parallel.mesh import cached_jit, shard_map
from ba_tpu.parallel.multihost import put_global, round1_jit


def eig_node_sharded(mesh: Mesh, key: jax.Array, state: SimState, m: int):
    """OM(m) agreement with the EIG tree's receiver axis sharded.

    state: SimState with batch B (sharded over "data") and n divisible by
    the node-axis size; m >= 1 static.  Returns the ``om1_agreement``-style
    dict with ``majorities`` sharded [B, n] and replicated quorum outputs.
    """
    B, n = state.faulty.shape
    n_node = mesh.shape["node"]
    assert n % n_node == 0, f"node axis {n_node} must divide n={n}"
    k1, key = jr.split(key)
    # Round 1 under jit (not eager): with a multi-process mesh the state
    # arrays are global, and only a traced computation may consume them.
    received = round1_jit(put_global(mesh, jr.key_data(k1), P()), state)

    def shard_fn(key_raw, order, leader, faulty, alive, rcv):
        key = jr.wrap_key_data(key_raw)
        node_idx = jax.lax.axis_index("node")
        data_idx = jax.lax.axis_index("data")
        b = order.shape[0]
        n_local = n // n_node
        i_global = node_idx * n_local + jnp.arange(n_local)
        local = lambda x: jnp.take(x, i_global, axis=1)
        k_shard = jr.fold_in(key, node_idx + n_node * data_idx)

        # Send phase: levels_local[l] is [b, n_local, n^l] — this chip's
        # receivers' copies; prev_global is the full previous level.
        levels_local = [local(rcv)[..., None]]  # [b, n_local, 1]
        prev_global = rcv[..., None]  # [b, n, 1]
        self_honest = i_global[None, :, None] == jnp.arange(n)[None, None, :]
        for level in range(m):
            p_sz = n**level
            coins = coin_bits(
                jr.fold_in(k_shard, level), (b, n_local, p_sz, n)
            )
            # relayed[b, i, p, j] = V_l[b, j, p] for this chip's receivers.
            relayed = jnp.transpose(prev_global, (0, 2, 1))[:, None, :, :]
            relayed = jnp.broadcast_to(relayed, (b, n_local, p_sz, n))
            lying = (
                faulty[:, None, None, :] & ~self_honest[:, :, None, :]
            )
            nxt = jnp.where(lying, coins, relayed).reshape(
                b, n_local, p_sz * n
            )
            levels_local.append(nxt)
            if level < m - 1:
                prev_global = jax.lax.all_gather(
                    nxt, "node", axis=1, tiled=True
                )

        # Resolve phase (local): bottom-up masked strict majorities,
        # mirroring core/eig.eig_resolve line for line on the local slice.
        is_leader = jnp.arange(n)[None, :] == leader[:, None]  # [b, n]
        resolved = levels_local[m]
        for level in range(m - 1, -1, -1):
            p_sz = n**level
            children = resolved.reshape(b, n_local, p_sz, n)
            in_path = jnp.asarray(_in_path_mask(n, level))  # [p_sz, n]
            valid = (
                alive[:, None, None, :]
                & ~is_leader[:, None, None, :]
                & ~in_path[None, None, :, :]
            )
            n_attack = jnp.sum((children == ATTACK) & valid, axis=-1)
            n_retreat = jnp.sum((children == RETREAT) & valid, axis=-1)
            resolved = strict_majority(n_attack, n_retreat)
            n_eligible = jnp.sum(valid, axis=-1)
            resolved = jnp.where(
                n_eligible > 0,
                resolved,
                levels_local[level].reshape(b, n_local, p_sz),
            )
        maj = resolved.reshape(b, n_local)
        is_leader_l = i_global[None, :] == leader[:, None]
        maj = jnp.where(is_leader_l, order[:, None], maj)

        alive_l = local(alive)
        att = jnp.sum((maj == ATTACK) & alive_l, axis=-1)
        ret = jnp.sum((maj == RETREAT) & alive_l, axis=-1)
        und = jnp.sum((maj == UNDEFINED) & alive_l, axis=-1)
        att, ret, und = jax.lax.psum((att, ret, und), "node")
        decision, needed, total = quorum_decision(att, ret, und)
        return maj, decision, needed, total, att, ret, und

    fn = cached_jit(
        ("eig", mesh, n, m),
        lambda: shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(
                P(),
                P("data"),
                P("data"),
                P("data", None),
                P("data", None),
                P("data", None),
            ),
            out_specs=(
                P("data", "node"),  # majorities
                P("data"),  # decision
                P("data"),  # needed
                P("data"),  # total
                P("data"),  # n_attack
                P("data"),  # n_retreat
                P("data"),  # n_undefined
            ),
        ),
    )
    key_raw = put_global(mesh, jr.key_data(key), P())
    maj, decision, needed, total, att, ret, und = fn(
        key_raw, state.order, state.leader, state.faulty, state.alive, received
    )
    return {
        "majorities": maj,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": att,
        "n_retreat": ret,
        "n_undefined": und,
    }
