"""Multi-host (multi-process) scaling: DCN x ICI global meshes.

The reference "scales" by spawning more general-threads in one OS process
(ba.py:427-437); its distributed backend is RPyC over localhost TCP.  This
framework's equivalent at real scale is a JAX global mesh spanning hosts:
every process owns one slice's chips, XLA collectives ride ICI inside a
slice and DCN between slices, and the same ``shard_map`` programs
(ba_tpu.parallel.sweep / node_parallel / sm_parallel / eig_parallel) run
unchanged — sharding is declarative, so "multi-host" is a mesh-shape
question, not a programming-model question (the How-to-Scale-Your-Model
recipe: pick a mesh, annotate shardings, let XLA insert collectives).

Axis policy: the instance/"data" axis maps to the DCN (inter-host)
dimension — independent consensus instances never communicate, so DCN
latency is invisible — and the "node" axis (generals of one big cluster,
whose all-to-all/psum traffic is hot) stays inside a slice on ICI.  This
mirrors the classic DP-outer / MP-inner layout.

Single-process fallback keeps every helper usable (and testable) on one
host with virtual CPU devices.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ba_tpu.parallel.mesh import make_mesh


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join (or skip) the multi-process JAX runtime; returns process count.

    Thin wrapper over ``jax.distributed.initialize`` — the framework's
    analogue of the reference's join protocol (discover_leader,
    ba.py:86-102): the coordinator is the "leader", every process dials
    it, and the global device view appears.  With no arguments (or in a
    single-process run) it is a no-op returning 1, so library code can
    call it unconditionally.
    """
    if coordinator_address is None and num_processes in (None, 1):
        return 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count()


def make_global_mesh(
    node_devices_per_host: int = 1,
    axis_names: tuple[str, str] = ("data", "node"),
) -> Mesh:
    """A (data, node) mesh over ALL processes' devices.

    The "node" axis is kept inside a host/slice (contiguous local devices,
    ICI); the "data" axis spans hosts (DCN) x the remaining local devices.
    With one process this degenerates to ``make_mesh`` over the local
    devices, so sweep/test code is identical either way.

    ``node_devices_per_host`` must divide each host's local device count.
    """
    devs = jax.devices()  # global, grouped by process
    counts: dict[int, int] = {}
    for d in devs:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    per_host = set(counts.values()) or {len(devs)}
    if len(per_host) != 1:
        raise ValueError(
            f"heterogeneous hosts unsupported: device counts {sorted(per_host)}"
        )
    n_local = per_host.pop()
    if node_devices_per_host > n_local or n_local % node_devices_per_host:
        raise ValueError(
            f"node_devices_per_host={node_devices_per_host} must divide "
            f"local device count {n_local}"
        )
    n_proc = max(len(counts), 1)
    data = n_proc * (n_local // node_devices_per_host)
    arr = np.empty((data, node_devices_per_host), dtype=object)
    # Keep each host's devices contiguous along "node".  jax.devices() is
    # documented to group by process, but the hot "node" axis silently
    # spanning hosts over DCN would defeat the whole axis policy, so sort
    # explicitly by (process, local ordinal) rather than trusting the
    # returned order (ADVICE r2).
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    for i, d in enumerate(devs):
        arr[i // node_devices_per_host, i % node_devices_per_host] = d
    return Mesh(arr, axis_names)


def put_global(mesh: Mesh, x, spec: PartitionSpec) -> jax.Array:
    """Host value -> one global array sharded as ``spec`` over ``mesh``.

    The multi-process-safe ingestion path: every process passes the SAME
    full value (numpy or local array) and contributes only its addressable
    shards (``jax.make_array_from_callback``), so it works identically on
    a single-process mesh and on a mesh spanning processes — where naive
    ``device_put`` of a locally-committed array can fail.  This is the
    framework's "scatter the membership roster to every node" step; the
    reference ships the same information over per-peer RPC instead
    (ba.py:86-102).

    Single-process meshes take the plain ``device_put`` path: it stays
    async and device-to-device, where the multi-process path's
    ``np.asarray`` would drain device values through the host on every
    call — a pure regression for the hot single-chip sweep.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


@jax.jit
def round1_jit(k_raw: jax.Array, state):
    """Round-1 broadcast under jit with a raw-uint32 key.

    The node-sharded protocols share this instead of calling
    ``round1_broadcast`` eagerly: on a multi-process mesh the state
    arrays are global, and only a traced computation may consume them;
    the key rides as replicated raw data (see ``put_global``) and is
    re-wrapped inside the trace.
    """
    import jax.random as jr

    from ba_tpu.core.om import round1_broadcast

    return round1_broadcast(jr.wrap_key_data(k_raw), state)


__all__ = [
    "init_distributed",
    "make_global_mesh",
    "make_mesh",
    "put_global",
    "round1_jit",
]
