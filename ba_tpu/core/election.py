"""Leader election: bully-by-lowest-id as a masked argmin.

The reference elects by polling every reachable peer's id and claiming
leadership iff none is lower (ba.py:126-157) — O(n) RPCs per candidate,
O(n^2) cluster-wide.  Concurrent elections converge because the winner
predicate (global lowest id among the alive) is deterministic; "election is
for life" (ba.py:124-125).  On TPU the whole thing is one reduction.
"""

from __future__ import annotations

import jax.numpy as jnp


def elect_lowest_id(ids: jnp.ndarray, alive: jnp.ndarray) -> jnp.ndarray:
    """Index of the alive node with the lowest id, per instance.

    ids: [B, n] int32, alive: [B, n] bool -> [B] int32 (index into the node
    axis).  If no node is alive the result is arbitrary (index 0), mirroring
    the reference where a fully-killed cluster simply has no one left to
    elect (and the REPL crashes on the next id lookup, SURVEY.md Q4).
    """
    big = jnp.iinfo(jnp.int32).max
    masked = jnp.where(alive, ids, big)
    return jnp.argmin(masked, axis=-1).astype(jnp.int32)
