"""Cheap batched fault coins: 32 coins per PRNG word.

The fault model burns enormous numbers of 1-bit coins — OM(m)'s relay
equivocation alone is [B, n, n^m] coins per round (generalising the
reference's ``random.randint(0, 1)`` per lie, ba.py:44-49) — and
``jr.randint``/``jr.bernoulli`` spend a full threefry word (~10 VPU ops)
per coin.  At bench scale that made coin generation the dominant cost of
the EIG path (measured r2: OM(3) n=10 at B=131k spends most of its ~100 ms
per round in threefry).  Drawing packed uint32 words and unpacking bits
cuts the threefry work 32x; the unpack itself is one shift+mask per
output element, the same order as the write traffic the coins already pay.

Streams differ from the randint formulation (same key -> different coins).
Nothing couples to the exact stream: the property tests are outcome-based,
the sharded paths use their own key folds, and the PyBackend differential
oracle draws from Python's ``random`` by design.
"""

from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core.types import COMMAND_DTYPE


def make_key(seed: int) -> jax.Array:
    """Typed PRNG key honoring the ``BA_TPU_RNG`` impl knob.

    ``BA_TPU_RNG=rbg`` swaps the *bit-generation* substrate to XLA's
    ``RngBitGenerator`` — the TPU's hardware-accelerated generator — while
    key derivation (``split``/``fold_in``) stays threefry-strength (that is
    jax's "rbg" impl contract; "unsafe_rbg" would weaken derivation too and
    is deliberately not offered).  The fault-coin streams this feeds are
    simulation randomness, not cryptography: every protocol property test
    is outcome-distribution-based, so the only requirement is iid uniform
    bits, which RngBitGenerator provides.  Default remains threefry2x32 —
    fully deterministic across backends — so differential tests and
    recorded artifacts stay reproducible.

    Measured cost, so nobody reaches for this knob expecting a win: on the
    TPU v5e bench chip ``rbg`` is 2.8-3.5x SLOWER than the default for
    these packed-bit coin workloads (same-window A/B, ``RNG_AB_r3.json``)
    — the hardware generator's wide draws don't amortize at the 1-word-
    per-32-coins rate ``coin_bits`` already achieves.  The knob is kept as
    a recorded negative result and an escape hatch for backends where
    threefry underperforms, not as a fast path.
    """
    impl = rng_impl()
    return jr.key(seed, impl=impl)


def rng_impl() -> str:
    """The resolved BA_TPU_RNG impl name (single source of truth for
    reporting in bench artifacts).  Allowlisted: anything else — including
    jax's "unsafe_rbg", which weakens key derivation — is rejected."""
    impl = os.environ.get("BA_TPU_RNG", "threefry2x32")
    if impl not in ("threefry2x32", "rbg"):
        raise ValueError(
            f"BA_TPU_RNG={impl!r} not supported; use 'threefry2x32' or 'rbg'"
        )
    return impl


def uniform_u8(key: jax.Array, shape) -> jnp.ndarray:
    """iid uniform draws on [0, 256) as int32: 4 draws per PRNG word.

    The collapsed SM relay compares uniforms against a per-(instance,
    value) Bernoulli threshold (core/sm.py); drawing 8-bit fields instead
    of ``jr.uniform`` f32 lanes quarters the threefry work — the dominant
    cost of the relay at sweep scale (VERDICT r2) — and drops the
    int->float conversion entirely.  Same [4, nwords] unpack orientation
    as ``coin_bits`` (byte-index major keeps the long word axis on vector
    lanes).
    """
    size = math.prod(shape)
    nwords = -(-size // 4)
    words = jr.bits(key, (nwords,), jnp.uint32)
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    vals = ((words[None, :] >> shifts[:, None]) & 0xFF).astype(jnp.int32)
    return vals.reshape(-1)[:size].reshape(shape)


def or_coin_threshold8(k_cnt: jnp.ndarray, gate: jnp.ndarray) -> jnp.ndarray:
    """8-bit threshold T with P(uniform_u8 < T) = 1 - 2^-k, gated to 0.

    The OR of k iid fair coins fires with probability 1 - 2^-k: exact in
    256ths for k <= 8; for k > 8 the threshold saturates at 256 (fire
    always, absolute error 2^-k, at most 2^-9, per draw).  ``gate`` False
    forces probability 0 (the chain-length bound of the signed relay).
    """
    t = jnp.where(
        k_cnt > 8, 256, 256 - (256 >> jnp.minimum(k_cnt, 8))
    )
    return jnp.where(gate, t, 0)


def coin_words(key: jax.Array, size: int) -> jnp.ndarray:
    """The packed uint32 word stream behind a ``coin_bits(key, shape)``
    draw of ``size`` coins — one ``jr.bits`` call, no unpack.  Callers
    that need a different unpack LAYOUT at the same bit mapping (see
    :func:`unpack_coin_words`) draw here."""
    return jr.bits(key, (-(-size // 32),), jnp.uint32)


def unpack_coin_words(words, shape, dtype=COMMAND_DTYPE) -> jnp.ndarray:
    """Row-major gather unpack of :func:`coin_words` — bit-exact with
    ``coin_bits``'s mapping (coin ``e`` is bit ``e // nwords`` of word
    ``e % nwords``), materialized coin-index-major (ISSUE 13).

    ``coin_bits``'s [32, nwords] unpack is the fast orientation when
    the coins feed ONE fused consumer; but when the coin plane feeds a
    select tree (the strategy lie table), XLA-CPU fuses the transposing
    unpack into every cube-sized consumer and the strided access
    defeats vectorization — measured ~2.3x of the whole agreement
    round (megastep_ab).  Gathering by a static coin->word index map
    instead produces the plane directly in row-major order: same bits,
    fusion-friendly layout.  ``words`` may carry leading batch axes
    (the gather maps index the LAST axis).
    """
    import numpy as _host_np  # host-side static index maps (trace time)

    size = math.prod(shape)
    nwords = -(-size // 32)
    e = _host_np.arange(size)
    wmap = jnp.asarray((e % nwords).reshape(shape).astype(_host_np.int32))
    bmap = jnp.asarray((e // nwords).reshape(shape).astype(_host_np.uint32))
    return ((words[..., wmap] >> bmap) & 1).astype(dtype)


def coin_bits(key: jax.Array, shape, dtype=COMMAND_DTYPE) -> jnp.ndarray:
    """iid fair coins of ``shape``: 0/1 in ``dtype`` (bool for masks).

    Unpack layout: [32, nwords] (bit index major) so the long word axis
    stays on vector lanes — the [nwords, 32] orientation puts a 32-wide
    minor dim on the VPU and runs ~2x slower than plain randint instead
    of ~2x faster (measured r2).  Any fixed bit->element bijection yields
    the same iid coin distribution, so the order is free to choose.
    """
    size = math.prod(shape)
    nwords = -(-size // 32)
    words = jr.bits(key, (nwords,), jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((words[None, :] >> shifts[:, None]) & 1).astype(dtype)
    return bits.reshape(-1)[:size].reshape(shape)
