"""OM(1): one-round oral-message majority vote, batched as tensor ops.

The reference's hot path (SURVEY.md section 4.2) is:

1. Round 1 (push): the primary sends its order to every other general; a
   faulty primary flips an independent coin per recipient — equivocation
   (ba.py:258-282).  The primary's own majority is set to the true command
   without exchanging (ba.py:284-285, SURVEY.md Q1).
2. Round 2 (pull): each lieutenant tallies its own received command plus
   ``get_order()`` from every other non-primary general (ba.py:159-186);
   faulty peers answer a fresh coin per query (ba.py:44-49).  Strict
   majority -> attack/retreat, exact tie -> undefined (ba.py:188-195).

Here both rounds are one fused tensor program over a [B, n, n] vote cube:
round 1 is a masked select on the leader row, round 2 is the all-to-all
"answers" matrix (the O(n^2) RPC mesh becomes a broadcast) and a masked
reduction per receiver.  Faulty behaviour is injected as seeded Bernoulli
masks — the vectorized equivalent of ``random.randint(0, 1)`` per call.

Adversary strategies (scenario engine, ISSUE 5): every send path takes
an optional per-general ``strategies`` plane ([B, n] int8,
``ba_tpu.scenario.strategies`` ids).  ``None`` (the default) and the
all-RANDOM plane are bit-exact with the historical coin behaviour (the
coins are drawn identically and selected through unchanged); other ids
replace a faulty sender's coin values branch-free (collusion, silence,
vote-splitting) so vmap/scan fusion is untouched.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core.quorum import majority_counts, quorum_decision, strict_majority
from ba_tpu.core.rng import coin_bits
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, RETREAT
from ba_tpu.scenario.strategies import lie_values


def _coin(key: jax.Array, shape) -> jnp.ndarray:
    """Fair coin over {RETREAT, ATTACK}, the fault model of ba.py:44-49."""
    return coin_bits(key, shape)


def round1_apply(
    state: SimState, coins: jnp.ndarray,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Round 1 from PRE-DRAWN coins (the coin-injected form, ISSUE 13):
    the batched round math with the PRNG draw factored out, so callers
    that must draw per-instance streams (``agreement_step``'s
    per-instance keys) can vmap ONLY the tiny draw and run this body
    batched — the strategy selects under vmap were the measured
    XLA-CPU pathology (``BENCH_pallas_r13.json``'s A/B)."""
    B, n = state.faulty.shape
    if strategies is not None:
        leader_strategy = jnp.take_along_axis(
            strategies, state.leader[:, None], axis=1
        )
        coins = lie_values(leader_strategy, coins, jnp.arange(n)[None, :])
    leader_onehot = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0
    leader_faulty = jnp.take_along_axis(state.faulty, state.leader[:, None], axis=1)
    received = jnp.where(leader_faulty, coins, state.order[:, None])
    received = jnp.where(leader_onehot, state.order[:, None], received)
    return received


def round1_broadcast(
    key: jax.Array, state: SimState, strategies: jnp.ndarray | None = None
) -> jnp.ndarray:
    """What each general received from the leader: [B, n] int8.

    Honest leader: everyone gets ``order``.  Faulty leader: an independent
    coin per recipient (ba.py:268-273) — or, with ``strategies``, the
    leader's strategy applied per recipient (a SILENT leader's recipients
    receive UNDEFINED, the dropped-message encoding).  The leader itself
    always holds the true order (ba.py:261).  Dead recipients' slots are
    computed but masked out downstream — keeping the shape static for XLA.
    """
    B, n = state.faulty.shape
    return round1_apply(state, _coin(key, (B, n)), strategies)


def round2_votes(
    key: jax.Array,
    state: SimState,
    received: jnp.ndarray,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """The all-to-all answer cube: answers[b, i, j] = what j tells asker i.

    Replaces the reference's O(n^2) ``get_order()`` RPC mesh (ba.py:169-186)
    with one broadcast + masked select.  Faulty responders lie with a fresh
    coin *per asker* — different callers can get different answers, the
    Byzantine behaviour of ba.py:44-49 — or, with ``strategies``, with
    responder j's strategy applied per asker (SILENT answers UNDEFINED,
    which no tally counts: the dead-peer try/except of ba.py:185-186 as an
    adversary choice).  A general answers itself truthfully (its own
    received command is its own first vote, ba.py:163-167) — note a faulty
    general still *tallies* honestly; its lies only affect what others
    hear from it (SURVEY.md Q3).
    """
    B, n = state.faulty.shape
    return round2_apply(state, received, _coin(key, (B, n, n)), strategies)


def round2_apply(
    state: SimState,
    received: jnp.ndarray,
    coins: jnp.ndarray,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Round 2 from PRE-DRAWN coins (see :func:`round1_apply`)."""
    n = state.faulty.shape[1]
    if strategies is not None:
        coins = lie_values(
            strategies[:, None, :], coins, jnp.arange(n)[None, :, None]
        )
    answers = jnp.where(state.faulty[:, None, :], coins, received[:, None, :])
    eye = jnp.eye(n, dtype=bool)[None]
    answers = jnp.where(eye, received[:, None, :], answers)
    return answers


def tally_majorities(state: SimState, received: jnp.ndarray, answers: jnp.ndarray) -> jnp.ndarray:
    """Per-general round-2 majority: [B, n] int8 in {RETREAT, ATTACK, UNDEFINED}.

    Vote weights mirror the reference exactly: asker i counts responder j iff
    j is alive and j is not the primary (ba.py:171-172 skips the primary;
    dead peers vanish via the silent try/except at ba.py:185-186); j == i is
    the general's own received command.  Strict-majority with tie ->
    UNDEFINED (ba.py:188-195).  The leader's majority is its own command
    regardless of faultiness (ba.py:284-285, Q1).
    """
    B, n = state.faulty.shape
    is_leader = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0
    weight = state.alive[:, None, :] & ~is_leader[:, None, :]
    n_attack = jnp.sum((answers == ATTACK) & weight, axis=-1)
    n_retreat = jnp.sum((answers == RETREAT) & weight, axis=-1)
    majority = strict_majority(n_attack, n_retreat)
    majority = jnp.where(is_leader, state.order[:, None], majority)
    return majority


def om1_round(
    key: jax.Array, state: SimState, strategies: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Full OM(1) message exchange -> per-general majorities [B, n] int8.

    ``strategies`` ([B, n] int8, scenario engine) selects each faulty
    general's adversary behaviour; ``None`` and the all-RANDOM plane are
    bit-exact with the coin-only fault model under the same key.
    """
    k1, k2 = jr.split(key)
    received = round1_broadcast(k1, state, strategies)
    answers = round2_votes(k2, state, received, strategies)
    return tally_majorities(state, received, answers)


def om1_round_from_coins(
    state: SimState,
    coins1: jnp.ndarray,
    coins2: jnp.ndarray,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """OM(1) from pre-drawn coin planes ([B, n] and [B, n, n]): the
    batched round math of :func:`om1_round` with the draws factored out
    — bit-identical when fed the same coins (``agreement_step`` vmaps
    only the per-instance draw; tests pin the equivalence)."""
    received = round1_apply(state, coins1, strategies)
    answers = round2_apply(state, received, coins2, strategies)
    return tally_majorities(state, received, answers)


def om1_agreement(key: jax.Array, state: SimState):
    """One complete agreement round: the ``actual-order`` hot path.

    Mirrors SURVEY.md section 4.2 end-to-end: round-1 broadcast, round-2
    all-to-all majority, then the global majority-of-majorities gather and
    3f+1 quorum decision (ba.py:197-255) — all in one jittable program.

    Returns a dict with per-general ``majorities`` [B, n] and the quorum
    outputs ``decision``/``needed``/``total``/``n_attack``/``n_retreat``/
    ``n_undefined`` (all [B]).
    """
    majorities = om1_round(key, state)
    n_attack, n_retreat, n_undefined = majority_counts(majorities, state.alive)
    decision, needed, total = quorum_decision(n_attack, n_retreat, n_undefined)
    return {
        "majorities": majorities,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": n_attack,
        "n_retreat": n_retreat,
        "n_undefined": n_undefined,
    }
