"""Pure-functional consensus core (jittable, batched).

Everything here is a pure function of (PRNG key, state tensors) -> tensors:
no Python-level control flow on traced values, static shapes throughout, so
XLA can fuse the whole agreement round into a handful of TPU kernels.
"""

from ba_tpu.core.types import (
    RETREAT,
    ATTACK,
    UNDEFINED,
    COMMAND_NAMES,
    command_from_name,
    command_name,
)
from ba_tpu.core.state import SimState, make_state
from ba_tpu.core.quorum import (
    quorum_threshold,
    quorum_decision,
    majority_counts,
    quorum_threshold_py,
)
from ba_tpu.core.om import om1_round, om1_agreement
from ba_tpu.core.eig import eig_agreement
from ba_tpu.core.election import elect_lowest_id
from ba_tpu.core.sm import (
    sm_round,
    sm_agreement,
    sm_relay_rounds,
    sm_relay_rounds_collapsed,
    sm_choice,
)

__all__ = [
    "RETREAT",
    "ATTACK",
    "UNDEFINED",
    "COMMAND_NAMES",
    "command_from_name",
    "command_name",
    "SimState",
    "make_state",
    "quorum_threshold",
    "quorum_decision",
    "majority_counts",
    "quorum_threshold_py",
    "om1_round",
    "om1_agreement",
    "eig_agreement",
    "elect_lowest_id",
    "sm_round",
    "sm_agreement",
    "sm_relay_rounds",
    "sm_relay_rounds_collapsed",
    "sm_choice",
]
