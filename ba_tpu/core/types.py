"""Command encoding shared by the whole framework.

The reference passes the strings "attack"/"retreat" on the wire and computes
the string "undefined" for ties (ba.py:159-195).  On TPU we encode commands as
int8 lanes so a full (instances x nodes x nodes) vote tensor stays tiny and
VPU-friendly:

    RETREAT   = 0
    ATTACK    = 1
    UNDEFINED = 2   (only ever produced by majority ties, never sent)

The reference tallies any non-"attack" answer as retreat (ba.py:163-167,
177-181), so on-the-wire values are strictly binary {0, 1}; UNDEFINED appears
only in majority outputs, mirroring ba.py:188-195.
"""

from __future__ import annotations

import jax.numpy as jnp

COMMAND_DTYPE = jnp.int8

RETREAT = 0
ATTACK = 1
UNDEFINED = 2

COMMAND_NAMES = ("retreat", "attack", "undefined")


def command_from_name(name: str) -> int:
    """Map a REPL command string to its int8 code.

    Mirrors the reference's tally rule (ba.py:163-167): anything that is not
    exactly "attack" counts as retreat.
    """
    return ATTACK if name == "attack" else RETREAT


def command_name(code: int) -> str:
    """Inverse mapping, for REPL output (ba.py:389: ``majority={m}``)."""
    return COMMAND_NAMES[int(code)]
