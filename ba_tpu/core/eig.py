"""Recursive OM(m) as a dense EIG (Exponential Information Gathering) tree.

The reference implements only the m=1 special case (one push round + one pull
round, ba.py:258-285 + ba.py:159-195).  This module generalises it to OM(m)
the TPU way: the message tree — node i's copy of "j_k said (j_{k-1} said ...
(leader said v))" for every relay path — is a dense tensor

    V_l[b, i, p]   with p in [n]^l flattened,  shape [B, n, n**l]

so the sending phase is l broadcasts (each the all-to-all relay round, no
RPC loop) and the resolve phase is l masked strict-majority reductions.
Python loops run over the *static* depth m, so under jit the whole tree
unrolls into straight-line XLA ops with static shapes.

Semantics are the natural OM(m) extension of the reference's rules:

- Faulty relays lie with an independent coin per (receiver, path) message
  (generalising ba.py:44-49); a general always keeps its own copies honest
  (generalising ba.py:163-167 / SURVEY.md Q3).
- The resolve majority at path p is over relays j that are alive, not the
  leader, and not already in p; ties (and all-UNDEFINED children) resolve to
  UNDEFINED, generalising ba.py:188-195.
- The leader's own majority is its true order (ba.py:284-285, Q1).

m=1 reproduces OM(1) exactly (test_eig.py checks equality against om.py).
Memory is O(B * n * n**m) int8 — fine for the survey's OM(3), n=10 bench
config; for n=1024-scale clusters use the SM(m) signed-message protocol
(``ba_tpu.core.sm``), which is O(B * n^2) per hop.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core.om import round1_broadcast
from ba_tpu.core.rng import coin_bits
from ba_tpu.core.quorum import majority_counts, quorum_decision, strict_majority
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, RETREAT
from ba_tpu.scenario.strategies import lie_values


def _coin(key: jax.Array, shape) -> jnp.ndarray:
    return coin_bits(key, shape)


def _in_path_mask(n: int, level: int) -> np.ndarray:
    """Static [n**level, n] bool: is relay j one of path p's digits?"""
    P = n**level
    mask = np.zeros((P, n), dtype=bool)
    p = np.arange(P)
    for k in range(level):
        digit = (p // (n**k)) % n
        mask[p, digit] = True
    return mask


def eig_send(
    key: jax.Array,
    state: SimState,
    m: int,
    strategies: jnp.ndarray | None = None,
) -> list[jnp.ndarray]:
    """Sending phase: build levels V_0..V_m of every general's EIG tree.

    V_0[b, i] is what the leader told i (round-1 broadcast with per-recipient
    equivocation coins, ba.py:258-282).  Each subsequent level is one relay
    round: V_{l+1}[b, i, p*n + j] = what j told i about path p — j's honest
    copy V_l[b, j, p], or a fresh coin if j is faulty (self-messages stay
    honest).  ``strategies`` replaces faulty relay j's coin with its
    strategy value per receiver i (scenario engine); all-RANDOM is the
    coin path bit-exactly.
    """
    B, n = state.faulty.shape
    keys = jr.split(key, m + 1)
    levels = [round1_broadcast(keys[0], state, strategies)]
    eye = jnp.eye(n, dtype=bool)
    for level in range(m):
        prev = levels[-1].reshape(B, n, n**level)
        P = n**level
        coins = _coin(keys[level + 1], (B, n, P, n))
        if strategies is not None:
            coins = lie_values(
                strategies[:, None, None, :],
                coins,
                jnp.arange(n)[None, :, None, None],
            )
        # relayed[b, i, p, j] = V_l[b, j, p], broadcast over receivers i.
        relayed = jnp.transpose(prev, (0, 2, 1))[:, None, :, :]
        relayed = jnp.broadcast_to(relayed, (B, n, P, n))
        lying = state.faulty[:, None, None, :] & ~eye[None, :, None, :]
        nxt = jnp.where(lying, coins, relayed)
        levels.append(nxt.reshape(B, n, P * n))
    return levels


def eig_resolve(state: SimState, levels: list[jnp.ndarray]) -> jnp.ndarray:
    """Resolve phase: fold the tree bottom-up with masked strict majorities.

    Returns per-general majorities [B, n] int8.  At each internal path p the
    children p.j are tallied over relays j with j alive, j != leader,
    j not in p (the reference's vote-weight rule ba.py:169-186 generalised);
    strict majority, tie -> UNDEFINED (ba.py:188-195).
    """
    B, n = state.faulty.shape
    m = len(levels) - 1
    resolved = levels[m].reshape(B, n, n**m)
    return _resolve_from(state, levels, resolved, m)


def _resolve_from(
    state: SimState,
    levels: list[jnp.ndarray],
    resolved: jnp.ndarray,
    start_level: int,
) -> jnp.ndarray:
    """The shared tail of the resolve fold: take ``resolved`` values at
    ``start_level`` (dense path: the raw deepest level; fused path: the
    output of eig_deepest_fused one level up) and fold the remaining
    levels down to per-general majorities [B, n] int8."""
    B, n = state.faulty.shape
    is_leader = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0  # [B, n]
    for level in range(start_level - 1, -1, -1):
        P = n**level
        children = resolved.reshape(B, n, P, n)
        in_path = jnp.asarray(_in_path_mask(n, level))  # [P, n] static
        valid = (
            state.alive[:, None, None, :]
            & ~is_leader[:, None, None, :]
            & ~in_path[None, None, :, :]
        )
        n_attack = jnp.sum((children == ATTACK) & valid, axis=-1)
        n_retreat = jnp.sum((children == RETREAT) & valid, axis=-1)
        resolved = strict_majority(n_attack, n_retreat)
        # Degenerate clusters (n < m+2): a path can run out of eligible
        # relays entirely; then the node's own stored copy stands in for the
        # empty majority — the OM(0) base case of the recursion — instead of
        # a spurious tie.  Keeps OM(m) consistent with OM(1) on tiny n.
        n_eligible = jnp.sum(valid, axis=-1)
        resolved = jnp.where(n_eligible > 0, resolved, levels[level].reshape(B, n, P))
    majorities = resolved.reshape(B, n)
    majorities = jnp.where(is_leader, state.order[:, None], majorities)
    return majorities


def _binomial_half(key: jax.Array, k: jnp.ndarray, max_k: int) -> jnp.ndarray:
    """Exact Binomial(k, 1/2) draws: popcount of the first k of max_k
    random bits per lane.  k int32 [...] (0 <= k <= max_k) -> int32 [...].

    The sum of k iid fair coins is all the resolve majority ever consumes,
    so drawing the SUM directly replaces k per-coin tensors with
    ceil(max_k/32) packed words per lane — the coin-collapse that makes
    the fused deepest EIG level possible (same move as the collapsed SM
    relay's OR-threshold, core/sm.py, but for counts instead of ORs).
    """
    W = -(-max_k // 32) if max_k > 0 else 1
    words = jr.bits(key, (*k.shape, W), jnp.uint32)
    base = jnp.arange(W, dtype=jnp.int32) * 32
    nbits = jnp.clip(k[..., None] - base, 0, 32)
    full = jnp.uint32(0xFFFFFFFF)
    mask = jnp.where(
        nbits >= 32, full,
        (jnp.uint32(1) << nbits.astype(jnp.uint32)) - jnp.uint32(1),
    )
    return jax.lax.population_count(words & mask).astype(jnp.int32).sum(-1)


def _path_digit_first(n: int, level: int) -> tuple[np.ndarray, np.ndarray]:
    """Static per-position path digits + first-occurrence flags.

    digits [level, P] int32: digit d of path p; first [level, P] bool:
    True where position d is the FIRST occurrence of that digit value in
    p.  The in-path exclusion is a SET (a relay appearing twice in a
    degenerate path is excluded once — _in_path_mask semantics), so
    per-position corrections must count each distinct digit value once.
    """
    P = n**level
    p = np.arange(P)
    digits = np.stack([(p // (n**d)) % n for d in range(level)])
    first = np.ones((level, P), bool)
    for d in range(level):
        for e in range(d):
            first[d] &= digits[d] != digits[e]
    return digits.astype(np.int32), first


def eig_deepest_fused(
    key: jax.Array,
    state: SimState,
    levels: list[jnp.ndarray],
    m: int,
    max_liars: int | None = None,
) -> jnp.ndarray:
    """The deepest EIG resolve level WITHOUT materializing level m.

    The dense path (eig_send + eig_resolve) builds V_m [B, n, n^m] — at
    n=1024, m=2 a GiB-scale int8 tensor written, read and coin-matched
    once (the r3 bench's HBM-bound 50 rounds/s).  But the deepest resolve
    only consumes per-path TALLIES, and those decompose exactly:

    - honest relays contribute their stored copies: an int8 einsum
      ``n_att_h[b,i,p] = sum_j m1[b,i,j] * att[b,j,p]`` over the
      [B, n, n^(m-1)] level-(m-1) tensor — MXU work, no n^m bytes;
    - lying relays contribute iid fair coins, and a sum of k fair coins
      is Binomial(k, 1/2) — drawn directly via popcount
      (``_binomial_half``), collapsing the coin tensor n-fold;
    - the in-path/self exclusions are per-digit elementwise corrections
      (static gathers, first-occurrence-deduplicated for degenerate
      repeated-digit paths).

    Distributionally identical to the dense deepest level (majorities
    depend on the tallies only; tallies have the same joint law), and
    bit-identical to it when no general is faulty (coin-free).  Returns
    ``resolved`` [B, n, n^(m-1)] ready for the remaining (small) resolve
    levels.

    ``max_liars`` sizes the popcount draw (default n-1, always safe;
    pass the known traitor cap to shrink the random words 32x).  The
    lying count is CLAMPED to it: a cap below the true count silently
    draws Binomial(max_liars, 1/2) instead of Binomial(k, 1/2) —
    under-dispersed tallies, a biased simulation.  Callers must derive
    the cap from the state (bench does ``int(faulty.sum(-1).max())``),
    never hardcode a guess.
    """
    B, n = state.faulty.shape
    level = m - 1  # the resolve level being produced
    P = n**level
    if max_liars is None:
        max_liars = n - 1
    prev = levels[level].reshape(B, n, P)
    att = (prev == ATTACK).astype(jnp.int8)  # relay j's copies, [B, j, P]
    is_leader = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0
    eligible = state.alive & ~is_leader  # [B, j]
    eye = jnp.eye(n, dtype=bool)
    # Honest-contribution weight: eligible j relaying truthfully to i
    # (faulty j's self-copy stays honest — eig_send's ``lying`` mask).
    m1 = eligible[:, None, :] & (~state.faulty[:, None, :] | eye[None])
    lying = eligible[:, None, :] & state.faulty[:, None, :] & ~eye[None]
    n_att = jnp.einsum(
        "bij,bjp->bip", m1.astype(jnp.int8), att,
        preferred_element_type=jnp.int32,
    )
    k = jnp.broadcast_to(
        lying.sum(-1, dtype=jnp.int32)[:, :, None], (B, n, P)
    )
    n_elig = jnp.broadcast_to(
        eligible.sum(-1, dtype=jnp.int32)[:, None, None], (B, n, P)
    )
    digits, firsts = _path_digit_first(n, level)
    arP = jnp.arange(P)
    for d in range(level):
        dg = jnp.asarray(digits[d])  # [P]
        fo = jnp.asarray(firsts[d])[None, None, :]  # [1, 1, P]
        # att[b, dg[p], p]: relay dg[p]'s own copy for path p.
        att_d = att.astype(jnp.int32)[:, dg, arP]  # [B, P]
        m1_d = m1.astype(jnp.int32)[:, :, dg]  # [B, i, P]
        n_att = n_att - jnp.where(fo, m1_d * att_d[:, None, :], 0)
        k = k - jnp.where(fo, lying.astype(jnp.int32)[:, :, dg], 0)
        n_elig = n_elig - jnp.where(
            fo, eligible.astype(jnp.int32)[:, dg][:, None, :], 0
        )
    k = jnp.minimum(k, max_liars)
    n_att = n_att + _binomial_half(key, k, max_liars)
    n_ret = n_elig - n_att
    resolved = strict_majority(n_att, n_ret)
    # Degenerate clusters: no eligible relays -> own stored copy stands in
    # (the OM(0) base case), exactly as eig_resolve's fallback.
    return jnp.where(n_elig > 0, resolved, prev)


def eig_round(
    key: jax.Array,
    state: SimState,
    m: int,
    max_liars: int | None = None,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Full OM(m) exchange -> per-general majorities [B, n] int8.

    m=0 degenerates to "trust the leader" (everyone's majority is what they
    received); m=1 is the reference's protocol.

    For m >= 2 the deepest level runs FUSED (``eig_deepest_fused``): the
    [B, n, n^m] tensor is never built, its honest tallies ride the MXU and
    its coins collapse to Binomial draws — O(n^(m-1)) memory instead of
    O(n^m), distributionally identical, bit-identical without traitors.
    ``BA_TPU_EIG_FUSED=0`` restores the fully-dense path (the two are
    differential-tested against each other).  m=1 always uses the dense
    path, which is bit-exact with om1_round (test_eig.py pins that).

    ``strategies`` (scenario engine) forces the DENSE path for m >= 2:
    the fused level's Binomial coin-collapse is a fair-coin identity and
    does not hold for coordinated adversaries (a strategy-aware fused
    level is a ROADMAP follow-on).  Passing it as None keeps today's
    fused behaviour bit-for-bit.
    """
    import os

    if m == 0:
        # round1_broadcast already pins the leader slot to the true order.
        return round1_broadcast(key, state, strategies)
    fused = (
        m >= 2
        and strategies is None
        and os.environ.get("BA_TPU_EIG_FUSED", "1") != "0"
    )
    if not fused:
        levels = eig_send(key, state, m, strategies)
        return eig_resolve(state, levels)
    k_send, k_coin = jr.split(key)
    levels = eig_send(k_send, state, m - 1)  # levels 0..m-1 only
    resolved = eig_deepest_fused(k_coin, state, levels, m, max_liars)
    return _resolve_from(state, levels, resolved, m - 1)


def eig_agreement(
    key: jax.Array, state: SimState, m: int, max_liars: int | None = None
):
    """OM(m) agreement + global quorum, the generalised ``actual-order``.

    Same output dict as ``om1_agreement`` (ba.py:376-399's hot path).
    ``max_liars`` tightens the fused deepest level's popcount width when
    the traitor cap is known (see eig_deepest_fused).
    """
    majorities = eig_round(key, state, m, max_liars)
    n_attack, n_retreat, n_undefined = majority_counts(majorities, state.alive)
    decision, needed, total = quorum_decision(n_attack, n_retreat, n_undefined)
    return {
        "majorities": majorities,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": n_attack,
        "n_retreat": n_retreat,
        "n_undefined": n_undefined,
    }
