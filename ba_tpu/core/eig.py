"""Recursive OM(m) as a dense EIG (Exponential Information Gathering) tree.

The reference implements only the m=1 special case (one push round + one pull
round, ba.py:258-285 + ba.py:159-195).  This module generalises it to OM(m)
the TPU way: the message tree — node i's copy of "j_k said (j_{k-1} said ...
(leader said v))" for every relay path — is a dense tensor

    V_l[b, i, p]   with p in [n]^l flattened,  shape [B, n, n**l]

so the sending phase is l broadcasts (each the all-to-all relay round, no
RPC loop) and the resolve phase is l masked strict-majority reductions.
Python loops run over the *static* depth m, so under jit the whole tree
unrolls into straight-line XLA ops with static shapes.

Semantics are the natural OM(m) extension of the reference's rules:

- Faulty relays lie with an independent coin per (receiver, path) message
  (generalising ba.py:44-49); a general always keeps its own copies honest
  (generalising ba.py:163-167 / SURVEY.md Q3).
- The resolve majority at path p is over relays j that are alive, not the
  leader, and not already in p; ties (and all-UNDEFINED children) resolve to
  UNDEFINED, generalising ba.py:188-195.
- The leader's own majority is its true order (ba.py:284-285, Q1).

m=1 reproduces OM(1) exactly (test_eig.py checks equality against om.py).
Memory is O(B * n * n**m) int8 — fine for the survey's OM(3), n=10 bench
config; for n=1024-scale clusters use the SM(m) signed-message protocol
(``ba_tpu.core.sm``), which is O(B * n^2) per hop.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core.om import round1_broadcast
from ba_tpu.core.rng import coin_bits
from ba_tpu.core.quorum import majority_counts, quorum_decision, strict_majority
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT


def _coin(key: jax.Array, shape) -> jnp.ndarray:
    return coin_bits(key, shape)


def _in_path_mask(n: int, level: int) -> np.ndarray:
    """Static [n**level, n] bool: is relay j one of path p's digits?"""
    P = n**level
    mask = np.zeros((P, n), dtype=bool)
    p = np.arange(P)
    for k in range(level):
        digit = (p // (n**k)) % n
        mask[p, digit] = True
    return mask


def eig_send(key: jax.Array, state: SimState, m: int) -> list[jnp.ndarray]:
    """Sending phase: build levels V_0..V_m of every general's EIG tree.

    V_0[b, i] is what the leader told i (round-1 broadcast with per-recipient
    equivocation coins, ba.py:258-282).  Each subsequent level is one relay
    round: V_{l+1}[b, i, p*n + j] = what j told i about path p — j's honest
    copy V_l[b, j, p], or a fresh coin if j is faulty (self-messages stay
    honest).
    """
    B, n = state.faulty.shape
    keys = jr.split(key, m + 1)
    levels = [round1_broadcast(keys[0], state)]
    eye = jnp.eye(n, dtype=bool)
    for level in range(m):
        prev = levels[-1].reshape(B, n, n**level)
        P = n**level
        coins = _coin(keys[level + 1], (B, n, P, n))
        # relayed[b, i, p, j] = V_l[b, j, p], broadcast over receivers i.
        relayed = jnp.transpose(prev, (0, 2, 1))[:, None, :, :]
        relayed = jnp.broadcast_to(relayed, (B, n, P, n))
        lying = state.faulty[:, None, None, :] & ~eye[None, :, None, :]
        nxt = jnp.where(lying, coins, relayed)
        levels.append(nxt.reshape(B, n, P * n))
    return levels


def eig_resolve(state: SimState, levels: list[jnp.ndarray]) -> jnp.ndarray:
    """Resolve phase: fold the tree bottom-up with masked strict majorities.

    Returns per-general majorities [B, n] int8.  At each internal path p the
    children p.j are tallied over relays j with j alive, j != leader,
    j not in p (the reference's vote-weight rule ba.py:169-186 generalised);
    strict majority, tie -> UNDEFINED (ba.py:188-195).
    """
    B, n = state.faulty.shape
    m = len(levels) - 1
    is_leader = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0  # [B, n]
    resolved = levels[m].reshape(B, n, n**m)
    for level in range(m - 1, -1, -1):
        P = n**level
        children = resolved.reshape(B, n, P, n)
        in_path = jnp.asarray(_in_path_mask(n, level))  # [P, n] static
        valid = (
            state.alive[:, None, None, :]
            & ~is_leader[:, None, None, :]
            & ~in_path[None, None, :, :]
        )
        n_attack = jnp.sum((children == ATTACK) & valid, axis=-1)
        n_retreat = jnp.sum((children == RETREAT) & valid, axis=-1)
        resolved = strict_majority(n_attack, n_retreat)
        # Degenerate clusters (n < m+2): a path can run out of eligible
        # relays entirely; then the node's own stored copy stands in for the
        # empty majority — the OM(0) base case of the recursion — instead of
        # a spurious tie.  Keeps OM(m) consistent with OM(1) on tiny n.
        n_eligible = jnp.sum(valid, axis=-1)
        resolved = jnp.where(n_eligible > 0, resolved, levels[level].reshape(B, n, P))
    majorities = resolved.reshape(B, n)
    majorities = jnp.where(is_leader, state.order[:, None], majorities)
    return majorities


def eig_round(key: jax.Array, state: SimState, m: int) -> jnp.ndarray:
    """Full OM(m) exchange -> per-general majorities [B, n] int8.

    m=0 degenerates to "trust the leader" (everyone's majority is what they
    received); m=1 is the reference's protocol.
    """
    if m == 0:
        # round1_broadcast already pins the leader slot to the true order.
        return round1_broadcast(key, state)
    levels = eig_send(key, state, m)
    return eig_resolve(state, levels)


def eig_agreement(key: jax.Array, state: SimState, m: int):
    """OM(m) agreement + global quorum, the generalised ``actual-order``.

    Same output dict as ``om1_agreement`` (ba.py:376-399's hot path).
    """
    majorities = eig_round(key, state, m)
    n_attack, n_retreat, n_undefined = majority_counts(majorities, state.alive)
    decision, needed, total = quorum_decision(n_attack, n_retreat, n_undefined)
    return {
        "majorities": majorities,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": n_attack,
        "n_retreat": n_retreat,
        "n_undefined": n_undefined,
    }
