"""Batched simulation state: a dataclass-of-arrays pytree.

The reference keeps per-general mutable state on a ``Process`` object
(ba.py:67-80: ``id``, ``primary``, ``faulty``, ``killed``, ``command``,
``majority``).  Here the whole cluster — and B independent clusters at once —
is a struct of dense arrays, so one ``vmap``-free batched program simulates
thousands of clusters per TPU core.

Axes convention: ``B`` = independent consensus instances, ``n`` = generals
(fixed capacity; elastic membership à la ``g-add``/``g-kill`` ba.py:415-437 is
modelled by the ``alive`` mask so shapes stay static under jit).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ba_tpu.core.types import COMMAND_DTYPE, RETREAT


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SimState:
    """State of B independent Byzantine-generals clusters of capacity n.

    Fields (all arrays; shapes in brackets):

    - ``order``  [B] int8   — the order the commander was told to issue
      (``actual-order <cmd>``, ba.py:376-381).
    - ``leader`` [B] int32  — index of the current primary (the reference
      tracks this as ``primary``/``primary_port``, ba.py:71-72).
    - ``faulty`` [B, n] bool — live fault-injection flags (``g-state <id>
      faulty``, ba.py:401-407).
    - ``alive``  [B, n] bool — membership mask: False = never spawned or
      killed (``g-kill``, ba.py:415-425).
    - ``ids``    [B, n] int32 — general ids (ascending from 1 in the
      reference, ba.py:344-351); kept explicit so election-by-lowest-id
      (ba.py:126-157) is an argmin, not an assumption.
    """

    order: jax.Array
    leader: jax.Array
    faulty: jax.Array
    alive: jax.Array
    ids: jax.Array

    @property
    def batch(self) -> int:
        return self.faulty.shape[0]

    @property
    def n(self) -> int:
        return self.faulty.shape[1]


def make_state(
    batch: int,
    n: int,
    *,
    order: Any = RETREAT,
    leader: Any = 0,
    faulty: Any = None,
    alive: Any = None,
) -> SimState:
    """Build a SimState with broadcastable defaults.

    Defaults mirror a fresh reference cluster: all alive, none faulty, G1
    (index 0, the lowest id) is primary (ba.py:354-363 + ba.py:126-157).
    """
    order_arr = jnp.broadcast_to(jnp.asarray(order, COMMAND_DTYPE), (batch,))
    leader_arr = jnp.broadcast_to(jnp.asarray(leader, jnp.int32), (batch,))
    if faulty is None:
        faulty_arr = jnp.zeros((batch, n), jnp.bool_)
    else:
        faulty_arr = jnp.broadcast_to(jnp.asarray(faulty, jnp.bool_), (batch, n))
    if alive is None:
        alive_arr = jnp.ones((batch, n), jnp.bool_)
    else:
        alive_arr = jnp.broadcast_to(jnp.asarray(alive, jnp.bool_), (batch, n))
    ids = jnp.broadcast_to(jnp.arange(1, n + 1, dtype=jnp.int32), (batch, n))
    return SimState(order_arr, leader_arr, faulty_arr, alive_arr, ids)
