"""SM(m): Lamport-Shostak-Pease signed-message Byzantine agreement, batched.

The reference implements only unsigned oral messages (OM(1)-style,
ba.py:258-285 + 159-195); SM(m) is the BASELINE.json north-star upgrade
("signed messages"), and the protocol that scales to n=1024, m=32 (config
#4): signatures collapse the O(n^m) EIG tree to O(n^2) per relay round,
because a value's *provenance* is carried by its signature chain instead of
by which tree path delivered it.

Protocol (Byzantine Generals paper, algorithm SM(m)):

1. The commander signs its order and sends it to every lieutenant.
2. For m relay rounds, every general forwards each properly-signed value it
   holds (appending its signature); a value's chain at relay round r has
   exactly r distinct signers.
3. Each general ends with the set V of commander-signed values it saw;
   ``choice(V)``: exactly one value -> that value, otherwise (empty, or the
   commander provably equivocated) -> UNDEFINED, mirroring the framework's
   tie convention (ba.py:188-195 maps ties to "undefined"; the paper's
   default-retreat choice is one jnp.where away).

Tensor model (all shapes static; B independent instances):

- ``seen[b, i, v]`` (v in {RETREAT, ATTACK}) is general i's V-set as a
  2-bit mask — the whole state of the protocol.
- Round 1 reuses ``round1_broadcast``: an honest commander sends its order,
  a faulty one equivocates with per-recipient coins (ba.py:268-273
  semantics).
- A relay round is one masked OR-reduction over senders — the all-to-all
  [B, n, n, 2] "who forwards what to whom" cube, the signed analogue of
  OM's answer cube.
- Forgery-freeness is structural: no general can *create* a value-entry —
  values only enter ``seen`` via the commander's round-1 row, so a faulty
  lieutenant's only powers are selective withholding (per-(receiver,
  sender, value) coins) and chain-laundering (below).  That is exactly the
  adversary of the signed-messages model.
- Chain-length soundness: a value accepted at relay round r carries a
  chain of r+1 distinct signers — the commander plus r relaying
  lieutenants (SM(m)'s acceptance rule).  If v was never held by an honest
  general before round r, all of those signers are traitors: the commander
  plus r lieutenant-traitors, i.e. r+1 <= t (coalition size, commander
  included).  The simulation enforces that bound: a coalition-only value
  can be first revealed only at relay rounds r < t_b (traitor count of
  instance b).  Once any honest general holds v, it relays to everyone the
  next round, so later faulty sends are redundant — the model lets them
  happen freely then.  This keeps every simulated execution reachable by a
  real adversary, which is what the IC1/IC2 property tests
  (tests/test_sm.py) rely on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core.om import round1_broadcast
from ba_tpu.core.rng import coin_bits, or_coin_threshold8, uniform_u8
from ba_tpu.core.quorum import majority_counts, quorum_decision
from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT, UNDEFINED
from ba_tpu.scenario.strategies import send_gate


def _initial_seen(state: SimState, received: jnp.ndarray) -> jnp.ndarray:
    """seen[b, i, v] after the commander's signed round-1 push."""
    B, n = state.faulty.shape
    vals = jnp.stack([received == RETREAT, received == ATTACK], axis=-1)
    return vals & state.alive[..., None]


def sm_relay_rounds(
    key: jax.Array,
    state: SimState,
    seen: jnp.ndarray,
    m: int,
    withhold: jnp.ndarray | None = None,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Run m relay rounds; returns the final seen[b, i, v] masks.

    ``withhold`` (optional, [m, B, n, n, 2] bool) pins the faulty senders'
    per-(round, receiver, sender, value) withholding decisions — the
    adversary schedule.  Default: fair coins, the vectorized analogue of
    the reference's per-call randomness (ba.py:44-49).

    ``strategies`` ([B, n] int8, scenario engine) shapes the coin gates
    instead: in SM(m) forgery-freeness is structural, so a strategy can
    only choose WHAT a faulty holder forwards — colluders forward only
    the coalition value, SILENT generals never forward (the ``withhold``
    schedule generalized), ADAPTIVE_SPLIT routes values by receiver
    parity.  Mutually exclusive with ``withhold`` (which pins the full
    cube); all-RANDOM is bit-exact with the default coins.  The
    chain-length soundness bound applies unchanged — gates only restrict
    sends the exact model already allowed.
    """
    if withhold is not None and strategies is not None:
        raise ValueError(
            "withhold pins the full send cube; strategies cannot also apply"
        )
    B, n = state.faulty.shape
    # Coalition size: traitors among the living (incl. a faulty commander).
    t = jnp.sum(state.faulty & state.alive, axis=-1)  # [B]

    honest = state.alive & ~state.faulty  # [B, n]
    for r in range(1, m + 1):  # relay round r: chains have r+1 signers
        if withhold is None:
            coins = coin_bits(jr.fold_in(key, r), (B, n, n, 2), bool)
            if strategies is not None:
                coins = send_gate(
                    strategies[:, None, :, None],
                    coins,
                    jnp.arange(n)[None, :, None, None],
                    jnp.arange(2)[None, None, None, :],
                )
        else:
            coins = ~withhold[r - 1]
        # Who was held by some honest general *before* this round: those
        # values are already public — faulty sends of them are unrestricted
        # (and redundant).  Coalition-only values obey the chain bound.
        held_honest = jnp.any(seen & honest[..., None], axis=1)  # [B, 2]
        # Coalition-only reveal at relay round r needs r+1 <= t distinct
        # traitor signers (commander + r relayers), hence r < t.
        chain_ok = (r < t)[:, None] | held_honest  # [B, 2]
        faulty_sends = (
            seen[:, None, :, :]  # sender j holds v
            & coins
            & state.faulty[:, None, :, None]
            & chain_ok[:, None, None, :]
        )
        honest_sends = seen[:, None, :, :] & honest[:, None, :, None]
        sends = (faulty_sends | honest_sends) & state.alive[:, None, :, None]
        incoming = jnp.any(sends, axis=2)  # [B, n, v] OR over senders
        seen = (seen | incoming) & state.alive[..., None]
    return seen


def sm_relay_rounds_collapsed(
    key: jax.Array,
    state: SimState,
    seen: jnp.ndarray,
    m: int,
) -> jnp.ndarray:
    """O(B*n)-per-round relay, distributionally exact for fair-coin traitors.

    In the exact cube (``sm_relay_rounds`` with ``withhold=None``), receiver
    i's incoming bit for value v is

        (OR of iid fair coins over the k faulty alive holders of v,
         gated by the chain bound)  OR  (v held by any honest general)

    and the coins are independent across receivers.  The OR of k iid
    Bernoulli(1/2) draws is Bernoulli(1 - 2^-k), still independent across
    receivers — so sample that directly and never materialise the
    [B, n, n, 2] send cube.  The transition law of the ``seen`` Markov
    chain matches the exact model round by round up to sampling
    granularity: the packed 8-bit threshold draw (``uniform_u8`` /
    ``or_coin_threshold8``, 4 draws per threefry word — the relay's
    dominant cost at sweep scale) realises Bernoulli(1 - 2^-k) exactly
    for k <= 8 traitor holders and saturates to probability 1 beyond
    (absolute error 2^-k, at most 2^-9, per draw; the earlier f32 ``jr.uniform``
    comparison carried the analogous bound from k = 25 on, at 4x the RNG
    cost).  tests/test_sm.py pins the equivalence both deterministically
    (t = 0) and statistically.

    This is the path that makes the n=1024 scale point (BASELINE config #4)
    cheap: an SM(m) round costs O(B * n) instead of O(B * n^2), so the
    quadratic term survives only where an explicit ``withhold`` schedule
    demands per-(receiver, sender) control.
    """
    B, n = state.faulty.shape
    t = jnp.sum(state.faulty & state.alive, axis=-1)  # [B]
    honest = state.alive & ~state.faulty
    traitor = state.faulty & state.alive

    def one_round(seen, r):
        held_honest = jnp.any(seen & honest[..., None], axis=1)  # [B, 2]
        chain_ok = (r < t)[:, None] | held_honest  # [B, 2]
        k_cnt = jnp.sum(seen & traitor[..., None], axis=1)  # [B, 2]
        thresh = or_coin_threshold8(k_cnt, chain_ok)  # [B, 2]
        u = uniform_u8(jr.fold_in(key, r), (B, n, 2))
        incoming = (u < thresh[:, None, :]) | held_honest[:, None, :]
        seen = (seen | incoming) & state.alive[..., None]
        return seen, None

    # Unroll only short relays: the m<=4 sweep path fuses fully (XLA
    # merges adjacent rounds' elementwise work), while large m keeps the
    # rolled scan — at m=32 even a 4x partial unroll ballooned the remote
    # Mosaic/XLA compile from ~1 min to >14 min (r3), and that config is
    # sequential-latency-bound, so unrolling buys nothing there.
    seen, _ = jax.lax.scan(
        one_round, seen, jnp.arange(1, m + 1), unroll=max(m, 1) if m <= 4 else 1
    )
    return seen


def choice_from_seen(seen: jnp.ndarray) -> jnp.ndarray:
    """The value part of choice(V): [..., 2] bool V-sets -> [...] int8.

    |V| == 1 -> the value; 0 or 2 (silent or provably-equivocating
    commander) -> UNDEFINED.  Shared by the unsharded path and the
    node-sharded one (ba_tpu.parallel.sm_parallel) so the tie convention
    lives in exactly one place.
    """
    has_r = seen[..., 0]
    has_a = seen[..., 1]
    return jnp.where(
        has_a & ~has_r,
        jnp.asarray(ATTACK, COMMAND_DTYPE),
        jnp.where(
            has_r & ~has_a,
            jnp.asarray(RETREAT, COMMAND_DTYPE),
            jnp.asarray(UNDEFINED, COMMAND_DTYPE),
        ),
    )


def sm_choice(state: SimState, seen: jnp.ndarray) -> jnp.ndarray:
    """choice(V) per general: [B, n] int8.

    The commander reports its own order (ba.py:284-285, SURVEY.md Q1
    parity); everyone else takes ``choice_from_seen``.
    """
    n = state.faulty.shape[1]
    choice = choice_from_seen(seen)
    is_leader = jax.nn.one_hot(state.leader, n, dtype=jnp.int8) > 0
    return jnp.where(is_leader, state.order[:, None], choice)


def sm_round(
    key: jax.Array,
    state: SimState,
    m: int,
    withhold: jnp.ndarray | None = None,
    sig_valid: jnp.ndarray | None = None,
    received: jnp.ndarray | None = None,
    collapsed: bool = False,
    strategies: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Full SM(m) exchange -> per-general choices [B, n] int8.

    ``sig_valid`` (optional [B, n] bool) marks which round-1 messages
    carried a valid commander signature — the hook through which the real
    batched Ed25519 kernel (ba_tpu.crypto.ed25519.verify) feeds the
    protocol; invalid messages are dropped before any value enters V.
    ``received`` (optional [B, n] int8) pins the round-1 broadcast — the
    signed pipeline (ba_tpu.crypto.signed) computes it first, signs it
    host-side, then passes it back in so sign and verify cover the same
    values.
    ``collapsed`` selects the O(B*n) fair-coin relay
    (``sm_relay_rounds_collapsed``); incompatible with ``withhold``, which
    needs the per-(receiver, sender) cube.
    ``strategies`` ([B, n] int8, scenario engine) shapes the commander's
    round-1 equivocation and the relay's withhold gates (see
    ``sm_relay_rounds``); it needs the exact cube too, so it is
    incompatible with ``collapsed`` (the collapsed relay's OR-collapse is
    a fair-coin identity) and with an explicit ``withhold``.
    """
    k1, k2 = jr.split(key)
    if received is None:
        received = round1_broadcast(k1, state, strategies)
    seen = _initial_seen(state, received)
    if sig_valid is not None:
        seen = seen & sig_valid[..., None]
    if collapsed:
        if withhold is not None:
            raise ValueError("collapsed relay cannot honor a withhold schedule")
        if strategies is not None:
            raise ValueError(
                "collapsed relay is a fair-coin identity; strategies need "
                "the exact per-(receiver, sender) cube"
            )
        seen = sm_relay_rounds_collapsed(k2, state, seen, m)
    else:
        seen = sm_relay_rounds(k2, state, seen, m, withhold, strategies)
    return sm_choice(state, seen)


def sm_agreement(
    key: jax.Array,
    state: SimState,
    m: int,
    withhold: jnp.ndarray | None = None,
    sig_valid: jnp.ndarray | None = None,
    received: jnp.ndarray | None = None,
    collapsed: bool = False,
    strategies: jnp.ndarray | None = None,
):
    """SM(m) agreement + the 3f+1 quorum layer: the signed ``actual-order``.

    Same output dict as ``om1_agreement`` (the REPL's hot path,
    ba.py:376-399) so backends can swap OM for SM transparently.
    """
    majorities = sm_round(
        key, state, m, withhold, sig_valid, received, collapsed, strategies
    )
    n_attack, n_retreat, n_undefined = majority_counts(majorities, state.alive)
    decision, needed, total = quorum_decision(n_attack, n_retreat, n_undefined)
    return {
        "majorities": majorities,
        "decision": decision,
        "needed": needed,
        "total": total,
        "n_attack": n_attack,
        "n_retreat": n_retreat,
        "n_undefined": n_undefined,
    }
