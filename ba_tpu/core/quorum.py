"""Quorum math: the 3f+1 decision rule, batched.

Reproduces ``Process.quorum`` / ``Process.get_majorities`` (ba.py:197-255)
exactly, including its quirks:

- ``k = (total - 1) // 3`` with ``needed = 2k + 1``, overridden to
  ``total - 1`` when ``total <= 3`` and to ``1`` when ``total == 1``
  (ba.py:227-235).
- Retreat is checked before attack, so a tie at the quorum level prefers
  retreat (ba.py:246-250, SURVEY.md Q7).
- Majorities are gathered from every *alive* node including the primary
  (killed ports are silently dropped by the try/except at ba.py:219-221,
  SURVEY.md Q2).
"""

from __future__ import annotations

import jax.numpy as jnp

from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED, COMMAND_DTYPE


def strict_majority(n_attack: jnp.ndarray, n_retreat: jnp.ndarray) -> jnp.ndarray:
    """Strict-majority vote: tie -> UNDEFINED (ba.py:188-195).

    The single copy of the core decision rule shared by the OM(1) tally, the
    EIG resolve, and the node-sharded round.
    """
    return jnp.where(
        n_attack > n_retreat,
        jnp.asarray(ATTACK, COMMAND_DTYPE),
        jnp.where(
            n_retreat > n_attack,
            jnp.asarray(RETREAT, COMMAND_DTYPE),
            jnp.asarray(UNDEFINED, COMMAND_DTYPE),
        ),
    )


def majority_counts(majorities: jnp.ndarray, alive: jnp.ndarray):
    """(n_attack, n_retreat, n_undefined) over alive nodes, per instance.

    The TPU analogue of the reference's gather loop over every port
    (ba.py:197-223): the O(n) RPC pull becomes one masked reduction.

    majorities: [B, n] int8, alive: [B, n] bool -> three [B] int32.
    """
    alive_i = alive.astype(jnp.int32)
    n_attack = jnp.sum(jnp.where(majorities == ATTACK, alive_i, 0), axis=-1)
    n_retreat = jnp.sum(jnp.where(majorities == RETREAT, alive_i, 0), axis=-1)
    n_undefined = jnp.sum(jnp.where(majorities == UNDEFINED, alive_i, 0), axis=-1)
    return n_attack, n_retreat, n_undefined


def quorum_threshold(total: jnp.ndarray) -> jnp.ndarray:
    """``needed`` as a function of ``total`` voters (ba.py:227-235)."""
    k = (total - 1) // 3
    needed = 2 * k + 1
    needed = jnp.where(total <= 3, total - 1, needed)
    needed = jnp.where(total == 1, 1, needed)
    return needed


def quorum_threshold_py(total: int) -> int:
    """Host-side mirror of :func:`quorum_threshold` for the REPL shell."""
    k = (total - 1) // 3
    needed = 2 * k + 1
    if total <= 3:
        needed = total - 1
    if total == 1:
        needed = 1
    return needed


def quorum_decision(n_attack, n_retreat, n_undefined):
    """Final decision per instance: RETREAT / ATTACK / UNDEFINED.

    Ordering matters and mirrors ba.py:246-253: retreat wins ties because it
    is checked first; UNDEFINED means "cannot be determined".

    Returns (decision [B] int8, needed [B] int32, total [B] int32).
    """
    total = n_attack + n_retreat + n_undefined
    needed = quorum_threshold(total)
    decision = jnp.where(
        needed <= n_retreat,
        jnp.asarray(RETREAT, COMMAND_DTYPE),
        jnp.where(
            needed <= n_attack,
            jnp.asarray(ATTACK, COMMAND_DTYPE),
            jnp.asarray(UNDEFINED, COMMAND_DTYPE),
        ),
    )
    # A fully-dead cluster (total == 0) must not "decide": the reference can
    # never reach this state (its REPL crashes first, SURVEY.md Q4), but our
    # alive-mask API makes it expressible, and needed = total - 1 = -1 would
    # otherwise fabricate a retreat consensus out of zero voters.
    decision = jnp.where(
        total == 0, jnp.asarray(UNDEFINED, COMMAND_DTYPE), decision
    )
    return decision, needed, total
