"""Pallas TPU kernels for the framework's hot ops (with jnp fallbacks).

The reference has no native code (/root/reference is pure Python over
rpyc, SURVEY.md section 2); in this framework the native-code axis is real
Pallas kernels for the ops that dominate the BASELINE workloads:

- ``ladder``   — Ed25519 scalar-mult, VMEM-resident limb-plane arithmetic
  (ba_tpu.ops.planes), two variants: the double-and-add-always
  ``scalar_mult`` (bit-exact vs the jnp path; host-fetch-timed r2:
  ~367k 256-bit scalar-mults/s at 64k lanes vs ~22k/s for the jnp
  matmul-convolution formulation at its best chunk size — ~17x, and the
  jnp path additionally collapses at larger batches)
  and the 4-bit-window ``window_mult`` (5 adds per 4 bits via an
  in-VMEM 16-entry table; ~1.25x the plain ladder, same group element
  modulo projective representation).  Verification runs ``window_mult``
  for [h]A over the mod-L-reduced 256-bit digest (ba_tpu.crypto.scalar).
- ``treeadd``  — 64-way Edwards point-add tree (two 8-to-1 VMEM levels)
  folding the fixed-base window points of [S]B, gathered by two exact
  int8 one-hot MXU einsums; replaces a second ladder entirely (64k
  lanes: ~91 ms vs 729 ms for the jnp scan).
- ``powchain`` — fixed-exponent exponentiation for decompression's
  (p-5)/8 modular square root: a 262-mul addition chain for that
  exponent (~1.9x less work than square-and-multiply), the generic
  bit-chain otherwise.
- ``modl``     — the 512-bit mod-L scalar reduction on byte-limb planes;
  the jnp formulation costs ~110 ms at 64k lanes from XLA materialising
  ~100 small intermediates, the kernel only the real 96 bytes/lane.
- ``decompress`` — the whole RFC 8032 decompression field chain (u, v,
  the uv^3/uv^7 candidates, the root check products) fused around the
  addition chain in one VMEM program; HBM sees only y in and the root
  candidates out.
- ``sha512_kernel`` — the unrolled 80-round SHA-512 compression for the
  verify digest h = SHA-512(R || A || M).
  All together: end-to-end batched verify went from ~8.7k (r1) to ~310k
  verifies/s serialized / ~410k pipelined at 64k-signature chunks
  (measured r2, host-fetch-timed).
- ``majority`` — the fused masked strict-majority reduction (the vote
  count of ba.py:159-195 and every EIG resolve level).  This op is HBM-
  bandwidth-bound and XLA's fusion already saturates it (r2 measurement:
  kernel ties the jnp path at R up to 4.1M rows), so core/eig.py and
  core/om.py deliberately keep their jnp formulations and no production
  path routes through the kernel — it is kept as the measured evidence
  point and as the fusion template (differential tests in test_ops.py).
- ``planes``   — shape-agnostic limb-plane field/Edwards arithmetic shared
  by the kernel bodies and their CPU differential anchors.
- ``sweep_step`` — the ENTIRE north-star signed-sweep agreement round as
  one kernel (round-1 broadcast, signature gate, m collapsed relay
  rounds, choice, quorum) with the TPU's in-core hardware PRNG; +28%
  same-window over the XLA composition (r3), 5/5 on-chip differential
  tests, and a shard_map form for the multi-chip data axis.
"""

from ba_tpu.ops.ladder import scalar_mult as ladder_scalar_mult
from ba_tpu.ops.majority import masked_majority_rows
from ba_tpu.ops.sweep_step import (
    fused_sharded_sweep_step,
    fused_signed_sweep_step,
)

__all__ = [
    "ladder_scalar_mult",
    "masked_majority_rows",
    "fused_signed_sweep_step",
    "fused_sharded_sweep_step",
]
