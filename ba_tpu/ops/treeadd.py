"""Pallas TPU kernel: 64-way Edwards point-add tree, VMEM-resident.

The fixed-base half of signature verification ([S]B, ba_tpu/crypto/
ed25519.fixed_base_mult) gathers one precomputed window point per 4-bit
digit — 64 points per lane — and folds them with 63 complete additions.
The jnp scan form pays the [484 x 43] matmul waste per field mul and
round-trips HBM every step (r2, like-for-like stage timings: 729 ms
for 64k lanes — 4x the
entire 256-step Pallas ladder).  Here the fold runs as two grid levels of
an 8-to-1 in-VMEM reduction:

    64 windows --(kernel, grid j=0..7: 7 adds)--> 8 partials --(kernel)--> 1

so each program holds 8 input points + temporaries (~3 MB VMEM), the tree's
intermediate levels never touch HBM, and total traffic is 73 points/lane
read + 9 written vs the scan's 128 round-trips.

Layout per coordinate: [W, 22, rows, 128] limb planes (the shared
[8, 128]-tile contract of ba_tpu.ops.ladder); the gather that produces the
input stays in XLA — on TPU a 1024-row table take lowers to an MXU one-hot
dot and costs ~0.1 ms for 64k lanes (measured r2), so only the point
arithmetic needs a kernel.

Differential contract: the same group element as folding the entries with
ed25519.point_add (projective representations differ by the fold order;
compared via point_eq).  Like the ladder, the assembled kernel is pinned
on real TPU (BA_TPU_TESTS_ON_TPU=1): the 7-add body hits the same XLA-CPU
compile blowup interpret mode rides on (>9 min for a 2-add body); CPU runs
cover the tile layout and the tree's pairing order instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.crypto.field import LIMBS
from ba_tpu.ops.ladder import TILE, TILE_ROWS, LANES, _from_tiles
from ba_tpu.ops.planes import p_point_add

WINDOWS = 64
_GROUP = 8  # points reduced per program; two levels cover 64


def _tree8_kernel(x_ref, y_ref, z_ref, t_ref, ox_ref, oy_ref, oz_ref, ot_ref):
    pts = [
        tuple([ref[w, i] for i in range(LIMBS)] for ref in (x_ref, y_ref, z_ref, t_ref))
        for w in range(_GROUP)
    ]
    while len(pts) > 1:
        pts = [p_point_add(pts[k], pts[k + 1]) for k in range(0, len(pts), 2)]
    for out_ref, planes in zip((ox_ref, oy_ref, oz_ref, ot_ref), pts[0]):
        for i in range(LIMBS):
            out_ref[0, i] = planes[i]


def _level(coords: list, n_in: int, grid_tiles: int, interpret: bool) -> list:
    """One 8-to-1 reduction level: [n_in, 22, rows, 128] -> [n_in//8, ...]."""
    n_out = n_in // _GROUP
    in_spec = pl.BlockSpec(
        (_GROUP, LIMBS, TILE_ROWS, LANES),
        lambda i, j: (j, 0, i, 0),
        memory_space=pltpu.VMEM,
    )
    out_spec = pl.BlockSpec(
        (1, LIMBS, TILE_ROWS, LANES),
        lambda i, j: (j, 0, i, 0),
        memory_space=pltpu.VMEM,
    )
    rows = coords[0].shape[2]
    out_shape = jax.ShapeDtypeStruct((n_out, LIMBS, rows, LANES), jnp.int32)
    return list(
        pl.pallas_call(
            _tree8_kernel,
            grid=(grid_tiles, n_out),
            in_specs=[in_spec] * 4,
            out_specs=(out_spec,) * 4,
            out_shape=(out_shape,) * 4,
            interpret=interpret,
        )(*coords)
    )


def entries_to_planes(entries: jnp.ndarray, batch_pad: int) -> list:
    """[B, W, 4, 22] -> per-coordinate [W, 22, rows, 128] plane tiles
    (zero-padded lanes; zeros are add-safe and discarded on unpad)."""
    B, W = entries.shape[:2]
    e = jnp.pad(entries, ((0, batch_pad - B), (0, 0), (0, 0), (0, 0)))
    e = jnp.transpose(e, (2, 1, 3, 0))  # [4, W, 22, batch_pad]
    return [e[c].reshape(W, LIMBS, batch_pad // LANES, LANES) for c in range(4)]


def fold64_planes(coords: list, B: int, interpret: bool = False) -> tuple:
    """Fold plane-major entries [64, 22, rows, 128] x 4 -> Point [B, 22] x 4
    via the two 8-to-1 kernel levels."""
    grid_tiles = (coords[0].shape[2] * LANES) // TILE
    coords = _level(coords, WINDOWS, grid_tiles, interpret)
    coords = _level(coords, _GROUP, grid_tiles, interpret)
    return tuple(_from_tiles(c[0], B) for c in coords)


@functools.partial(jax.jit, static_argnames=("interpret",))
def tree_point_add(entries: jnp.ndarray, *, interpret: bool = False) -> tuple:
    """Fold 64 points per lane: entries [B, 64, 4, 22] int32 (carried-form
    limbs; gathered table rows are canonical, which is stricter) -> Point
    tuple of [B, 22] arrays, equal to left-fold/any-order point_add of the
    64 entries (the complete addition law is associative on the group).
    """
    B, W = entries.shape[:2]
    assert W == WINDOWS, f"tree_point_add is specialized to 64 windows, got {W}"
    batch_pad = -(-B // TILE) * TILE
    coords = entries_to_planes(entries, batch_pad)
    return fold64_planes(coords, B, interpret)
