"""Pallas TPU kernel: the SHA-512 compression function, fully unrolled.

Third verify bottleneck (after the scalar-mult ladder and the sqrt pow
chain): the challenge hash h = SHA-512(R || A || M) costs ~23 ms at 16k
lanes on the jnp path — whose lax.scan shifts a 16-word sliding window
with two [16, B] concatenates per round (~160 MB of shuffling per block).
In a kernel the 80 rounds unroll statically, so the message-schedule
window is Python-level register renaming, the round constants are
immediate scalars, and the whole block transform stays in VMEM.

64-bit words live as (hi, lo) uint32 plane pairs exactly as in
ba_tpu/crypto/sha512.py — the round functions are imported from there, so
kernel and jnp path share one implementation of the SHA-512 math and the
differential contract is plumbing-only (tests/test_ops.py pins both).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.crypto.sha512 import (
    _IH,
    _IL,
    _KH,
    _KL,
    _add64,
    _add64_many,
    _big_sigma0,
    _big_sigma1,
    _small_sigma0,
    _small_sigma1,
)
from ba_tpu.ops.ladder import LANES, TILE, TILE_ROWS, _from_tiles, _to_tiles

ROWS = TILE_ROWS


def _sha_kernel(n_blocks, wh_ref, wl_ref, out_ref):
    state = _sha_state(n_blocks, wh_ref, wl_ref)
    for i, (sh, sl) in enumerate(state):
        out_ref[2 * i] = sh
        out_ref[2 * i + 1] = sl


def _sha_state(n_blocks, wh_ref, wl_ref):
    """The compression body shared by the plain and fused kernels:
    returns the 8 final (hi, lo) uint32 state plane pairs."""
    shape = (ROWS, LANES)
    state = [
        (
            jnp.full(shape, jnp.uint32(int(_IH[i]))),
            jnp.full(shape, jnp.uint32(int(_IL[i]))),
        )
        for i in range(8)
    ]
    for blk in range(n_blocks):
        w = [
            (wh_ref[blk * 16 + i], wl_ref[blk * 16 + i]) for i in range(16)
        ]
        regs = list(state)
        for t in range(80):
            if t < 16:
                wt = w[t]
            else:
                s0 = _small_sigma0(*w[t - 15])
                s1 = _small_sigma1(*w[t - 2])
                wt = _add64_many(s1, w[t - 7], s0, w[t - 16])
                w.append(wt)
            a, b, c, d, e, f, g, h = regs
            S1 = _big_sigma1(*e)
            ch = (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])
            k = (jnp.uint32(int(_KH[t])), jnp.uint32(int(_KL[t])))
            t1 = _add64_many(h, S1, ch, k, wt)
            S0 = _big_sigma0(*a)
            maj = (
                (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
                (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
            )
            t2 = _add64(S0[0], S0[1], maj[0], maj[1])
            regs = [
                _add64(t1[0], t1[1], t2[0], t2[1]),
                a, b, c,
                _add64(d[0], d[1], t1[0], t1[1]),
                e, f, g,
            ]
        state = [
            _add64(sh, sl, nh, nl)
            for (sh, sl), (nh, nl) in zip(state, regs)
        ]
    return state


def _sha_modl_kernel(n_blocks, wh_ref, wl_ref, out_ref):
    """SHA-512 -> digest mod L, fused: the challenge/nonce scalar path of
    verification and signing (h = H(R||A||M) mod L, r = H(prefix||M) mod
    L) never writes the 64-byte digest to HBM — the state words split
    into byte planes in registers and flow straight into the mod-L fold
    chain (ops/modl.modl_core)."""
    from ba_tpu.ops.modl import modl_core

    state = _sha_state(n_blocks, wh_ref, wl_ref)
    v = []
    for sh, sl in state:
        # Digest bytes are the big-endian bytes of hi then lo per word;
        # extract in uint32 (logical shifts), convert the in-range bytes.
        for word in (sh, sl):
            v.extend(
                ((word >> s) & 0xFF).astype(jnp.int32)
                for s in (24, 16, 8, 0)
            )
    modl_core(v, out_ref)


@functools.partial(jax.jit, static_argnames=("n_blocks", "interpret"))
def sha512_blocks_mod_l(wh: jnp.ndarray, wl: jnp.ndarray, n_blocks: int,
                        *, interpret: bool = False) -> jnp.ndarray:
    """Fused compress + mod-L: same inputs as ``sha512_blocks`` but the
    output is the digest reduced mod L — uint8 [B, 32]."""
    B = wh.shape[0]
    batch_pad = -(-B // TILE) * TILE
    nw = n_blocks * 16

    spec = lambda k: pl.BlockSpec((k, ROWS, LANES), lambda i: (0, i, 0),
                                  memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_sha_modl_kernel, n_blocks),
        grid=(batch_pad // TILE,),
        in_specs=[spec(nw), spec(nw)],
        out_specs=spec(32),
        out_shape=jax.ShapeDtypeStruct(
            (32, batch_pad // LANES, LANES), jnp.int32
        ),
        interpret=interpret,
    )(
        _to_tiles(wh.reshape(B, nw), batch_pad),
        _to_tiles(wl.reshape(B, nw), batch_pad),
    )
    return _from_tiles(out, B).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("n_blocks", "interpret"))
def sha512_blocks(wh: jnp.ndarray, wl: jnp.ndarray, n_blocks: int,
                  *, interpret: bool = False) -> jnp.ndarray:
    """Compress padded blocks: wh/wl [B, n_blocks, 16] uint32 (big-endian
    word halves) -> 16 uint32 state words [B, 16] ((hi, lo) interleaved).
    """
    B = wh.shape[0]
    batch_pad = -(-B // TILE) * TILE
    nw = n_blocks * 16

    spec = lambda k: pl.BlockSpec((k, ROWS, LANES), lambda i: (0, i, 0),
                                  memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_sha_kernel, n_blocks),
        grid=(batch_pad // TILE,),
        in_specs=[spec(nw), spec(nw)],
        out_specs=spec(16),
        out_shape=jax.ShapeDtypeStruct(
            (16, batch_pad // LANES, LANES), jnp.uint32
        ),
        interpret=interpret,
    )(
        _to_tiles(wh.reshape(B, nw), batch_pad),
        _to_tiles(wl.reshape(B, nw), batch_pad),
    )
    return _from_tiles(out, B)
