"""Pallas TPU kernel: the ENTIRE mutating scenario round, fused (ISSUE 13).

The flexible scenario path (``parallel/pipeline._scenario_scan``) runs
each round as a chain of small XLA programs — event application, the
lowest-alive-id election, two threefry coin draws, the strategy lie
table, the OM(1) answer cube, three tallies and the counter fold — whose
XLA-CPU form pays per-op materialization and (pre-ISSUE-13) the
strategy select-chain pathology, leaving it ~27x behind the fused sweep
kernel (``ops/sweep_step.py``) in rounds/dispatch-second
(``BENCH_scenario_r8.json``).  This kernel runs ``rounds`` complete
mutating rounds for the whole batch inside one ``pallas_call``:

- every intermediate (state planes, coin words, the answer cube, the
  per-round tallies) lives in VMEM/registers; the state planes are read
  once and written once;
- **in-kernel threefry2x32 counter mode**: the donated
  :class:`~ba_tpu.parallel.pipeline.KeySchedule` threads through the
  kernel's key/counter arguments, and the kernel reproduces jax's
  ``fold_in`` → ``fold_in`` → ``split`` → ``bits`` derivation chain
  EXACTLY (int32 add/xor/rotate lanes; logical shifts emulated with
  arithmetic-shift + static masks so everything stays in the int32
  lanes Mosaic likes) — so RANDOM and ADAPTIVE_SPLIT coins are
  **bit-exact** against the XLA scan core under the same keys, which is
  what lets one campaign cross engines mid-run (checkpoints, serving
  cohorts, parity tests).  The word layout is the counter-mode pair
  schedule of jax's ``threefry_2x32`` (odd sizes pad with one zero
  count; ``coin_bits``'s bit-index-major unpack) — precomputed as
  static index maps per (n) specialization, so the kernel does no
  integer division;
- strategies evaluate the SAME branch-free lie table the XLA path uses
  (:func:`ba_tpu.scenario.strategies.lie_table` — one formulation, two
  engines);
- the per-round outputs (decision column, per-instance leaders, the
  3-bin histogram, the cumulative counter block) park into register
  accumulators via lane selects (the ``ops/sweep_step.py`` trick) and
  land in one store after the round loop — no dynamic output stores.

Three jitted wrappers mirror the XLA megasteps' signatures, return
tuples and donation contracts exactly (``pallas_scenario_megastep`` /
``pallas_pipeline_megastep`` / ``pallas_coalesced_megastep``), so the
engine's dispatch loops swap callables without touching the depth-k
retire discipline, the counter thread, or checkpoints.  House pattern:
``interpret=True`` runs the kernel as jnp ops on CPU (CI pins
bit-exactness there, tests/test_megastep.py); ``interpret=False``
compiles through Mosaic on TPU — reachable via
``pipeline_sweep(engine="pallas")`` / ``BA_TPU_ENGINE`` (the tunnel
measurement rides the consolidated real-TPU pass, ROADMAP).

SUPPORT ENVELOPE (the engine-select seam enforces it eagerly):
OM(1) only (``m == 1`` — the dense EIG tree for m >= 2 stays on the
XLA core), single device (mesh ``data == 1``), oral messages (the
signed path host-signs between rounds and never enters the scenario
scan).  Everything here is batch-local, so the VMEM budget is
O(B * n^2) for the answer cube — the serving and scenario shapes the
ROADMAP names; huge-batch campaigns stay on the XLA core via
``engine="auto"`` fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.core.state import SimState
from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT, UNDEFINED
from ba_tpu.scenario.strategies import lie_table

LANES = 128
SUBLANES = 8
_INT_MAX = np.int32(np.iinfo(np.int32).max)
# The counter block is at most SCENARIO_COUNTER_NAMES long (5); padded
# to one sublane tile.  Spelled locally (ops must not import parallel).
_CPAD = 8


def _pad_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


# -- in-kernel threefry2x32 ---------------------------------------------------
#
# jax's threefry_2x32 on int32 lanes.  Additions wrap (two's complement
# == uint32 mod 2^32), XOR is bitwise, and the rotate's logical right
# shift is emulated as arithmetic-shift-then-mask (the shift amounts
# are STATIC rotation constants, so the masks fold to literals) —
# keeping the whole cipher in plain int32 vector ops.  Verified
# word-exact against jax.random in tests/test_megastep.py.

_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))
_PARITY = 0x1BD11BDA  # threefry key-schedule parity constant


def _lshr(x, k: int):
    """Logical right shift by STATIC k in int32 lanes."""
    if k == 0:
        return x
    return (x >> k) & ((1 << (32 - k)) - 1)


def _rotl(x, d: int):
    return (x << d) | _lshr(x, 32 - d)


def tf2x32(k0, k1, x0, x1):
    """One threefry2x32 block: int32 key words + count words (any
    mutually broadcastable shapes) -> the two int32 output words."""
    ks2 = k0 ^ k1 ^ _PARITY
    ks = (k0, k1, ks2)
    x0 = x0 + k0
    x1 = x1 + k1
    for i in range(5):
        for r in _ROTATIONS[i % 2]:
            x0 = x0 + x1
            x1 = _rotl(x1, r)
            x1 = x0 ^ x1
        x0 = x0 + ks[(i + 1) % 3]
        x1 = x1 + ks[(i + 2) % 3] + (i + 1)
    return x0, x1


def _fold_in(k0, k1, data):
    """jax's ``fold_in``: threefry of the key over the 32-bit data word
    (count pair ``(0, data)`` — the uint32 ``threefry_seed`` layout)."""
    zero = jnp.zeros_like(data)
    return tf2x32(k0, k1, zero, data)


def _split2(k0, k1):
    """jax's ``split(key)`` -> two keys: counter-mode words over
    ``iota(4)`` with the pair schedule (0,2)/(1,3); subkey ``a`` takes
    the first output word of each pair, ``b`` the second."""
    two = jnp.full_like(k0, 2)
    three = jnp.full_like(k0, 3)
    ya0, ya1 = tf2x32(k0, k1, jnp.zeros_like(k0), two)
    yb0, yb1 = tf2x32(k0, k1, jnp.ones_like(k0), three)
    return (ya0, yb0), (ya1, yb1)


def _word_maps(size: int, shape: tuple) -> np.ndarray:
    """Static counter/bit maps reproducing ``coin_bits``'s unpack for a
    draw of ``size`` coins, laid out as ``shape`` (row-major).

    Returns int32 ``[4, *shape]``: rows (c0, c1, sel, bit) where the
    threefry word behind coin ``e`` is ``tf(key, c0, c1)[sel]`` and the
    coin is bit ``bit`` of it — the pair schedule of jax's
    ``threefry_2x32`` over ``iota(nwords)`` (odd word counts pair their
    last count with a zero pad) composed with the bit-index-major
    unpack of ``core/rng.coin_bits`` (coin e -> word ``e % nwords``,
    bit ``e // nwords``).  Padded positions (beyond ``size``) clamp to
    coin 0 — their values are masked off downstream, the clamp only
    keeps the shift amounts in range.
    """
    nwords = -(-size // 32)
    half = (nwords + (nwords % 2)) // 2
    e = np.minimum(np.arange(int(np.prod(shape)), dtype=np.int64), size - 1)
    w = e % nwords
    bit = e // nwords
    j = np.where(w < half, w, w - half)
    c1 = np.where(j + half < nwords, j + half, 0)
    sel = (w < half).astype(np.int64)  # 1 -> first output word
    return np.stack([j, c1, sel, bit]).reshape((4,) + shape).astype(np.int32)


def _coins(k0, k1, maps):
    """Draw the mapped coin block: ``maps`` is a ``[4, ...]`` int32
    array (:func:`_word_maps` rows, broadcastable against the key
    words) -> int32 coins in {0, 1}."""
    y0, y1 = tf2x32(k0, k1, maps[0], maps[1])
    word = jnp.where(maps[2] == 1, y0, y1)
    # Low bit survives the arithmetic shift for any bit index < 32.
    return (word >> maps[3]) & 1


# -- the kernel ---------------------------------------------------------------


def _megastep_kernel(
    *refs,
    B: int,
    n: int,
    rounds: int,
    scenario: bool,
    slot_mode: bool,
    with_counters: bool,
):
    """One fused dispatch: ``rounds`` mutating agreement rounds for the
    whole [B, n] batch.  ``refs`` unpacks positionally in the order
    :func:`_megastep_call` builds its operand list (statics decide
    which refs exist).  All arithmetic is int32; every per-round output
    parks into a lane-indexed register accumulator and stores once."""
    it = iter(refs)
    ctr_ref = next(it)  # SMEM [1]: the schedule counter at entry
    maps1_ref = next(it)  # [8, n_pad] round-1 coin maps (4 live rows)
    maps2_ref = next(it)  # [4, n_pad, n_pad] round-2 cube maps
    order_ref = next(it)  # [B_pad, 1]
    leader_ref = next(it)
    k0_ref = next(it)  # [B_pad, 1] per-row base-key words
    k1_ref = next(it)
    idx_ref = next(it)  # [B_pad, 1] instance-index fold (0s in slot mode)
    faulty_ref = next(it)  # [B_pad, n_pad]
    alive_ref = next(it)
    ids_ref = next(it)
    strat_ref = next(it) if scenario else None
    ctr_in_ref = next(it) if with_counters else None
    if scenario:
        ev_kill_ref = next(it)  # [rounds, B_pad, n_pad] each
        ev_revive_ref = next(it)
        ev_fset_ref = next(it)
        ev_sset_ref = next(it)
    out_alive_ref = next(it)
    out_faulty_ref = next(it)
    out_leader_ref = next(it)
    out_strat_ref = next(it) if scenario else None
    out_maj_ref = next(it) if slot_mode else None
    out_dec_ref = next(it)  # [B_pad, R_pad]
    out_lead_ref = next(it) if scenario else None
    out_hist_ref = next(it) if not slot_mode else None  # [8, R_pad]
    out_ctr_ref = next(it) if with_counters else None

    B_pad, n_pad = faulty_ref.shape
    R_pad = out_dec_ref.shape[1]
    n_counters = 5 if scenario else 3

    iota_j = jax.lax.broadcasted_iota(jnp.int32, (B_pad, n_pad), 1)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (B_pad, 1), 0)
    valid_row = (iota_b < B).astype(jnp.int32)  # padded batch rows
    lane_r = jax.lax.broadcasted_iota(jnp.int32, (1, R_pad), 1)
    crow = jax.lax.broadcasted_iota(jnp.int32, (_CPAD, 1), 0)
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (1, n_pad, n_pad), 1)
        == jax.lax.broadcasted_iota(jnp.int32, (1, n_pad, n_pad), 2)
    ).astype(jnp.int32)
    recv_i = jax.lax.broadcasted_iota(jnp.int32, (1, n_pad, 1), 1)

    ctr0 = ctr_ref[0]
    order = order_ref[:]
    k0c, k1c, idxc = k0_ref[:], k1_ref[:], idx_ref[:]
    maps1 = maps1_ref[0:4, :][:, None, :]  # [4, 1, n_pad]
    maps2 = maps2_ref[:][:, None, :, :]  # [4, 1, n_pad, n_pad]

    def _col(values):
        """Stack up-to-_CPAD scalars into an [_CPAD, 1] column via row
        selects (Mosaic has no scalar scatter; the rows are static)."""
        col = jnp.zeros((_CPAD, 1), jnp.int32)
        for r, v in enumerate(values):
            col = jnp.where(crow == r, v, col)
        return col

    def body(rr, carry):
        (alive, faulty, leader, strat, ctr_cum, maj_keep, acc) = carry

        if scenario:
            kill = ev_kill_ref[rr]
            revive = ev_revive_ref[rr]
            fset = ev_fset_ref[rr]
            sset = ev_sset_ref[rr]
            alive = jnp.maximum(alive * (1 - kill), revive)
            faulty = jnp.where(fset >= 0, (fset > 0).astype(jnp.int32), faulty)
            strat = jnp.where(sset >= 0, sset, strat)
            lmask = (iota_j == leader).astype(jnp.int32)
            leader_alive = jnp.sum(lmask * alive, axis=1, keepdims=True)
            # elect_lowest_id as masked min + first-index-of-min (the
            # argmin tie rule): all-dead rows elect index 0, like the
            # XLA path's argmin over an all-big row.
            masked = jnp.where(alive > 0, ids_ref[:], _INT_MAX)
            rowmin = jnp.min(masked, axis=1, keepdims=True)
            elected = jnp.min(
                jnp.where(masked == rowmin, iota_j, n_pad),
                axis=1,
                keepdims=True,
            )
            leader = jnp.where(leader_alive > 0, leader, elected)

        lmask = (iota_j == leader).astype(jnp.int32)
        leader_faulty = jnp.sum(lmask * faulty, axis=1, keepdims=True)

        # Round keys: fold_in(fold_in(base, ctr0 + rr), instance index)
        # then split — jax's exact derivation chain, per row.
        kr0, kr1 = _fold_in(k0c, k1c, jnp.full_like(k0c, ctr0) + rr)
        ki0, ki1 = _fold_in(kr0, kr1, idxc)
        (ka0, ka1), (kb0, kb1) = _split2(ki0, ki1)

        # Round 1 (push): n coins per instance; faulty leader lies per
        # recipient through the shared lie table, honest leader pushes
        # the order, the leader itself always holds the true order.
        coin1 = _coins(ka0, ka1, maps1)  # [B_pad, n_pad]
        if scenario:
            lstrat = jnp.sum(lmask * strat, axis=1, keepdims=True)
            known, even_v, odd_v = lie_table(lstrat, jnp.int32)
            coin1 = jnp.where(
                known, jnp.where((iota_j & 1) == 0, even_v, odd_v), coin1
            )
        received = jnp.where(leader_faulty > 0, coin1, order)
        received = jnp.where(lmask > 0, order, received)

        # Round 2 (pull): the [B, n, n] answer cube — responder j lies
        # to asker i with a fresh coin (or its strategy's table row);
        # the diagonal is the general's own received command.
        coin2 = _coins(kb0[:, None, :], kb1[:, None, :], maps2)
        if scenario:
            known2, ev2, ov2 = lie_table(strat[:, None, :], jnp.int32)
            coin2 = jnp.where(
                known2, jnp.where((recv_i & 1) == 0, ev2, ov2), coin2
            )
        lied = faulty[:, None, :] * (1 - eye)
        answers = jnp.where(lied > 0, coin2, received[:, None, :])
        weight = (alive * (1 - lmask))[:, None, :]
        n_att = jnp.sum((answers == ATTACK) * weight, axis=2)
        n_ret = jnp.sum((answers == RETREAT) * weight, axis=2)
        maj = jnp.where(
            n_att > n_ret,
            jnp.int32(ATTACK),
            jnp.where(n_ret > n_att, jnp.int32(RETREAT), jnp.int32(UNDEFINED)),
        )
        maj = jnp.where(lmask > 0, order, maj)

        # Majority-of-majorities + the reference's 3f+1 thresholds
        # (core/quorum.py formulas verbatim, incl. the zero-voter guard).
        c_att = jnp.sum((maj == ATTACK) * alive, axis=1, keepdims=True)
        c_ret = jnp.sum((maj == RETREAT) * alive, axis=1, keepdims=True)
        c_und = jnp.sum((maj == UNDEFINED) * alive, axis=1, keepdims=True)
        total = c_att + c_ret + c_und
        needed = 2 * ((total - 1) // 3) + 1
        needed = jnp.where(total <= 3, total - 1, needed)
        needed = jnp.where(total == 1, 1, needed)
        dec = jnp.where(
            needed <= c_ret,
            jnp.int32(RETREAT),
            jnp.where(needed <= c_att, jnp.int32(ATTACK), jnp.int32(UNDEFINED)),
        )
        dec = jnp.where(total == 0, jnp.int32(UNDEFINED), dec)

        # Per-instance property verdicts shared by both counter modes.
        big = jnp.int32(127)  # the XLA delta's int8 sentinel
        lt = alive * (1 - lmask)
        mmax = jnp.max(jnp.where(lt > 0, maj, -big), axis=1, keepdims=True)
        mmin = jnp.min(jnp.where(lt > 0, maj, big), axis=1, keepdims=True)
        disagree = (mmax != mmin) & (
            jnp.sum(lt, axis=1, keepdims=True) > 0
        )
        traitor = jnp.sum(faulty * alive, axis=1, keepdims=True) > 0
        equivocation = (disagree & traitor).astype(jnp.int32)
        if scenario:
            hlt = lt * (1 - faulty)
            hmax = jnp.max(jnp.where(hlt > 0, maj, -big), axis=1, keepdims=True)
            hmin = jnp.min(jnp.where(hlt > 0, maj, big), axis=1, keepdims=True)
            ic1 = (
                (hmax != hmin)
                & (jnp.sum(hlt, axis=1, keepdims=True) > 0)
            ).astype(jnp.int32)
            disobey = (
                jnp.sum(hlt * (maj != order), axis=1, keepdims=True) > 0
            )
            ic2 = ((leader_faulty == 0) & disobey).astype(jnp.int32)

        park = lane_r == rr
        (acc_dec, acc_lead, acc_hist, acc_ctr) = acc
        acc_dec = jnp.where(park, dec, acc_dec)
        if scenario:
            acc_lead = jnp.where(park, leader, acc_lead)
        if slot_mode:
            if with_counters:
                cols = [
                    (dec == UNDEFINED).astype(jnp.int32),
                    jnp.ones_like(dec),  # one instance: always unanimous
                    equivocation,
                ]
                if scenario:
                    cols += [ic1, ic2]
                ctr_cum = [c + d for c, d in zip(ctr_cum, cols)]
                acc_ctr = [
                    jnp.where(park, c, a) for c, a in zip(ctr_cum, acc_ctr)
                ]
            maj_keep = maj
        else:
            h0 = jnp.sum(valid_row * (dec == RETREAT), keepdims=True)
            h1 = jnp.sum(valid_row * (dec == ATTACK), keepdims=True)
            h2 = jnp.sum(valid_row * (dec == UNDEFINED), keepdims=True)
            acc_hist = jnp.where(park, _col([h0, h1, h2]), acc_hist)
            if with_counters:
                qf = jnp.sum(valid_row * (dec == UNDEFINED), keepdims=True)
                unanimous = (
                    jnp.maximum(jnp.maximum(h0, h1), h2) == B
                ).astype(jnp.int32)
                eq = jnp.sum(valid_row * equivocation, keepdims=True)
                deltas = [qf, unanimous, eq]
                if scenario:
                    deltas += [
                        jnp.sum(valid_row * ic1, keepdims=True),
                        jnp.sum(valid_row * ic2, keepdims=True),
                    ]
                ctr_cum = ctr_cum + _col(deltas)
                acc_ctr = jnp.where(park, ctr_cum, acc_ctr)

        acc = (acc_dec, acc_lead, acc_hist, acc_ctr)
        return (alive, faulty, leader, strat, ctr_cum, maj_keep, acc)

    zero_plane = jnp.zeros((B_pad, n_pad), jnp.int32)
    zero_br = jnp.zeros((B_pad, R_pad), jnp.int32)
    if with_counters:
        if slot_mode:
            ctr_init = [
                ctr_in_ref[:, c : c + 1] for c in range(n_counters)
            ]
            acc_ctr0 = [zero_br for _ in range(n_counters)]
        else:
            ctr_init = ctr_in_ref[:]  # [_CPAD, 1]
            acc_ctr0 = jnp.zeros((_CPAD, R_pad), jnp.int32)
    else:
        ctr_init, acc_ctr0 = jnp.zeros((1, 1), jnp.int32), zero_br
    carry0 = (
        alive_ref[:],
        faulty_ref[:],
        leader_ref[:],
        strat_ref[:] if scenario else zero_plane,
        ctr_init,
        jnp.full((B_pad, n_pad), UNDEFINED, jnp.int32),
        (
            zero_br,  # decisions
            zero_br,  # leaders
            jnp.zeros((_CPAD, R_pad), jnp.int32),  # histogram bins
            acc_ctr0,
        ),
    )
    alive, faulty, leader, strat, _, maj_keep, acc = jax.lax.fori_loop(
        0, rounds, body, carry0
    )
    acc_dec, acc_lead, acc_hist, acc_ctr = acc

    out_alive_ref[:] = alive
    out_faulty_ref[:] = faulty
    out_leader_ref[:] = leader
    if scenario:
        out_strat_ref[:] = strat
        out_lead_ref[:] = acc_lead
    if slot_mode:
        out_maj_ref[:] = maj_keep
    out_dec_ref[:] = acc_dec
    if not slot_mode:
        out_hist_ref[:] = acc_hist
    if with_counters:
        if slot_mode:
            for c in range(n_counters):
                out_ctr_ref[c] = acc_ctr[c]
        else:
            out_ctr_ref[:] = acc_ctr


def _key_cols(key_data, B: int, B_pad: int, slot_mode: bool):
    """The per-row base-key word columns ([B_pad, 1] int32 x2) from a
    KeySchedule's raw data ((2,) shared base, or [B, 2] per-slot)."""
    kd = jax.lax.bitcast_convert_type(key_data, jnp.int32)
    if slot_mode:
        k0 = jnp.pad(kd[:, 0], (0, B_pad - B))[:, None]
        k1 = jnp.pad(kd[:, 1], (0, B_pad - B))[:, None]
    else:
        k0 = jnp.broadcast_to(kd[0], (B_pad, 1)).astype(jnp.int32)
        k1 = jnp.broadcast_to(kd[1], (B_pad, 1)).astype(jnp.int32)
    return k0, k1


def _megastep_call(
    state: SimState,
    sched,
    strategy,
    counters,
    events,
    *,
    rounds: int,
    scenario: bool,
    slot_mode: bool,
    with_counters: bool,
    interpret: bool,
):
    """Trace-time: pad, stage the static coin maps, run the kernel, and
    un-pad.  Returns ``(state, leaders[R,B] | None, maj[B,n] | None,
    decisions[R,B], histograms[R,3] | None, counter_rows)`` — the
    wrappers below reshape into their XLA twins' exact tuples."""
    B, n = state.faulty.shape
    B_pad = _pad_up(max(B, 1), SUBLANES)
    n_pad = _pad_up(max(n, 1), LANES)
    R_pad = _pad_up(rounds, LANES)
    n_counters = 5 if scenario else 3

    def pad2(x, fill=0):
        return jnp.pad(
            x.astype(jnp.int32),
            ((0, B_pad - B), (0, n_pad - n)),
            constant_values=fill,
        )

    def pad1(x):
        return jnp.pad(x.astype(jnp.int32), (0, B_pad - B))[:, None]

    maps1 = np.zeros((SUBLANES, n_pad), np.int32)
    maps1[0:4, :n] = _word_maps(n, (n,))
    maps2 = np.zeros((4, n_pad, n_pad), np.int32)
    maps2[:, :n, :n] = _word_maps(n * n, (n, n))

    k0, k1 = _key_cols(sched.key_data, B, B_pad, slot_mode)
    idx = np.zeros((B_pad, 1), np.int32)
    if not slot_mode:
        # The campaign engine folds the GLOBAL instance index; the
        # kernel is single-device, so that is just arange(B).
        idx[:B, 0] = np.arange(B)

    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    operands = [
        jnp.reshape(sched.counter, (1,)).astype(jnp.int32),
        jnp.asarray(maps1),
        jnp.asarray(maps2),
        pad1(state.order),
        pad1(state.leader),
        k0,
        k1,
        jnp.asarray(idx),
        pad2(state.faulty),
        pad2(state.alive),
        pad2(state.ids),
    ]
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + [vmem] * 10
    if scenario:
        operands.append(pad2(strategy))
        in_specs.append(vmem)
    if with_counters:
        if slot_mode:
            cpad = jnp.pad(
                counters.astype(jnp.int32),
                ((0, B_pad - B), (0, _CPAD - n_counters)),
            )
        else:
            cpad = jnp.pad(
                counters.astype(jnp.int32), (0, _CPAD - n_counters)
            )[:, None]
        operands.append(cpad)
        in_specs.append(vmem)
    if scenario:
        for name, fill in (
            ("kill", 0), ("revive", 0), ("set_faulty", -1),
            ("set_strategy", -1),
        ):
            plane = events[name].astype(jnp.int32)
            operands.append(
                jnp.pad(
                    plane,
                    ((0, 0), (0, B_pad - B), (0, n_pad - n)),
                    constant_values=fill,
                )
            )
            in_specs.append(vmem)

    S = jax.ShapeDtypeStruct
    out_shape = [
        S((B_pad, n_pad), jnp.int32),  # alive
        S((B_pad, n_pad), jnp.int32),  # faulty
        S((B_pad, 1), jnp.int32),  # leader
    ]
    if scenario:
        out_shape.append(S((B_pad, n_pad), jnp.int32))  # strategy
    if slot_mode:
        out_shape.append(S((B_pad, n_pad), jnp.int32))  # majorities
    out_shape.append(S((B_pad, R_pad), jnp.int32))  # decisions
    if scenario:
        out_shape.append(S((B_pad, R_pad), jnp.int32))  # leaders
    if not slot_mode:
        out_shape.append(S((_CPAD, R_pad), jnp.int32))  # histograms
    if with_counters:
        out_shape.append(
            S((n_counters, B_pad, R_pad), jnp.int32)
            if slot_mode
            else S((_CPAD, R_pad), jnp.int32)
        )

    # Donation THROUGH the pallas_call (ISSUE 14 satellite, the PR 12
    # follow-on): the padded state planes alias their same-shape
    # outputs (alive -> out 0, faulty -> out 1, leader -> out 2,
    # strategy -> out 3), so XLA recycles those buffers in place
    # instead of allocating fresh outputs every dispatch.  Safe by the
    # kernel's access pattern: every aliased input ref is read exactly
    # once into the fori_loop carry BEFORE the loop, and the aliased
    # output refs are written exactly once AFTER it.  The operand
    # indices are fixed by the operands list above (leader=4, faulty=8,
    # alive=9; strategy follows ids at 11 when scenario).
    aliases = {9: 0, 8: 1, 4: 2}
    if scenario:
        aliases[11] = 3
    outs = pl.pallas_call(
        functools.partial(
            _megastep_kernel,
            B=B,
            n=n,
            rounds=rounds,
            scenario=scenario,
            slot_mode=slot_mode,
            with_counters=with_counters,
        ),
        grid=(1,),
        in_specs=in_specs,
        out_specs=[vmem] * len(out_shape),
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)

    it = iter(outs)
    alive = next(it)[:B, :n] > 0
    faulty = next(it)[:B, :n] > 0
    leader = next(it)[:B, 0]
    new_state = SimState(state.order, leader, faulty, alive, state.ids)
    strat_out = (
        next(it)[:B, :n].astype(jnp.int8) if scenario else None
    )
    maj = (
        next(it)[:B, :n].astype(COMMAND_DTYPE) if slot_mode else None
    )
    decisions = next(it)[:B, :rounds].T.astype(COMMAND_DTYPE)
    leaders = next(it)[:B, :rounds].T if scenario else None
    histograms = (
        next(it)[:3, :rounds].T if not slot_mode else None
    )
    if with_counters:
        raw = next(it)
        if slot_mode:
            counter_rows = jnp.transpose(raw[:, :B, :rounds], (2, 1, 0))
        else:
            counter_rows = raw[:n_counters, :rounds].T
    else:
        counter_rows = None
    return new_state, strat_out, maj, decisions, leaders, histograms, counter_rows


def _check_supported(m: int, fn: str) -> None:
    if m != 1:
        raise ValueError(
            f"{fn} supports OM(1) only (m == 1, got m={m}); the m >= 2 "
            f"dense EIG tree stays on the XLA scan core "
            f"(engine='xla'/'auto')"
        )


def _advance(sched, rounds: int):
    # Lazy import: pipeline imports this module for the engine seam.
    from ba_tpu.parallel.pipeline import KeySchedule

    return KeySchedule(sched.key_data, sched.counter + rounds)


@functools.partial(
    jax.jit,
    static_argnames=(
        "rounds", "m", "max_liars", "unroll", "collect_decisions",
        "interpret",
    ),
    donate_argnums=(0, 1, 2),
)
def pallas_scenario_megastep(  # ba-lint: donates(state, sched, strategy)
    state: SimState,
    sched,
    strategy: jax.Array,
    counters: jax.Array,
    events: dict,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    interpret: bool = False,
):
    """The Pallas twin of ``parallel.pipeline.scenario_megastep``: same
    arguments, same donation contract (state/sched/strategy consumed),
    same return tuple ``(state, sched, strategy, histograms, leaders,
    counter_rounds[, decisions])`` — bit-exact against the XLA scan
    core under the same KeySchedule (tests/test_megastep.py pins every
    output incl. the RANDOM coins).  ``unroll`` is accepted for
    signature parity and ignored: the kernel's round loop is already
    one fused dispatch.  ``max_liars`` likewise (OM(1) never reads it).
    """
    _check_supported(m, "pallas_scenario_megastep")
    del max_liars, unroll
    new_state, strat_out, _, decisions, leaders, histograms, rows = (
        _megastep_call(
            state, sched, strategy, counters, events,
            rounds=rounds, scenario=True, slot_mode=False,
            with_counters=True, interpret=interpret,
        )
    )
    out = (_advance(sched, rounds), strat_out, histograms, leaders, rows)
    if collect_decisions:
        out += (decisions,)
    return (new_state, *out)


@functools.partial(
    jax.jit,
    static_argnames=(
        "rounds", "m", "max_liars", "unroll", "collect_decisions",
        "interpret",
    ),
    donate_argnums=(0, 1),
)
def pallas_pipeline_megastep(  # ba-lint: donates(state, sched)
    state: SimState,
    sched,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    collect_decisions: bool = False,
    counters: jax.Array | None = None,
    interpret: bool = False,
):
    """The Pallas twin of ``parallel.pipeline.pipeline_megastep`` (the
    plain non-mutating sweep): same signature, donation and return
    tuple ``(state, sched, histograms[, decisions][, counter_rounds])``.
    The kernel simply runs with no event planes and no strategy plane —
    the RANDOM coin path, bit-exact vs the XLA core."""
    _check_supported(m, "pallas_pipeline_megastep")
    del max_liars, unroll
    new_state, _, _, decisions, _, histograms, rows = _megastep_call(
        state, sched, None, counters, None,
        rounds=rounds, scenario=False, slot_mode=False,
        with_counters=counters is not None, interpret=interpret,
    )
    out = (new_state, _advance(sched, rounds), histograms)
    if collect_decisions:
        out += (decisions,)
    if counters is not None:
        out += (rows,)
    return out


@functools.partial(
    jax.jit,
    static_argnames=("rounds", "m", "max_liars", "unroll", "scenario",
                     "interpret"),
    donate_argnums=(0, 1, 2),
)
def pallas_coalesced_megastep(  # ba-lint: donates(state, sched, strategy)
    state: SimState,
    sched,
    strategy: jax.Array | None,
    slot_counters: jax.Array,
    events: dict | None,
    *,
    rounds: int,
    m: int = 1,
    max_liars: int | None = None,
    unroll: int = 1,
    scenario: bool = False,
    interpret: bool = False,
):
    """The Pallas twin of ``parallel.pipeline.coalesced_megastep`` (the
    serving batch): per-slot base keys folding instance index 0,
    per-slot counter blocks, the carried final-round majorities — same
    signature, donation and return tuple ``(state, sched, strategy,
    last_majorities, decisions, counter_rows[, leaders])``, so every
    slot stays bit-identical to its own B=1 run whichever engine the
    cohort resolved to."""
    _check_supported(m, "pallas_coalesced_megastep")
    del max_liars, unroll
    new_state, strat_out, maj, decisions, leaders, _, rows = (
        _megastep_call(
            state, sched, strategy, slot_counters, events,
            rounds=rounds, scenario=scenario, slot_mode=True,
            with_counters=True, interpret=interpret,
        )
    )
    out = (
        new_state, _advance(sched, rounds),
        strat_out if scenario else strategy, maj, decisions, rows,
    )
    if scenario:
        out += (leaders,)
    return out
