"""Pallas TPU kernel: fused masked strict-majority reduction.

The core vote-counting op of the framework — the tensorised form of the
reference's O(n^2) poll mesh (/root/reference/ba.py:159-195) and of every
EIG resolve level (ba_tpu/core/eig.py:98-115): for each row (a receiver,
or a receiver x path pair), count ATTACK/RETREAT over the valid responders
and emit the strict majority, falling back to the row's own stored value
when no responder is eligible.

One kernel pass fuses compare + mask + two reductions + the majority
select, reading ``answers``/``valid`` exactly once.  Measured r2 on one
chip (R up to 4.1M rows, K in {4, 10, 128}): XLA's fusion of the jnp
formulation ties or beats this kernel — the op is HBM-bandwidth-bound and
already saturated — so core/eig.py and core/om.py intentionally keep the
jnp path and nothing routes through here in production; the kernel is the
measured-evidence point for that decision (SURVEY.md section 2's native-
kernel obligation) and the template for heavier fusions.

Layout: rows tile the sublane axis, responders pad onto the 128-lane axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT, UNDEFINED

ROW_TILE = 256
LANES = 128


def _majority_kernel(answers_ref, valid_ref, fallback_ref, out_ref):
    # Per-row values stay int32 [ROW_TILE, 1] throughout: mixing i1/int8
    # (32, 128)-tiled vectors into the narrow column hits a Mosaic relayout
    # bug ("non-singleton logical dimension is replicated"); the int8 cast
    # happens outside the kernel.
    a = answers_ref[:].astype(jnp.int32)  # [ROW_TILE, K_pad]
    v = valid_ref[:].astype(jnp.int32)  # padding lanes already 0
    att = jnp.sum(jnp.where(a == ATTACK, v, 0), axis=1, keepdims=True)
    ret = jnp.sum(jnp.where(a == RETREAT, v, 0), axis=1, keepdims=True)
    maj = jnp.where(
        att > ret,
        jnp.int32(ATTACK),
        jnp.where(ret > att, jnp.int32(RETREAT), jnp.int32(UNDEFINED)),
    )
    n_eligible = jnp.sum(v, axis=1, keepdims=True)
    out_ref[:] = jnp.where(n_eligible > 0, maj, fallback_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_majority_rows(
    answers: jnp.ndarray,
    valid: jnp.ndarray,
    fallback: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Strict majority per row: answers/valid [R, K], fallback [R] -> [R].

    Tie -> UNDEFINED; zero valid responders -> the fallback value (the EIG
    OM(0) base case, eig.py:110-115; pass UNDEFINED to reproduce the plain
    OM(1) tally, where an empty electorate ties at 0 == 0).  Semantics
    match core/quorum.strict_majority + the eig_resolve guard exactly
    (differential-tested in tests/test_ops.py).
    """
    R, K = answers.shape
    r_pad = -(-R // ROW_TILE) * ROW_TILE
    k_pad = -(-K // LANES) * LANES
    answers = jnp.pad(answers, ((0, r_pad - R), (0, k_pad - K)))
    valid = jnp.pad(valid, ((0, r_pad - R), (0, k_pad - K)))  # False pad
    fallback = jnp.pad(fallback, (0, r_pad - R))[:, None].astype(jnp.int32)
    grid = r_pad // ROW_TILE
    out = pl.pallas_call(
        _majority_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((ROW_TILE, k_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_TILE, k_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r_pad, 1), jnp.int32),
        interpret=interpret,
    )(answers, valid, fallback)
    return out[:R, 0].astype(COMMAND_DTYPE)
