"""Pallas TPU kernel: batched 512-bit reduction mod L (the group order).

The jnp formulation (ba_tpu/crypto/scalar.py) is ~100 small ops over
[B, ~50] byte-limb tensors; XLA materialises most of the intermediates,
so at 64k lanes it costs ~110 ms for what is ~6 MB of real input/output
(measured r2) — pure fusion pathology.  Here the whole fold plan runs on
byte-limb planes in VMEM: one [8, 128] tile per limb, every fold constant
a Python-int immediate, ~2k vector ops per tile, traffic exactly the 64
input and 32 output bytes per lane.

Algorithm: identical to scalar.py (2^256 === -16*delta folds, one exact
2^252 fold, one conditional subtract), but with the C port's carry style
(ba_tpu/native/ed25519.cpp sc_carry): a single sequential pass whose
final carry lands in a signed top limb — exact for negative values, and
sequential chains are free inside a kernel where "limbs" are vector
registers.

Differential contract: byte-identical to scalar.reduce_mod_l for every
input (interpret-mode + real-TPU tests in tests/test_ops.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ba_tpu.crypto.scalar import _C16, _DELTA, _L32
from ba_tpu.ops.ladder import (
    LANES, TILE, TILE_ROWS, _from_tiles, _to_tiles, plane_out_shape,
    plane_spec,
)

_C16_I = [int(b) for b in _C16]
_DELTA_I = [int(b) for b in _DELTA]
_L32_I = [int(b) for b in _L32]


def _fold256(v: list) -> list:
    """value === lo - hi * C16 (mod L); consumes limbs 32+ entirely."""
    hi = v[32:]
    out = v[:32] + [0] * max(0, 16 + len(hi) - 32)
    for j, cj in enumerate(_C16_I):
        if not cj:
            continue
        for i, h in enumerate(hi):
            out[j + i] = out[j + i] - cj * h
    return out


def _carry_seq(v: list) -> list:
    """One exact sequential base-256 pass; signed carry into the top limb."""
    c = 0
    out = list(v)
    for i in range(len(out) - 1):
        x = out[i] + c
        c = x >> 8
        out[i] = x - (c << 8)
    out[-1] = out[-1] + c
    return out


def _modl_kernel(h_ref, out_ref):
    modl_core([h_ref[i] for i in range(64)], out_ref)


def modl_core(v: list, out_ref) -> None:
    """The in-kernel mod-L body on 64 int32 byte planes: shared by the
    standalone kernel above and the fused SHA-512+mod-L kernel
    (ops/sha512_kernel._sha_modl_kernel), which feeds it digest bytes
    straight from registers — no HBM round trip between hash and
    reduction (VERDICT r4 item 5: mod_l was 569 ns/sig of pure dispatch
    + traffic overhead as a standalone stage)."""
    v = _carry_seq(_fold256(v) + [0])   # 49 limbs; |value| < 2^385
    v = _carry_seq(_fold256(v) + [0])   # 34 limbs; |value| < 2^260
    v = _fold256(v)                     # 32 limbs touched; |value| < 2^258
    # Make nonnegative (+4L > the worst negative) and normalise.
    v = v + [0, 0]
    for i, li in enumerate(_L32_I):
        v[i] = v[i] + 4 * li
    v = _carry_seq(v)                   # 34 limbs, value in (0, 2^259)
    # Exact fold at 2^252: hi <= 143.
    hi = (v[31] >> 4) + (v[32] << 4) + (v[33] << 12)
    v[31] = v[31] & 0xF
    v = v[:32]
    for j, dj in enumerate(_DELTA_I):
        if dj:
            v[j] = v[j] - hi * dj
    # + L once -> (0, 2L); carry; one conditional subtract of L.
    for i, li in enumerate(_L32_I):
        v[i] = v[i] + li
    v = _carry_seq(v + [0])             # 33 limbs, top == 0
    borrow = jnp.zeros((TILE_ROWS, LANES), jnp.int32)
    diffs = []
    for i in range(33):
        li = _L32_I[i] if i < 32 else 0
        x = v[i] - li + borrow
        borrow = x >> 8
        diffs.append(x - (borrow << 8))
    ge = borrow >= 0  # no final borrow <=> value >= L
    for i in range(32):
        out_ref[i] = jnp.where(ge, diffs[i], v[i])


@functools.partial(jax.jit, static_argnames=("interpret",))
def reduce_mod_l_planes(
    h_bytes: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    """Drop-in Pallas replacement for ``scalar.reduce_mod_l``:
    uint8 [B, 64] -> uint8 [B, 32]."""
    B = h_bytes.shape[0]
    batch_pad = -(-B // TILE) * TILE
    tiles = _to_tiles(h_bytes.astype(jnp.int32), batch_pad)
    out = pl.pallas_call(
        _modl_kernel,
        grid=(batch_pad // TILE,),
        in_specs=[plane_spec(64)],
        out_specs=plane_spec(32),
        out_shape=plane_out_shape(32, batch_pad),
        interpret=interpret,
    )(tiles)
    return _from_tiles(out, B).astype(jnp.uint8)
