"""Pallas TPU kernel: the field-arithmetic core of point decompression.

RFC 8032 5.1.3 (ba_tpu/crypto/ed25519.decompress) needs ~10 field muls
around the (p-5)/8 square-root chain: u = y^2-1, v = d y^2+1, the
uv^3/uv^7 candidates, and the v x^2 root check.  Run as jnp matmul-form
muls they cost ~half of decompress (like-for-like stage timings r2); here
they ride in the same VMEM program as the addition-chain exponentiation
(ops/powchain.sqrt_chain), so decompression touches HBM once on the way
in (y) and once on the way out.

The kernel returns both root candidates (x and x*sqrt(-1)) plus vxx and
u; the cheap data-dependent tail — which root is valid, the sign-bit
flip, ok-masking — stays in jnp where canonical equality already lives
(ba_tpu/crypto/ed25519.decompress).

Differential contract: each output equals the corresponding jnp
intermediate value (same field element; carried forms may differ).
Like the ladder, the fused kernel is pinned on real TPU only
(tests/test_ops.py; interpret-under-jit blows past a 9-minute XLA-CPU
compile) — its pieces are CPU-covered separately (plane ops, the
sqrt_chain algebra + interpret run in ops/powchain tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ba_tpu.crypto.field import LIMBS
from ba_tpu.crypto.oracle import D, P, SQRT_M1
from ba_tpu.ops.ladder import (
    TILE, _from_tiles, _to_tiles, plane_out_shape, plane_spec,
)
from ba_tpu.ops.planes import const_planes, p_add, p_carry, p_mul, p_sub
from ba_tpu.ops.powchain import p_sq_n, sqrt_chain

_D_PLANES = const_planes(D % P)
_SQRTM1_PLANES = const_planes(SQRT_M1)
_ONE = const_planes(1)


def _decompress_kernel(y_ref, x_ref, xalt_ref, vxx_ref, u_ref):
    y = p_carry([y_ref[i] for i in range(LIMBS)])
    one = list(_ONE)
    yy = p_mul(y, y)
    u = p_carry(p_sub(yy, one))  # subtrahend-safe form for later users
    v = p_carry(p_add(p_mul(yy, _D_PLANES), one))
    v3 = p_mul(p_mul(v, v), v)
    v7 = p_mul(p_mul(v3, v3), v)
    t = sqrt_chain(p_mul(u, v7), p_mul, p_sq_n)
    x = p_mul(p_mul(u, v3), t)
    x_alt = p_mul(x, _SQRTM1_PLANES)
    vxx = p_mul(v, p_mul(x, x))
    for ref, planes in (
        (x_ref, x), (xalt_ref, x_alt), (vxx_ref, vxx), (u_ref, u)
    ):
        for i in range(LIMBS):
            ref[i] = planes[i] + jnp.zeros_like(y[0])


@functools.partial(jax.jit, static_argnames=("interpret",))
def decompress_core(y: jnp.ndarray, *, interpret: bool = False) -> tuple:
    """y limbs [B, 22] -> (x, x*sqrt(-1), v*x^2, u = y^2-1), each [B, 22].

    The caller picks the valid root via canonical equality of vxx with
    +-u and applies the encoding's sign bit (ed25519.decompress).
    """
    B = y.shape[0]
    batch_pad = -(-B // TILE) * TILE
    tiles = _to_tiles(y, batch_pad)
    outs = pl.pallas_call(
        _decompress_kernel,
        grid=(batch_pad // TILE,),
        in_specs=[plane_spec(LIMBS)],
        out_specs=(plane_spec(LIMBS),) * 4,
        out_shape=(plane_out_shape(LIMBS, batch_pad),) * 4,
        interpret=interpret,
    )(tiles)
    return tuple(_from_tiles(o, B) for o in outs)
