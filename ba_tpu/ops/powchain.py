"""Pallas TPU kernel: fixed-exponent field exponentiation (square chains).

Second of the verify bottlenecks after the scalar-mult ladder: point
decompression (RFC 8032 5.1.3, ba_tpu/crypto/ed25519.decompress) computes
the modular square root via ``(u v^7) ^ ((p-5)/8)`` — a 252-step
square-and-multiply over GF(2^255-19), ~380 field muls per lane that the
jnp path runs as matmul convolutions with HBM round-trips between steps
(~half of decompress's ~70 ms at 16k lanes, measured r2).  Same recipe as
ops/ladder.py: limb-plane arithmetic (ops/planes.py) VMEM-resident across
the whole chain, exponent bits packed into SMEM words, one grid program
per 1024-lane tile.

The exponent is a static Python int (the kernel is specialized per
exponent, like ``field.pow_const``); generic exponents run LSB-first
square-and-multiply with a branch-free select, matching pow_const's
semantics bit for bit (differential tests in tests/test_ops.py).

The one exponent verification actually uses, (p-5)/8 = 2^252 - 3, is
nearly all ones, so square-and-multiply burns ~504 field muls per lane.
For it the kernel runs an addition chain instead (the classic
2^k-1 tower: 1,2,4,5,10,20,40,50,100,200,250): 251 squarings + 11
multiplies = 262 muls, ~1.9x less work, with the squaring runs as
fori_loops so the kernel trace stays small.  The chain is shared with a
pure-jnp twin (``sqrt_chain``) so its algebra is testable on CPU without
Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.crypto.field import LIMBS
from ba_tpu.ops.ladder import (
    LANES, TILE, TILE_ROWS, _from_tiles, _to_tiles, plane_out_shape,
    plane_spec,
)
from ba_tpu.ops.planes import const_planes, p_carry, p_mul, p_select

_ONE_PLANES = const_planes(1)


def _tower_250(z, mul, sq_n):
    """The shared 2^k-1 tower: returns t_250 = z^(2^250 - 1).

    Invariant: t_k = z^(2^k - 1); t_{2k} = t_k^(2^k) * t_k.  Generic over
    the arithmetic so the kernels (plane ops + fori_loop) and the CPU
    algebra tests (ba_tpu.crypto.field on plain arrays) share one chain.
    """
    t1 = z
    t2 = mul(sq_n(t1, 1), t1)
    t4 = mul(sq_n(t2, 2), t2)
    t5 = mul(sq_n(t4, 1), t1)
    t10 = mul(sq_n(t5, 5), t5)
    t20 = mul(sq_n(t10, 10), t10)
    t40 = mul(sq_n(t20, 20), t20)
    t50 = mul(sq_n(t40, 10), t10)
    t100 = mul(sq_n(t50, 50), t50)
    t200 = mul(sq_n(t100, 100), t100)
    return mul(sq_n(t200, 50), t50)


def sqrt_chain(z, mul, sq_n):
    """z ** (2^252 - 3) via the 2^k-1 addition-chain tower: the result is
    t_250^(2^2) * z = z^((2^250-1)*4 + 1) = z^(2^252 - 3)."""
    return mul(sq_n(_tower_250(z, mul, sq_n), 2), z)


def inv_chain(z, mul, sq_n):
    """z ** (p - 2) = 1/z via the same tower: p - 2 = 2^255 - 21 =
    (2^250 - 1) * 2^5 + 11, so the result is t_250^(2^5) * z^11 — 254
    squarings + 13 multiplies vs ~505 muls for bit-chain square-and-
    multiply.  The device signer's point compression is the caller
    (ba_tpu.crypto.ed25519.compress): one modular inverse per signature
    to land the projective R on affine coordinates before encoding.
    """
    z2 = sq_n(z, 1)
    z9 = mul(sq_n(z2, 2), z)  # z^8 * z
    z11 = mul(z9, z2)
    return mul(sq_n(_tower_250(z, mul, sq_n), 5), z11)


def p_sq_n(x, n):
    """n plane squarings as a fori_loop (n static) — the kernel-side
    squaring-run helper shared by every addition-chain kernel."""
    return jax.lax.fori_loop(0, n, lambda _, v: p_mul(v, v), x)


def _sqrt_chain_kernel(a_ref, out_ref):
    z = p_carry([a_ref[i] for i in range(LIMBS)])
    result = sqrt_chain(z, p_mul, p_sq_n)
    for i in range(LIMBS):
        out_ref[i] = result[i]


def _inv_chain_kernel(a_ref, out_ref):
    z = p_carry([a_ref[i] for i in range(LIMBS)])
    result = inv_chain(z, p_mul, p_sq_n)
    for i in range(LIMBS):
        out_ref[i] = result[i]


def _pow_kernel(nbits, a_ref, words_ref, out_ref):
    base = p_carry([a_ref[i] for i in range(LIMBS)])
    shape = (TILE_ROWS, LANES)
    result = [jnp.full(shape, c, jnp.int32) for c in _ONE_PLANES]

    def body(t, state):
        result, base = state
        word = words_ref[t >> 5, 0]
        bit = (word >> (t & 31)) & 1
        result = p_select(bit == 1, p_mul(result, base), result)
        return (result, p_mul(base, base))

    result, _ = jax.lax.fori_loop(0, nbits, body, (result, base))
    for i in range(LIMBS):
        out_ref[i] = result[i]


_SQRT_EXP = (2**255 - 19 - 5) // 8  # (p-5)/8 = 2^252 - 3
_INV_EXP = 2**255 - 19 - 2  # p - 2 (Fermat inversion)


@functools.partial(jax.jit, static_argnames=("e", "interpret"))
def pow_planes(a: jnp.ndarray, e: int, *, interpret: bool = False):
    """Drop-in Pallas replacement for ``field.pow_const``: a[B, 22] ** e.

    ``e`` is static; output is in carried form like pow_const's.  The
    decompression exponent (p-5)/8 and the inversion exponent p-2 route
    through their addition-chain kernels (~1.9x less work); every other
    exponent runs the generic bit-chain.
    """
    B = a.shape[0]
    batch_pad = -(-B // TILE) * TILE
    grid = batch_pad // TILE
    tiles = _to_tiles(a, batch_pad)
    if e in (_SQRT_EXP, _INV_EXP):
        out = pl.pallas_call(
            _sqrt_chain_kernel if e == _SQRT_EXP else _inv_chain_kernel,
            grid=(grid,),
            in_specs=[plane_spec(LIMBS)],
            out_specs=plane_spec(LIMBS),
            out_shape=plane_out_shape(LIMBS, batch_pad),
            interpret=interpret,
        )(tiles)
        return _from_tiles(out, B)
    nbits = max(e.bit_length(), 1)
    nw = -(-nbits // 32)
    words = np.zeros((nw, 1), np.uint32)
    for i in range(nbits):
        if (e >> i) & 1:
            words[i // 32, 0] |= np.uint32(1 << (i % 32))
    words = words.view(np.int32)
    out = pl.pallas_call(
        functools.partial(_pow_kernel, nbits),
        grid=(grid,),
        in_specs=[
            plane_spec(LIMBS),
            pl.BlockSpec((nw, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=plane_spec(LIMBS),
        out_shape=plane_out_shape(LIMBS, batch_pad),
        interpret=interpret,
    )(tiles, jnp.asarray(words))
    return _from_tiles(out, B)
