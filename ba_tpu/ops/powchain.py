"""Pallas TPU kernel: fixed-exponent field exponentiation (square chains).

Second of the verify bottlenecks after the scalar-mult ladder: point
decompression (RFC 8032 5.1.3, ba_tpu/crypto/ed25519.decompress) computes
the modular square root via ``(u v^7) ^ ((p-5)/8)`` — a 252-step
square-and-multiply over GF(2^255-19), ~380 field muls per lane that the
jnp path runs as matmul convolutions with HBM round-trips between steps
(~half of decompress's ~70 ms at 16k lanes, measured r2).  Same recipe as
ops/ladder.py: limb-plane arithmetic (ops/planes.py) VMEM-resident across
the whole chain, exponent bits packed into SMEM words, one grid program
per 1024-lane tile.

The exponent is a static Python int (the kernel is specialized per
exponent, like ``field.pow_const``); the chain is LSB-first
square-and-multiply with a branch-free select, matching pow_const's
semantics bit for bit (differential tests in tests/test_ops.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.crypto.field import LIMBS
from ba_tpu.ops.ladder import LANES, TILE, TILE_ROWS, _from_tiles, _to_tiles
from ba_tpu.ops.planes import const_planes, p_carry, p_mul, p_select

_ONE_PLANES = const_planes(1)


def _pow_kernel(nbits, a_ref, words_ref, out_ref):
    base = p_carry([a_ref[i] for i in range(LIMBS)])
    shape = (TILE_ROWS, LANES)
    result = [jnp.full(shape, c, jnp.int32) for c in _ONE_PLANES]

    def body(t, state):
        result, base = state
        word = words_ref[t >> 5, 0]
        bit = (word >> (t & 31)) & 1
        result = p_select(bit == 1, p_mul(result, base), result)
        return (result, p_mul(base, base))

    result, _ = jax.lax.fori_loop(0, nbits, body, (result, base))
    for i in range(LIMBS):
        out_ref[i] = result[i]


@functools.partial(jax.jit, static_argnames=("e", "interpret"))
def pow_planes(a: jnp.ndarray, e: int, *, interpret: bool = False):
    """Drop-in Pallas replacement for ``field.pow_const``: a[B, 22] ** e.

    ``e`` is static; output is in carried form like pow_const's.
    """
    B = a.shape[0]
    nbits = max(e.bit_length(), 1)
    nw = -(-nbits // 32)
    words = np.zeros((nw, 1), np.uint32)
    for i in range(nbits):
        if (e >> i) & 1:
            words[i // 32, 0] |= np.uint32(1 << (i % 32))
    words = words.view(np.int32)
    batch_pad = -(-B // TILE) * TILE
    grid = batch_pad // TILE
    tiles = _to_tiles(a, batch_pad)
    out = pl.pallas_call(
        functools.partial(_pow_kernel, nbits),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((LIMBS, TILE_ROWS, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nw, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((LIMBS, TILE_ROWS, LANES), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (LIMBS, batch_pad // LANES, LANES), jnp.int32
        ),
        interpret=interpret,
    )(tiles, jnp.asarray(words))
    return _from_tiles(out, B)
