"""Pallas TPU kernel: the ENTIRE signed-sweep agreement step, fused.

The north-star hot path (BASELINE config #5; bench_sweep10k_signed's
``one_bucket``) is a chain of small elementwise programs — round-1
broadcast (ba.py:258-282 semantics), signature-mask select, m collapsed
relay rounds (core/sm.py), choice + majority counts + 3f+1 quorum
(ba.py:159-255) — whose XLA form pays per-op HBM round trips, layout
changes, and threefry coin generation (the measured r2/r3 bound: "VPU
throughput, packed-u8 RNG + elementwise relay").  This kernel runs the
whole step for a [TILE, n] block of instances inside VMEM:

- every intermediate (received row, seen planes, per-instance scalars)
  lives in registers/VMEM — state is read once and one decision column is
  written back;
- fault coins and relay draws come from the TPU's in-core hardware PRNG
  (``pltpu.prng_seed`` / ``prng_random_bits``), replacing threefry
  entirely (one u32 draw per lane per relay round: byte 0 gates RETREAT,
  byte 1 gates ATTACK — iid 8-bit uniforms, exactly the packed-u8
  discipline of core/rng.uniform_u8);
- the per-round reductions (honest-held flags, traitor-holder counts) and
  the final majority/quorum math are row reductions over the lane axis,
  fused with everything else;
- ``rounds`` chains independent agreement rounds in ONE dispatch via an
  in-kernel fori_loop (state planes read once, PRNG stream continuing,
  decisions packed 15-per-int32-column into a register accumulator),
  dividing the per-dispatch tunnel/grid overhead by the round count — the
  r4 answer to SWEEP_STAGES_r3.json's finding that dispatch, not compute,
  bounds the fused step; the r5 loop form makes compile cost O(1) in the
  round count (the r4 unrolled trace hit a >25 min remote-compile
  frontier at 240 rounds, ROUNDS_AB_r4.json).

Semantics mirror the XLA path op-for-op (round1_broadcast ->
sig_valid_from_tables -> _initial_seen & sig_valid ->
sm_relay_rounds_collapsed -> sm_choice -> majority_counts ->
quorum_decision, incl. the needed-overrides, retreat-first tie Q7, and
the zero-voter guard) — only the PRNG stream differs, which nothing
couples to (core/rng.py's stream-freedom note).  With zero traitors the
step is draw-independent and must match the XLA path bit-for-bit;
tests/test_ops.py pins that plus distributional equivalence with
traitors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.core.types import ATTACK, COMMAND_DTYPE, RETREAT, UNDEFINED

import os

# Instances per kernel invocation.  64 keeps ~10 int32 [TILE, 1024] planes
# comfortably in VMEM (~2.6 MB); BA_TPU_FUSED_TILE overrides for tuning
# (read at import, like the sibling kernels' tile constants).
TILE = int(os.environ.get("BA_TPU_FUSED_TILE", 64))
LANES = 128

# Rounds traced per loop iteration: the compile-time/throughput dial.
# Mosaic lowers fori_loop only at unroll=1 or full unroll, so partial
# unrolling is done BY HAND — the loop body is a Python-unrolled block of
# _UNROLL rounds, keeping trace size O(unroll) regardless of K (the r4
# frontier was O(K)) while cross-round ILP stays visible to Mosaic's
# scheduler.  BA_TPU_FUSED_UNROLL overrides for tuning.
_UNROLL = int(os.environ.get("BA_TPU_FUSED_UNROLL", 5))
if _UNROLL < 1:  # same loud-at-import policy as the tile/rounds guards
    raise ValueError(f"BA_TPU_FUSED_UNROLL={_UNROLL} must be >= 1")


def _step_kernel(seed_ref, order_ref, leader_ref, faulty_ref, alive_ref,
                 ok_r_ref, ok_a_ref, dec_ref, *, m: int, rounds: int):
    T, N = faulty_ref.shape
    pltpu.prng_seed(seed_ref[0], pl.program_id(0))

    faulty = faulty_ref[:]  # int32 0/1, padded lanes 0
    alive = alive_ref[:]
    order = order_ref[:]  # [T, 1] int32 (0/1)
    leader = leader_ref[:]  # [T, 1] int32

    iota = jax.lax.broadcasted_iota(jnp.int32, (T, N), 1)
    is_leader = iota == leader  # [T, N] bool

    leader_faulty = jnp.sum(
        jnp.where(is_leader, faulty, 0), axis=1, keepdims=True
    )  # [T, 1]
    honest = alive * (1 - faulty)
    traitor = alive * faulty
    t = jnp.sum(traitor, axis=1, keepdims=True)  # coalition size [T, 1]

    # ``rounds`` independent agreement rounds per dispatch, batch-resident:
    # the state planes are read once, the PRNG stream simply continues
    # across rounds (iid draws), and each round's decision packs into 2
    # bits of an int32 output column (decisions are in {0, 1, 2}; 15
    # rounds per column, ceil(rounds/15) columns).  The round loop is an
    # IN-KERNEL fori_loop (r4 ran a Python loop traced into straight-line
    # Mosaic, which hit a compile frontier: K=240 sat in the remote
    # compiler >25 min, ROUNDS_AB_r4.json) — trace and compile cost are
    # now O(unroll), not O(K).  All columns live in one [T, n_cols] int32
    # register accumulator (tile 64 x 128 lanes = 32 KB — nowhere near
    # the 16 MB scoped-VMEM limit that the r4 unrolled trace's per-column
    # concatenate blew); a filled column lands in it via a lane select,
    # and one store writes everything at the end.  Round 0's draw order
    # is identical to the single-round kernel, so rounds=1 stays
    # bit-compatible with r3's kernel.
    n_cols = dec_ref.shape[1]
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (T, n_cols), 1)

    def _one_round(rr, carry):
        acc_col, acc_all = carry
        # Round 1: honest leader pushes order; faulty leader flips a coin
        # per recipient (ba.py:268-273); the leader holds the true order.
        coin = (
            pltpu.bitcast(pltpu.prng_random_bits((T, N)), jnp.int32) & 1
        )
        received = jnp.where(leader_faulty > 0, coin, order)
        received = jnp.where(is_leader, order, received)

        # Signature gate: per-copy validity from the per-value table
        # verdicts (crypto/signed.sig_valid_from_tables, V=2 select).
        sig_ok = jnp.where(received == ATTACK, ok_a_ref[:], ok_r_ref[:])

        # Initial V-sets (core/sm._initial_seen, sig-gated).
        gate = alive * sig_ok
        seen_r = jnp.where(received == RETREAT, gate, 0)
        seen_a = jnp.where(received == ATTACK, gate, 0)

        # m collapsed relay rounds (core/sm.sm_relay_rounds_collapsed):
        # the OR of k traitor-holder coins is Bernoulli(1 - 2^-k),
        # realised as an 8-bit threshold draw (core/rng.or_coin_threshold8:
        # exact for k <= 8, saturating beyond with error <= 2^-9 per
        # draw).  The honest-held OR (``incoming = draw | held_honest``)
        # is folded into the threshold: held => thresh 256 > any u8, i.e.
        # "fire always" — this keeps every per-instance flag an int32
        # column (narrow i1/int8 vectors hit a Mosaic relayout bug; see
        # ops/majority.py).
        for r in range(1, m + 1):
            draws = pltpu.bitcast(pltpu.prng_random_bits((T, N)), jnp.int32)
            u_r = draws & 0xFF
            u_a = (draws >> 8) & 0xFF
            new_planes = []
            for seen, u in ((seen_r, u_r), (seen_a, u_a)):
                held_cnt = jnp.sum(seen * honest, axis=1, keepdims=True)
                k = jnp.sum(seen * traitor, axis=1, keepdims=True)
                t8 = jnp.where(k > 8, 256, 256 - (256 >> jnp.minimum(k, 8)))
                thresh = jnp.where(
                    held_cnt > 0, 256, jnp.where(r < t, t8, 0)
                )  # chain bound: coalition-only reveal needs r < t
                new_planes.append(jnp.where(u < thresh, alive, seen * alive))
            seen_r, seen_a = new_planes

        # choice(V) (core/sm.sm_choice): |V|==1 -> the value, else
        # UNDEFINED; the leader reports its own order (Q1 parity).
        has_r = seen_r > 0
        has_a = seen_a > 0
        maj = jnp.where(
            has_a & ~has_r,
            jnp.int32(ATTACK),
            jnp.where(has_r & ~has_a, jnp.int32(RETREAT), jnp.int32(UNDEFINED)),
        )
        maj = jnp.where(is_leader, order, maj)

        # Majority-of-majorities over alive nodes + quorum thresholds with
        # the reference's overrides (core/quorum, ba.py:197-255).
        n_a = jnp.sum(jnp.where(maj == ATTACK, alive, 0), axis=1, keepdims=True)
        n_r = jnp.sum(jnp.where(maj == RETREAT, alive, 0), axis=1, keepdims=True)
        n_u = jnp.sum(jnp.where(maj == UNDEFINED, alive, 0), axis=1, keepdims=True)
        total = n_a + n_r + n_u
        needed = 2 * ((total - 1) // 3) + 1
        needed = jnp.where(total <= 3, total - 1, needed)
        needed = jnp.where(total == 1, 1, needed)
        dec = jnp.where(
            needed <= n_r,
            jnp.int32(RETREAT),
            jnp.where(needed <= n_a, jnp.int32(ATTACK), jnp.int32(UNDEFINED)),
        )
        dec = jnp.where(total == 0, jnp.int32(UNDEFINED), dec)
        acc_col = acc_col * 4 + dec
        # Column bookkeeping, all vector selects: when round rr fills its
        # column ((rr+1) % 15 == 0 or it is the last round), park acc_col
        # in lane rr // 15 of the accumulator and reset it.  The rr <
        # rounds guard masks the hand-unroll's padded tail rounds (their
        # draws advance the PRNG stream harmlessly, but an unguarded park
        # at the 15-boundary would overwrite the last real column).
        filled = ((rr + 1) % 15 == 0) | (rr == rounds - 1)
        hit = filled & (rr < rounds) & (col_iota == rr // 15)
        acc_all = jnp.where(hit, acc_col, acc_all)
        acc_col = jnp.where(filled, 0, acc_col)
        return acc_col, acc_all

    unroll = min(rounds, _UNROLL)

    def _block(b, carry):  # hand-unrolled: Mosaic has no partial unroll
        for u in range(unroll):
            carry = _one_round(b * unroll + u, carry)
        return carry

    _, acc_all = jax.lax.fori_loop(
        0,
        -(-rounds // unroll),
        _block,
        (jnp.zeros((T, 1), jnp.int32), jnp.zeros((T, n_cols), jnp.int32)),
    )
    dec_ref[:] = acc_all


@functools.partial(
    jax.jit, static_argnames=("m", "rounds", "tile", "interpret")
)
def fused_signed_sweep_step(
    seed: jnp.ndarray,
    order: jnp.ndarray,
    leader: jnp.ndarray,
    faulty: jnp.ndarray,
    alive: jnp.ndarray,
    ok: jnp.ndarray,
    m: int = 3,
    rounds: int = 1,
    *,
    tile: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``rounds`` fused signed-sweep agreement rounds in ONE dispatch.

    Returns decisions [B] int8 for rounds=1 (r3-bit-compatible), else
    [B, rounds] int8 — column r is round r's independent decision.  The
    state planes stay VMEM-resident across all rounds, so per-dispatch
    overhead (tunnel latency, grid setup, state reads) amortizes by
    ``rounds``; the kernel packs each round's {0,1,2} decision into 2
    bits of an int32 output column, 15 rounds per column (measured r4:
    dispatch overhead still dominated at 15, so the column axis extends
    the chain — ROUNDS_AB_r4.json).  The round loop is in-kernel (r5), so
    compile cost no longer grows with ``rounds``; the cap is one padded
    lane register of packed columns (15 * 128), far past the measured
    marginal-cost asymptote.

    seed: int32 [1] (vary per step — the kernel folds in the tile index);
    order [B] int8/int32; leader [B] int32; faulty/alive [B, n] bool;
    ok [B, 2] bool (per-value table-verify verdicts, RETREAT/ATTACK order).
    """
    tile = TILE if tile is None else tile  # explicit 0 is a loud error below
    if tile <= 0:
        raise ValueError(f"tile={tile} must be positive")
    if not 1 <= rounds <= 1920:
        raise ValueError(f"rounds={rounds} outside [1, 1920] (15 rounds "
                         "per packed column, one 128-lane column register)")
    B, n = faulty.shape
    n_cols = -(-rounds // 15)
    b_pad = -(-B // tile) * tile
    n_pad = -(-n // LANES) * LANES

    def pad2(x):
        return jnp.pad(x.astype(jnp.int32), ((0, b_pad - B), (0, n_pad - n)))

    def pad1(x):
        return jnp.pad(x.astype(jnp.int32), (0, b_pad - B))[:, None]

    grid = b_pad // tile
    col = lambda i: (i, 0)  # noqa: E731
    vcol = pl.BlockSpec((tile, 1), col, memory_space=pltpu.VMEM)
    vplane = pl.BlockSpec((tile, n_pad), col, memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_step_kernel, m=m, rounds=rounds),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # seed [1]
            vcol,  # order
            vcol,  # leader
            vplane,  # faulty
            vplane,  # alive
            vcol,  # ok retreat
            vcol,  # ok attack
        ],
        out_specs=pl.BlockSpec((tile, n_cols), col, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, n_cols), jnp.int32),
        interpret=interpret,
    )(
        seed.astype(jnp.int32),
        pad1(order),
        pad1(leader),
        pad2(faulty),
        pad2(alive),
        pad1(ok[:, 0]),
        pad1(ok[:, 1]),
    )
    if rounds == 1:
        return out[:B, 0].astype(COMMAND_DTYPE)
    pieces = []
    for c in range(n_cols):
        rc = min(15, rounds - 15 * c)  # rounds packed in column c
        shifts = 2 * (rc - 1 - jnp.arange(rc, dtype=jnp.int32))
        pieces.append((out[:B, c : c + 1] >> shifts[None, :]) & 3)
    dec = pieces[0] if n_cols == 1 else jnp.concatenate(pieces, axis=1)
    return dec.astype(COMMAND_DTYPE)


def fused_sharded_sweep_step(
    mesh,
    seed: jnp.ndarray,
    order: jnp.ndarray,
    leader: jnp.ndarray,
    faulty: jnp.ndarray,
    alive: jnp.ndarray,
    ok: jnp.ndarray,
    m: int = 3,
    rounds: int = 1,
) -> jnp.ndarray:
    """The fused step over a multi-chip mesh: instances shard on "data".

    The v4-8 composition of the north star: consensus instances are
    independent, so the batch axis lays out on the mesh's "data" axis with
    ZERO cross-chip traffic during the round (same layout contract and
    ``put_global`` ingestion as ``parallel.sharded_sweep``, so meshes that
    span processes work) — each device runs the fused kernel on its local
    shard, seeded with its axis index times a wide odd stride so adjacent
    per-step seeds never alias a neighbour shard's stream.  On a 1-device
    mesh this is bit-identical to ``fused_signed_sweep_step`` (axis index
    0 folds to the same seed), which is the hardware test's anchor
    (tests/test_ops.py).  The jitted shard program is memoized via
    ``parallel.mesh.cached_jit`` (keyed on mesh/shapes/m) so per-round
    calls never retrace.
    """
    from jax.sharding import PartitionSpec as P

    from ba_tpu.parallel.mesh import cached_jit
    from ba_tpu.parallel.mesh import shard_map as _shard_map
    from ba_tpu.parallel.multihost import put_global

    pspec = P("data")
    row = P("data", None)

    def build():
        def local(seed, order, leader, faulty, alive, ok):
            idx = jax.lax.axis_index("data")
            # Wide odd stride: per-step seeds increment by 1, so a stride
            # of 1 would replay shard k's streams as shard k-1's next step.
            return fused_signed_sweep_step(
                seed + idx * jnp.int32(-1640531527),  # 0x9E3779B9 as int32
                order, leader, faulty, alive, ok, m, rounds,
            )

        return _shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), pspec, pspec, row, row, row),
            out_specs=pspec if rounds == 1 else row,
            # The pallas_call inside has no vma annotation on its outputs;
            # replication checking has nothing to verify here anyway (the
            # kernel writes purely shard-local decisions).
            check_vma=False,
        )

    fn = cached_jit(("fused_sweep", mesh, faulty.shape, m, rounds), build)
    args = [
        put_global(mesh, x, s)
        for x, s in (
            (order, pspec), (leader, pspec), (faulty, row),
            (alive, row), (ok, row),
        )
    ]
    return fn(jnp.asarray(seed, jnp.int32), *args)
