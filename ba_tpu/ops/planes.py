"""Limb-plane GF(2^255-19) + Edwards arithmetic: the Pallas kernel's math.

``ba_tpu.crypto.field`` lays a field element out as the trailing axis of a
[B, 22] tensor — convenient for jnp, but on TPU the 22-limb axis wastes
vector lanes (22 << 128) and every limb shift is a lane shuffle.  Here the
SAME math is expressed over a *list of 22 arrays* ("planes"), one per limb:
the limb axis becomes Python-level structure, so a limb shift is register
renaming (free), the schoolbook convolution is exactly 484 vector MACs
(the [484 x 43] matmul form burns 43x that in zeros), and every plane op
vectorises over whatever shape the planes carry — a [B] vector in plain
jnp, an [8, 128] VMEM tile inside the Pallas ladder kernel
(ba_tpu.ops.ladder).  These functions are pure and shape-agnostic, so the
kernel body and the differential-test fallback share one implementation.

Bounds are inherited verbatim from ba_tpu/crypto/field.py (see carry()'s
contract there); reference: /root/reference has no crypto — this is the
north-star signed-message machinery (BASELINE.json config #3).
"""

from __future__ import annotations

import jax.numpy as jnp

from ba_tpu.crypto.field import BITS, FOLD, LIMBS, P_INT, _np_limbs

# Constant field elements as plain Python-int plane lists: broadcasting
# int * array keeps them shape-agnostic (and free inside the kernel).


def const_planes(v: int) -> list[int]:
    return [int(x) for x in _np_limbs(v % P_INT)]


def p_fold_pass(x: list) -> list:
    """field._fold_pass() on planes: one parallel carry pass, limb 21's
    carry wrapping to limb 0 * FOLD (exact for negative limbs)."""
    c = [v >> BITS for v in x]
    r = [v - (cc << BITS) for v, cc in zip(x, c)]
    return [
        r[k] + (c[k - 1] if k > 0 else c[LIMBS - 1] * FOLD)
        for k in range(LIMBS)
    ]


def p_carry(x: list) -> list:
    """field.carry() on planes: 5 parallel fold passes, same contract."""
    for _ in range(5):
        x = p_fold_pass(x)
    return x


def p_reduce_wide(w: list) -> list:
    """field._reduce_wide() on 43 convolution planes -> 22 carried planes."""
    for _ in range(2):
        c = [v >> BITS for v in w]
        r = [v - (cc << BITS) for v, cc in zip(w, c)]
        w = r + [0]
        for k in range(len(c)):
            w[k + 1] = w[k + 1] + c[k]
    lo = [w[k] + w[LIMBS + k] * FOLD for k in range(LIMBS)]
    lo[1] = lo[1] + w[2 * LIMBS] * (361 << 6)
    return p_carry(lo)


def p_mul(a: list, b: list) -> list:
    """Field multiply on planes: the 484-MAC schoolbook convolution."""
    conv = [0] * (2 * LIMBS - 1)
    for i in range(LIMBS):
        ai = a[i]
        if isinstance(ai, int) and ai == 0:
            continue
        for j in range(LIMBS):
            bj = b[j]
            if isinstance(bj, int) and bj == 0:
                continue
            conv[i + j] = conv[i + j] + ai * bj
    return p_reduce_wide(conv)


def p_add(a: list, b: list) -> list:
    return [x + y for x, y in zip(a, b)]


def p_sub(a: list, b: list) -> list:
    return [x - y for x, y in zip(a, b)]


def p_mul2(a: list) -> list:
    """mul_small(a, 2): the only small-constant multiply point_add needs."""
    return p_carry([x * 2 for x in a])


def p_select(mask, a: list, b: list) -> list:
    """Per-element select between two plane lists; mask broadcasts."""
    return [jnp.where(mask, x, y) for x, y in zip(a, b)]


def p_point_select(mask, p: tuple, q: tuple) -> tuple:
    """Point-level select: (X, Y, Z, T) plane-list tuples."""
    return tuple(p_select(mask, a, b) for a, b in zip(p, q))


_16P_PLANES = [int(x) for x in _np_limbs(16 * P_INT)]
_P_PLANES = [int(x) for x in _np_limbs(P_INT)]


def p_canonical(a: list) -> list:
    """field.canonical() on planes: the unique representative in [0, p),
    every limb in [0, 4096).  Same pass structure limb for limb (so the
    two stay differentially testable); sequential chains are free here —
    "limbs" are vector registers inside a kernel."""
    a = [x + c for x, c in zip(p_carry(a), _16P_PLANES)]
    a = p_carry(a)
    for _ in range(3):
        top = a[LIMBS - 1] >> 4
        a[LIMBS - 1] = a[LIMBS - 1] - (top << 4)
        a[0] = a[0] + top * 38
        a = p_fold_pass(a)
    for _ in range(3):
        borrow = a[0] * 0
        limbs = []
        for i in range(LIMBS):
            v = a[i] - _P_PLANES[i] + borrow
            borrow = v >> BITS
            limbs.append(v - (borrow << BITS))
        ge = borrow >= 0
        a = [jnp.where(ge, l, x) for l, x in zip(limbs, a)]
    c = a[0] * 0
    out = []
    for i in range(LIMBS):
        v = a[i] + c
        c = v >> BITS
        out.append(v - (c << BITS))
    return out


def p_eq(a: list, b: list):
    """field.eq() on planes: canonical equality -> a bool array."""
    ok = None
    for x, y in zip(p_canonical(a), p_canonical(b)):
        e = x == y
        ok = e if ok is None else (ok & e)
    return ok


# -- Edwards points as 4 plane lists (X, Y, Z, T) -----------------------------

from ba_tpu.crypto.oracle import B_X, B_Y, D, P  # noqa: E402

D2_PLANES = const_planes(2 * D % P)
BASE_PLANES = (
    const_planes(B_X),
    const_planes(B_Y),
    const_planes(1),
    const_planes(B_X * B_Y % P),
)


def p_identity(zeros_like) -> tuple:
    """Identity point planes; ``zeros_like`` is a concrete zero array of the
    plane shape (kernels pass a VMEM-tile zero, tests a [B] zero)."""
    z = [zeros_like] * LIMBS
    one = [zeros_like + 1] + [zeros_like] * (LIMBS - 1)
    return (z, one, list(one), list(z))


def p_point_add(p: tuple, q: tuple) -> tuple:
    """ed25519.point_add on planes: complete unified addition, 9 muls."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = p_mul(p_sub(y1, x1), p_sub(y2, x2))
    b = p_mul(p_add(y1, x1), p_add(y2, x2))
    c = p_mul(p_mul(t1, t2), D2_PLANES)
    d = p_mul2(p_mul(z1, z2))
    e = p_sub(b, a)
    f = p_sub(d, c)
    g = p_add(d, c)
    h = p_add(b, a)
    return (p_mul(e, f), p_mul(g, h), p_mul(f, g), p_mul(e, h))


def p_point_dbl(p: tuple, with_t: bool = True) -> tuple:
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 7 muls, or 8 with T.

    2P from (X : Y : Z : _): A = X^2, B = Y^2, C = 2Z^2, E = 2XY,
    G = B - A, F = G - C, H = -(A + B); out (EF, GH, FG, EH).  The input
    T is never read, so a doubling chain can skip computing T on every
    step but the last (``with_t=False`` -> T planes are zeros; only the
    step feeding a ``p_point_add`` needs the true T).  Identical group
    element to ``p_point_add(p, p)`` in a different projective
    representation (compare via point_eq, as with the window fold).

    Bounds: E, H are single-lazy combinations of carried mul outputs
    (within carry()'s documented multiply-safe envelope); G is carried
    explicitly so F = G - C stays single-lazy too — a double-lazy operand
    would push the schoolbook convolution past int32.
    """
    x, y, z, _ = p
    a = p_mul(x, x)
    b = p_mul(y, y)
    c = p_mul2(p_mul(z, z))
    e = p_mul2(p_mul(x, y))
    g = p_carry(p_sub(b, a))
    f = p_sub(g, c)
    zero = a[0] * 0
    h = p_sub([zero] * LIMBS, p_add(a, b))
    t_planes = p_mul(e, h) if with_t else [zero] * LIMBS
    return (p_mul(e, f), p_mul(g, h), p_mul(f, g), t_planes)
