"""Pallas TPU kernel: the Ed25519 double-and-add ladder, VMEM-resident.

The hot op of batched signature verification (BASELINE config #3) is
``[k]P`` over 512 scalar bits — 1024 complete Edwards additions per lane.
The jnp path (ba_tpu.crypto.ed25519.scalar_mult) expresses each field
multiply as a [.., 484] x [484, 43] matmul whose 0/1 anti-diagonal matrix
wastes 43x the necessary MACs, and its lax.scan carry (8 coordinate
tensors) round-trips HBM every step.  This kernel fixes both:

- limb-major planes (ba_tpu.ops.planes): a field element is 22 separate
  [8, 128] VMEM tiles, so the schoolbook convolution is exactly 484 vector
  MACs on the VPU and every limb shift is register renaming;
- the whole 512-step ladder runs inside one kernel invocation per batch
  tile: points, temporaries and the bit-packed scalars (16 uint32 words per
  lane) never leave VMEM.

Layout: batch is padded to 1024-lane tiles shaped [8, 128] (sublane x
lane); a point is [22, 8g, 128] per coordinate; scalars are packed LSB-
first into [nbits/32, 8g, 128] int32 words.  Grid = one program per tile.

Differential contract: bit-for-bit equal to ed25519.scalar_mult (and hence
to the pure-Python oracle).  The assembled kernel is pinned on real TPU
(BA_TPU_TESTS_ON_TPU=1, test_ladder_pallas_matches_scalar_mult_tpu); plain
CPU runs cover the shared plane arithmetic and the packing/tiling plumbing
instead — interpret mode would execute ~5M interpreted vector ops per tile
and an XLA-CPU jit of the 2-point-add body compiles for >9 minutes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ba_tpu.crypto.field import LIMBS
from ba_tpu.ops.planes import (
    p_eq,
    p_identity,
    p_mul,
    p_point_add,
    p_point_dbl,
    p_point_select,
)

TILE_ROWS = 8
LANES = 128
TILE = TILE_ROWS * LANES


def _ladder_kernel(nbits, x_ref, y_ref, z_ref, t_ref, bits_ref,
                   ox_ref, oy_ref, oz_ref, ot_ref):
    q = tuple(
        [ref[i] for i in range(LIMBS)]
        for ref in (x_ref, y_ref, z_ref, t_ref)
    )
    zero = jnp.zeros((TILE_ROWS, LANES), jnp.int32)
    acc = p_identity(zero)

    def body(t, state):
        acc, q = state
        word = bits_ref[pl.ds(t >> 5, 1)][0]  # [8, 128]
        bit = (word >> (t & 31)) & 1
        added = p_point_add(acc, q)
        acc = p_point_select(bit == 1, added, acc)
        q = p_point_add(q, q)
        return (acc, q)

    acc, _ = jax.lax.fori_loop(0, nbits, body, (acc, q))
    for out_ref, planes in zip((ox_ref, oy_ref, oz_ref, ot_ref), acc):
        for i in range(LIMBS):
            out_ref[i] = planes[i]


def _window_acc(nwin, x_ref, y_ref, z_ref, t_ref, bits_ref):
    """4-bit-window scalar mult body: acc = 16*acc + T[digit_w], MSB-first.

    Builds the 16-entry multiples table of the per-lane point in VMEM
    (14 additions), then runs nwin windows of 4 doublings + one 16-way
    masked table select + one addition.  The doublings use the dedicated
    7/8-mul formula (p_point_dbl) and skip the T coordinate on all but
    the last — only the window's closing p_point_add reads T — cutting
    the per-window point arithmetic from 45 to ~38 field muls vs the
    unified-add-only form; ~5.6 MB of VMEM table.  Same packed-words bit
    layout as the plain ladder.  Shared by the plain window kernel and
    the verify-fused one.
    """
    p = tuple(
        [ref[i] for i in range(LIMBS)]
        for ref in (x_ref, y_ref, z_ref, t_ref)
    )
    zero = jnp.zeros((TILE_ROWS, LANES), jnp.int32)
    table = [p_identity(zero), p]
    for j in range(2, 16):
        table.append(p_point_add(table[j - 1], p))

    def body(t, acc):
        w = nwin - 1 - t  # MSB-first
        for k in range(4):
            acc = p_point_dbl(acc, with_t=(k == 3))
        word = bits_ref[pl.ds(w >> 3, 1)][0]  # [8, 128]
        digit = (word >> (4 * (w & 7))) & 15
        entry = table[0]
        for j in range(1, 16):
            entry = p_point_select(digit == j, table[j], entry)
        return p_point_add(acc, entry)

    return jax.lax.fori_loop(0, nwin, body, p_identity(zero))


def _window_kernel(nwin, x_ref, y_ref, z_ref, t_ref, bits_ref,
                   ox_ref, oy_ref, oz_ref, ot_ref):
    acc = _window_acc(nwin, x_ref, y_ref, z_ref, t_ref, bits_ref)
    for out_ref, planes in zip((ox_ref, oy_ref, oz_ref, ot_ref), acc):
        for i in range(LIMBS):
            out_ref[i] = planes[i]


def _window_verify_kernel(nwin, x_ref, y_ref, z_ref, t_ref, bits_ref,
                          rx_ref, ry_ref, rz_ref, rt_ref,
                          lx_ref, ly_ref, lz_ref, ok_ref):
    """The verification epilogue fused onto the [h]A window mult: computes
    right = R + acc and the projective equality left == right WITHOUT
    writing any point back to HBM — one int32 verdict plane replaces 88
    coordinate planes of output plus a separate XLA add/eq program
    (VERDICT r4 item 5: finish_add_eq cost 584 ns/sig standalone).  The
    left point [S]B arrives affine-extended from the fixed-base fold, but
    equality is projective (cross-multiplied), so only X, Y, Z are read.
    """
    acc = _window_acc(nwin, x_ref, y_ref, z_ref, t_ref, bits_ref)
    r = tuple(
        [ref[i] for i in range(LIMBS)]
        for ref in (rx_ref, ry_ref, rz_ref, rt_ref)
    )
    xr, yr, zr, _ = p_point_add(r, acc)
    xl = [lx_ref[i] for i in range(LIMBS)]
    yl = [ly_ref[i] for i in range(LIMBS)]
    zl = [lz_ref[i] for i in range(LIMBS)]
    ok = p_eq(p_mul(xl, zr), p_mul(xr, zl)) & p_eq(p_mul(yl, zr), p_mul(yr, zl))
    ok_ref[0] = ok.astype(jnp.int32)


def _to_tiles(x: jnp.ndarray, batch_pad: int) -> jnp.ndarray:
    """[B, k] -> plane-major [k, rows, 128] (zero-padded; zeros are
    add-safe).  Shared tile-layout contract for every ops kernel."""
    B, k = x.shape
    x = jnp.pad(x, ((0, batch_pad - B), (0, 0)))
    return jnp.transpose(x, (1, 0)).reshape(k, batch_pad // LANES, LANES)


def _from_tiles(tiles: jnp.ndarray, B: int) -> jnp.ndarray:
    """Inverse of ``_to_tiles``: [k, rows, 128] -> [B, k]."""
    return jnp.transpose(tiles.reshape(tiles.shape[0], -1), (1, 0))[:B]


def plane_spec(k: int) -> pl.BlockSpec:
    """The shared per-tile BlockSpec for k-limb plane tensors
    [k, rows, 128]: one 1024-lane tile per grid step, all limbs resident
    in VMEM.  Every ops kernel wrapper must build its specs through this
    helper so the tile-layout contract lives in one place."""
    return pl.BlockSpec(
        (k, TILE_ROWS, LANES), lambda i: (0, i, 0),
        memory_space=pltpu.VMEM,
    )


def plane_out_shape(k: int, batch_pad: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct((k, batch_pad // LANES, LANES), jnp.int32)


def _pack_bits(bits: jnp.ndarray, batch_pad: int) -> jnp.ndarray:
    """[B, nbits] {0,1} int32 -> [nbits/32, rows, 128] packed words."""
    B, nbits = bits.shape
    assert nbits % 32 == 0
    w = bits.reshape(B, nbits // 32, 32) << jnp.arange(32, dtype=jnp.int32)
    words = w.sum(axis=-1, dtype=jnp.int32)  # [B, nw]
    words = jnp.pad(words, ((0, batch_pad - B), (0, 0)))
    return jnp.transpose(words, (1, 0)).reshape(-1, batch_pad // LANES, LANES)


def _mult_call(kernel_fn, point: tuple, bits: jnp.ndarray, interpret: bool):
    """Shared tiling/spec plumbing for both scalar-mult kernels: pack the
    coords and bits into the tile layout, launch one program per 1024-lane
    tile, un-tile the product point."""
    B, nbits = bits.shape
    assert nbits % 32 == 0
    batch_pad = -(-B // TILE) * TILE
    grid = batch_pad // TILE
    coords = [_to_tiles(c, batch_pad) for c in point]
    words = _pack_bits(bits.astype(jnp.int32), batch_pad)
    outs = pl.pallas_call(
        kernel_fn,
        grid=(grid,),
        in_specs=[plane_spec(LIMBS)] * 4 + [plane_spec(nbits // 32)],
        out_specs=(plane_spec(LIMBS),) * 4,
        out_shape=(plane_out_shape(LIMBS, batch_pad),) * 4,
        interpret=interpret,
    )(*coords, words)
    return tuple(_from_tiles(o, B) for o in outs)


@functools.partial(jax.jit, static_argnames=("interpret",))
def scalar_mult(point: tuple, bits: jnp.ndarray, *, interpret: bool = False):
    """Drop-in Pallas replacement for ``ed25519.scalar_mult``.

    point: (X, Y, Z, T) limb tensors [B, 22]; bits [B, nbits] LSB-first,
    nbits a static multiple of 32.  Returns the product point, [B, 22] x 4.
    """
    nbits = bits.shape[1]
    return _mult_call(
        functools.partial(_ladder_kernel, nbits), point, bits, interpret
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def window_verify(
    point: tuple,
    bits: jnp.ndarray,
    r_point: tuple,
    left: tuple,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused ``left == r_point + [k]point`` -> bool [B].

    The whole verification tail in one kernel: the [h]A window mult, the
    R + [h]A completion add, and the cross-multiplied projective equality
    against [S]B.  ``point``/``r_point`` are (X, Y, Z, T) limb tensors
    [B, 22]; ``left`` needs only (X, Y, Z).  Verdicts on lanes whose
    decompression failed are garbage — callers gate on the encoding masks
    (ed25519.verify does).
    """
    B, nbits = bits.shape
    assert nbits % 32 == 0
    batch_pad = -(-B // TILE) * TILE
    grid = batch_pad // TILE
    coords = [_to_tiles(c, batch_pad) for c in point]
    coords += [_to_tiles(c, batch_pad) for c in r_point]
    coords += [_to_tiles(c, batch_pad) for c in left[:3]]
    words = _pack_bits(bits.astype(jnp.int32), batch_pad)
    out = pl.pallas_call(
        functools.partial(_window_verify_kernel, nbits // 4),
        grid=(grid,),
        in_specs=[plane_spec(LIMBS)] * 4 + [plane_spec(nbits // 32)]
        + [plane_spec(LIMBS)] * 7,
        out_specs=plane_spec(1),
        out_shape=plane_out_shape(1, batch_pad),
        interpret=interpret,
    )(*coords[:4], words, *coords[4:])
    return _from_tiles(out, B)[:, 0] != 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def window_mult(point: tuple, bits: jnp.ndarray, *, interpret: bool = False):
    """[k]P via the 4-bit-window kernel — same contract as ``scalar_mult``
    but ~1.25x faster (5 adds per 4 bits instead of 8); the result is the
    same group element with a different projective representation (the
    fold order differs), so compare via point_eq, not limbs.  nbits must
    be a multiple of 32 (nibble windows ride the same packed words).
    """
    nbits = bits.shape[1]
    return _mult_call(
        functools.partial(_window_kernel, nbits // 4), point, bits, interpret
    )
