"""ba_tpu — a TPU-native Byzantine-agreement framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
mathiasplans/byzantine-agreement (reference: /root/reference/ba.py): the
Byzantine Generals problem with leader election, order broadcast, majority
voting, 3f+1 quorum decisions, live fault injection, and elastic membership —
rebuilt as massively-batched tensor programs over (instances x nodes x nodes)
arrays instead of thread-per-general RPC.

Layout (mirrors SURVEY.md section 1's layer map, TPU-first):

- ``ba_tpu.core``     — pure-functional protocol math: OM(1), recursive
  OM(m)/EIG, SM(m) signed messages, quorum thresholds, election. The
  reference's L3 protocol logic (ba.py:126-319) as jittable tensor ops.
- ``ba_tpu.ops``      — Pallas TPU kernels: the Ed25519 scalar-mult ladder
  (limb-plane VMEM arithmetic) and the fused masked-majority reduce, each
  with jnp fallbacks and measured justifications (see ops/__init__).
- ``ba_tpu.crypto``   — batched Ed25519 / SHA-512 (JAX int32-limb programs;
  pure-Python RFC 8032 oracle + the baked-in native ``cryptography`` wheel
  as host signer, both differential-tested against each other).
- ``ba_tpu.parallel`` — device-mesh sharding: instance-axis data parallelism
  and node-axis "sequence parallelism" with XLA collectives; the TPU
  equivalent of the reference's RPyC/TCP backend (ba.py:79-102).
- ``ba_tpu.runtime``  — the thin stateful host shell: membership registry,
  election-for-life, failure detection, and the REPL with byte-identical
  output (reference L2/L4, ba.py:66-122,354-445).
- ``ba_tpu.scenario`` — declarative adversary & membership campaigns:
  the REPL's ``g-kill``/``g-add``/``g-state`` session as data (JSON
  specs -> dense per-round device planes) plus coordinated adversary
  strategies, executed by the pipelined mutating megastep
  (``parallel.scenario_sweep``) with on-device IC1/IC2 verdicts.
"""

__version__ = "0.1.0"
