"""Elastic fleet tier (ISSUE 20): replicated serving, consistent-hash
routing, serve-drain live migration.

Three host-tier modules (BA301: importing any of them never touches
jax — the engine is reached only inside a replica's campaign lane):

- :mod:`ba_tpu.fleet.replica` — ``FleetConfig`` / ``Replica`` /
  ``ReplicaManager``: N in-process ``AgreementService`` replicas with
  per-replica registries, warm-gated ring entry, campaign lanes and
  the crash-consistent campaign ledger.
- :mod:`ba_tpu.fleet.router` — ``HashRing`` / ``FleetRouter`` /
  ``RoutedTicket``: cohort-keyed consistent-hash routing, bounded
  overload hops with origin ``retry_after_s`` propagation,
  reroute-on-death, ``autoscale_signal`` consumption.
- :mod:`ba_tpu.fleet.migrate` — ``drain`` / handoff headers /
  ``adopt_orphans``: checkpoint-fingerprint-verified live migration
  over the repo's one carry-checkpoint format.

Quickstart::

    from ba_tpu.fleet import FleetConfig, FleetRouter, ReplicaManager

    mgr = ReplicaManager(FleetConfig(replicas=2, root="/tmp/fleet"))
    mgr.start()                      # boot + warm barrier per replica
    router = FleetRouter(mgr)
    t = router.submit(AgreementRequest(kind="run-rounds", rounds=8))
    out = t.result(timeout=60)       # survives replica death/drain
    mgr.drain("replica-0")           # live-migrates its campaigns
    mgr.stop()
"""

from ba_tpu.fleet.migrate import (
    DrainStop,
    HandoffRefused,
    adopt_orphans,
    drain,
    read_handoff,
    resume_handoff,
    verify_handoff,
    write_handoff,
)
from ba_tpu.fleet.replica import (
    REPLICA_STATES,
    CampaignHandle,
    CampaignSpec,
    FleetConfig,
    Replica,
    ReplicaManager,
    read_ledger,
)
from ba_tpu.fleet.router import FleetRouter, HashRing, RoutedTicket

__all__ = [
    "REPLICA_STATES",
    "CampaignHandle",
    "CampaignSpec",
    "DrainStop",
    "FleetConfig",
    "FleetRouter",
    "HandoffRefused",
    "HashRing",
    "Replica",
    "ReplicaManager",
    "RoutedTicket",
    "adopt_orphans",
    "drain",
    "read_handoff",
    "read_ledger",
    "resume_handoff",
    "verify_handoff",
    "write_handoff",
]
