"""The fleet router (ISSUE 20): consistent-hash request routing over
ready replicas, overload-aware hop retries, reroute-on-death, and the
``autoscale_signal`` control loop.

**Ring.**  Placement is a classic consistent-hash ring — ``vnodes``
sha256 points per member, request keyed on the full cohort LABEL
(``serve.cohort_label(cohort_key(req))``: scenario-ness, rounds,
padded capacity, engine, ``m``, ``signed``) — so every request of one
cohort lands on the same replica and coalesces there (splitting a
cohort across replicas would halve batching efficiency for zero
balance gain), while distinct cohorts spread.  Membership changes move
only the cohorts that hashed to the departed/arrived member: the
vnode construction is deterministic (test-pinned), so source and
target of any move are derivable offline from the member list alone.

**Overload as a load signal.**  An :class:`~ba_tpu.runtime.serve.
Overloaded` admission is not a dead end but a hop: the router retries
the next ring member (bounded — ``max_hops``), and when EVERY hop
rejects it re-raises with the ORIGIN replica's ``retry_after_s``
(first hop = the cohort's hash home) — the origin's queue depth is the
signal the client should back off against; recomputing a cold default
at the router would tell a 64-deep fleet to hammer back in 100 ms
(unit-pinned next to the ``COLD_RETRY_AFTER_S`` pin).

**Never a hung client.**  A replica that dies or drains fails its
queued tickets with :class:`~ba_tpu.runtime.serve.ServeError`;
:class:`RoutedTicket` catches exactly that terminal (deadline and
request failures re-raise untouched — those are OUTCOMES) and
re-submits on the next surviving member, bounded by ``max_hops``
reroutes, inside the caller's original ``result(timeout=...)`` budget.

**Autoscale.**  The router CONSUMES the PR 17 ``autoscale_signal``
contract: :meth:`FleetRouter.apply_autoscale` takes a signal record
(from the SLO engine's stream or :meth:`control_step`'s own synthesis
through ``obs.slo.recommend_replicas``) and starts/drains replicas to
the recommendation — drains go through ``migrate.drain``, so scale-in
never abandons a campaign.

Host-tier by lint contract (BA301): importing this module never
touches jax.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time

from ba_tpu import obs
from ba_tpu.runtime.serve import (
    DeadlineExceeded,
    Overloaded,
    RequestFailed,
    ServeError,
    cohort_key,
    cohort_label,
)
from ba_tpu.utils import metrics as _metrics


def _point(member: str, vnode: int) -> int:
    digest = hashlib.sha256(f"{member}#{vnode}".encode()).hexdigest()
    return int(digest[:16], 16)


def _key_point(key: str) -> int:
    return int(hashlib.sha256(key.encode()).hexdigest()[:16], 16)


class HashRing:
    """Deterministic consistent-hash ring: ``vnodes`` sha256 points per
    member; ``prefer(key)`` walks clockwise from the key's point and
    returns every member once, in preference order (hash home first —
    the same order in every process that knows the member list)."""

    def __init__(self, members=(), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes={vnodes} must be >= 1")
        self.vnodes = vnodes
        self._points: list = []
        self._owners: list = []
        self._members: tuple = ()
        self.rebuild(members)

    def rebuild(self, members) -> None:
        members = tuple(sorted(set(members)))
        pairs = sorted(
            (_point(m, v), m)
            for m in members
            for v in range(self.vnodes)
        )
        self._points = [p for p, _ in pairs]
        self._owners = [m for _, m in pairs]
        self._members = members

    @property
    def members(self) -> tuple:
        return self._members

    def prefer(self, key: str) -> list:
        """Preference order for ``key``: unique members from its ring
        point clockwise.  Empty ring → empty list."""
        if not self._points:
            return []
        start = bisect.bisect_right(self._points, _key_point(key))
        order: list = []
        seen = set()
        n = len(self._owners)
        for i in range(n):
            owner = self._owners[(start + i) % n]
            if owner not in seen:
                seen.add(owner)
                order.append(owner)
        return order


class RoutedTicket:
    """The client's handle on a ROUTED request: wraps the live
    replica's :class:`~ba_tpu.runtime.serve.Ticket` and, when that
    replica dies or drains before dispatch (``ServeError``), re-submits
    on the next surviving ring member — transparently, inside the
    caller's ``result`` budget, bounded by the router's ``max_hops``.
    Deadline/request failures and timeouts re-raise untouched: those
    are outcomes, not routing events.  Single-caller contract (like
    ``Ticket``): ``result`` is not re-entrant."""

    def __init__(self, router, request, deadline_s, replica_name,
                 ticket, admit_hops: int):
        self._router = router
        self.request = request
        self.deadline_s = deadline_s
        self.replica = replica_name
        self.ticket = ticket
        self.admit_hops = admit_hops
        self.reroutes = 0
        self.tried = [replica_name]

    @property
    def id(self):
        return self.ticket.id

    def done(self) -> bool:
        return self.ticket.done()

    def result(self, timeout: float | None = None):
        deadline = (
            None if timeout is None else time.perf_counter() + timeout
        )
        while True:
            remaining = (
                None
                if deadline is None
                else max(0.0, deadline - time.perf_counter())
            )
            try:
                return self.ticket.result(remaining)
            except (DeadlineExceeded, RequestFailed):
                raise
            except Overloaded:
                raise
            except ServeError as dead:
                # The replica stopped before dispatching us (death or
                # drain) — re-home on the next surviving member.
                self._router._rehop(self, dead)


class FleetRouter:
    """Routes requests over a :class:`~ba_tpu.fleet.replica.
    ReplicaManager`'s ready set (module docstring for the design)."""

    def __init__(self, manager):
        self.manager = manager
        self.config = manager.config
        self.run_id = manager.run_id
        self._ring = HashRing(vnodes=self.config.vnodes)
        self._lock = threading.Lock()
        self._routes = 0
        self._reroutes = 0

    # -- ring membership -----------------------------------------------------

    def _sync_ring(self) -> list:
        ready = {r.name: r for r in self.manager.ready()}
        with self._lock:
            if tuple(sorted(ready)) != self._ring.members:
                self._ring.rebuild(ready)
        return ready

    def _emit_route(self, ticket, cohort: str, replica: str, hops: int,
                    rerouted: bool, **fields) -> None:
        rec = {
            "event": "router_route",
            "v": _metrics.SCHEMA_VERSION,
            "request_id": ticket.id if ticket is not None else None,
            "cohort": cohort,
            "replica": replica,
            "hops": hops,
            "rerouted": rerouted,
            "run_id": self.run_id,
            **fields,
        }
        if ticket is not None:
            tctx = ticket._trace
            rec["trace_id"], rec["span_id"] = tctx[0], tctx[1]
            rec["traceparent"] = _metrics.format_traceparent(
                tctx[0], tctx[1]
            )
        _metrics.emit(rec)

    # -- routing -------------------------------------------------------------

    def submit(self, request, deadline_s=...) -> RoutedTicket:
        """Admit on the cohort's hash home, hopping the ring on
        overload (bounded).  On total rejection, re-raises with the
        ORIGIN replica's ``retry_after_s`` — never a recomputed cold
        default (module docstring)."""
        ready = self._sync_ring()
        if not ready:
            raise ServeError("fleet has no ready replica")
        label = cohort_label(cohort_key(request))
        order = self._ring.prefer(label)[: self.config.max_hops]
        origin: Overloaded | None = None
        hops = 0
        for name in order:
            rep = ready.get(name)
            if rep is None or not rep.ready():
                continue
            hops += 1
            try:
                ticket = rep.submit(request, deadline_s=deadline_s)
            except Overloaded as e:
                if origin is None:
                    origin = e
                continue
            except ServeError:
                # Closed between the ready check and the submit (the
                # drain/death race) — not a member anymore, keep
                # walking the ring.
                continue
            with self._lock:
                self._routes += 1
            self._emit_route(ticket, label, name, hops, False)
            return RoutedTicket(
                self, request, deadline_s, name, ticket, hops
            )
        if origin is None:
            raise ServeError(
                "fleet has no ready replica for cohort " + label
            )
        obs.instant(
            "router_reject", cohort=label, hops=hops,
            retry_after_s=origin.retry_after_s,
        )
        # Every hop shed: the ORIGIN's hint is the real backpressure
        # signal (its queue depth x its observed batch rate) — hop
        # rejections must not launder it into a colder, smaller value.
        raise Overloaded(
            f"fleet overloaded after {hops} hop(s): {origin}",
            retry_after_s=origin.retry_after_s,
            tier=origin.tier,
            reason=origin.reason,
        )

    def _rehop(self, routed: RoutedTicket, dead: ServeError) -> None:
        """Re-home a routed ticket whose replica stopped before
        dispatch (called from :meth:`RoutedTicket.result`)."""
        if routed.reroutes >= self.config.max_hops:
            raise ServeError(
                f"request {routed.id} exhausted {routed.reroutes} "
                f"reroute(s): {dead}"
            ) from dead
        ready = self._sync_ring()
        label = cohort_label(cohort_key(routed.request))
        overload: Overloaded | None = None
        for name in self._ring.prefer(label):
            if name in routed.tried:
                continue
            rep = ready.get(name)
            if rep is None or not rep.ready():
                continue
            routed.tried.append(name)
            try:
                ticket = rep.submit(
                    routed.request, deadline_s=routed.deadline_s
                )
            except Overloaded as e:
                if overload is None:
                    overload = e
                continue
            except ServeError:
                continue  # same drain/death race as in submit()
            routed.reroutes += 1
            routed.replica = name
            routed.ticket = ticket
            with self._lock:
                self._reroutes += 1
            self._emit_route(
                ticket, label, name, routed.reroutes, True,
                from_replica=routed.tried[-2],
            )
            return
        if overload is not None:
            raise overload
        raise ServeError(
            f"request {routed.id}: no surviving replica to re-home "
            f"onto ({dead})"
        ) from dead

    # -- autoscale -----------------------------------------------------------

    def apply_autoscale(self, signal: dict) -> dict:
        """Consume one ``autoscale_signal`` record (the PR 17
        contract): start replicas up to the recommendation, or drain
        surplus ones (through ``migrate.drain`` — scale-in migrates,
        never abandons).  Returns ``{"started": [...], "drained":
        [...]}``."""
        recommended = int(signal["recommended"])
        recommended = max(1, min(recommended, self.config.max_replicas))
        ready = self.manager.ready()
        started, drained = [], []
        while len(ready) < recommended:
            rep = self.manager.start_replica()
            started.append(rep.name)
            ready = self.manager.ready()
        while len(ready) > max(1, recommended):
            victim = ready[-1]
            self.manager.drain(victim.name)
            drained.append(victim.name)
            ready = self.manager.ready()
        if started or drained:
            obs.instant(
                "fleet_autoscale", recommended=recommended,
                started=len(started), drained=len(drained),
            )
        return {"started": started, "drained": drained}

    def control_step(self) -> dict:
        """One control-loop tick: read fleet pressure (max per-replica
        queue occupancy, the process ``health_slo_burn`` gauge), run it
        through ``obs.slo.recommend_replicas``, EMIT the resulting
        ``autoscale_signal`` record and apply it."""
        ready = self.manager.ready()
        queue_frac = max(
            (r.health()["queue_frac"] for r in ready), default=0.0
        )
        burn = obs.default_registry().gauge("health_slo_burn").value
        recommended, reason = obs.slo.recommend_replicas(
            queue_frac,
            burn,
            replicas=len(ready),
            max_replicas=self.config.max_replicas,
        )
        rec = {
            "event": "autoscale_signal",
            "v": _metrics.SCHEMA_VERSION,
            "run_id": self.run_id,
            "recommended": recommended,
            "replicas": len(ready),
            "burn": round(float(burn), 6),
            "queue_frac": round(float(queue_frac), 6),
            "reason": reason,
            "source": "fleet_router",
        }
        _metrics.emit(rec)
        action = self.apply_autoscale(rec)
        return {**rec, **action}

    def stats(self) -> dict:
        with self._lock:
            routes, reroutes = self._routes, self._reroutes
        return {
            "replicas": [r.health() for r in self.manager.all()],
            "ready": len(self.manager.ready()),
            "routes": routes,
            "reroutes": reroutes,
            "members": list(self._ring.members),
        }
