"""Serve-drain live migration (ISSUE 20): move in-flight campaigns
between replicas through the repo's ONE checkpoint format.

A drain is a *move, not an outcome*: the draining replica's campaign
lanes stop at their next retire-point checkpoint (the supervisor's
``on_checkpoint`` hook — fired AFTER the carry checkpoint and its rows
sidecar are durably on disk — raises :class:`DrainStop`), each lane
writes a **handoff header** next to its checkpoint family, and the
adopting replica resumes through the engine's existing ``resume="auto"``
machinery: the ``{round}``-templated family plus the rows-sidecar chain
reassemble the FULL campaign history, so the migrated result is
bit-identical to the uninterrupted run.  Carry checkpoints are
device-count-free (gather-on-write / reshard-on-read), so the source
and target replica meshes may differ.

``DrainStop`` deliberately subclasses :class:`BaseException`: the
supervisor's attempt loop recovers from ``Exception`` (that is its job)
and re-raises only ``KeyboardInterrupt``/``SystemExit`` — a drain must
ride the same out-of-band lane, never burn the recovery budget as a
fake fault.

The handoff header is the migration's TRUST BOUNDARY.  It names the
campaign doc, the checkpoint family, the round cursor, the campaign
fingerprint (``campaign_sha256``) and the protocol ``signed`` flag;
:func:`verify_handoff` re-reads the checkpoint's own meta (jax-free,
``utils/snapshot.validate_carry_checkpoint``) and refuses — loudly,
:class:`HandoffRefused` — a header whose fingerprint or signed flag
contradicts the checkpoint it points at.  A forged header can therefore
never splice an unsigned carry into a signed campaign (or vice versa):
cross-protocol resume is refused at adoption, before any engine work.

SIGKILLed replicas write no handoff at all.  Their campaigns are
recovered by :func:`adopt_orphans` from the dead replica's append-only
ledger (``replica.py`` writes it fsync'd, crash-consistent): any
admitted-but-unfinished campaign whose newest on-disk checkpoint
validates AND matches the ledgered fingerprint is re-run from its doc —
``resume="auto"`` then re-verifies the same fingerprint a second time
inside the supervisor.

Host-tier by lint contract (BA301): importing this module never touches
jax — verification is numpy + stdlib, and the engine is only reached by
the adopting replica's campaign lane.
"""

from __future__ import annotations

import json
import os

from ba_tpu import obs
from ba_tpu.utils import metrics as _metrics
from ba_tpu.utils import snapshot as _snapshot

HANDOFF_FORMAT = "ba-fleet-handoff"
HANDOFF_VERSION = 1

# The handoff header's required keys (doc-schema, mirrored by the
# DESIGN § and checked by read_handoff).
HANDOFF_KEYS = (
    "format", "v", "campaign", "doc", "template", "round", "rounds",
    "checkpoint", "fingerprint", "signed", "from_replica",
)


class DrainStop(BaseException):
    """Out-of-band drain signal raised from a campaign's checkpoint
    hook (BaseException ON PURPOSE — module docstring)."""

    def __init__(self, round_cursor: int, path: str):
        super().__init__(f"drain at round {round_cursor}: {path}")
        self.round_cursor = round_cursor
        self.path = path


class HandoffRefused(ValueError):
    """The handoff header contradicts the checkpoint it points at (or
    is malformed): the adoption is refused before any engine work."""


def _emit_migration(phase: str, campaign: str, from_replica: str,
                    **fields) -> None:
    _metrics.emit({
        "event": "migration",
        "v": _metrics.SCHEMA_VERSION,
        "phase": phase,
        "campaign": campaign,
        "from_replica": from_replica,
        **fields,
    })


def write_handoff(
    path: str,
    *,
    campaign: str,
    doc: dict,
    template: str,
    round_cursor: int,
    rounds: int,
    checkpoint: str,
    fingerprint: str,
    signed: bool,
    from_replica: str,
    run_id: str | None = None,
    traceparent: str | None = None,
) -> dict:
    """Write the handoff header atomically (temp + ``os.replace`` +
    fsync — the snapshot module's crash discipline) and return it."""
    header = {
        "format": HANDOFF_FORMAT,
        "v": HANDOFF_VERSION,
        "campaign": campaign,
        "doc": dict(doc),
        "template": template,
        "round": int(round_cursor),
        "rounds": int(rounds),
        "checkpoint": checkpoint,
        "fingerprint": fingerprint,
        "signed": bool(signed),
        "from_replica": from_replica,
    }
    if run_id is not None:
        header["run_id"] = run_id
    if traceparent is not None:
        header["traceparent"] = traceparent
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(header, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return header


def read_handoff(path: str) -> dict:
    """Load and shape-check a handoff header (format/version/keys);
    raises :class:`HandoffRefused` on anything malformed."""
    try:
        with open(path, encoding="utf-8") as f:
            header = json.load(f)
    except (OSError, ValueError) as e:
        raise HandoffRefused(f"unreadable handoff {path}: {e}") from e
    if not isinstance(header, dict):
        raise HandoffRefused(f"handoff {path} is not an object")
    if header.get("format") != HANDOFF_FORMAT:
        raise HandoffRefused(
            f"handoff {path}: format {header.get('format')!r} != "
            f"{HANDOFF_FORMAT!r}"
        )
    if header.get("v") != HANDOFF_VERSION:
        raise HandoffRefused(
            f"handoff {path}: version {header.get('v')!r} != "
            f"{HANDOFF_VERSION}"
        )
    missing = [k for k in HANDOFF_KEYS if k not in header]
    if missing:
        raise HandoffRefused(f"handoff {path}: missing keys {missing}")
    return header


def verify_handoff(header: dict) -> dict:
    """The adoption-side trust check: validate the checkpoint the
    header points at and refuse any contradiction.  Returns the
    checkpoint's meta.

    - the checkpoint must pass full schema+digest validation
      (``validate_carry_checkpoint`` — numpy + stdlib, no jax);
    - the header's ``fingerprint`` must equal the checkpoint meta's
      ``campaign_sha256`` (a handoff cannot point a resume at a
      FOREIGN campaign family);
    - the header's ``signed`` flag must equal the checkpoint meta's
      ``signed`` flag — the cross-protocol refusal: a forged header
      cannot splice an unsigned carry into a signed campaign's resume
      (protocol semantics travel WITH the carry, never the header).
    """
    path = header["checkpoint"]
    try:
        meta = _snapshot.validate_carry_checkpoint(path)
    except (OSError, ValueError) as e:
        raise HandoffRefused(
            f"handoff checkpoint {path} failed validation: {e}"
        ) from e
    fp = meta.get("campaign_sha256")
    if fp != header["fingerprint"]:
        raise HandoffRefused(
            f"handoff fingerprint {header['fingerprint']!r} != "
            f"checkpoint campaign_sha256 {fp!r} ({path})"
        )
    if bool(meta.get("signed")) != bool(header["signed"]):
        raise HandoffRefused(
            f"cross-protocol handoff refused: header signed="
            f"{bool(header['signed'])} but checkpoint {path} carries "
            f"signed={bool(meta.get('signed'))}"
        )
    return meta


def drain(replica, *, timeout_s: float | None = None) -> list:
    """Serve-drain one live replica: close serving admission (queued
    requests re-home through the router's :class:`ServeError` reroute
    path), stop every campaign lane at its next checkpoint, and write
    one handoff header per in-flight campaign.

    Returns the handoff header paths.  A replica with ZERO in-flight
    campaigns drains to the empty list as a strict no-op: no handoff
    files, no checkpoint files, nothing to adopt (the edge the tests
    pin — an empty drain must not litter the fleet root with empty
    state someone later mistakes for a campaign).
    """
    replica.set_state("draining")
    _emit_migration(
        "drain_start", "", replica.name,
        campaigns=len(replica.campaigns()),
    )
    rehomed = replica.service.handoff(timeout=timeout_s)
    obs.instant(
        "fleet_drain", replica=replica.name, rehomed=len(rehomed)
    )
    paths = replica.drain_campaigns(timeout_s=timeout_s)
    replica.set_state("stopped")
    replica.service.stop(drain=False, timeout=timeout_s)
    return paths


def resume_handoff(path: str, replica, *, verify: bool = True):
    """Adopt one handed-off campaign on ``replica``: read + verify the
    header, rebuild the campaign from its doc and resume through the
    supervisor's ``resume="auto"`` (which re-verifies the fingerprint
    against the family a second time).  Returns the campaign handle."""
    header = read_handoff(path)
    if verify:
        verify_handoff(header)
    from ba_tpu.fleet.replica import CampaignSpec

    spec = CampaignSpec.from_doc(header["doc"])
    _emit_migration(
        "resume", spec.campaign, header["from_replica"],
        to_replica=replica.name, round=header["round"],
        run_id=header.get("run_id"),
    )
    return replica.run_campaign(spec)


def adopt_orphans(fleet_root: str, dead_replica: str, replica) -> list:
    """Recover a SIGKILLed replica's campaigns from its ledger: every
    admitted-but-unfinished campaign whose newest on-disk checkpoint
    validates and matches the ledgered ``campaign_sha256`` is resumed
    on ``replica`` (adoption BY FINGERPRINT — a stray family squatting
    on the template path is skipped, never spliced).  A campaign that
    died before its first checkpoint restarts from round 0 (nothing to
    verify; ``resume="auto"`` finds no family and starts fresh).

    Returns the adopted campaign handles.
    """
    from ba_tpu.fleet.replica import CampaignSpec, read_ledger

    handles = []
    for entry in read_ledger(fleet_root, dead_replica):
        if entry["status"] != "orphaned":
            continue
        spec = CampaignSpec.from_doc(entry["doc"])
        fp = entry.get("fingerprint")
        if fp is not None:
            found = _snapshot.newest_valid_checkpoint(
                entry["template"],
                quarantine=False,
                below=spec.rounds,
                accept=lambda meta, _fp=fp: (
                    meta.get("campaign_sha256") == _fp
                ),
            )
            if found is None:
                # Checkpoints ledgered but none survive validation +
                # fingerprint match: refuse the adoption rather than
                # resume an unverifiable family.
                _emit_migration(
                    "adopt_refused", spec.campaign, dead_replica,
                    to_replica=replica.name,
                )
                continue
        _emit_migration(
            "adopt", spec.campaign, dead_replica,
            to_replica=replica.name,
            verified=fp is not None,
        )
        handles.append(replica.run_campaign(spec))
    return handles
