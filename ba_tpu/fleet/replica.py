"""Replicated serving (ISSUE 20): N in-process ``AgreementService``
replicas under one manager, each with its own metrics registry, its own
dispatcher thread and its own campaign lanes.

One process, many replicas — the same one-process discipline as
``runtime/serve.py`` (a replica IS a service plus a name, a state
machine and a campaign ledger), so the fleet tier is testable without
any multi-process scaffolding while keeping every seam a real
multi-host deployment needs:

- **State machine** (``replica_state`` records): ``new → booting →
  ready`` on the happy path, ``ready → draining → stopped`` on a
  serve-drain (``migrate.drain``), ``→ dead`` on a kill.  The router
  only ever routes to ``ready`` replicas; a replica enters the ring
  AFTER its warm barrier (compile-ahead on boot — the fleet-wide
  ``compiles_on_request_path == 0`` invariant).
- **Campaign lanes**: long campaigns run on per-campaign threads
  through ``runtime/supervisor.supervised_sweep`` with a
  ``{round}``-templated checkpoint family under the fleet root —
  shared, replica-agnostic paths, so ANY replica resumes a family
  bit-exactly through ``resume="auto"`` and the rows-sidecar chain.
- **Crash-consistent ledger**: every lane appends fsync'd JSONL rows
  (``admit`` → ``checkpoint``* → ``done``|``handoff``) to the
  replica's ledger under the fleet root.  A SIGKILLed replica leaves
  admitted-but-unfinished rows behind; ``migrate.adopt_orphans`` scans
  exactly those and re-verifies each family by its ledgered
  ``campaign_sha256`` fingerprint before adopting.
- **Lock-free health**: per-replica health reads the replica's OWN
  gauge objects (``serve_queue_depth``/``serve_shed_tier`` — gauge
  reads are plain attribute loads, no lock), never ``stats()`` (which
  takes the service's queue condition).

Thread discipline (BA501): the replica's mutable state (``_state``,
``_campaigns``) is written only under ``_lock``; the drain/kill flags
are ``threading.Event``s (their own synchronization); everything else
is either thread-confined to the lane that owns it or append-only.

Host-tier by lint contract (BA301): importing this module never
touches jax — the engine is reached lazily inside the campaign lane
(``_campaign_main``), exactly the ``runtime/serve.py`` seam.

Environment (``FleetConfig.from_env``): ``BA_TPU_FLEET_REPLICAS`` /
``BA_TPU_FLEET_HOPS`` / ``BA_TPU_FLEET_VNODES`` / ``BA_TPU_FLEET_ROOT``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading

from ba_tpu import obs
from ba_tpu.fleet import migrate
from ba_tpu.obs.registry import MetricsRegistry
from ba_tpu.runtime import serve as serve_mod
from ba_tpu.utils import metrics as _metrics
from ba_tpu.utils import snapshot as _snapshot

REPLICA_STATES = (
    "new", "booting", "ready", "draining", "stopped", "dead"
)

# Environment knobs (README "Environment knobs" table + BA603).
FLEET_REPLICAS_ENV = "BA_TPU_FLEET_REPLICAS"
FLEET_HOPS_ENV = "BA_TPU_FLEET_HOPS"
FLEET_VNODES_ENV = "BA_TPU_FLEET_VNODES"
FLEET_ROOT_ENV = "BA_TPU_FLEET_ROOT"


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """The fleet tier's dials: initial replica count, the router's
    reroute bound and virtual-node fan-out, and the shared fleet root
    (campaign checkpoint families + replica ledgers).  ``root=None``
    is a serving-only fleet: requests route, campaigns refuse."""

    replicas: int = 2
    max_hops: int = 3
    vnodes: int = 64
    root: str | None = None
    max_replicas: int = 8

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas={self.replicas} must be >= 1")
        if self.max_hops < 1:
            raise ValueError(f"max_hops={self.max_hops} must be >= 1")
        if self.vnodes < 1:
            raise ValueError(f"vnodes={self.vnodes} must be >= 1")
        if self.max_replicas < self.replicas:
            raise ValueError(
                f"max_replicas={self.max_replicas} < replicas="
                f"{self.replicas}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "FleetConfig":
        # Each knob reads through its module constant directly (not a
        # helper parameter): BA603's cross-module read resolver follows
        # name constants, not call arguments.
        def _int(env_name, raw, field):
            if raw and field not in overrides:
                try:
                    overrides[field] = int(raw)
                except ValueError:
                    raise ValueError(
                        f"{env_name}={raw!r} is not an integer"
                    ) from None

        _int(FLEET_REPLICAS_ENV, os.environ.get(FLEET_REPLICAS_ENV, ""),
             "replicas")
        _int(FLEET_HOPS_ENV, os.environ.get(FLEET_HOPS_ENV, ""),
             "max_hops")
        _int(FLEET_VNODES_ENV, os.environ.get(FLEET_VNODES_ENV, ""),
             "vnodes")
        root = os.environ.get(FLEET_ROOT_ENV, "")
        if root and "root" not in overrides:
            overrides["root"] = root
        return cls(**overrides)


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """A seed-reconstructible campaign: everything a replica needs to
    (re)build the exact same supervised sweep — on ANY replica, after
    any number of migrations — lives in this doc.  The identity the
    supervisor fingerprints (key bytes, rounds, scenario content) is a
    pure function of these fields, which is what makes handoff/adopt
    verification possible at all."""

    campaign: str
    seed: int
    state_seed: int
    batch: int
    rounds: int
    capacity: int = 4
    rounds_per_dispatch: int = 1
    checkpoint_every: int = 4
    scenario: dict | None = None

    def __post_init__(self):
        if not self.campaign or not isinstance(self.campaign, str):
            raise ValueError("campaign id must be a non-empty string")
        if any(c in self.campaign for c in (os.sep, "..", "\x00")):
            raise ValueError(
                f"campaign id {self.campaign!r} must be a plain name "
                f"(it becomes a directory under the fleet root)"
            )
        for f in ("batch", "rounds", "capacity", "rounds_per_dispatch",
                  "checkpoint_every"):
            if getattr(self, f) < 1:
                raise ValueError(f"{f}={getattr(self, f)} must be >= 1")

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        if doc["scenario"] is None:
            del doc["scenario"]
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "CampaignSpec":
        if not isinstance(doc, dict):
            raise ValueError(f"campaign doc must be a dict, got {doc!r}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise ValueError(f"campaign doc has unknown keys {unknown}")
        return cls(**doc)


class CampaignHandle:
    """The replica's handle on one campaign lane: terminal ``outcome``
    in ``{"completed", "handoff", "abandoned", "error"}`` plus the
    matching payload (result dict / handoff path / error)."""

    def __init__(self, spec: CampaignSpec, directory: str, template: str):
        self.spec = spec
        self.directory = directory
        self.template = template
        self.outcome: str | None = None
        self.result: dict | None = None
        self.error: BaseException | None = None
        self.handoff_path: str | None = None
        self.fingerprint: str | None = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)


def ledger_path(root: str, replica: str) -> str:
    return os.path.join(root, "replicas", replica, "ledger.jsonl")


def read_ledger(root: str, replica: str) -> list:
    """Fold a replica's ledger into per-campaign status entries:
    ``{"campaign", "doc", "template", "fingerprint", "status"}`` with
    ``status`` one of ``done`` / ``handoff`` / ``orphaned`` (admitted,
    never finished — the adoption set after a kill)."""
    path = ledger_path(root, replica)
    entries: dict = {}
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue  # torn final line from a killed writer
        cid = row.get("campaign")
        ev = row.get("ev")
        if not cid or not ev:
            continue
        if ev == "admit":
            entries[cid] = {
                "campaign": cid,
                "doc": row.get("doc"),
                "template": row.get("template"),
                "fingerprint": None,
                "status": "orphaned",
            }
        elif cid in entries:
            if ev == "checkpoint":
                entries[cid]["fingerprint"] = row.get("fingerprint")
            elif ev == "done":
                entries[cid]["status"] = "done"
            elif ev == "handoff":
                entries[cid]["status"] = "handoff"
    return list(entries.values())


class Replica:
    """One named serving replica: an ``AgreementService`` on its own
    registry, a state machine, and campaign lanes (class docstring of
    the module for the architecture)."""

    def __init__(
        self,
        name: str,
        config: FleetConfig | None = None,
        serve_config=None,
        fault_plan=None,
        run_id: str | None = None,
    ):
        self.name = name
        self.config = config or FleetConfig.from_env()
        self.registry = MetricsRegistry()
        self.serve_config = serve_config or serve_mod.ServeConfig.from_env()
        self.service = serve_mod.AgreementService(
            self.serve_config, fault_plan=fault_plan,
            registry=self.registry,
        )
        self.run_id = run_id
        self._lock = threading.Lock()
        self._state = "new"
        self._campaigns: dict[str, CampaignHandle] = {}
        self._drain_ev = threading.Event()
        self._killed = threading.Event()
        self._ledger_lock = threading.Lock()

    # -- state machine -------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def set_state(self, state: str) -> None:
        assert state in REPLICA_STATES, state
        with self._lock:
            prev, self._state = self._state, state
        rec = {
            "event": "replica_state",
            "v": _metrics.SCHEMA_VERSION,
            "replica": self.name,
            "state": state,
            "prev": prev,
        }
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        _metrics.emit(rec)

    def ready(self) -> bool:
        return self.state == "ready"

    # -- lifecycle -----------------------------------------------------------

    def start(self, warm_timeout_s: float | None = None) -> "Replica":
        """Boot: start the service and — with warmup configured — hold
        at the warm barrier until every planned signature was attempted
        with zero errors (``WarmupRunner.ok``) BEFORE going ``ready``:
        ring entry is gated on compile-ahead, so no fleet member ever
        pays a request-path compile after boot."""
        self.set_state("booting")
        self.service.start()
        if not self.service.warm_barrier(warm_timeout_s):
            raise serve_mod.ServeError(
                f"replica {self.name}: warm barrier not reached within "
                f"{warm_timeout_s}s"
            )
        warmup = self.service._warmup
        if warmup is not None and not warmup.ok():
            raise serve_mod.ServeError(
                f"replica {self.name}: warmup finished with "
                f"{warmup.errors} error(s) — refusing ring entry cold"
            )
        self.set_state("ready")
        return self

    def stop(self, timeout: float | None = None) -> None:
        self._drain_ev.set()
        for handle in self.campaigns():
            handle.wait(timeout)
        self.service.stop(drain=True, timeout=timeout)
        self.set_state("stopped")

    def kill(self) -> None:
        """The in-process stand-in for SIGKILL: serving stops without
        drain (queued tickets fail — the router's reroute signal), and
        campaign lanes are ABANDONED: no handoff header, no ledger
        ``done`` row — only the periodic checkpoints and the fsync'd
        ledger survive, exactly the on-disk residue a real SIGKILL
        leaves for ``migrate.adopt_orphans``."""
        self._killed.set()
        self._drain_ev.set()
        self.set_state("dead")
        self.service.stop(drain=False)

    # -- serving -------------------------------------------------------------

    def submit(self, request, deadline_s=...):
        return self.service.submit(request, deadline_s=deadline_s)

    def health(self) -> dict:
        """Lock-free health view: plain attribute reads off this
        replica's own gauge/counter objects (never ``stats()``, which
        takes the service's queue condition)."""
        reg = self.registry
        depth = reg.gauge("serve_queue_depth").value
        limit = self.serve_config.max_queue
        return {
            "replica": self.name,
            "state": self.state,
            "queue_depth": depth,
            "queue_frac": depth / limit if limit else 0.0,
            "tier": reg.gauge("serve_shed_tier").value,
            "admitted": reg.counter("serve_admitted_total").value,
            "rejected": reg.counter("serve_rejected_total").value,
        }

    # -- campaign lanes ------------------------------------------------------

    def campaigns(self) -> list:
        with self._lock:
            return list(self._campaigns.values())

    def campaign(self, cid: str) -> CampaignHandle | None:
        with self._lock:
            return self._campaigns.get(cid)

    def _ledger(self, row: dict) -> None:
        path = ledger_path(self.config.root, self.name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        line = json.dumps(row, sort_keys=True) + "\n"
        with self._ledger_lock:
            with open(path, "a", encoding="utf-8") as f:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())

    def run_campaign(self, spec: CampaignSpec) -> CampaignHandle:
        """Start (or adopt — same call: ``resume="auto"`` makes them
        one operation) a campaign lane.  Requires a fleet root: the
        checkpoint family and the ledger are the migration substrate."""
        if self.config.root is None:
            raise ValueError(
                "campaigns need a fleet root (FleetConfig.root / "
                f"{FLEET_ROOT_ENV}) — serving-only fleets cannot "
                "migrate what they cannot checkpoint"
            )
        if not self.ready():
            raise serve_mod.ServeError(
                f"replica {self.name} is {self.state}, not ready"
            )
        directory = os.path.join(
            self.config.root, "campaigns", spec.campaign
        )
        os.makedirs(directory, exist_ok=True)
        template = os.path.join(directory, "ck_{round}.npz")
        handle = CampaignHandle(spec, directory, template)
        with self._lock:
            if spec.campaign in self._campaigns and not (
                self._campaigns[spec.campaign].done()
            ):
                raise ValueError(
                    f"campaign {spec.campaign!r} already running on "
                    f"{self.name}"
                )
            self._campaigns[spec.campaign] = handle
        thread = threading.Thread(
            target=self._campaign_main,
            args=(handle,),
            name=f"ba-fleet-{self.name}-{spec.campaign}",
            daemon=True,
        )
        thread.start()
        return handle

    def drain_campaigns(self, timeout_s: float | None = None) -> list:
        """Stop every lane at its next checkpoint and collect the
        handoff header paths (``migrate.drain`` calls this after the
        serve-side handoff).  Zero lanes → the empty list, no files."""
        self._drain_ev.set()
        paths = []
        for handle in self.campaigns():
            handle.wait(timeout_s)
            if handle.outcome == "handoff":
                paths.append(handle.handoff_path)
        return paths

    def _campaign_main(self, handle: CampaignHandle) -> None:
        spec = handle.spec
        try:
            self._ledger({
                "ev": "admit",
                "campaign": spec.campaign,
                "doc": spec.to_doc(),
                "template": handle.template,
            })
            result = self._campaign_lane(handle)
        except migrate.DrainStop as stop:
            if self._killed.is_set():
                # SIGKILL simulation: die mid-lane, write NOTHING more.
                handle.outcome = "abandoned"
            else:
                self._write_handoff(handle, stop)
        except Exception as e:
            handle.error = e
            handle.outcome = "error"
            obs.instant(
                "fleet_campaign_error", replica=self.name,
                campaign=spec.campaign, error=type(e).__name__,
            )
        else:
            handle.result = result
            handle.outcome = "completed"
            self._ledger({"ev": "done", "campaign": spec.campaign})
        finally:
            handle._event.set()

    def _campaign_lane(self, handle: CampaignHandle) -> dict:
        # The ONLY jax-reaching frame in the fleet tier (BA301 seam):
        # rebuild the campaign from its seed-doc and run it supervised,
        # checkpointing into the shared family.  The checkpoint hook
        # fires AFTER carry + rows sidecar are durable — the safe
        # drain point.
        spec = handle.spec
        import jax.random as jr

        from ba_tpu.parallel import make_sweep_state
        from ba_tpu.runtime.supervisor import (
            SupervisorConfig,
            supervised_sweep,
        )

        key = jr.key(spec.seed)
        state = make_sweep_state(
            jr.key(spec.state_seed), spec.batch, spec.capacity
        )
        scenario = None
        rounds = spec.rounds
        if spec.scenario is not None:
            from ba_tpu.scenario import compile_scenario, from_dict

            scenario = compile_scenario(
                from_dict(dict(spec.scenario)), spec.batch,
                spec.capacity, sparse=True,
            )
            rounds = None

        def hook(round_cursor, path):
            if handle.fingerprint is None:
                try:
                    handle.fingerprint = _snapshot.validate_carry_checkpoint(
                        path
                    ).get("campaign_sha256")
                except (OSError, ValueError):
                    pass
            self._ledger({
                "ev": "checkpoint",
                "campaign": spec.campaign,
                "round": int(round_cursor),
                "path": path,
                "fingerprint": handle.fingerprint,
            })
            if self._drain_ev.is_set() or self._killed.is_set():
                raise migrate.DrainStop(int(round_cursor), path)

        return supervised_sweep(
            key,
            state,
            rounds,
            scenario=scenario,
            rounds_per_dispatch=spec.rounds_per_dispatch,
            collect_decisions=True,
            checkpoint_every=spec.checkpoint_every,
            checkpoint_path=handle.template,
            on_checkpoint=hook,
            config=SupervisorConfig(timeout_s=60.0),
        )

    def _write_handoff(self, handle: CampaignHandle,
                       stop: migrate.DrainStop) -> None:
        spec = handle.spec
        try:
            meta = _snapshot.validate_carry_checkpoint(stop.path)
        except (OSError, ValueError):
            meta = {}
        path = os.path.join(handle.directory, "handoff.json")
        migrate.write_handoff(
            path,
            campaign=spec.campaign,
            doc=spec.to_doc(),
            template=handle.template,
            round_cursor=stop.round_cursor,
            rounds=spec.rounds,
            checkpoint=stop.path,
            fingerprint=meta.get("campaign_sha256"),
            signed=bool(meta.get("signed")),
            from_replica=self.name,
            run_id=meta.get("run_id"),
            traceparent=meta.get("traceparent"),
        )
        self._ledger({
            "ev": "handoff", "campaign": spec.campaign, "path": path,
        })
        migrate._emit_migration(
            "handoff", spec.campaign, self.name,
            round=stop.round_cursor, path=path,
            run_id=meta.get("run_id"),
        )
        handle.handoff_path = path
        handle.outcome = "handoff"


class ReplicaManager:
    """Owns the replica roster: boot (thread-per-replica, overlapped
    warmups), name allocation, lookup, drain-to-survivor, kill, stop.
    The router reads ``ready()`` for ring membership."""

    def __init__(
        self,
        config: FleetConfig | None = None,
        serve_config=None,
        fault_plans: dict | None = None,
    ):
        self.config = config or FleetConfig.from_env()
        self.serve_config = serve_config
        self._fault_plans = dict(fault_plans or {})
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._counter = 0
        self.run_id = obs.flight.derive_run_id(
            "fleet", self.config.replicas, self.config.vnodes,
            self.config.root or "",
        )

    def _new_replica(self) -> Replica:
        with self._lock:
            name = f"replica-{self._counter}"
            self._counter += 1
        rep = Replica(
            name,
            config=self.config,
            serve_config=self.serve_config,
            fault_plan=self._fault_plans.get(name),
            run_id=self.run_id,
        )
        with self._lock:
            self._replicas[name] = rep
        return rep

    def start(self, n: int | None = None,
              warm_timeout_s: float | None = None) -> list:
        """Boot ``n`` (default: the configured count) replicas with
        OVERLAPPED warm barriers (the executable cache is shared, so
        follower replicas load what the first one compiled)."""
        n = self.config.replicas if n is None else n
        reps = [self._new_replica() for _ in range(n)]
        errors: list = []

        def boot(rep):
            try:
                rep.start(warm_timeout_s)
            except Exception as e:
                errors.append((rep.name, e))

        threads = [
            threading.Thread(
                target=boot, args=(r,), name=f"ba-fleet-boot-{r.name}",
                daemon=True,
            )
            for r in reps
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            name, err = errors[0]
            raise serve_mod.ServeError(
                f"replica {name} failed to boot: {err}"
            ) from err
        return reps

    def start_replica(self,
                      warm_timeout_s: float | None = None) -> Replica:
        return self._new_replica().start(warm_timeout_s)

    def get(self, name: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(name)

    def all(self) -> list:
        with self._lock:
            return list(self._replicas.values())

    def ready(self) -> list:
        return [r for r in self.all() if r.ready()]

    def drain(self, name: str, target: str | None = None,
              timeout_s: float | None = None) -> list:
        """Serve-drain ``name`` and resume each handed-off campaign on
        ``target`` (default: the first OTHER ready replica).  Returns
        the adopted campaign handles ([] for a zero-campaign drain —
        the strict no-op edge)."""
        rep = self.get(name)
        if rep is None:
            raise KeyError(f"no replica {name!r}")
        paths = migrate.drain(rep, timeout_s=timeout_s)
        if not paths:
            return []
        if target is not None:
            dst = self.get(target)
        else:
            dst = next(
                (r for r in self.ready() if r.name != name), None
            )
        if dst is None:
            raise serve_mod.ServeError(
                f"drained {name} with {len(paths)} in-flight "
                f"campaign(s) but no ready replica can adopt them"
            )
        return [migrate.resume_handoff(p, dst) for p in paths]

    def kill(self, name: str) -> None:
        rep = self.get(name)
        if rep is None:
            raise KeyError(f"no replica {name!r}")
        rep.kill()

    def adopt_orphans(self, dead: str, target: str | None = None) -> list:
        """Recover a killed replica's campaigns onto ``target`` (the
        fingerprint-verified path — ``migrate.adopt_orphans``)."""
        if self.config.root is None:
            return []
        if target is not None:
            dst = self.get(target)
        else:
            dst = next((r for r in self.ready() if r.name != dead), None)
        if dst is None:
            raise serve_mod.ServeError(
                f"no ready replica to adopt {dead}'s orphans"
            )
        return migrate.adopt_orphans(self.config.root, dead, dst)

    def stop(self, timeout: float | None = None) -> None:
        for rep in self.all():
            if rep.state in ("stopped", "dead"):
                continue
            rep.stop(timeout)
