// Native CPU Ed25519 (RFC 8032) + SHA-512: the framework's C++ fallback
// path for host-side signing/verification at batch scale.
//
// Role in the framework (see SURVEY.md section 2): the TPU-native
// "native code" axis is the Pallas kernel set (ba_tpu/ops); this module is
// the *CPU* native path — batched commander signing for the signed SM(m)
// sweeps (ba_tpu/crypto/signed.py) without per-call Python overhead, and a
// third independent verifier for differential testing against the Python
// oracle (ba_tpu/crypto/oracle.py) and the batched device kernels.
//
// Every magic constant (SHA-512 round constants, curve constants, base
// point, group order and its fold constants) is generated into
// constants.h by ba_tpu/native/__init__.py FROM the Python oracle — the
// ground truth the test suite pins against RFC 8032 vectors — so nothing
// here is hand-transcribed.
//
// Field arithmetic: GF(2^255-19) as 5 x 51-bit limbs in u64 with
// unsigned __int128 products (the classic "donna" radix). Scalar (mod L)
// arithmetic: base-256 limb folds, a direct port of the proven fold plan
// in ba_tpu/crypto/scalar.py (2^256 === -16*delta, then one exact 2^252
// fold).  Points: extended twisted-Edwards (X:Y:Z:T), the same complete
// a=-1 addition law as the device path (ba_tpu/crypto/ed25519.py).
//
// NOT constant-time: this is a throughput/testing path for public data
// (commander signatures are public protocol messages), not a secret-key
// hygiene library.

#include <stdint.h>
#include <stddef.h>
#include <stdlib.h>
#include <string.h>

#include "constants.h"

typedef uint8_t u8;
typedef uint64_t u64;
typedef int64_t i64;
typedef unsigned __int128 u128;

// ---------------------------------------------------------------- SHA-512

typedef struct {
    u64 h[8];
    u8 buf[128];
    u64 len;  // total bytes
} sha512_ctx;

static inline u64 rotr64(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

static void sha512_init(sha512_ctx* c) {
    for (int i = 0; i < 8; i++) c->h[i] = SHA512_H0[i];
    c->len = 0;
}

static void sha512_block(sha512_ctx* c, const u8* p) {
    u64 w[80];
    for (int i = 0; i < 16; i++) {
        w[i] = 0;
        for (int j = 0; j < 8; j++) w[i] = (w[i] << 8) | p[i * 8 + j];
    }
    for (int i = 16; i < 80; i++) {
        u64 s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
        u64 s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = c->h[0], b = c->h[1], d = c->h[3], e = c->h[4];
    u64 cc = c->h[2], f = c->h[5], g = c->h[6], h = c->h[7];
    for (int i = 0; i < 80; i++) {
        u64 S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        u64 ch = (e & f) ^ (~e & g);
        u64 t1 = h + S1 + ch + SHA512_K[i] + w[i];
        u64 S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        u64 mj = (a & b) ^ (a & cc) ^ (b & cc);
        u64 t2 = S0 + mj;
        h = g; g = f; f = e; e = d + t1;
        d = cc; cc = b; b = a; a = t1 + t2;
    }
    c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
    c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void sha512_update(sha512_ctx* c, const u8* p, size_t n) {
    size_t fill = (size_t)(c->len & 127);
    c->len += n;
    if (fill) {
        size_t take = 128 - fill;
        if (take > n) take = n;
        memcpy(c->buf + fill, p, take);
        p += take; n -= take; fill += take;
        if (fill < 128) return;
        sha512_block(c, c->buf);
    }
    while (n >= 128) { sha512_block(c, p); p += 128; n -= 128; }
    if (n) memcpy(c->buf, p, n);
}

static void sha512_final(sha512_ctx* c, u8 out[64]) {
    u64 bits_hi = c->len >> 61, bits_lo = c->len << 3;
    size_t fill = (size_t)(c->len & 127);
    u8 pad[256];
    memset(pad, 0, sizeof pad);
    pad[0] = 0x80;
    size_t padlen = ((fill < 112) ? 112 : 240) - fill;
    for (int i = 0; i < 8; i++) {
        pad[padlen + i] = (u8)(bits_hi >> (56 - 8 * i));
        pad[padlen + 8 + i] = (u8)(bits_lo >> (56 - 8 * i));
    }
    sha512_update(c, pad, padlen + 16);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++) out[i * 8 + j] = (u8)(c->h[i] >> (56 - 8 * j));
}

static void sha512_3(u8 out[64], const u8* a, size_t an, const u8* b,
                     size_t bn, const u8* m, size_t mn) {
    sha512_ctx c;
    sha512_init(&c);
    if (an) sha512_update(&c, a, an);
    if (bn) sha512_update(&c, b, bn);
    if (mn) sha512_update(&c, m, mn);
    sha512_final(&c, out);
}

// ------------------------------------------------- GF(2^255-19), 5x51 bits

#define MASK51 ((1ULL << 51) - 1)

typedef struct { u64 v[5]; } fe;

static void fe_frombytes(fe* h, const u8 s[32]) {
    u64 w[4];
    for (int i = 0; i < 4; i++) {
        w[i] = 0;
        for (int j = 7; j >= 0; j--) w[i] = (w[i] << 8) | s[i * 8 + j];
    }
    h->v[0] = w[0] & MASK51;
    h->v[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
    h->v[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
    h->v[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
    h->v[4] = (w[3] >> 12) & MASK51;  // top bit dropped (callers mask)
}

static void fe_carry(fe* h) {
    u64* v = h->v;
    for (int pass = 0; pass < 2; pass++) {
        for (int i = 0; i < 4; i++) {
            v[i + 1] += v[i] >> 51;
            v[i] &= MASK51;
        }
        u64 c = v[4] >> 51;
        v[4] &= MASK51;
        v[0] += 19 * c;
    }
}

// Canonical little-endian bytes; input limbs < 2^52.
static void fe_tobytes(u8 s[32], const fe* f) {
    fe t = *f;
    fe_carry(&t);
    // Conditionally subtract p (at most twice: value < 2p + eps).
    for (int rep = 0; rep < 2; rep++) {
        i64 b[5];
        b[0] = (i64)t.v[0] - (i64)(MASK51 - 18);  // p0 = 2^51 - 19
        for (int i = 1; i < 5; i++) b[i] = (i64)t.v[i] - (i64)MASK51;
        for (int i = 0; i < 4; i++) {
            i64 borrow = b[i] >> 51;  // arithmetic: 0 or -1
            b[i] -= borrow << 51;
            b[i + 1] += borrow;
        }
        if (b[4] >= 0) for (int i = 0; i < 5; i++) t.v[i] = (u64)b[i];
    }
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    u64 w[4] = {w0, w1, w2, w3};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++) s[i * 8 + j] = (u8)(w[i] >> (8 * j));
}

static void fe_add(fe* h, const fe* f, const fe* g) {
    for (int i = 0; i < 5; i++) h->v[i] = f->v[i] + g->v[i];
}

// h = f - g, offset by 2p to stay nonnegative; limbs < 2^53.
static void fe_sub(fe* h, const fe* f, const fe* g) {
    h->v[0] = f->v[0] + 0xFFFFFFFFFFFDAULL - g->v[0];
    for (int i = 1; i < 5; i++)
        h->v[i] = f->v[i] + 0xFFFFFFFFFFFFEULL - g->v[i];
}

// Inputs: limbs < 2^54.  Output: carried, limbs < 2^52.
static void fe_mul(fe* h, const fe* f, const fe* g) {
    const u64 *a = f->v, *b = g->v;
    u64 b19_1 = 19 * b[1], b19_2 = 19 * b[2], b19_3 = 19 * b[3], b19_4 = 19 * b[4];
    u128 t0 = (u128)a[0] * b[0] + (u128)a[1] * b19_4 + (u128)a[2] * b19_3
            + (u128)a[3] * b19_2 + (u128)a[4] * b19_1;
    u128 t1 = (u128)a[0] * b[1] + (u128)a[1] * b[0] + (u128)a[2] * b19_4
            + (u128)a[3] * b19_3 + (u128)a[4] * b19_2;
    u128 t2 = (u128)a[0] * b[2] + (u128)a[1] * b[1] + (u128)a[2] * b[0]
            + (u128)a[3] * b19_4 + (u128)a[4] * b19_3;
    u128 t3 = (u128)a[0] * b[3] + (u128)a[1] * b[2] + (u128)a[2] * b[1]
            + (u128)a[3] * b[0] + (u128)a[4] * b19_4;
    u128 t4 = (u128)a[0] * b[4] + (u128)a[1] * b[3] + (u128)a[2] * b[2]
            + (u128)a[3] * b[1] + (u128)a[4] * b[0];
    u64 r0, r1, r2, r3, r4, c;
    r0 = (u64)t0 & MASK51; t1 += (u64)(t0 >> 51);
    r1 = (u64)t1 & MASK51; t2 += (u64)(t1 >> 51);
    r2 = (u64)t2 & MASK51; t3 += (u64)(t2 >> 51);
    r3 = (u64)t3 & MASK51; t4 += (u64)(t3 >> 51);
    r4 = (u64)t4 & MASK51; c = (u64)(t4 >> 51);
    r0 += 19 * c; c = r0 >> 51; r0 &= MASK51; r1 += c;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

static void fe_sq(fe* h, const fe* f) { fe_mul(h, f, f); }

static void fe_1(fe* h) { memset(h, 0, sizeof *h); h->v[0] = 1; }
static void fe_0(fe* h) { memset(h, 0, sizeof *h); }

// f ** e for a little-endian byte exponent (square-and-multiply, LSB-first).
static void fe_pow(fe* h, const fe* f, const u8* e, int nbytes) {
    fe result, base = *f;
    fe_1(&result);
    for (int i = 0; i < nbytes; i++) {
        for (int bit = 0; bit < 8; bit++) {
            if ((e[i] >> bit) & 1) fe_mul(&result, &result, &base);
            fe_sq(&base, &base);
        }
    }
    *h = result;
}

static void fe_inv(fe* h, const fe* f) { fe_pow(h, f, PM2_BYTES, 32); }

static int fe_eq(const fe* f, const fe* g) {
    u8 a[32], b[32];
    fe_tobytes(a, f);
    fe_tobytes(b, g);
    return memcmp(a, b, 32) == 0;
}

static int fe_iszero(const fe* f) {
    static const u8 zero[32] = {0};
    u8 a[32];
    fe_tobytes(a, f);
    return memcmp(a, zero, 32) == 0;
}

// --------------------------------------------- points (extended, a = -1)

typedef struct { fe x, y, z, t; } ge;

static fe FE_D, FE_D2, FE_SQRTM1, FE_BX, FE_BY;
static int CONSTS_READY = 0;

static void ge_identity(ge* p) {
    fe_0(&p->x); fe_1(&p->y); fe_1(&p->z); fe_0(&p->t);
}

static void ge_base(ge* p) {
    p->x = FE_BX; p->y = FE_BY; fe_1(&p->z);
    fe_mul(&p->t, &FE_BX, &FE_BY);
}

// Complete unified addition (add-2008-hwcd-3, a=-1) — the device formula.
static void ge_add(ge* r, const ge* p, const ge* q) {
    fe a, b, c, d, e, f, g, h, t1, t2;
    fe_sub(&t1, &p->y, &p->x);
    fe_sub(&t2, &q->y, &q->x);
    fe_mul(&a, &t1, &t2);
    fe_add(&t1, &p->y, &p->x);
    fe_add(&t2, &q->y, &q->x);
    fe_mul(&b, &t1, &t2);
    fe_mul(&c, &p->t, &q->t);
    fe_mul(&c, &c, &FE_D2);
    fe_mul(&d, &p->z, &q->z);
    fe_add(&d, &d, &d);
    fe_carry(&d);
    fe_sub(&e, &b, &a);
    fe_sub(&f, &d, &c);
    fe_add(&g, &d, &c);
    fe_add(&h, &b, &a);
    fe_mul(&r->x, &e, &f);
    fe_mul(&r->y, &g, &h);
    fe_mul(&r->z, &f, &g);
    fe_mul(&r->t, &e, &h);
}

// [k]P, k a little-endian byte scalar (double-and-add, LSB-first).
static void ge_scalarmult(ge* r, const ge* p, const u8* k, int nbytes) {
    ge acc, q = *p;
    ge_identity(&acc);
    for (int i = 0; i < nbytes; i++) {
        for (int bit = 0; bit < 8; bit++) {
            if ((k[i] >> bit) & 1) ge_add(&acc, &acc, &q);
            ge_add(&q, &q, &q);
        }
    }
    *r = acc;
}

// Fixed-base window table: T[w][j] = [j * 256^w]B — byte windows, twice
// the stride of the device path's 4-bit scheme (ba_tpu/crypto/
// ed25519.fixed_base_mult): [k]B is 32 complete additions and no
// doublings.  1.3 MB of table (32 x 256 x 160 B) and an 8k-addition
// one-time init (~the cost of ~130 signs) buy a 2x cut in the per-sign
// point arithmetic — the right trade for a batch signer that signs tens
// of thousands of times per process (the sweep's 2 signs/commander).
static ge BASE_TABLE[32][256];

static void base_table_init(void) {
    ge step;
    ge_base(&step);
    for (int w = 0; w < 32; w++) {
        ge_identity(&BASE_TABLE[w][0]);
        for (int j = 1; j < 256; j++)
            ge_add(&BASE_TABLE[w][j], &BASE_TABLE[w][j - 1], &step);
        ge_add(&step, &BASE_TABLE[w][255], &step);  // 256^(w+1) B
    }
}

// [k]B via the window table; k is 32 little-endian bytes.
static void ge_scalarmult_base(ge* r, const u8 k[32]) {
    ge acc;
    ge_identity(&acc);
    for (int i = 0; i < 32; i++)
        ge_add(&acc, &acc, &BASE_TABLE[i][k[i]]);
    *r = acc;
}

static void consts_init(void) {
    if (CONSTS_READY) return;
    fe_frombytes(&FE_D, D_BYTES);
    fe_frombytes(&FE_D2, D2_BYTES);
    fe_frombytes(&FE_SQRTM1, SQRTM1_BYTES);
    fe_frombytes(&FE_BX, BX_BYTES);
    fe_frombytes(&FE_BY, BY_BYTES);
    base_table_init();
    CONSTS_READY = 1;
}

static void ge_tobytes_with_zi(u8 s[32], const ge* p, const fe* zi) {
    fe x, y;
    fe_mul(&x, &p->x, zi);
    fe_mul(&y, &p->y, zi);
    fe_tobytes(s, &y);
    u8 xb[32];
    fe_tobytes(xb, &x);
    s[31] |= (xb[0] & 1) << 7;
}

static void ge_tobytes(u8 s[32], const ge* p) {
    fe zi;
    fe_inv(&zi, &p->z);
    ge_tobytes_with_zi(s, p, &zi);
}

// Batched point encoding with one shared inversion (Montgomery's trick):
// the per-point fe_inv (~254 squarings) is the dominant cost of encoding
// a fixed-base product on one core — prefix-product the Z's, invert the
// total once, and peel per-point inverses back out (3 muls + 1/chunk of
// an inversion per point).  Chunked so the working set stays in L1 and
// OpenMP can split batches when cores exist.
#define TOBYTES_CHUNK 256

static void ge_tobytes_batch(u8* out, size_t stride, const ge* pts,
                             size_t count) {
#pragma omp parallel for schedule(static)
    for (long c0 = 0; c0 < (long)count; c0 += TOBYTES_CHUNK) {
        size_t n = count - (size_t)c0;
        if (n > TOBYTES_CHUNK) n = TOBYTES_CHUNK;
        const ge* p = pts + c0;
        u8* o = out + stride * (size_t)c0;
        fe pre[TOBYTES_CHUNK];  // pre[i] = z_0 * ... * z_i
        pre[0] = p[0].z;
        for (size_t i = 1; i < n; i++) fe_mul(&pre[i], &pre[i - 1], &p[i].z);
        fe inv;
        fe_inv(&inv, &pre[n - 1]);  // 1/(z_0 ... z_{n-1})
        for (size_t i = n - 1; i > 0; i--) {
            fe zi;
            fe_mul(&zi, &inv, &pre[i - 1]);  // 1/z_i
            ge_tobytes_with_zi(o + stride * i, &p[i], &zi);
            fe_mul(&inv, &inv, &p[i].z);  // drop z_i from the pool
        }
        ge_tobytes_with_zi(o, &p[0], &inv);  // inv == 1/z_0
    }
}

// RFC 8032 5.1.3 decode; returns 0 on invalid encodings.
static int ge_frombytes(ge* p, const u8 s[32]) {
    // y < p (after masking the sign bit)?
    u8 yb[32];
    memcpy(yb, s, 32);
    int sign = yb[31] >> 7;
    yb[31] &= 0x7F;
    for (int i = 31; i >= 0; i--) {
        if (yb[i] < P_BYTES[i]) break;
        if (yb[i] > P_BYTES[i]) return 0;
        if (i == 0) return 0;  // y == p
    }
    fe y, yy, u, v, v3, v7, t, x, vxx, neg;
    fe_frombytes(&y, yb);
    fe one;
    fe_1(&one);
    fe_sq(&yy, &y);
    fe_sub(&u, &yy, &one);
    fe_carry(&u);  // u is a subtrahend below: keep limbs under the 2p offset
    fe_mul(&v, &yy, &FE_D);
    fe_add(&v, &v, &one);
    fe_carry(&v);
    fe_sq(&v3, &v);
    fe_mul(&v3, &v3, &v);
    fe_sq(&v7, &v3);
    fe_mul(&v7, &v7, &v);
    fe_mul(&t, &u, &v7);
    fe_pow(&t, &t, PM5D8_BYTES, 32);
    fe_mul(&x, &u, &v3);
    fe_mul(&x, &x, &t);
    fe_sq(&vxx, &x);
    fe_mul(&vxx, &vxx, &v);
    fe_0(&neg);
    fe_sub(&neg, &neg, &u);
    if (fe_eq(&vxx, &u)) {
        // x is the root
    } else if (fe_eq(&vxx, &neg)) {
        fe_mul(&x, &x, &FE_SQRTM1);
    } else {
        return 0;  // not a square: off-curve
    }
    u8 xb[32];
    fe_tobytes(xb, &x);
    if (fe_iszero(&x) && sign == 1) return 0;  // non-canonical x=0
    if ((xb[0] & 1) != sign) {
        fe_0(&neg);
        fe_sub(&x, &neg, &x);
        fe_carry(&x);
    }
    p->x = x; p->y = y; fe_1(&p->z);
    fe_mul(&p->t, &x, &y);
    return 1;
}

static int ge_eq(const ge* p, const ge* q) {
    fe a, b;
    fe_mul(&a, &p->x, &q->z);
    fe_mul(&b, &q->x, &p->z);
    if (!fe_eq(&a, &b)) return 0;
    fe_mul(&a, &p->y, &q->z);
    fe_mul(&b, &q->y, &p->z);
    return fe_eq(&a, &b);
}

// ------------------------------------------- scalars mod L (base-256 limbs)

// Port of ba_tpu/crypto/scalar.py's fold plan, i64 limbs.
static void sc_fold256(i64* v, int n_in) {
    // v[0:n_in] -> v[0:16+(n_in-32)]: value === lo - hi * C16 (mod L).
    i64 hi[40];
    int nh = n_in - 32;
    for (int i = 0; i < nh; i++) hi[i] = v[32 + i];
    for (int i = 32; i < n_in; i++) v[i] = 0;  // all hi limbs consumed
    for (int j = 0; j < 17; j++) {
        i64 cj = (i64)C16_BYTES[j];
        if (!cj) continue;
        for (int i = 0; i < nh; i++) v[j + i] -= cj * hi[i];
    }
}

// One exact sequential pass: limbs 0..n-2 land in [0, 256); the final
// carry folds into v[n-1], which stays a small SIGNED limb (never
// dropped, so negative values survive the pass exactly).
static void sc_carry(i64* v, int n) {
    i64 c = 0;
    for (int i = 0; i < n - 1; i++) {
        i64 x = v[i] + c;
        c = x >> 8;
        v[i] = x - (c << 8);
    }
    v[n - 1] += c;
}

// in: 64 little-endian bytes -> out: 32 bytes, value mod L.
static void sc_reduce64(u8 out[32], const u8 in[64]) {
    i64 v[64];
    for (int i = 0; i < 64; i++) v[i] = in[i];
    sc_fold256(v, 64);   // touches 0..47; |value| < 2^385
    sc_carry(v, 49);     // limbs 0..47 in [0,256), v[48] small signed
    sc_fold256(v, 49);   // touches 0..32; |value| < 2^260
    sc_carry(v, 34);
    sc_fold256(v, 34);   // touches 0..17; |value| < 2^258 (lo < 2^257)
    // make nonnegative: + 4L > 2^135 covers the worst negative; value
    // lands in (0, 2^257 + 4L) < 2^259.
    for (int i = 0; i < 32; i++) v[i] += 4 * (i64)L_BYTES[i];
    sc_carry(v, 34);     // exact: limbs 0..32 in [0,256), v[33] == 0
    // exact fold at 2^252: value < 2^259 -> v[32] < 8, hi <= 143.
    i64 hi = (v[31] >> 4) + (v[32] << 4) + (v[33] << 12);
    v[31] &= 0xF;
    v[32] = v[33] = 0;
    for (int j = 0; j < 16; j++) v[j] -= hi * (i64)DELTA_BYTES[j];
    // + L once -> value in (0, 2^252 + L) subset (0, 2L); carry, then one
    // conditional subtract of L (second rep is a provable no-op kept for
    // symmetry with fe_tobytes).
    for (int i = 0; i < 32; i++) v[i] += (i64)L_BYTES[i];
    sc_carry(v, 33);
    for (int rep = 0; rep < 2; rep++) {
        i64 b[33], borrow = 0;
        for (int i = 0; i < 33; i++) {
            i64 li = i < 32 ? (i64)L_BYTES[i] : 0;
            i64 x = v[i] - li + borrow;
            borrow = x >> 8;
            b[i] = x - (borrow << 8);
        }
        if (borrow == 0)
            for (int i = 0; i < 33; i++) v[i] = b[i];
    }
    for (int i = 0; i < 32; i++) out[i] = (u8)v[i];
}

// out = (a * b + c) mod L  (a, b, c: 32 little-endian bytes, values < 2^255).
static void sc_muladd(u8 out[32], const u8 a[32], const u8 b[32], const u8 c[32]) {
    i64 v[64];
    for (int i = 0; i < 64; i++) v[i] = 0;
    for (int i = 0; i < 32; i++)
        for (int j = 0; j < 32; j++) v[i + j] += (i64)a[i] * (i64)b[j];
    for (int i = 0; i < 32; i++) v[i] += (i64)c[i];
    // exact sequential carry: value < 2^510 + 2^256 fits 64 limbs
    i64 carry = 0;
    u8 wide[64];
    for (int i = 0; i < 64; i++) {
        i64 x = v[i] + carry;
        carry = x >> 8;
        wide[i] = (u8)(x & 0xFF);
    }
    sc_reduce64(out, wide);
}

// s < L?  (little-endian byte compare)
static int sc_canonical(const u8 s[32]) {
    for (int i = 31; i >= 0; i--) {
        if (s[i] < L_BYTES[i]) return 1;
        if (s[i] > L_BYTES[i]) return 0;
    }
    return 0;  // s == L
}

// ------------------------------------------------------------- public API

extern "C" {

// One-time table/constant setup.  The Python loader calls this exactly
// once, under its own lock, right after dlopen — before any other entry
// point — so the in-library consts_init() calls below are belt-and-braces
// for direct C users, not the synchronization mechanism.
void ba_ed25519_init(void) { consts_init(); }

int ba_ed25519_publickey(const u8 sk[32], u8 pk[32]) {
    consts_init();
    u8 h[64];
    sha512_3(h, sk, 32, NULL, 0, NULL, 0);
    h[0] &= 248; h[31] &= 63; h[31] |= 64;
    ge A;
    ge_scalarmult_base(&A, h);
    ge_tobytes(pk, &A);
    return 1;
}

int ba_ed25519_sign(const u8 sk[32], const u8 pk[32], const u8* msg,
                    size_t msg_len, u8 sig[64]) {
    consts_init();
    u8 h[64], nonce[64], hram[64], r[32], k[32];
    sha512_3(h, sk, 32, NULL, 0, NULL, 0);
    u8 a[32];
    memcpy(a, h, 32);
    a[0] &= 248; a[31] &= 63; a[31] |= 64;
    sha512_3(nonce, h + 32, 32, msg, msg_len, NULL, 0);
    sc_reduce64(r, nonce);
    ge R;
    ge_scalarmult_base(&R, r);
    ge_tobytes(sig, &R);
    sha512_3(hram, sig, 32, pk, 32, msg, msg_len);
    sc_reduce64(k, hram);
    sc_muladd(sig + 32, k, a, r);
    return 1;
}

int ba_ed25519_verify(const u8 pk[32], const u8* msg, size_t msg_len,
                      const u8 sig[64]) {
    consts_init();
    if (!sc_canonical(sig + 32)) return 0;
    ge A, R;
    if (!ge_frombytes(&A, pk)) return 0;
    if (!ge_frombytes(&R, sig)) return 0;
    u8 hram[64], k[32];
    sha512_3(hram, sig, 32, pk, 32, msg, msg_len);
    sc_reduce64(k, hram);
    ge sB, hA, rhs;
    ge_scalarmult_base(&sB, sig + 32);
    ge_scalarmult(&hA, &A, k, 32);
    ge_add(&rhs, &R, &hA);
    return ge_eq(&sB, &rhs);
}

// Batch entry points are phased so every point encoding goes through the
// shared-inversion path (ge_tobytes_batch): compute all the fixed-base
// products first, then encode them together.  Per item that leaves
// 32 window additions + hashes + scalar arithmetic — the inversion that
// dominated the per-call path is amortized to ~nothing.

void ba_ed25519_publickey_batch(const u8* sks, size_t count, u8* pks) {
    consts_init();
    if (count == 0) return;
    ge* A = (ge*)malloc(count * sizeof(ge));
    if (!A) {  // degraded fallback: per-call path, no batch allocation
        for (size_t i = 0; i < count; i++)
            ba_ed25519_publickey(sks + 32 * i, pks + 32 * i);
        return;
    }
#pragma omp parallel for schedule(static)
    for (long i = 0; i < (long)count; i++) {
        u8 h[64];
        sha512_3(h, sks + 32 * i, 32, NULL, 0, NULL, 0);
        h[0] &= 248; h[31] &= 63; h[31] |= 64;
        ge_scalarmult_base(&A[i], h);
    }
    ge_tobytes_batch(pks, 32, A, count);
    free(A);
}

void ba_ed25519_sign_batch(const u8* sks, const u8* pks, const u8* msgs,
                           size_t msg_len, size_t count, u8* sigs) {
    consts_init();
    if (count == 0) return;
    ge* R = (ge*)malloc(count * sizeof(ge));
    u8* ra = (u8*)malloc(count * 64);  // r scalar + clamped a per item
    if (!R || !ra) {
        free(R); free(ra);
        for (size_t i = 0; i < count; i++)
            ba_ed25519_sign(sks + 32 * i, pks + 32 * i, msgs + msg_len * i,
                            msg_len, sigs + 64 * i);
        return;
    }
#pragma omp parallel for schedule(static)
    for (long i = 0; i < (long)count; i++) {
        u8 h[64], nonce[64];
        u8* r = ra + 64 * i;
        u8* a = ra + 64 * i + 32;
        sha512_3(h, sks + 32 * i, 32, NULL, 0, NULL, 0);
        memcpy(a, h, 32);
        a[0] &= 248; a[31] &= 63; a[31] |= 64;
        sha512_3(nonce, h + 32, 32, msgs + msg_len * i, msg_len, NULL, 0);
        sc_reduce64(r, nonce);
        ge_scalarmult_base(&R[i], r);
    }
    ge_tobytes_batch(sigs, 64, R, count);  // R bytes -> sig[0:32]
#pragma omp parallel for schedule(static)
    for (long i = 0; i < (long)count; i++) {
        u8 hram[64], k[32];
        sha512_3(hram, sigs + 64 * i, 32, pks + 32 * i, 32,
                 msgs + msg_len * i, msg_len);
        sc_reduce64(k, hram);
        sc_muladd(sigs + 64 * i + 32, k, ra + 64 * i + 32, ra + 64 * i);
    }
    free(R);
    free(ra);
}

void ba_ed25519_verify_batch(const u8* pks, const u8* msgs, size_t msg_len,
                             size_t count, const u8* sigs, u8* oks) {
    consts_init();
#pragma omp parallel for schedule(static)
    for (long i = 0; i < (long)count; i++)
        oks[i] = (u8)ba_ed25519_verify(pks + 32 * i, msgs + msg_len * i,
                                       msg_len, sigs + 64 * i);
}

void ba_sha512(const u8* msg, size_t len, u8 out[64]) {
    sha512_3(out, msg, len, NULL, 0, NULL, 0);
}

}  // extern "C"
