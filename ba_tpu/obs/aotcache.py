"""Persistent compiled-executable cache: AOT specializations the
serving path can dispatch without ever compiling on a request
(ISSUE 11 tentpole).

The persistent XLA cache (``utils/platform.enable_compilation_cache``)
already turns repeat compiles into disk reads — but a disk read still
happens INSIDE the first dispatch of each specialization, on whatever
thread issued it.  For a service that is the request path:
``BENCH_serving_r11.json`` reads p50 22 ms / p99 1.27 s because
first-window compiles land on request latency.  This module is the next
step: whole ``jax.stages.Compiled`` executables, AOT lower+compiled OFF
the request path (``runtime/warmup.py``'s background pass) and persisted
NEXT TO the XLA cache, so a warm process — or a warm fleet sharing the
directory — dispatches every bucketed specialization without paying even
the deserialize inside a request.

Cache entries are keyed by the SAME named-axes compile signature the
recompile explainer and the cross-run ledger speak
(``obs/instrument.py``): ``fn`` + axes dict + jax/jaxlib versions +
backend.  The filename tag hashes only (fn, axes) — the env components
live in the entry HEADER and are verified on every load, so a
stale-toolchain entry is an OBSERVABLE eager invalidation (counted,
entry removed, fresh compile) rather than a silent never-hit.  The
degradation ladder, in order:

- **signature mismatch** (jax/jaxlib/backend/axes drift): the entry is
  invalidated eagerly — removed, counted, ``None`` returned; the caller
  falls back to a fresh compile.  A stale executable must never load,
  and a load that would misexecute is structurally impossible because
  the comparison covers every key component.
- **corrupt entry** (bad magic/header/pickle, or a deserialize the
  backend refuses): quarantined to ``<entry>.corrupt`` (the
  ``utils/snapshot.py`` discipline — bytes kept for post-mortem, the
  family never trips on it twice), counted, fresh compile.
- **plain miss**: fresh compile (the engine's jit path — compile-on-miss
  always works; the serving dispatcher counts it as a request-path
  compile).

DONATION CONTRACT ON LOADS: a deserialized executable preserves the
program's input/output aliasing — the donated carry is consumed exactly
as by the jit path (pinned by test).  But its ``memory_analysis()`` is
EMPTY, the same persistent-cache-hit trap ``obs/xla.py``'s
``_compile_uncached`` documents — which is why :meth:`ExecutableCache
.ensure` compiles through ``_compile_uncached`` (real memory stats),
harvests the cost/memory analyses ONCE, and stores them in the entry
header: ``alias_bytes`` in the header is the donation-regression
evidence for every future process that loads the entry, and
``obs/xla.introspect`` reuses these recorded analyses instead of paying
its own second uncached compile (the ISSUE 11 dedupe).

This module is an obs module: HOST-TIER by the ba-lint BA301 contract —
it never imports ``ba_tpu.core``/``ba_tpu.ops`` (even lazily) and
imports jax only inside function bodies.  Specialization BUILDERS (axes
-> abstract args, which need the jitted trees) therefore live in
``parallel/pipeline.py`` (``AOT_SPECS``) and are passed IN as callables.

``BA_TPU_AOT_CACHE`` overrides the directory (``0`` disables
persistence — the cache still memoizes in-process); the default sits
next to the persistent XLA cache at ``~/.cache/ba_tpu/aot``.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import threading
import time

# ONE spelling of the CompiledMemoryStats attr -> record-field mapping
# (obs/xla.py owns it; its module level is stdlib-only, so this import
# stays jax-free): a memory field added there lands in entry headers —
# and thus in introspect's dedupe branch — without a drift hazard.
from ba_tpu.obs.xla import _MEMORY_FIELDS

CACHE_ENV = "BA_TPU_AOT_CACHE"
ENTRY_FORMAT = "ba_tpu.aot_executable"
ENTRY_VERSION = 1
_MAGIC = b"BAAOT1\n"

# Fields harvested from the FRESH compile's analyses into every entry
# header (and the in-process analyses registry below) — the same set
# obs/xla.introspect records, so the dedupe path emits identical shapes.
ANALYSIS_FIELDS = ("flops", "bytes_accessed") + tuple(
    field for _attr, field in _MEMORY_FIELDS
)

# In-process analyses registry: (fn, frozen axes) -> {field: number}.
# Written by every ExecutableCache on ensure()/load; read by
# obs/xla.introspect so a signature the aotcache already compiled (with
# REAL memory stats) never pays introspection's second uncached compile.
_analyses_lock = threading.Lock()
_analyses: dict = {}


def cache_dir() -> str | None:
    """The entry directory: ``BA_TPU_AOT_CACHE`` (``0`` disables), else
    ``~/.cache/ba_tpu/aot`` — next to the persistent XLA cache's default
    so one cache hygiene policy covers both."""
    env = os.environ.get(CACHE_ENV, "")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "ba_tpu", "aot")


def env_signature() -> dict:
    """The process-constant key components: a serialized executable is
    only valid under the exact toolchain + backend — AND ba_tpu release
    — that produced it (a package upgrade may change a megastep's
    traced computation under unchanged axes names; without the version
    component a stale executable would load and silently diverge from
    the jit path, the one failure the bit-exactness contract cannot
    tolerate.  Development edits between releases share a version
    string — clear ``BA_TPU_AOT_CACHE`` or the cache dir when editing
    megastep semantics in place)."""
    import jax

    import ba_tpu

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:  # pragma: no cover - jax without jaxlib
        jaxlib_version = "unknown"
    return {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
        "backend": jax.default_backend(),
        "ba_tpu_version": getattr(ba_tpu, "__version__", "unknown"),
    }


def full_signature(fn: str, axes: dict, env: dict | None = None) -> dict:
    """The complete entry key: fn + named axes + env components — the
    ledger's compile signature extended with the backend."""
    sig = {"fn": fn}
    sig.update(axes)
    sig.update(env if env is not None else env_signature())
    return sig


def _axes_tag(fn: str, axes: dict) -> str:
    blob = json.dumps({"fn": fn, "axes": axes}, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def entry_path(directory: str, fn: str, axes: dict) -> str:
    """One stable filename per (fn, axes).  Env components are NOT in
    the tag on purpose: a toolchain bump must surface as an observable
    header-mismatch invalidation at load, not a silent never-hit that
    strands stale entries forever."""
    return os.path.join(directory, f"{fn}-{_axes_tag(fn, axes)}.aot")


def _freeze(axes: dict):
    from ba_tpu.obs.instrument import _freeze as freeze

    return freeze(axes)


def _jsonable(sig: dict) -> dict:
    """The signature as it reads back from a JSON header — comparisons
    must happen in this form or a tuple-vs-list difference would read as
    a spurious invalidation."""
    return json.loads(json.dumps(sig, sort_keys=True, default=str))


def record_analyses(fn: str, axes: dict, fields: dict) -> None:
    with _analyses_lock:
        _analyses[(fn, _freeze(axes))] = {
            f: fields[f] for f in ANALYSIS_FIELDS if f in fields
        }


def recorded_analyses(fn: str, axes: dict) -> dict | None:
    """The cost/memory analyses an ExecutableCache harvested for this
    signature (fresh compile's real stats — possibly in a previous
    process, read back from the entry header), or None.  The
    ``obs/xla.introspect`` dedupe source."""
    with _analyses_lock:
        got = _analyses.get((fn, _freeze(axes)))
        return dict(got) if got is not None else None


def reset_recorded_analyses() -> None:
    """Test hook: forget every harvested analysis."""
    with _analyses_lock:
        _analyses.clear()


class ExecutableCache:
    """Thread-safe executable cache: in-process memo over a persistent
    entry directory (``directory=None`` resolves :func:`cache_dir`; a
    disabled directory keeps the memo, drops persistence).

    - :meth:`get` — the ENGINE's request-path lookup: memo, then disk.
      Never compiles; a miss returns None and the engine's jit path
      compiles as it always did (counted by the serving dispatcher).
    - :meth:`ensure` — the WARMUP path: memo, then disk, then a fresh
      AOT compile through ``obs/xla._compile_uncached`` (real memory
      stats — the persistent-cache-hit trap), persisted.

    Both store a cross-run LEDGER row at acquisition
    (``obs.instrument.note_ledger``) so the signature joins the next
    process's warmup replay set — but deliberately NOT a jit
    first-call mark: an AOT compile never populates jit's executable
    cache, and a marked-but-jit-cold signature would later read as a
    cached ``dispatch`` while paying a real, uncounted request-path
    compile.  Warm dispatches skip the classifier entirely (the engine
    spans them ``dispatch`` with ``warm=True``); cache-less jit
    dispatches classify exactly as before.
    """

    def __init__(self, directory: str | None = None):
        self.directory = cache_dir() if directory is None else (
            directory or None
        )
        self._lock = threading.Lock()
        self._mem: dict = {}   # key -> compiled callable
        self._meta: dict = {}  # key -> entry header dict
        # Negative memo: signatures a get() already probed the disk for
        # and found nothing.  Without it, every dispatch window of an
        # unwarmed signature would re-stat the entry file on the
        # REQUEST path (the engine consults get() before each
        # dispatch).  ensure() clears the mark, so a warmup completing
        # mid-run becomes visible; an entry another PROCESS writes
        # after our first probe stays invisible until restart —
        # documented, and cheaper than per-dispatch I/O.
        self._absent: set = set()
        self._env: dict | None = None  # lazy: env_signature() needs jax
        self.counts = {
            "compiles": 0,     # fresh AOT compiles this process
            "loads": 0,        # disk entries deserialized
            "memo_hits": 0,
            "misses": 0,       # get() found nothing anywhere
            "invalidated": 0,  # eager signature-mismatch rejections
            "corrupt": 0,      # quarantined entries
            "evicted": 0,      # call-time failures dropped from memo
            "store_errors": 0,
        }

    def enabled(self) -> bool:
        return self.directory is not None

    def _key(self, fn: str, axes: dict):
        return (fn, _freeze(axes))

    def _env_sig(self) -> dict:
        if self._env is None:
            self._env = env_signature()
        return self._env

    def _note_ledger(self, fn: str, axes: dict) -> None:
        # Ledger row ONLY — never the jit first-call classifier: an AOT
        # compile does not populate jit's executable cache, so marking
        # the signature `seen` would make a later cache-less jit
        # dispatch read as a cached `dispatch` while paying a real,
        # uncounted request-path compile.  (The engine's warm dispatches
        # skip the classifier entirely — pipeline._dispatch_span.)
        from ba_tpu.obs import instrument

        instrument.note_ledger(fn, dict(axes))

    # -- request-path lookup -------------------------------------------------

    def get(self, fn: str, axes: dict):
        """The dispatcher's pre-dispatch consult: a warm executable for
        this exact signature, or None (never compiles)."""
        key = self._key(fn, axes)
        with self._lock:
            exe = self._mem.get(key)
            if exe is not None:
                self.counts["memo_hits"] += 1
                return exe
            if not self.enabled() or key in self._absent:
                self.counts["misses"] += 1
                return None
        loaded = self._load(fn, axes)
        if loaded is None:
            with self._lock:
                self._absent.add(key)
                self.counts["misses"] += 1
            return None
        exe, header = loaded
        with self._lock:
            self._mem[key] = exe
            self._meta[key] = header
            self.counts["loads"] += 1
        record_analyses(fn, axes, header)
        self._note_ledger(fn, axes)
        return exe

    def evict(self, fn: str, axes: dict) -> None:
        """Drop a signature whose memoized executable failed at CALL
        time (the engine's warm-dispatch fallback): forget the memo,
        quarantine the disk entry (it deserialized but cannot run —
        keep the bytes for post-mortem, never trip on them again), and
        negative-mark so later lookups skip straight to the jit path."""
        key = self._key(fn, axes)
        with self._lock:
            self._mem.pop(key, None)
            self._meta.pop(key, None)
            self._absent.add(key)
            self.counts["evicted"] += 1
        if self.enabled():
            path = entry_path(self.directory, fn, axes)
            if os.path.exists(path):
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass

    # -- warmup path ---------------------------------------------------------

    def ensure(self, fn: str, axes: dict, builder) -> dict:
        """Make this signature warm: memo -> disk load -> fresh AOT
        compile (+persist).  ``builder(axes)`` returns ``(jitted, args,
        kwargs)`` with abstract (ShapeDtypeStruct) array arguments —
        ``parallel.pipeline.AOT_SPECS`` provides them.  Returns a status
        dict (``status`` in ``cached``/``loaded``/``compiled``, plus
        ``wall_s`` and — for fresh compiles — ``alias_bytes``).
        Exceptions propagate: the warmup runner counts and continues.
        """
        key = self._key(fn, axes)
        with self._lock:
            if key in self._mem:
                return {"status": "cached", "wall_s": 0.0}
        t0 = time.perf_counter()
        if self.enabled():
            loaded = self._load(fn, axes)
            if loaded is not None:
                exe, header = loaded
                with self._lock:
                    self._mem[key] = exe
                    self._meta[key] = header
                    self.counts["loads"] += 1
                record_analyses(fn, axes, header)
                self._note_ledger(fn, axes)
                return {
                    "status": "loaded",
                    "wall_s": round(time.perf_counter() - t0, 6),
                }
        exe, header = self._compile(fn, axes, builder)
        with self._lock:
            self._mem[key] = exe
            self._meta[key] = header
            self._absent.discard(key)
            self.counts["compiles"] += 1
        record_analyses(fn, axes, header)
        self._note_ledger(fn, axes)
        return {
            "status": "compiled",
            "wall_s": round(time.perf_counter() - t0, 6),
            "alias_bytes": header.get("alias_bytes", 0),
        }

    def _compile(self, fn: str, axes: dict, builder):
        # _compile_uncached, not plain .compile(): a persistent-XLA-cache
        # HIT would hand back an executable with EMPTY memory stats, and
        # the alias_bytes evidence stored below would silently read
        # "donation broken" forever (the obs/xla.py trap, documented at
        # its _compile_uncached).
        from ba_tpu.obs.xla import _compile_uncached, _scalar

        jitted, args, kwargs = builder(dict(axes))
        lowered = jitted.lower(*args, **kwargs)
        compiled = _compile_uncached(lowered)
        try:
            cost = compiled.cost_analysis()
        except Exception:  # some backends only analyze pre-compile
            cost = lowered.cost_analysis()
        try:
            mem = compiled.memory_analysis()
        except Exception:  # pragma: no cover - backend without stats
            mem = None
        header = {
            "format": ENTRY_FORMAT,
            "v": ENTRY_VERSION,
            "fn": fn,
            "axes": dict(axes),
            "signature": _jsonable(
                full_signature(fn, axes, env=self._env_sig())
            ),
            "flops": _scalar(cost, "flops"),
            "bytes_accessed": _scalar(cost, "bytes accessed"),
        }
        for attr, field in _MEMORY_FIELDS:
            header[field] = int(getattr(mem, attr, 0)) if mem is not None else 0
        if self.enabled():
            self._store(fn, axes, compiled, header)
        return compiled, header

    # -- disk entries --------------------------------------------------------

    def _store(self, fn: str, axes: dict, compiled, header: dict) -> None:
        from jax.experimental.serialize_executable import serialize

        try:
            payload = pickle.dumps(serialize(compiled))
        except (ValueError, TypeError, pickle.PicklingError):
            # A backend whose executables do not serialize: the memo
            # still serves this process; persistence silently degrades.
            with self._lock:
                self.counts["store_errors"] += 1
            return
        head = json.dumps(header, sort_keys=True, default=str).encode()
        path = entry_path(self.directory, fn, axes)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(_MAGIC)
                fh.write(struct.pack(">I", len(head)))
                fh.write(head)
                fh.write(payload)
            os.replace(tmp, path)
        except OSError:
            with self._lock:
                self.counts["store_errors"] += 1
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _load(self, fn: str, axes: dict):
        """One disk entry -> (executable, header), or None through the
        documented degradation ladder (module docstring): mismatch
        invalidates eagerly, corruption quarantines, absence is a plain
        miss — a load NEVER raises into the caller."""
        path = entry_path(self.directory, fn, axes)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            if not data.startswith(_MAGIC):
                raise ValueError("bad magic")
            off = len(_MAGIC)
            (hlen,) = struct.unpack(">I", data[off:off + 4])
            header = json.loads(data[off + 4:off + 4 + hlen])
            payload = data[off + 4 + hlen:]
            if (
                header.get("format") != ENTRY_FORMAT
                or header.get("v") != ENTRY_VERSION
                or not isinstance(header.get("signature"), dict)
            ):
                raise ValueError("bad header")
        except (OSError, ValueError, struct.error):
            self._quarantine(path)
            return None
        # EAGER invalidation on ANY key-component mismatch: axes (a
        # hash-collision guard), jax/jaxlib versions, backend.  A stale
        # entry must fall back to a fresh compile — never deserialize
        # under a toolchain it was not built for.
        want = _jsonable(full_signature(fn, axes, env=self._env_sig()))
        if header["signature"] != want:
            with self._lock:
                self.counts["invalidated"] += 1
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            serialized, in_tree, out_tree = pickle.loads(payload)
            exe = deserialize_and_load(serialized, in_tree, out_tree)
        except Exception:
            # Bad pickle bytes OR a backend refusing the deserialize:
            # either way the entry is unusable — quarantine + fresh
            # compile, never a crash on the warm path.
            self._quarantine(path)
            return None
        return exe, header

    def _quarantine(self, path: str) -> None:
        with self._lock:
            self.counts["corrupt"] += 1
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
