"""Metrics registry: typed counters, gauges, and log-bucketed histograms.

The aggregation layer between the raw JSONL sink (``utils/metrics.py`` —
one record per event) and the span tracer (``obs/trace.py`` — one record
per phase): instruments accumulate in memory at negligible cost (a lock
plus a few scalar ops; safe to update even with all observability
disabled, since nothing is written until a snapshot is requested), and
dump two ways:

- ``emit_snapshot()`` — one versioned ``{"event": "metrics_snapshot",
  "v": 1, "metrics": {...}}`` record into the JSONL sink (a no-op when
  the sink is disabled, preserving the zero-file-writes guarantee);
- ``prometheus_text()`` — Prometheus-style text exposition on demand
  (the REPL's ``stats`` command, ``bench.py --obs``'s ``metrics.prom``).

Histograms are log-bucketed: bucket ``i`` counts values in
``(base * factor**(i-1), base * factor**i]`` (values ≤ base land in
bucket 0, values past the last edge in the ``+Inf`` overflow bucket).
The defaults (base 1 µs, factor 2, 40 buckets) span sub-microsecond
host ops through ~10-minute compiles in one histogram; occupancy-style
integer histograms pass ``base=1.0``.
"""

from __future__ import annotations

import threading

from ba_tpu.utils import metrics as _metrics


class Counter:
    """Monotonic counter (events, dispatches, retires, signs...)."""

    kind = "counter"

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins instantaneous value (cache enabled, live depth...)."""

    kind = "gauge"

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Log-bucketed distribution (latencies, occupancy, compile time)."""

    kind = "histogram"

    def __init__(
        self,
        lock: threading.Lock,
        base: float = 1e-6,
        factor: float = 2.0,
        n_buckets: int = 40,
    ):
        if base <= 0 or factor <= 1 or n_buckets < 1:
            raise ValueError(
                f"bad histogram shape: base={base} factor={factor} "
                f"n_buckets={n_buckets}"
            )
        self._lock = lock
        self.base = base
        self.factor = factor
        # _counts[i] for i < n_buckets covers (edge(i-1), edge(i)];
        # _counts[n_buckets] is the +Inf overflow bucket.
        self._counts = [0] * (n_buckets + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def _index(self, v: float) -> int:
        last = len(self._counts) - 1
        if v <= self.base:
            return 0
        edge = self.base
        for i in range(1, last):
            edge *= self.factor
            if v <= edge:
                return i
        return last

    def record(self, v: float) -> None:
        i = self._index(v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def edge(self, i: int) -> float:
        """Upper boundary of bucket ``i`` (inclusive)."""
        return self.base * self.factor**i

    def peek(self) -> dict:
        """A lock-free read of the histogram's state (ISSUE 9 health
        sampling): every field is a GIL-atomic attribute read and the
        bucket list copies element-by-element under the GIL, so a
        concurrent ``record`` can at worst make the copy off by the
        in-flight sample — monitoring-grade consistency without ever
        contending with the engine's hot-loop updates."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "counts": list(self._counts),
        }

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            out = {
                "type": "histogram",
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
            }
        # Sparse [upper_edge, count] pairs, non-empty buckets only — a
        # 40-bucket histogram with 3 occupied buckets snapshots 3 pairs.
        # The overflow edge is the STRING "+Inf", not float('inf'):
        # json.dumps would serialize the float as the bare token
        # `Infinity`, which Python's json accepts but strict consumers
        # (jq, JSON.parse, Go) reject — breaking the every-record-parses
        # schema contract.
        out["buckets"] = [
            ["+Inf" if i == len(counts) - 1 else self.edge(i), c]
            for i, c in enumerate(counts)
            if c
        ]
        return out


def delta_quantile(hist: Histogram, counts_then, counts_now, q: float):
    """Approximate quantile of the samples recorded BETWEEN two
    ``Histogram.peek`` calls: the upper edge of the bucket where the
    delta-cumulative count crosses ``q`` (``inf`` for the overflow
    bucket; ``None`` for an empty window).

    The repo's ONE windowed-quantile implementation (ISSUE 17): the
    health sampler (``obs/health.py``) and the SLO engine
    (``obs/slo.py``) both difference lock-free ``peek()`` snapshots
    through this helper, so a bucket-walk fix lands in every consumer
    at once.  ``counts_then`` may be ``None`` (no baseline yet — the
    whole histogram is the window).
    """
    if counts_then is None:
        counts_then = [0] * len(counts_now)
    deltas = [
        max(0, now - then) for now, then in zip(counts_now, counts_then)
    ]
    total = sum(deltas)
    if not total:
        return None
    need = q * total
    cum = 0
    for i, c in enumerate(deltas):
        cum += c
        if cum >= need:
            if i == len(deltas) - 1:
                return float("inf")
            return hist.edge(i)
    return None


class MetricsRegistry:
    """Thread-safe name → instrument map with snapshot/exposition dumps."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict = {}

    def _get(self, name: str, factory):
        # Naming contract (ISSUE 9 satellite; docs/DESIGN.md §8): a
        # metric whose value is ONE device's share of something spells
        # it with the `_per_shard` SUFFIX — `scenario_plane_bytes` vs
        # `scenario_plane_bytes_per_shard`.  Enforced at instrument
        # creation so a future mesh gauge cannot drift to
        # `per_shard_plane_bytes` / `plane_per_shard_bytes` and split
        # dashboards across two spellings of the same denominator.
        if "per_shard" in name and not name.endswith("_per_shard"):
            raise ValueError(
                f"metric name {name!r} mentions per_shard but does not "
                f"END with '_per_shard' — the per-device-share suffix "
                f"rule (DESIGN §8) keeps mesh gauge names joinable"
            )
        # The serving front-end's family (ISSUE 10) mirrors the rule in
        # the other direction: a metric owned by the service spells the
        # `serve_` PREFIX — `serve_queue_depth`, never `queue_serve_*`
        # — so one Prometheus prefix match scrapes the whole service
        # dashboard.  Token-wise ("_"-split), not substring: names like
        # `equivocation_observed` contain "serve" only as letters.
        if "serve" in name.split("_") and not name.startswith("serve_"):
            raise ValueError(
                f"metric name {name!r} mentions the serve token but "
                f"does not START with 'serve_' — the service-metric "
                f"prefix rule (DESIGN §8) keeps the serving dashboard "
                f"one prefix match"
            )
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
        return inst

    def get(self, name: str):
        """The existing instrument named ``name``, or None — WITHOUT
        creating one and WITHOUT taking the registry lock (a dict read
        is atomic under the GIL).  The health sampler's lock-free read
        path: sampling must never contend with the engine's hot-loop
        updates."""
        return self._instruments.get(name)

    def counter(self, name: str) -> Counter:
        inst = self._get(name, lambda: Counter(self._lock))
        if not isinstance(inst, Counter):
            raise TypeError(f"{name!r} is a {inst.kind}, not a counter")
        return inst

    def gauge(self, name: str) -> Gauge:
        inst = self._get(name, lambda: Gauge(self._lock))
        if not isinstance(inst, Gauge):
            raise TypeError(f"{name!r} is a {inst.kind}, not a gauge")
        return inst

    def histogram(self, name: str, **shape) -> Histogram:
        # Shape kwargs (base/factor/n_buckets) apply on first creation
        # only; later lookups return the existing instrument unchanged.
        inst = self._get(name, lambda: Histogram(self._lock, **shape))
        if not isinstance(inst, Histogram):
            raise TypeError(f"{name!r} is a {inst.kind}, not a histogram")
        return inst

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._instruments.items())
        return {name: inst.snapshot() for name, inst in items}

    def emit_snapshot(self, sink=None, **extra) -> dict:
        """One versioned ``metrics_snapshot`` record into the JSONL sink.

        A no-op write when the sink is disabled (the snapshot dict is
        still built and returned, so callers can inspect it either way).
        ``extra`` keys ride on the record (platform, config name...).
        """
        record = {"event": "metrics_snapshot", "v": _metrics.SCHEMA_VERSION,
                  **extra, "metrics": self.snapshot()}
        (sink or _metrics.default_sink()).emit(record)
        return record

    def prometheus_text(self) -> str:
        """Prometheus text exposition of every instrument.

        Histogram buckets are cumulative with an ``+Inf`` terminator, as
        the format requires.  Only occupied edges are emitted (sparse):
        cumulative counts stay correct at every listed edge, so the
        output is valid exposition text, just without zero-delta lines.
        """
        lines = []
        for name, inst in sorted(self.snapshot().items()):
            pname = "".join(
                c if c.isalnum() or c in "_:" else "_" for c in name
            )
            lines.append(f"# TYPE {pname} {inst['type']}")
            if inst["type"] in ("counter", "gauge"):
                lines.append(f"{pname} {inst['value']}")
                continue
            cum = 0
            for le, c in inst["buckets"]:
                cum += c
                le_s = le if le == "+Inf" else format(le, ".6g")
                lines.append(f'{pname}_bucket{{le="{le_s}"}} {cum}')
            if not inst["buckets"] or inst["buckets"][-1][0] != "+Inf":
                lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{pname}_sum {inst['sum']}")
            lines.append(f"{pname}_count {inst['count']}")
        return "\n".join(lines) + ("\n" if lines else "")


_default: MetricsRegistry | None = None


def default_registry() -> MetricsRegistry:
    """Process-wide registry (lazily created; tests swap ``_default``)."""
    global _default
    if _default is None:
        _default = MetricsRegistry()
    return _default
