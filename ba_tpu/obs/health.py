"""Live health view: a lock-free periodic sampler over the metrics
registry (ISSUE 9).

The registry (``obs/registry.py``) already accumulates everything an
operator needs to answer "is this campaign healthy RIGHT NOW" — it just
never computed the derived quantities or exposed them as a stable gauge
family.  This module adds the sampler:

- **lock-free reads**: a sample touches only GIL-atomic attribute reads
  (``registry.get`` + counter/gauge ``.value``, ``Histogram.peek``) —
  it never takes the registry lock and never syncs the device, so a
  sampler firing from the engine's ``host_work`` overlap slot
  (``pipeline_sweep(health_every=N)``) adds ZERO synchronization to the
  dispatch schedule (the no-blocking proof re-runs with it live);
- **derived health metrics** per sample window (deltas between
  consecutive samples, not process-lifetime aggregates):
  ``rounds_per_s``, ``depth_occupancy`` (mean in-flight dispatches over
  the window), ``retire_lag_p50_s``/``retire_lag_p99_s`` (quantiles of
  the window's retire-lag bucket deltas), ``watchdog_margin_s``
  (configured retire timeout − the WINDOW's worst dispatch latency,
  read off the latency histogram's bucket deltas: the distance to a
  stall declaration, unpolluted by dispatch 0's compile or a previous
  sweep's lifetime max), and the per-shard byte imbalance of
  a mesh campaign (max device share ÷ mean — 1.0 is perfectly
  balanced);
- **three outputs per sample**: the returned dict, a ``health_*`` gauge
  family written back into the registry (so the Prometheus exposition
  and the REPL's ``stats`` both carry it), and — when a JSONL sink is
  live — one versioned ``{"event": "health_snapshot", "v": 1}`` record
  (stamped with the active ``run_id`` like every in-scope record, so
  the flight recorder's timeline carries the health trajectory).

``repl.py``'s ``stats --live`` renders a sample from the process-wide
default sampler (rates are since the PREVIOUS ``stats --live`` call).
Host-tier by lint contract: ba-lint BA301 proves ``obs/health.py``
never imports through ``ba_tpu.core``/``ba_tpu.ops``; the lock-free
claim above is machine-checked too — the declaration below puts the
whole module under BA502 (single-opcode GIL-atomic reads only: no
read-modify-write on shared state, no iteration over shared
containers, no lock acquisition).
"""

# ba-lint: lockfree

from __future__ import annotations

import time

from ba_tpu.obs import registry as _registry
from ba_tpu.utils import metrics as _metrics

# The gauge family one sample writes back (the Prometheus exposition's
# `health_*` block).  None-valued fields are skipped, never written as
# fake zeros.
HEALTH_GAUGES = (
    "health_rounds_per_s",
    "health_depth_occupancy",
    "health_retire_lag_p50_s",
    "health_retire_lag_p99_s",
    "health_watchdog_margin_s",
    "health_plane_imbalance",
    "health_carry_imbalance",
    # Written by an installed SLO engine (obs/slo.py, ISSUE 17), not by
    # the sampler itself — listed here because they are part of the
    # same lock-free `health_*` read surface (REPL, shed ladder).
    "health_slo_burn",
    "health_slo_worst_p99_s",
)


def _counter_value(reg, name: str) -> int:
    inst = reg.get(name)
    return inst.value if inst is not None else 0


def _gauge_value(reg, name: str):
    inst = reg.get(name)
    return inst.value if inst is not None else None


def _hist_peek(reg, name: str):
    inst = reg.get(name)
    return inst.peek() if inst is not None else None


def _delta_quantile(hist, counts_then, counts_now, q: float):
    """Back-compat alias: the windowed-quantile walk now lives on the
    registry as :func:`ba_tpu.obs.registry.delta_quantile` (ISSUE 17
    promoted it so the SLO engine shares the one implementation)."""
    return _registry.delta_quantile(hist, counts_then, counts_now, q)


class HealthSampler:
    """Periodic health sampling with per-window deltas.

    One sampler = one observation stream: consecutive :meth:`sample`
    calls difference counters and histogram buckets, so two independent
    consumers (a REPL and an engine loop) should each hold their own.
    ``timeout_s`` is the retire-watchdog timeout the margin is measured
    against (None = no margin reported).
    """

    def __init__(self, registry=None, timeout_s: float | None = None):
        self._registry = registry
        self.timeout_s = timeout_s
        self._last_t: float | None = None
        self._last_rounds = 0
        self._last_retires = 0
        self._last_occ = (0, 0.0)  # (count, sum) of the occupancy hist
        self._last_lag_counts = None
        self._last_lat_counts = None
        self.samples = 0

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else _registry.default_registry()
        )

    def prime(self) -> None:
        """Record the current registry state as the window baseline
        WITHOUT producing a sample.  The engine primes its per-sweep
        sampler before the first dispatch, so the first emitted sample
        is a real window of THIS campaign — never a blend of every
        earlier sweep's process-lifetime totals (the registry is
        process-global; a fresh sampler's zero baselines would read the
        lifetime aggregates as one giant first window)."""
        reg = self._reg()
        self._last_t = time.perf_counter()
        self._last_rounds = _counter_value(reg, "pipeline_rounds_total")
        self._last_retires = _counter_value(reg, "pipeline_retires_total")
        occ = _hist_peek(reg, "pipeline_depth_occupancy")
        if occ is not None:
            self._last_occ = (occ["count"], occ["sum"])
        lag = _hist_peek(reg, "pipeline_retire_lag_s")
        if lag is not None:
            self._last_lag_counts = lag["counts"]
        lat = _hist_peek(reg, "pipeline_dispatch_latency_s")
        if lat is not None:
            self._last_lat_counts = lat["counts"]

    def sample(self, emit: bool = False, sink=None, **extra) -> dict:
        """Take one sample: lock-free reads → derived dict → ``health_*``
        gauges (and, with ``emit``, one ``health_snapshot`` record).
        ``extra`` keys ride the record (dispatch index, campaign name).
        """
        reg = self._reg()
        now = time.perf_counter()
        rounds = _counter_value(reg, "pipeline_rounds_total")
        retires = _counter_value(reg, "pipeline_retires_total")
        occ = _hist_peek(reg, "pipeline_depth_occupancy")
        lag_hist = reg.get("pipeline_retire_lag_s")
        lag = lag_hist.peek() if lag_hist is not None else None
        lat = _hist_peek(reg, "pipeline_dispatch_latency_s")

        dt = None if self._last_t is None else now - self._last_t
        rounds_per_s = None
        if dt and dt > 0:
            rounds_per_s = (rounds - self._last_rounds) / dt

        # Every derived metric below is a PER-WINDOW delta between this
        # sample and the previous one (or prime()) — never a
        # process-lifetime aggregate: the registry outlives campaigns,
        # and a lifetime max/mean would alarm on dispatch 0's compile
        # (or a previous sweep) forever.  A sampler with no window yet
        # reports None rather than fake lifetime numbers.
        windowed = dt is not None
        occupancy = None
        if windowed and occ is not None:
            d_count = occ["count"] - self._last_occ[0]
            d_sum = occ["sum"] - self._last_occ[1]
            if d_count > 0:
                occupancy = d_sum / d_count

        p50 = p99 = None
        if (
            windowed
            and lag is not None
            and lag_hist is not None
            and self._last_lag_counts is not None
        ):
            p50 = _delta_quantile(
                lag_hist, self._last_lag_counts, lag["counts"], 0.5
            )
            p99 = _delta_quantile(
                lag_hist, self._last_lag_counts, lag["counts"], 0.99
            )

        # The window's worst dispatch latency, as the upper edge of the
        # highest bucket the window touched (the histogram's .max is
        # lifetime-scoped; buckets are the only windowable signal — the
        # edge over-reads by at most one bucket factor, which errs the
        # margin conservative).
        lat_hist = reg.get("pipeline_dispatch_latency_s")
        lat_max = None
        if (
            windowed
            and lat is not None
            and lat_hist is not None
            and self._last_lat_counts is not None
        ):
            lat_max = _delta_quantile(
                lat_hist, self._last_lat_counts, lat["counts"], 1.0
            )
        margin = None
        if (
            self.timeout_s is not None
            and lat_max is not None
            and lat_max != float("inf")
        ):
            margin = self.timeout_s - lat_max

        shards = _gauge_value(reg, "pipeline_shards")
        plane_shard = _gauge_value(reg, "scenario_plane_bytes_per_shard")
        carry_shard = _gauge_value(reg, "pipeline_carry_bytes_per_shard")
        # Both imbalances are MEASURED by the engine (max device share /
        # mean, from addressable-shard metadata at stage/stage-in time —
        # parallel/pipeline.py), never derived here from totals: a
        # total/shards identity could only ever read 1.0.
        carry_imb = _gauge_value(reg, "pipeline_carry_imbalance")
        plane_imb = _gauge_value(reg, "scenario_plane_imbalance")

        snap = {
            "interval_s": round(dt, 6) if dt is not None else None,
            "rounds_per_s": (
                round(rounds_per_s, 3) if rounds_per_s is not None else None
            ),
            "rounds_total": rounds,
            "retires_total": retires,
            "depth_occupancy": (
                round(occupancy, 3) if occupancy is not None else None
            ),
            "retire_lag_p50_s": p50,
            "retire_lag_p99_s": p99,
            "dispatch_latency_max_s": lat_max,
            "watchdog_timeout_s": self.timeout_s,
            "watchdog_margin_s": (
                round(margin, 6) if margin is not None else None
            ),
            "shards": int(shards) if shards else None,
            "plane_bytes_per_shard": plane_shard,
            "carry_bytes_per_shard": carry_shard,
            "plane_imbalance": (
                round(plane_imb, 4) if plane_imb is not None else None
            ),
            "carry_imbalance": carry_imb,
            "stalls_total": _counter_value(reg, "pipeline_stalls_total"),
        }

        for gauge, key in (
            ("health_rounds_per_s", "rounds_per_s"),
            ("health_depth_occupancy", "depth_occupancy"),
            ("health_retire_lag_p50_s", "retire_lag_p50_s"),
            ("health_retire_lag_p99_s", "retire_lag_p99_s"),
            ("health_watchdog_margin_s", "watchdog_margin_s"),
            ("health_plane_imbalance", "plane_imbalance"),
            ("health_carry_imbalance", "carry_imbalance"),
        ):
            v = snap[key]
            if v is not None and v != float("inf"):
                reg.gauge(gauge).set(v)

        self._last_t = now
        self._last_rounds = rounds
        self._last_retires = retires
        if occ is not None:
            self._last_occ = (occ["count"], occ["sum"])
        if lag is not None:
            self._last_lag_counts = lag["counts"]
        if lat is not None:
            self._last_lat_counts = lat["counts"]
        # Single-writer bookkeeping: only the sampler itself ever
        # increments, so the RMW cannot interleave with another writer
        # — waived by name rather than restructured.
        self.samples += 1  # ba-lint: disable=BA502

        if emit:
            record = {
                "event": "health_snapshot",
                "v": _metrics.SCHEMA_VERSION,
                **extra,
                **{
                    k: (None if v == float("inf") else v)
                    for k, v in snap.items()
                },
            }
            (sink or _metrics.default_sink()).emit(record)

        # ISSUE 17: an installed SLO engine reports on THIS sampler's
        # cadence — the same host_work overlap slot, so SLO evaluation
        # adds zero synchronization to the dispatch schedule.  Its own
        # report_every_s throttle decides whether a record is actually
        # due.  An engine bug must never take down the sweep that is
        # sampling, hence the counted-not-raised error path.
        from ba_tpu.obs import slo as _slo  # local: obs→obs, optional

        eng = _slo.installed()
        if eng is not None:
            try:
                eng.maybe_report(sink=sink)
            except Exception:
                reg.counter("slo_report_errors_total").inc()
        return snap


_default: HealthSampler | None = None


def default_sampler() -> HealthSampler:
    """Process-wide sampler (the REPL's ``stats --live`` stream: rates
    are measured since the previous call on THIS sampler)."""
    global _default
    if _default is None:
        _default = HealthSampler()
    return _default
