"""Streaming SLO engine: per-phase latency attribution, per-tenant
accounting, error-budget burn rates, autoscaling signals (ISSUE 17).

The serving stack already emits a versioned ``request`` record per
terminal outcome (``runtime/serve.py``) and samples health on the
engine's ``host_work`` overlap slot (``obs/health.py``).  This module
closes ROADMAP direction 5's gap — "raw p50/p99 with zero attribution"
— by folding those records into a streaming evaluator:

- **Lifecycle attribution**: every request decomposes into
  ``queue_s / coalesce_s / compile_s / dispatch_s / retire_lag_s`` with
  the pinned invariant ``sum(phases) ≈ wall_s`` (the service stamps the
  per-phase perf_counter timestamps; this engine only *aggregates* and
  *checks*).  Phase distributions are kept per ``(cohort, tenant)`` in
  the registry's log-bucketed :class:`~ba_tpu.obs.registry.Histogram`
  machinery — O(1) memory per group, quantiles via the promoted
  :func:`ba_tpu.obs.registry.delta_quantile` (the SAME implementation
  the health sampler uses).
- **Error budgets + burn rates** (SRE-workbook multi-window style): an
  :class:`SLOObjective` declares a latency threshold, a target fraction
  and three windows (fast / slow / budget).  ``burn = (bad/total) /
  (1 - target)`` per window; an alert **fires** only when BOTH the fast
  and the slow window burn at ≥ ``burn_threshold`` (fast alone is
  noise, slow alone is stale) and **clears** when either drops below.
  Good/bad events live in O(1) time-bucketed rings — no per-request
  storage anywhere.
- **Zero added syncs**: the engine never touches a device.  Reports
  ride the health sampler's cadence (``HealthSampler.sample`` invokes
  :meth:`SLOEngine.maybe_report` on the installed engine), i.e. the
  same ``host_work`` overlap slot the no-blocking proof already pins.
- **Records out** (all run_id-stamped, strict-JSON clean):
  ``{"event": "slo_report", "v": 1}`` (per-group phase p50/p99 +
  outcome/reject attribution, per-objective budget/burn),
  ``{"event": "slo_alert", "v": 1}`` (fire/clear transitions only) and
  ``{"event": "autoscale_signal", "v": 1}`` (queue pressure + burn →
  replica-count recommendation — the contract ROADMAP direction 1's
  elastic router consumes).  Two gauges — ``health_slo_burn`` (worst
  gate burn) and ``health_slo_worst_p99_s`` — join the lock-free
  ``health_*`` surface so the shed ladder and the REPL read SLO state
  without parsing records.

Host-tier and jax-free by construction (ba-lint BA301 covers every
``ba_tpu.obs`` module): ``python -m ba_tpu.obs.slo`` validates policies
and renders offline reports without ever importing jax.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import threading
import time

from ba_tpu.obs import flight as _flight
from ba_tpu.obs import registry as _registry
from ba_tpu.utils import metrics as _metrics

POLICY_FORMAT = "ba_tpu.slo_policy"
POLICY_VERSION = 1

# The five attribution phases, in lifecycle order.  Their sum must
# telescope to wall_s (admitted → delivered) within ATTRIB_TOL_S — the
# service stamps consecutive perf_counter marks, so the identity is
# exact modulo record rounding (6 dp per field).
PHASES = ("queue_s", "coalesce_s", "compile_s", "dispatch_s", "retire_lag_s")
ATTRIB_TOL_S = 2e-3

# Hard cap on distinct (cohort, tenant) groups: the engine is O(1) per
# group, but tenants are caller-controlled strings — past the cap, new
# groups fold into one overflow bucket instead of growing without bound.
MAX_GROUPS = 64
OVERFLOW_GROUP = ("~other", "~other")


class SLOPolicyError(ValueError):
    """A policy document failed eager validation."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SLOPolicyError(msg)


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One latency objective: ``target`` fraction of matched requests
    must complete (status ok) within ``latency_s``, measured against an
    error budget over ``window_s``.  ``tenant`` / ``cohort`` / ``kind``
    select which requests count (None = all); a rejected or expired
    request always counts bad.  Plain data, eagerly validated."""

    name: str
    latency_s: float
    target: float = 0.99
    window_s: float = 3600.0
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    burn_threshold: float = 8.0
    tenant: str | None = None
    cohort: str | None = None
    kind: str | None = None

    def __post_init__(self):
        _require(
            bool(self.name) and isinstance(self.name, str),
            "objective name must be a non-empty string",
        )
        _require(
            isinstance(self.latency_s, (int, float)) and self.latency_s > 0,
            f"objective {self.name!r}: latency_s must be > 0",
        )
        _require(
            isinstance(self.target, (int, float)) and 0 < self.target < 1,
            f"objective {self.name!r}: target must be in (0, 1)",
        )
        for field in ("window_s", "fast_window_s", "slow_window_s"):
            v = getattr(self, field)
            _require(
                isinstance(v, (int, float)) and v > 0,
                f"objective {self.name!r}: {field} must be > 0",
            )
        _require(
            self.fast_window_s <= self.slow_window_s <= self.window_s,
            f"objective {self.name!r}: windows must nest "
            f"(fast_window_s <= slow_window_s <= window_s)",
        )
        _require(
            isinstance(self.burn_threshold, (int, float))
            and self.burn_threshold > 0,
            f"objective {self.name!r}: burn_threshold must be > 0",
        )
        for field in ("tenant", "cohort", "kind"):
            v = getattr(self, field)
            _require(
                v is None or (isinstance(v, str) and v),
                f"objective {self.name!r}: {field} must be None or a "
                f"non-empty string",
            )

    def matches(self, cohort: str, tenant: str, kind) -> bool:
        if self.tenant is not None and self.tenant != tenant:
            return False
        if self.cohort is not None and self.cohort != cohort:
            return False
        if self.kind is not None and self.kind != kind:
            return False
        return True


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """A set of objectives plus engine dials.  JSON round-trips through
    :meth:`to_doc` / :meth:`from_doc` under the pinned
    ``{"format": "ba_tpu.slo_policy", "v": 1}`` header."""

    objectives: tuple = ()
    report_every_s: float = 1.0
    autoscale: bool = True
    max_replicas: int = 8

    def __post_init__(self):
        _require(
            len(self.objectives) >= 1,
            "policy needs at least one objective",
        )
        names = [o.name for o in self.objectives]
        _require(
            len(names) == len(set(names)),
            f"objective names must be unique, got {names}",
        )
        _require(
            isinstance(self.report_every_s, (int, float))
            and self.report_every_s > 0,
            "report_every_s must be > 0",
        )
        _require(
            isinstance(self.max_replicas, int) and self.max_replicas >= 1,
            "max_replicas must be an int >= 1",
        )

    def to_doc(self) -> dict:
        return {
            "format": POLICY_FORMAT,
            "v": POLICY_VERSION,
            "report_every_s": self.report_every_s,
            "autoscale": self.autoscale,
            "max_replicas": self.max_replicas,
            "objectives": [
                {
                    k: v
                    for k, v in dataclasses.asdict(o).items()
                    if v is not None
                }
                for o in self.objectives
            ],
        }

    @classmethod
    def from_doc(cls, doc) -> "SLOPolicy":
        _require(isinstance(doc, dict), "policy document must be an object")
        _require(
            doc.get("format") == POLICY_FORMAT,
            f"policy format must be {POLICY_FORMAT!r}, "
            f"got {doc.get('format')!r}",
        )
        _require(
            doc.get("v") == POLICY_VERSION,
            f"policy version must be {POLICY_VERSION}, got {doc.get('v')!r}",
        )
        objs = doc.get("objectives")
        _require(
            isinstance(objs, list) and objs,
            "policy objectives must be a non-empty list",
        )
        allowed_obj = {f.name for f in dataclasses.fields(SLOObjective)}
        built = []
        for i, o in enumerate(objs):
            _require(
                isinstance(o, dict), f"objective #{i} must be an object"
            )
            unknown = set(o) - allowed_obj
            _require(
                not unknown,
                f"objective #{i} has unknown keys {sorted(unknown)}",
            )
            built.append(SLOObjective(**o))
        allowed_top = {
            "format",
            "v",
            "objectives",
            "report_every_s",
            "autoscale",
            "max_replicas",
        }
        unknown = set(doc) - allowed_top
        _require(not unknown, f"policy has unknown keys {sorted(unknown)}")
        kwargs = {}
        for k in ("report_every_s", "autoscale", "max_replicas"):
            if k in doc:
                kwargs[k] = doc[k]
        return cls(objectives=tuple(built), **kwargs)

    @classmethod
    def load(cls, path: str) -> "SLOPolicy":
        with open(path, encoding="utf-8") as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError as e:
                raise SLOPolicyError(f"{path}: not valid JSON — {e}") from e
        return cls.from_doc(doc)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_doc(), f, indent=2, sort_keys=True)
            f.write("\n")


def default_policy() -> SLOPolicy:
    """The policy ``BA_TPU_SLO=1`` installs: one catch-all wall-latency
    objective with SRE-workbook-shaped windows, scaled for interactive
    serving."""
    return SLOPolicy(
        objectives=(
            SLOObjective(
                name="serve-wall",
                latency_s=0.5,
                target=0.99,
                window_s=3600.0,
                fast_window_s=60.0,
                slow_window_s=600.0,
                burn_threshold=8.0,
            ),
        ),
        report_every_s=1.0,
    )


def recommend_replicas(
    queue_frac,
    burn,
    replicas: int = 1,
    max_replicas: int = 8,
) -> tuple:
    """Pure replica-count recommendation from queue pressure + gate
    burn — the ``autoscale_signal`` contract ROADMAP direction 1's
    router consumes.  Returns ``(recommended, reason)``.

    Ladder (first match wins; None inputs read as no pressure):

    - burn ≥ 2×threshold-normalized (i.e. ``burn >= 2``) or queue ≥
      87.5% full → double (budget is burning fast or admission is about
      to shed);
    - burn ≥ 1 or queue ≥ 50% → +1 replica;
    - burn < 0.5 and queue < 25% → −1 replica (scale-in, floor 1);
    - otherwise hold.
    """
    qf = 0.0 if queue_frac is None else float(queue_frac)
    b = 0.0 if burn is None else float(burn)
    if b >= 2.0 or qf >= 0.875:
        reason = "burn_hard" if b >= 2.0 else "queue_hard"
        return min(max_replicas, max(replicas * 2, replicas + 1)), reason
    if b >= 1.0 or qf >= 0.5:
        reason = "burn_soft" if b >= 1.0 else "queue_soft"
        return min(max_replicas, replicas + 1), reason
    if b < 0.5 and qf < 0.25 and replicas > 1:
        return replicas - 1, "decay"
    return replicas, "steady"


class _WindowRing:
    """Good/bad event counts over a sliding time window in O(buckets)
    memory: ``n_slots`` time buckets of ``window_s / n_slots`` seconds,
    each ``[epoch_index, good, bad]``; a slot is lazily reset when its
    epoch comes round again, so no timer thread and no per-event
    allocation."""

    def __init__(self, window_s: float, n_slots: int = 12):
        self.window_s = float(window_s)
        self.width = self.window_s / n_slots
        self._slots = [[None, 0, 0] for _ in range(n_slots)]

    def _slot(self, t: float):
        epoch = int(t // self.width)
        slot = self._slots[epoch % len(self._slots)]
        if slot[0] != epoch:
            slot[0] = epoch
            slot[1] = 0
            slot[2] = 0
        return slot

    def add(self, t: float, good: int = 0, bad: int = 0) -> None:
        slot = self._slot(t)
        slot[1] += good
        slot[2] += bad

    def totals(self, t: float) -> tuple:
        """(good, bad) over the window ending at ``t``."""
        lo = int(t // self.width) - len(self._slots) + 1
        good = bad = 0
        for epoch, g, b in self._slots:
            if epoch is not None and epoch >= lo:
                good += g
                bad += b
        return good, bad


class _Group:
    """Per-(cohort, tenant) streaming state: one log-bucketed histogram
    per phase plus wall, outcome/reject tallies, and the per-report
    peek baselines the windowed quantiles difference against."""

    def __init__(self, lock):
        self.hists = {
            name: _registry.Histogram(lock) for name in PHASES + ("wall_s",)
        }
        self.baselines = {name: None for name in self.hists}
        self.counts = {"ok": 0, "failed": 0, "expired": 0, "rejected": 0}
        self.reject_reasons: dict = {}
        self.kinds: set = set()
        self.attribution_checked = 0
        self.attribution_bad = 0
        self.window_events = 0


class _Objective:
    """An :class:`SLOObjective` plus its three live rings."""

    def __init__(self, spec: SLOObjective):
        self.spec = spec
        self.fast = _WindowRing(spec.fast_window_s)
        self.slow = _WindowRing(spec.slow_window_s)
        self.budget = _WindowRing(spec.window_s, n_slots=24)
        self.alerting = False


def _burn(good: int, bad: int, target: float):
    """SRE burn rate: observed bad fraction over the window divided by
    the budgeted bad fraction.  None on an empty window (no data is not
    the same as healthy)."""
    total = good + bad
    if not total:
        return None
    return (bad / total) / (1.0 - target)


def _num(v):
    """Strict-JSON scalar: quantile walks return inf for the overflow
    bucket; records carry null instead (json.dumps would emit the bare
    token ``Infinity``, which strict consumers reject)."""
    if v is None or v == float("inf"):
        return None
    return round(float(v), 6)


class SLOEngine:
    """Folds ``request`` / ``admission`` records into per-group phase
    distributions and per-objective burn windows; emits ``slo_report``
    / ``slo_alert`` / ``autoscale_signal`` records on demand.

    Thread-safety: :meth:`fold` takes the engine lock (it is called
    from the service's dispatcher/submit threads); :meth:`maybe_report`
    takes it too, briefly, to snapshot.  Nothing in here ever touches a
    device or takes the metrics-registry lock — gauge writes go through
    the registry's instrument API, reads through lock-free ``get``.
    """

    def __init__(self, policy: SLOPolicy, registry=None, clock=None):
        self.policy = policy
        self._registry = registry
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._hist_lock = threading.Lock()
        self._groups: dict = {}
        self._objectives = [_Objective(o) for o in policy.objectives]
        self._last_report_t: float | None = None
        self.reports = 0
        self.queue_frac = None  # service-stamped, GIL-atomic write/read
        self.replicas = 1
        self.last_worst = None  # REPL-readable summary of the last report
        fingerprint = json.dumps(policy.to_doc(), sort_keys=True)
        self.run_id = _flight.resolve_run_id("slo", fingerprint)

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else _registry.default_registry()
        )

    def _group(self, cohort: str, tenant: str) -> _Group:
        key = (cohort, tenant)
        g = self._groups.get(key)
        if g is None:
            if len(self._groups) >= MAX_GROUPS:
                key = OVERFLOW_GROUP
                g = self._groups.get(key)
                if g is None:
                    g = self._groups[key] = _Group(self._hist_lock)
                return g
            g = self._groups[key] = _Group(self._hist_lock)
        return g

    # ------------------------------------------------------------------
    # Fold

    def fold(self, rec: dict, t: float | None = None) -> None:
        """Consume one JSONL record dict.  Only ``request`` and
        rejected ``admission`` records count; everything else is
        ignored, so a caller may pipe the whole stream through."""
        event = rec.get("event")
        if event == "request":
            self._fold_request(rec, t)
        elif event == "admission" and rec.get("decision") == "reject":
            self._fold_reject(rec, t)

    def _fold_request(self, rec: dict, t: float | None) -> None:
        now = self._clock() if t is None else t
        status = rec.get("status")
        cohort = rec.get("cohort") or "-"
        tenant = rec.get("tenant") or "-"
        kind = rec.get("kind")
        wall = rec.get("wall_s")
        with self._lock:
            g = self._group(cohort, tenant)
            g.window_events += 1
            if kind:
                g.kinds.add(kind)
            if status in g.counts:
                g.counts[status] += 1
            if isinstance(wall, (int, float)):
                g.hists["wall_s"].record(wall)
            phase_sum = 0.0
            phases_seen = 0
            for name in PHASES:
                v = rec.get(name)
                if isinstance(v, (int, float)):
                    g.hists[name].record(v)
                    phase_sum += v
                    phases_seen += 1
            # The attribution invariant is only claimed for ok rows:
            # every phase stamped, sum telescopes to wall (DESIGN §8).
            if (
                status == "ok"
                and phases_seen == len(PHASES)
                and isinstance(wall, (int, float))
            ):
                g.attribution_checked += 1
                if abs(phase_sum - wall) > ATTRIB_TOL_S:
                    g.attribution_bad += 1
            good_if_fast = status == "ok" and isinstance(wall, (int, float))
            for obj in self._objectives:
                if not obj.spec.matches(cohort, tenant, kind):
                    continue
                good = good_if_fast and wall <= obj.spec.latency_s
                obj.fast.add(now, good=int(good), bad=int(not good))
                obj.slow.add(now, good=int(good), bad=int(not good))
                obj.budget.add(now, good=int(good), bad=int(not good))

    def _fold_reject(self, rec: dict, t: float | None) -> None:
        now = self._clock() if t is None else t
        cohort = rec.get("cohort") or "-"
        tenant = rec.get("tenant") or "-"
        reason = rec.get("reason") or "unknown"
        kind = rec.get("kind")
        with self._lock:
            g = self._group(cohort, tenant)
            g.window_events += 1
            g.counts["rejected"] += 1
            g.reject_reasons[reason] = g.reject_reasons.get(reason, 0) + 1
            for obj in self._objectives:
                if obj.spec.matches(cohort, tenant, kind):
                    obj.fast.add(now, bad=1)
                    obj.slow.add(now, bad=1)
                    obj.budget.add(now, bad=1)

    # ------------------------------------------------------------------
    # Report

    def maybe_report(self, now=None, sink=None, force: bool = False):
        """Emit one ``slo_report`` (plus any alert transitions and an
        ``autoscale_signal``) if ``report_every_s`` has elapsed since
        the last one.  Returns the report record, or None when not due.
        Called on the health sampler's cadence — never from a device
        callback, never blocking on anything."""
        now = self._clock() if now is None else now
        if (
            not force
            and self._last_report_t is not None
            and now - self._last_report_t < self.policy.report_every_s
        ):
            return None
        out_sink = sink or _metrics.default_sink()
        with self._lock:
            # Alerts first, so the report's per-objective ``alerting``
            # flag reflects THIS tick's fire/clear decision.
            alerts = self._update_alerts(now)
            report = self._build_report(now)
            self._last_report_t = now
            self.reports += 1
        for alert in alerts:
            out_sink.emit(alert)
        out_sink.emit(report)
        if self.policy.autoscale:
            out_sink.emit(self._autoscale_signal(report))
        self._write_gauges(report)
        return report

    def _build_report(self, now: float) -> dict:
        groups = []
        worst_p99 = None
        worst_group = None
        for (cohort, tenant), g in sorted(self._groups.items()):
            phases = {}
            for name, hist in g.hists.items():
                peek = hist.peek()
                p50 = _registry.delta_quantile(
                    hist, g.baselines[name], peek["counts"], 0.5
                )
                p99 = _registry.delta_quantile(
                    hist, g.baselines[name], peek["counts"], 0.99
                )
                g.baselines[name] = peek["counts"]
                phases[name] = {"p50": _num(p50), "p99": _num(p99)}
            wall_p99 = phases["wall_s"]["p99"]
            if wall_p99 is not None and (
                worst_p99 is None or wall_p99 > worst_p99
            ):
                worst_p99 = wall_p99
                dominant = max(
                    PHASES,
                    key=lambda n: (phases[n]["p99"] or 0.0),
                )
                worst_group = {
                    "cohort": cohort,
                    "tenant": tenant,
                    "p99_s": wall_p99,
                    "phase": dominant,
                }
            groups.append(
                {
                    "cohort": cohort,
                    "tenant": tenant,
                    "window_events": g.window_events,
                    "counts": dict(g.counts),
                    "reject_reasons": dict(g.reject_reasons),
                    "phases": phases,
                    "attribution_checked": g.attribution_checked,
                    "attribution_bad": g.attribution_bad,
                }
            )
            g.window_events = 0
        objectives = []
        worst_burn = None
        for obj in self._objectives:
            fg, fb = obj.fast.totals(now)
            sg, sb = obj.slow.totals(now)
            bg, bb = obj.budget.totals(now)
            burn_fast = _burn(fg, fb, obj.spec.target)
            burn_slow = _burn(sg, sb, obj.spec.target)
            # The GATE burn: the multi-window alert fires on min(fast,
            # slow) — fast alone is noise, slow alone is stale — so the
            # scalar the shed ladder / autoscaler reads is that min.
            gate = None
            if burn_fast is not None and burn_slow is not None:
                gate = min(burn_fast, burn_slow)
            budget_remaining = None
            if bg + bb:
                budget_remaining = 1.0 - (bb / (bg + bb)) / (
                    1.0 - obj.spec.target
                )
            if gate is not None and (worst_burn is None or gate > worst_burn):
                worst_burn = gate
            objectives.append(
                {
                    "name": obj.spec.name,
                    "target": obj.spec.target,
                    "latency_s": obj.spec.latency_s,
                    "good": bg,
                    "bad": bb,
                    "burn_fast": _num(burn_fast),
                    "burn_slow": _num(burn_slow),
                    "burn": _num(gate),
                    "budget_remaining": _num(budget_remaining),
                    "alerting": obj.alerting,
                }
            )
        self.last_worst = (
            None
            if worst_group is None
            else {**worst_group, "burn": _num(worst_burn)}
        )
        return {
            "event": "slo_report",
            "v": _metrics.SCHEMA_VERSION,
            "run_id": self.run_id,
            "groups": groups,
            "objectives": objectives,
            "worst_burn": _num(worst_burn),
            "worst_p99_s": _num(worst_p99),
        }

    def _update_alerts(self, now: float) -> list:
        """Fire/clear transitions since the last report — emitted as
        ``slo_alert`` records, transitions only (steady state is the
        report's ``alerting`` flag)."""
        alerts = []
        for obj in self._objectives:
            fg, fb = obj.fast.totals(now)
            sg, sb = obj.slow.totals(now)
            burn_fast = _burn(fg, fb, obj.spec.target)
            burn_slow = _burn(sg, sb, obj.spec.target)
            both_hot = (
                burn_fast is not None
                and burn_slow is not None
                and burn_fast >= obj.spec.burn_threshold
                and burn_slow >= obj.spec.burn_threshold
            )
            if both_hot != obj.alerting:
                obj.alerting = both_hot
                alerts.append(
                    {
                        "event": "slo_alert",
                        "v": _metrics.SCHEMA_VERSION,
                        "run_id": self.run_id,
                        "objective": obj.spec.name,
                        "state": "fire" if both_hot else "clear",
                        "burn_fast": _num(burn_fast),
                        "burn_slow": _num(burn_slow),
                        "threshold": obj.spec.burn_threshold,
                    }
                )
        return alerts

    def _autoscale_signal(self, report: dict) -> dict:
        qf = self.queue_frac
        burn = report.get("worst_burn")
        recommended, reason = recommend_replicas(
            qf, burn, self.replicas, self.policy.max_replicas
        )
        return {
            "event": "autoscale_signal",
            "v": _metrics.SCHEMA_VERSION,
            "run_id": self.run_id,
            "queue_frac": _num(qf),
            "burn": burn,
            "replicas": self.replicas,
            "recommended": recommended,
            "reason": reason,
        }

    def _write_gauges(self, report: dict) -> None:
        reg = self._reg()
        burn = report.get("worst_burn")
        # An empty fast window (no traffic) reads as ZERO burn, never a
        # held-over stale value: a last-write-wins gauge that kept the
        # burst's peak would pin the shed ladder at tier 2 after the
        # storm has long drained.
        reg.gauge("health_slo_burn").set(burn if burn is not None else 0.0)
        p99 = report.get("worst_p99_s")
        if p99 is not None:
            reg.gauge("health_slo_worst_p99_s").set(p99)


# ----------------------------------------------------------------------
# Process-wide installation (the health sampler's hook target)

_installed: SLOEngine | None = None


def install(engine: SLOEngine | None) -> SLOEngine | None:
    """Install ``engine`` as the process-wide SLO engine (None
    uninstalls).  The health sampler invokes ``maybe_report`` on the
    installed engine after every sample; the serving front-end folds
    its request/admission records into it.  Returns the engine."""
    global _installed
    _installed = engine
    return engine


def installed() -> SLOEngine | None:
    return _installed


# ----------------------------------------------------------------------
# CLI — jax-free by construction, like the scenario/chaos/search CLIs:
#   python -m ba_tpu.obs.slo validate <policy.json> ...
#   python -m ba_tpu.obs.slo default [out.json]
#   python -m ba_tpu.obs.slo report <records.jsonl> [policy.json]


def _cli_validate(paths) -> int:
    if not paths:
        print("validate: needs at least one policy path", file=sys.stderr)
        return 2
    rc = 0
    for path in paths:
        try:
            policy = SLOPolicy.load(path)
        except (OSError, SLOPolicyError) as e:
            print(f"{path}: FAIL — {e}", file=sys.stderr)
            rc = 1
            continue
        # Round-trip pin: to_doc(from_doc(doc)) must be a fixed point.
        again = SLOPolicy.from_doc(policy.to_doc())
        if again != policy:
            print(f"{path}: FAIL — round-trip not a fixed point")
            rc = 1
            continue
        print(
            f"{path}: OK — {len(policy.objectives)} objective(s), "
            f"report every {policy.report_every_s}s"
        )
    return rc


def _cli_default(argv) -> int:
    doc = default_policy().to_doc()
    text = json.dumps(doc, indent=2, sort_keys=True)
    if argv:
        with open(argv[0], "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"wrote {argv[0]}")
    else:
        print(text)
    return 0


def _cli_report(argv) -> int:
    if not argv:
        print(
            "report: needs a records.jsonl path [policy.json]",
            file=sys.stderr,
        )
        return 2
    records_path = argv[0]
    policy = SLOPolicy.load(argv[1]) if len(argv) > 1 else default_policy()
    engine = SLOEngine(policy)
    last_ts = None
    try:
        with open(records_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ts = rec.get("ts")
                if isinstance(ts, (int, float)):
                    last_ts = ts
                # Offline fold: the record's own wall-clock timestamp
                # is the event time, so burn windows replay correctly.
                engine.fold(rec, t=last_ts if last_ts is not None else 0.0)
    except OSError as e:
        print(f"{records_path}: FAIL — {e}", file=sys.stderr)
        return 1
    # The default sink is a no-op unless BA_TPU_METRICS points somewhere
    # — an offline report prints, it does not append to a live ledger.
    report = engine.maybe_report(
        now=last_ts if last_ts is not None else 0.0, force=True
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def main(argv) -> int:
    if not argv:
        print(
            "usage: python -m ba_tpu.obs.slo "
            "{validate <policy.json> ... | default [out.json] | "
            "report <records.jsonl> [policy.json]}",
            file=sys.stderr,
        )
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "validate":
        return _cli_validate(rest)
    if cmd == "default":
        return _cli_default(rest)
    if cmd == "report":
        return _cli_report(rest)
    print(f"unknown command {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
