"""Device-tier observability: XLA artifact introspection and profiler
capture — what the compiled program actually costs, below the dispatch
boundary the host spans (``obs/trace.py``) cannot see.

Three capabilities, all opt-in and zero-cost when disabled:

- **Artifact introspection** (:func:`introspect`): AOT lower + compile a
  jitted callable against the abstract shapes of a real call, harvest
  XLA's ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (argument / output / temp / **alias** bytes)
  into registry gauges plus ONE versioned
  ``{"event": "compiled_artifact", "v": 1, ...}`` JSONL record per
  compile key.  ``alias_bytes`` is the load-bearing number: it is how
  many input bytes XLA aliased onto outputs, i.e. direct evidence that
  the ``donate_argnums`` contract (``parallel/pipeline.py``) actually
  held — a donation regression shows up as ``alias_bytes: 0`` in the
  artifact, not as a silent 2x allocation rate.  Callers gate on
  :func:`enabled` (the JSONL sink is live, or ``BA_TPU_HLO`` is set) so
  the disabled path never imports jax from here, never compiles, and
  never emits.
- **HLO dumps** (``BA_TPU_HLO=dir``): alongside each artifact record,
  write the lowered StableHLO and the backend-optimized HLO text of the
  compiled executable into ``dir`` — the raw material for "what did XLA
  do to my megastep" questions the numbers alone can't answer.
- **Profiler capture hook** (``BA_TPU_XPROF=dir`` / ``bench.py
  --xprof``): a :func:`xprof_session` context manager around
  ``jax.profiler.start_trace``/``stop_trace`` plus :func:`annotate` —
  ``jax.profiler.TraceAnnotation`` markers the engine places on megastep
  dispatch and retire so the device timeline (TensorBoard / xprof)
  aligns with the host span trace's phases.

Caveats, stated so nobody re-learns them: an AOT ``.compile()`` does NOT
share jit's executable cache, so introspection pays one extra compile
per specialization (a persistent-cache load when
``BA_TPU_COMPILE_CACHE`` is on; seconds on CPU, potentially a minute
through the TPU tunnel — which is why it only runs when the sink or an
HLO dir asks for it).  The ISSUE 11 dedupe removes the double-compile
where possible: a signature the executable cache (``obs/aotcache.py``)
already AOT-compiled — with real memory stats, by the same
``_compile_uncached`` discipline — reuses those harvested analyses, and
``_compile_uncached`` runs only on true cache misses.  Meshed calls are introspected at their UNSHARDED
global shapes (the sharded executable may differ in layout; flops and
alias accounting are shape-level properties and carry over).

This module must stay importable without jax (``ba_tpu.obs`` pulls it in
unconditionally): every jax import lives inside a function body.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import sys

_HLO_ENV = "BA_TPU_HLO"
_XPROF_ENV = "BA_TPU_XPROF"

# Record fields harvested from CompiledMemoryStats, in record order.
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)

_warned_fns: set = set()


def hlo_dir() -> str | None:
    """The HLO dump directory (``BA_TPU_HLO``), or None."""
    return os.environ.get(_HLO_ENV) or None


def enabled() -> bool:
    """Should :func:`introspect` run at all?

    True when the JSONL sink is live (``BA_TPU_METRICS`` / ``bench.py
    --obs``) or an HLO dump directory is configured — the two consumers
    of the artifact.  Everything else (no ``BA_TPU_*`` set) stays on the
    zero-records, zero-extra-compiles path.
    """
    if hlo_dir() is not None:
        return True
    from ba_tpu.utils import metrics

    return metrics.default_sink().enabled


def abstractify(tree):
    """Concrete arrays -> ShapeDtypeStructs (lowering never touches or
    consumes buffers this way).  Callers that introspect AFTER a
    donating dispatch capture the abstract signature with this BEFORE
    the buffers are consumed; idempotent on already-abstract values."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype")
        else x,
        tree,
    )


def _compile_uncached(lowered):
    """AOT-compile with the persistent XLA cache bypassed.

    A persistent-cache HIT deserializes the executable with EMPTY memory
    stats — ``memory_analysis()`` then reports ``alias_bytes: 0`` and
    the donation evidence silently degrades to "donation broken" on any
    warm process (measured on jax 0.4.37 / CPU: first compile 1024
    alias bytes, cache-hit recompile 0).  Introspection wants the
    analysis, not the compile-time saving, so it pays the real compile.

    Flipping ``jax_enable_compilation_cache`` alone is NOT enough:
    ``compilation_cache.is_cache_used`` memoizes its decision on first
    use, so a warm process ignores the flag.  ``reset_cache()`` clears
    that memo (both directions — the second call below re-arms the
    restored setting for the rest of the process).
    """
    import jax
    from jax.experimental.compilation_cache import compilation_cache as cc

    enabled = jax.config.jax_enable_compilation_cache
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        cc.reset_cache()
        return lowered.compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", enabled)
        cc.reset_cache()


def _scalar(analysis, field):
    """One named scalar out of a cost_analysis dict (or list of them)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    try:
        return float(analysis.get(field, 0.0))
    except (AttributeError, TypeError, ValueError):
        return 0.0


def introspect(jitted, fn: str, args=(), kwargs=None, axes=None):
    """AOT-compile ``jitted`` at the abstract signature of ``args`` /
    ``kwargs`` and emit one ``compiled_artifact`` record.

    Returns the record dict, or None when disabled or when the backend
    refuses the analysis (one warning per ``fn``, never an exception —
    introspection must not take the agreement path down with it).
    ``axes`` is the caller's named static signature (the same dict the
    recompile explainer sees); it rides the record so artifacts are
    joinable against ``recompile`` records and host spans.
    """
    if not enabled():
        return None
    from ba_tpu import obs
    from ba_tpu.utils import metrics

    # Dedupe against the executable cache (ISSUE 11): when the aotcache
    # already AOT-compiled this exact signature — with REAL memory stats
    # (its ensure() pays _compile_uncached for precisely that) — reuse
    # the harvested analyses instead of paying a SECOND uncached compile
    # here.  HLO dumping still needs the live lowered/compiled objects,
    # so an active BA_TPU_HLO keeps the full path.
    cached = None
    if hlo_dir() is None and axes is not None:
        from ba_tpu.obs import aotcache

        cached = aotcache.recorded_analyses(fn, dict(axes))
    if cached is not None:
        record = {
            "event": "compiled_artifact",
            "v": metrics.SCHEMA_VERSION,
            "fn": fn,
            "axes": dict(axes),
            "flops": cached.get("flops", 0.0),
            "bytes_accessed": cached.get("bytes_accessed", 0.0),
        }
        for _attr, field in _MEMORY_FIELDS:
            record[field] = int(cached.get(field, 0))
        record["donation_aliased"] = record["alias_bytes"] > 0
        record["hlo_dump"] = None
        record["source"] = "aotcache"
    else:
        try:
            with obs.timed_span(
                "xla_introspect", "xla_introspect_s", fn=fn
            ):
                abs_args = abstractify(tuple(args))
                abs_kwargs = abstractify(dict(kwargs or {}))
                lowered = jitted.lower(*abs_args, **abs_kwargs)
                compiled = _compile_uncached(lowered)
                try:
                    cost = compiled.cost_analysis()
                except Exception:  # some backends analyze pre-compile
                    cost = lowered.cost_analysis()
                mem = compiled.memory_analysis()
            record = {
                "event": "compiled_artifact",
                "v": metrics.SCHEMA_VERSION,
                "fn": fn,
                "axes": dict(axes or {}),
                "flops": _scalar(cost, "flops"),
                "bytes_accessed": _scalar(cost, "bytes accessed"),
            }
            for attr, field in _MEMORY_FIELDS:
                record[field] = (
                    int(getattr(mem, attr, 0)) if mem is not None else 0
                )
            record["donation_aliased"] = record["alias_bytes"] > 0
            record["hlo_dump"] = _dump_hlo(
                fn, record["axes"], lowered, compiled
            )
        except Exception as exc:  # best-effort: warn once per fn, move on
            if fn not in _warned_fns:
                _warned_fns.add(fn)
                print(
                    f"ba_tpu.obs.xla: introspection of {fn!r} failed "
                    f"({exc!r}); skipping",
                    file=sys.stderr,
                )
            return None
    metrics.emit(record)
    reg = obs.default_registry()
    for field in ("flops", "bytes_accessed", "temp_bytes", "alias_bytes"):
        reg.gauge(f"xla_{fn}_{field}").set(record[field])
    obs.instant(
        "compiled_artifact",
        fn=fn,
        flops=record["flops"],
        alias_bytes=record["alias_bytes"],
    )
    return record


def _dump_hlo(fn: str, axes: dict, lowered, compiled) -> str | None:
    """Write StableHLO + optimized-HLO text under ``BA_TPU_HLO`` (one
    stable name per (fn, axes) so re-runs overwrite, not accumulate).
    Returns the common path stem, or None when dumping is off."""
    directory = hlo_dir()
    if directory is None:
        return None
    tag = hashlib.sha256(
        json.dumps(axes, sort_keys=True, default=str).encode()
    ).hexdigest()[:10]
    os.makedirs(directory, exist_ok=True)
    stem = os.path.join(directory, f"{fn}-{tag}")
    with open(stem + ".stablehlo.txt", "w") as fh:
        fh.write(lowered.as_text())
    try:
        optimized = compiled.as_text()
    except Exception:  # pragma: no cover - backend without HLO text
        optimized = ""
    if optimized:
        with open(stem + ".optimized.txt", "w") as fh:
            fh.write(optimized)
    return stem


# -- jax.profiler capture hook ------------------------------------------------

_xprof_active = False


def xprof_active() -> bool:
    """A capture session is running, or ``BA_TPU_XPROF`` asks for
    annotations (TraceMe markers are cheap and harmless un-captured)."""
    return _xprof_active or bool(os.environ.get(_XPROF_ENV))


def annotate(name: str, **attrs):
    """A ``jax.profiler.TraceAnnotation`` when capture is active, else a
    free nullcontext — the engine wraps megastep dispatch/retire in this
    so the device timeline carries the same phase names as the host
    trace."""
    if not xprof_active():
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name, **attrs)


@contextlib.contextmanager
def xprof_session(directory: str):
    """Capture a ``jax.profiler`` trace of the enclosed block into
    ``directory`` (view with TensorBoard/xprof).  ``bench.py --xprof
    DIR`` wraps its config loop in this; ``BA_TPU_XPROF=dir`` is the
    env spelling bench honors as the flag's default."""
    global _xprof_active
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.profiler.start_trace(directory)
    _xprof_active = True
    try:
        yield directory
    finally:
        _xprof_active = False
        jax.profiler.stop_trace()
