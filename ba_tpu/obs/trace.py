"""Span tracing: a thread-safe monotonic ring-buffer tracer with Chrome
trace-event export.

The host-side timeline counterpart of ``bench.py --profile`` (which traces
*device* kernels via jax.profiler): this tracer records where the HOST
spends its time — jit compile vs. cached dispatch, pipeline dispatch /
retire / host_work phases, Ed25519 host signing, election and failover
transitions, REPL command handling — as closed spans in a fixed-capacity
ring buffer, exportable to Chrome trace-event JSON loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``.

Clocking: all span timestamps and durations come from
``time.perf_counter_ns`` — monotonic, ns resolution, immune to wall-clock
steps.  The epoch is arbitrary (process start), which is fine for a
trace: viewers only care about relative placement.

Enable with ``BA_TPU_TRACE``: unset/empty/``0`` disables (spans are a
single attribute check + generator frame, and the buffer NEVER grows — the
overhead-guard test pins that); ``1`` enables buffering; any other value
is a path the default tracer exports to at process exit.  ``bench.py
--obs DIR`` enables programmatically and exports to ``DIR/trace.json``.

This module must stay importable without jax and must never touch device
values: spans wrap HOST phases only (a span inside a jitted/scan body
would time tracing, not execution — ``scripts/ci.sh`` lints for that).
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import threading
import time

from ba_tpu.utils import metrics as _metrics

# One span record: (name, start perf_counter_ns, duration ns, thread id,
# attrs dict | None).  Instant events use duration -1.
_INSTANT = -1


class Tracer:
    """Fixed-capacity ring buffer of host spans.

    ``capacity`` bounds memory (oldest spans drop first — a long campaign
    keeps its most recent window, which is the window being diagnosed).
    ``enabled=None`` derives from ``BA_TPU_TRACE``; a bool forces.
    """

    def __init__(self, capacity: int = 65536, enabled: bool | None = None):
        if enabled is None:
            env = os.environ.get("BA_TPU_TRACE", "")
            enabled = bool(env) and env != "0"
        self.enabled = enabled
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._pid = os.getpid()

    def __len__(self) -> int:
        return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context manager recording one closed span around its body.

        Thread-safe: concurrent spans from the pipelined engine's
        ``host_work`` lane interleave cleanly (each record carries its
        thread id, so the Chrome export lays them out on separate rows).
        """
        if not self.enabled:
            yield
            return
        # Run correlation (ISSUE 9): spans recorded inside a flight-
        # recorder run scope carry the campaign's run_id, so the Chrome
        # trace joins the JSONL ledger on the same key.  One global read
        # when enabled; explicit run_id attrs win.
        rid = _metrics.active_run_id()
        if rid is not None:
            attrs.setdefault("run_id", rid)
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            dur = time.perf_counter_ns() - t0
            with self._lock:
                self._buf.append(
                    (name, t0, dur, threading.get_ident(), attrs or None)
                )

    def instant(self, name: str, **attrs) -> None:
        """A zero-duration marker (election flips, cache enablement...)."""
        if not self.enabled:
            return
        rid = _metrics.active_run_id()
        if rid is not None:
            attrs.setdefault("run_id", rid)
        with self._lock:
            self._buf.append(
                (
                    name,
                    time.perf_counter_ns(),
                    _INSTANT,
                    threading.get_ident(),
                    attrs or None,
                )
            )

    # -- export --------------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """The buffer as Chrome trace-event dicts (``ph`` ``X``/``i``).

        Timestamps are microseconds (the trace-event unit); complete
        spans carry ``dur``; every event has ``pid``/``tid`` so Perfetto
        groups rows by thread.
        """
        with self._lock:
            records = list(self._buf)
        events = []
        for name, t0, dur, tid, attrs in records:
            ev = {
                "name": name,
                "ts": t0 / 1e3,
                "pid": self._pid,
                "tid": tid,
                "args": attrs or {},
            }
            if dur == _INSTANT:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = dur / 1e3
            events.append(ev)
        return events

    def export_chrome(self, path: str) -> str:
        """Write the buffer as a Chrome trace-event JSON file at ``path``."""
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
        }
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return path


_default: Tracer | None = None


def default_tracer() -> Tracer:
    """Process-wide tracer configured from ``BA_TPU_TRACE`` (lazily).

    When the env value is a path (not ``0``/``1``), an atexit hook
    exports the Chrome trace there — the no-code-changes way to trace a
    whole REPL session or sweep campaign.
    """
    global _default
    if _default is None:
        _default = Tracer()
        env = os.environ.get("BA_TPU_TRACE", "")
        if env not in ("", "0", "1"):
            atexit.register(_export_at_exit, _default, env)
    return _default


def _export_at_exit(tracer: Tracer, path: str) -> None:
    """Best-effort exit export: a bad BA_TPU_TRACE path must not end an
    otherwise-clean session with a traceback."""
    if not tracer.enabled:
        return
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tracer.export_chrome(path)
    except OSError as e:
        import sys

        print(f"ba_tpu.obs: trace export to {path!r} failed: {e}",
              file=sys.stderr)


def span(name: str, **attrs):
    """Module-level ``span`` on the default tracer (the common spelling)."""
    return default_tracer().span(name, **attrs)


def instant(name: str, **attrs) -> None:
    default_tracer().instant(name, **attrs)
