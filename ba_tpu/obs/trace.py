"""Span tracing: a thread-safe monotonic ring-buffer tracer with Chrome
trace-event export.

The host-side timeline counterpart of ``bench.py --profile`` (which traces
*device* kernels via jax.profiler): this tracer records where the HOST
spends its time — jit compile vs. cached dispatch, pipeline dispatch /
retire / host_work phases, Ed25519 host signing, election and failover
transitions, REPL command handling — as closed spans in a fixed-capacity
ring buffer, exportable to Chrome trace-event JSON loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``.

Clocking: all span timestamps and durations come from
``time.perf_counter_ns`` — monotonic, ns resolution, immune to wall-clock
steps.  The epoch is arbitrary (process start), which is fine for a
trace: viewers only care about relative placement.

Enable with ``BA_TPU_TRACE``: unset/empty/``0`` disables (spans are a
single attribute check + generator frame, and the buffer NEVER grows — the
overhead-guard test pins that); ``1`` enables buffering; any other value
is a path the default tracer exports to at process exit.  ``bench.py
--obs DIR`` enables programmatically and exports to ``DIR/trace.json``.

This module must stay importable without jax and must never touch device
values: spans wrap HOST phases only (a span inside a jitted/scan body
would time tracing, not execution — ``scripts/ci.sh`` lints for that).

Fleet trace context (ISSUE 19): this module also owns the
``(trace_id, span_id, parent_id)`` causal context that flows across
threads, coalesced serve batches, the sign-pool pickle pipes, and
supervisor resume boundaries.  The storage primitive and the W3C
traceparent codec live in ``utils/metrics.py`` (the sink stamps every
record emitted inside a scope; pool workers decode without importing
the obs package); THIS module owns creation and scoping: contexts are
per-thread and never inherited implicitly — every hop is an explicit
``child_context``/``scope`` pair, which is what makes the assembled
span tree trustworthy.  External callers inject a parent via the
``AgreementRequest.traceparent`` field or ``BA_TPU_TRACE_CONTEXT``;
``current_traceparent()`` extracts the active position for outbound
propagation (checkpoint headers, pool task tuples).
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import threading
import time

from ba_tpu.utils import metrics as _metrics

# One span record: (name, start perf_counter_ns, duration ns, thread id,
# attrs dict | None).  Instant events use duration -1.
_INSTANT = -1


class Tracer:
    """Fixed-capacity ring buffer of host spans.

    ``capacity`` bounds memory (oldest spans drop first — a long campaign
    keeps its most recent window, which is the window being diagnosed).
    ``enabled=None`` derives from ``BA_TPU_TRACE``; a bool forces.
    """

    def __init__(self, capacity: int = 65536, enabled: bool | None = None):
        if enabled is None:
            env = os.environ.get("BA_TPU_TRACE", "")
            enabled = bool(env) and env != "0"
        self.enabled = enabled
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._pid = os.getpid()

    def __len__(self) -> int:
        return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context manager recording one closed span around its body.

        Thread-safe: concurrent spans from the pipelined engine's
        ``host_work`` lane interleave cleanly (each record carries its
        thread id, so the Chrome export lays them out on separate rows).
        """
        if not self.enabled:
            yield
            return
        # Run correlation (ISSUE 9): spans recorded inside a flight-
        # recorder run scope carry the campaign's run_id, so the Chrome
        # trace joins the JSONL ledger on the same key.  One global read
        # when enabled; explicit run_id attrs win.
        rid = _metrics.active_run_id()
        if rid is not None:
            attrs.setdefault("run_id", rid)
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            dur = time.perf_counter_ns() - t0
            with self._lock:
                self._buf.append(
                    (name, t0, dur, threading.get_ident(), attrs or None)
                )

    def instant(self, name: str, **attrs) -> None:
        """A zero-duration marker (election flips, cache enablement...)."""
        if not self.enabled:
            return
        rid = _metrics.active_run_id()
        if rid is not None:
            attrs.setdefault("run_id", rid)
        with self._lock:
            self._buf.append(
                (
                    name,
                    time.perf_counter_ns(),
                    _INSTANT,
                    threading.get_ident(),
                    attrs or None,
                )
            )

    # -- export --------------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """The buffer as Chrome trace-event dicts (``ph`` ``X``/``i``).

        Timestamps are microseconds (the trace-event unit); complete
        spans carry ``dur``; every event has ``pid``/``tid`` so Perfetto
        groups rows by thread.
        """
        with self._lock:
            records = list(self._buf)
        events = []
        for name, t0, dur, tid, attrs in records:
            ev = {
                "name": name,
                "ts": t0 / 1e3,
                "pid": self._pid,
                "tid": tid,
                "args": attrs or {},
            }
            if dur == _INSTANT:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = dur / 1e3
            events.append(ev)
        return events

    def export_chrome(self, path: str) -> str:
        """Write the buffer as a Chrome trace-event JSON file at ``path``."""
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
        }
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return path


_default: Tracer | None = None


def default_tracer() -> Tracer:
    """Process-wide tracer configured from ``BA_TPU_TRACE`` (lazily).

    When the env value is a path (not ``0``/``1``), an atexit hook
    exports the Chrome trace there — the no-code-changes way to trace a
    whole REPL session or sweep campaign.
    """
    global _default
    if _default is None:
        _default = Tracer()
        env = os.environ.get("BA_TPU_TRACE", "")
        if env not in ("", "0", "1"):
            atexit.register(_export_at_exit, _default, env)
    return _default


def _export_at_exit(tracer: Tracer, path: str) -> None:
    """Best-effort exit export: a bad BA_TPU_TRACE path must not end an
    otherwise-clean session with a traceback."""
    if not tracer.enabled:
        return
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tracer.export_chrome(path)
    except OSError as e:
        import sys

        print(f"ba_tpu.obs: trace export to {path!r} failed: {e}",
              file=sys.stderr)


def span(name: str, **attrs):
    """Module-level ``span`` on the default tracer (the common spelling)."""
    return default_tracer().span(name, **attrs)


def instant(name: str, **attrs) -> None:
    default_tracer().instant(name, **attrs)


def flush_export() -> str | None:
    """Export the default tracer's buffer to the ``BA_TPU_TRACE`` path
    NOW, instead of waiting for atexit.

    The supervisor's fatal paths (recovery budget exhausted, poisonous
    window, unrecoverable resume) call this before re-raising: the
    atexit hook alone loses the trace exactly when it matters most —
    an embedding that calls ``os._exit``, a fatal that unwinds into a
    harness which kills the process, or a crashed campaign someone
    wants to diagnose FROM the trace.  Best-effort and idempotent (a
    later atexit export simply overwrites with a superset).  Returns
    the path written, or None when ``BA_TPU_TRACE`` is not a path.
    """
    env = os.environ.get("BA_TPU_TRACE", "")
    if env in ("", "0", "1"):
        return None
    _export_at_exit(default_tracer(), env)
    return env


# -- fleet trace context (ISSUE 19) -------------------------------------------
#
# A context is the plain tuple ``(trace_id, span_id, parent_id)`` — the
# exact shape utils/metrics stores thread-locally and stamps onto every
# record emitted in scope.  trace_id: 32 hex chars, constant across the
# whole causal tree; span_id: 16 hex chars, this position; parent_id:
# the position one hop up (None at the root).

TRACE_CONTEXT_ENV = "BA_TPU_TRACE_CONTEXT"


def current() -> tuple | None:
    """The calling thread's active ``(trace_id, span_id, parent_id)``,
    or None when untraced."""
    return _metrics.active_trace_context()


def current_traceparent() -> str | None:
    """The active context as a W3C traceparent string (for outbound
    propagation: checkpoint headers, pool task tuples, external
    responses), or None when untraced."""
    ctx = _metrics.active_trace_context()
    if ctx is None:
        return None
    return _metrics.format_traceparent(ctx[0], ctx[1])


def new_context(parent=None) -> tuple:
    """A fresh context: a child of ``parent`` when given, a new root
    otherwise.  ``parent`` may be a context tuple or a traceparent
    string (a malformed string degrades to a new root — external input
    must never raise into the request path)."""
    if isinstance(parent, str):
        parsed = _metrics.parse_traceparent(parent)
        if parsed is None:
            parent = None
        else:
            return (parsed[0], _metrics.new_span_id(), parsed[1])
    if parent is None:
        return (_metrics.new_trace_id(), _metrics.new_span_id(), None)
    return (parent[0], _metrics.new_span_id(), parent[1])


def child_context(parent=None) -> tuple:
    """A child of ``parent`` (default: the thread's active context; a
    new root when untraced)."""
    return new_context(parent if parent is not None else current())


@contextlib.contextmanager
def scope(ctx: tuple | None):
    """Install ``ctx`` as the thread's active context for the body
    (None: a no-op pass-through), restoring the previous context on
    exit — exception-safe, so a failed dispatch cannot leak its window
    context onto the dispatcher thread."""
    if ctx is None:
        yield None
        return
    prev = _metrics.set_trace_context(ctx)
    try:
        yield ctx
    finally:
        _metrics.set_trace_context(prev)


@contextlib.contextmanager
def inject_scope(traceparent: str | None = None, mark: str | None = None):
    """The engine-entry ambient scope: keep an already-active context
    (explicit propagation wins), else adopt ``traceparent`` (a resumed
    campaign's checkpoint header), else adopt ``BA_TPU_TRACE_CONTEXT``
    (external injection), else stay untraced.  Adoption activates a
    CHILD of the injected position — the injected span belongs to the
    caller; our records must parent under it, never impersonate it.

    ``mark`` names the adopted position: ON ADOPTION ONLY (never on the
    pass-through of an already-active context — that position is the
    propagator's to record), a zero-duration ``trace_span`` record
    materializes the minted root IMMEDIATELY, so a campaign killed
    mid-flight still leaves the span its windows parent under
    in-stream — without it, every child span would merge unparented."""
    if current() is not None:
        yield current()
        return
    parent = traceparent or os.environ.get(TRACE_CONTEXT_ENV) or None
    if parent is None or _metrics.parse_traceparent(parent) is None:
        yield None
        return
    with scope(new_context(parent)) as ctx:
        if mark is not None:
            emit_trace_span(mark, ctx, time.perf_counter(), 0.0)
        yield ctx


def emit_trace_span(name: str, ctx: tuple, t0_perf: float, dur_s: float,
                    **attrs) -> None:
    """Append one explicit span NODE to the JSONL stream.

    Most spans ride existing records (the sink stamps trace/span/parent
    ids onto whatever a scope emits — ``flight_span``, ``request``,
    ``sign_pool`` records ARE tree nodes); this is for the few causal
    positions with no existing record to ride, e.g. the dispatcher's
    coalesced-batch fan-in node.  ``t0_perf`` is ``time.perf_counter()``
    at span start — the clock the shard's ``clock_anchor`` aligns."""
    _metrics.emit(
        {
            "event": "trace_span",
            "v": _metrics.SCHEMA_VERSION,
            "name": name,
            "trace_id": ctx[0],
            "span_id": ctx[1],
            "parent_id": ctx[2],
            "t_perf": round(t0_perf, 6),
            "dur_s": round(dur_s, 6),
            **attrs,
        }
    )
