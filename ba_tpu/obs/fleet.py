"""Fleet-scope stream aggregation (ISSUE 19): merge per-process sink
shards, align their clocks, and reconstruct per-request causal trees.

Every earlier obs tier observes ONE process; the system is already
multi-process (sign-pool workers, supervisor auto-resume children,
multihost legs).  This module is the read side of the sharded sink
(``BA_TPU_METRICS=dir/`` — ``utils/metrics.MetricsSink``'s directory
mode): each process appended ``<pid>.<token>.jsonl`` with a
``clock_anchor`` first line; here the shards merge into one
deterministic stream and assemble into:

- :func:`assemble_request_trace` — a versioned ``request_trace`` record
  per served request: the full cross-process span tree (client ->
  dispatcher -> coalesced window -> engine dispatch/retire -> pool
  worker sign/verify), the spans of OTHER requests' traces grafted in
  through the dispatcher's ``fan_in`` edges, the extracted critical
  path, and a per-hop attribution whose sum is pinned against the
  PR 17 phase invariant (``sum(PHASES) ~= wall_s`` within
  ``ATTRIB_TOL_S``).
- :class:`FleetSummary` — the per-replica / per-cohort health+SLO
  rollup (the record contract the elastic-fleet router consumes next
  to ``autoscale_signal``), rendered by ``scripts/obs_report.py
  --fleet`` and the REPL's ``stats --fleet`` line.

Clock-anchor alignment rule: a shard's anchor pairs one
``time.perf_counter()`` reading with one ``time.time()`` reading taken
back-to-back at shard open; ``offset = anchor.ts - anchor.perf_t``
maps that process's monotonic clock onto the shared unix axis, so any
record carrying a ``t_perf`` field aligns as ``t_perf + offset``
(records without one fall back to their coarse ``ts`` stamp).  Merge
determinism: records sort by ``(aligned_t, shard_name, line_index)`` —
a total order over static inputs — so two assembly runs over the same
shard directory are byte-identical (:func:`merge_digest` pins it).

Host-tier by contract (BA301): stdlib + ``utils.metrics`` +
``obs.slo`` only, importable without jax — aggregation runs from CI,
routers, and copied-artifact laptops.  Reading is lock-free and
torn-tail tolerant (a SIGKILLed writer's half line is skipped, like
``obs/flight``'s reader) — aggregation never adds a sync or a lock to
any writer.
"""

from __future__ import annotations

import json
import os
import re

from ba_tpu.obs.slo import ATTRIB_TOL_S, PHASES
from ba_tpu.utils import metrics as _metrics

# The shard filename grammar (DESIGN §8): <pid>.<token>.jsonl, where
# token is the writer's active run id at shard open, else a random
# process token.  The filename is PROVENANCE only — merging always
# joins on the run_id/trace_id fields, never on names.
SHARD_RE = re.compile(r"^(\d+)\.(.+)\.jsonl$")


def list_shards(path: str) -> list:
    """Sorted ``(shard_name, shard_path)`` pairs under a sink dir."""
    out = []
    for name in sorted(os.listdir(path)):
        if SHARD_RE.match(name):
            out.append((name, os.path.join(path, name)))
    return out


def read_shard(path: str) -> list:
    """One shard's records, in file order.  Tolerates a torn tail and
    blank lines (a SIGKILL mid-write must not poison the merge) — like
    ``obs/flight``'s reader, malformed lines are skipped, not fatal."""
    records = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        pass
    return records


def shard_offset(records) -> float | None:
    """The shard's perf_counter->unix offset from its latest
    ``clock_anchor`` (the perf epoch is process-constant, so any anchor
    works; the latest is freshest against wall-clock steps)."""
    offset = None
    for rec in records:
        if rec.get("event") == "clock_anchor":
            perf_t, ts = rec.get("perf_t"), rec.get("ts")
            if isinstance(perf_t, (int, float)) and isinstance(
                ts, (int, float)
            ):
                offset = ts - perf_t
    return offset


def merge_shards(path: str) -> list:
    """Every shard's records on ONE aligned, deterministic axis.

    Each returned record is a copy annotated with ``shard`` (its
    source file) and ``t_align`` (its position on the shared unix
    axis: ``t_perf + offset`` when the record carries a perf stamp and
    the shard has an anchor, its coarse ``ts`` otherwise).  Order is
    ``(t_align, shard, line_index)`` — total, so re-merging the same
    directory is byte-identical.
    """
    merged = []
    for name, shard_path in list_shards(path):
        records = read_shard(shard_path)
        offset = shard_offset(records)
        for idx, rec in enumerate(records):
            t_perf = rec.get("t_perf")
            if isinstance(t_perf, (int, float)) and offset is not None:
                t_align = t_perf + offset
            else:
                ts = rec.get("ts")
                t_align = ts if isinstance(ts, (int, float)) else 0.0
            merged.append(
                (round(t_align, 6), name, idx,
                 dict(rec, shard=name, t_align=round(t_align, 6)))
            )
    merged.sort(key=lambda item: item[:3])
    return [item[3] for item in merged]


def merge_digest(records) -> str:
    """A canonical digest of a merged stream — two assembly runs over
    one shard directory must agree byte-for-byte (the bench's
    ``merge_deterministic`` pin)."""
    import hashlib

    payload = json.dumps(
        records, sort_keys=True, separators=(",", ":")
    ).encode()
    return hashlib.sha256(payload).hexdigest()


# -- span trees ---------------------------------------------------------------


def _shard_pid(shard) -> int | None:
    m = SHARD_RE.match(shard or "")
    return int(m.group(1)) if m else None


def _node(rec) -> dict:
    return {
        "span_id": rec["span_id"],
        "parent_id": rec.get("parent_id"),
        "name": rec.get("name") or rec.get("event") or "?",
        "events": [],
        "shard": rec.get("shard"),
        "pid": _shard_pid(rec.get("shard")),
        "t_align": rec.get("t_align"),
        "dur_s": None,
    }


def _fold(node, rec) -> None:
    node["events"].append(rec.get("event") or "?")
    if node["parent_id"] is None and rec.get("parent_id") is not None:
        node["parent_id"] = rec["parent_id"]
    if rec.get("event") == "trace_span" and rec.get("name"):
        node["name"] = rec["name"]  # the explicit node record names it
    dur = rec.get("dur_s")
    if dur is None:
        dur = rec.get("latency_s")  # flight_span's span duration
    if isinstance(dur, (int, float)):
        node["dur_s"] = round(
            max(node["dur_s"] or 0.0, float(dur)), 6
        )


def span_nodes(records) -> dict:
    """Span-id -> node, merging every record that carries the span
    (events-on-span: a request record and its retries land on ONE
    node).  ``records`` should already be merged/aligned."""
    nodes: dict = {}
    for rec in records:
        sid = rec.get("span_id")
        if not isinstance(sid, str):
            continue
        node = nodes.get(sid)
        if node is None:
            node = nodes[sid] = _node(rec)
        _fold(node, rec)
    return nodes


def _descendants(nodes, root_sid) -> set:
    children: dict = {}
    for sid, node in nodes.items():
        children.setdefault(node["parent_id"], []).append(sid)
    out, frontier = set(), [root_sid]
    while frontier:
        sid = frontier.pop()
        if sid in out:
            continue
        out.add(sid)
        frontier.extend(children.get(sid, ()))
    return out


def assemble_request_trace(records, request_id=None) -> dict | None:
    """One served request's cross-process span tree as a versioned
    ``request_trace`` record (None when no traced request matches).

    Tree membership: the spans of the request's own trace whose parent
    chain tops out at THIS request's root (coalesced members can share
    one trace id — an external caller injecting the same traceparent
    into every request — so a sibling request's subtree in the same
    trace is excluded by ownership, not by trace id), plus — through
    the dispatcher's coalesced-batch ``fan_in`` edges — the shared
    batch subtree owned by a different member, reparented under this
    request's root (one request -> one tree, even though the engine
    work was shared).  A same-trace span whose chain dies at an
    UNKNOWN parent stays in (and shows up in ``unparented``): orphans
    are breakage to surface, never to filter away.  ``unparented``
    lists the non-root spans whose parent resolves to no known span —
    the kill-mid-request test and the bench pin it empty.

    The critical path is the request's own five-phase decomposition
    (queue -> coalesce -> compile -> dispatch -> retire), and
    ``within_tol`` pins its sum against the PR 17 invariant:
    ``|sum(PHASES) - wall_s| <= ATTRIB_TOL_S``.
    """
    req = None
    for rec in records:
        if rec.get("event") != "request" or "trace_id" not in rec:
            continue
        if request_id is not None and rec.get("id") != request_id:
            continue
        req = rec  # last wins: the freshest terminal record
    if req is None:
        return None
    trace_id, root_sid = req["trace_id"], req.get("span_id")

    own = [r for r in records if r.get("trace_id") == trace_id]
    all_nodes = span_nodes(own)
    sibling_roots = {
        r.get("span_id")
        for r in own
        if r.get("event") == "request" and r.get("span_id") != root_sid
    }

    def _chain_top(sid):
        # The top of a span's parent chain within this trace: our root,
        # a sibling request's root, or (orphan) the first span whose
        # parent is unknown.  Cycle-guarded — corrupt data stays IN so
        # the unparented audit can flag it.
        seen = set()
        while sid not in seen:
            seen.add(sid)
            node = all_nodes.get(sid)
            parent = node["parent_id"] if node else None
            if parent is None or parent not in all_nodes:
                return sid
            sid = parent
        return sid

    nodes = {
        sid: node
        for sid, node in all_nodes.items()
        if _chain_top(sid) not in sibling_roots
    }
    # Fan-in grafts: a batch span that names our root but is not
    # already ours by ownership (another member's trace, or a sibling-
    # owned batch inside a SHARED trace) adopts our root as parent, and
    # brings its whole subtree (window spans, sign stage, pool workers)
    # along.
    for rec in records:
        if rec.get("event") != "trace_span":
            continue
        if rec.get("span_id") in nodes:
            continue
        fan_in = rec.get("fan_in") or []
        if root_sid not in fan_in:
            continue
        foreign = [
            r for r in records if r.get("trace_id") == rec.get("trace_id")
        ]
        foreign_nodes = span_nodes(foreign)
        keep = _descendants(foreign_nodes, rec["span_id"])
        for sid in keep:
            node = dict(foreign_nodes[sid])
            if sid == rec["span_id"]:
                node["parent_id"] = root_sid
                node["fan_in"] = sorted(fan_in)
            nodes.setdefault(sid, node)

    known = set(nodes)
    unparented = sorted(
        sid
        for sid, node in nodes.items()
        if sid != root_sid
        and (node["parent_id"] is None or node["parent_id"] not in known)
    )
    spans = [
        nodes[sid]
        for sid in sorted(
            nodes, key=lambda s: (nodes[s]["t_align"] or 0.0, s)
        )
    ]

    hops = [
        {"hop": name, "s": round(float(req[name]), 6)}
        for name in PHASES
        if isinstance(req.get(name), (int, float))
    ]
    attribution_s = round(sum(h["s"] for h in hops), 6)
    wall_s = req.get("wall_s")
    within_tol = (
        len(hops) == len(PHASES)
        and isinstance(wall_s, (int, float))
        and abs(attribution_s - wall_s) <= ATTRIB_TOL_S
    )
    return {
        "event": "request_trace",
        "v": _metrics.SCHEMA_VERSION,
        "trace_id": trace_id,
        "request_id": req.get("id"),
        "run_id": req.get("run_id"),
        "root_span": root_sid,
        "spans": spans,
        "span_count": len(spans),
        "processes": sorted(
            {n["pid"] for n in spans if n["pid"] is not None}
        ),
        "unparented": unparented,
        "critical_path": hops,
        "attribution_s": attribution_s,
        "wall_s": wall_s,
        "within_tol": within_tol,
    }


def request_ids(records) -> list:
    """Every traced request id in the merged stream, in stream order."""
    out, seen = [], set()
    for rec in records:
        if rec.get("event") == "request" and "trace_id" in rec:
            rid = rec.get("id")
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
    return out


# -- fleet rollup -------------------------------------------------------------


def _quantile(sorted_vals, q) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * q))
    return round(sorted_vals[idx], 6)


class FleetSummary:
    """Fold a merged stream into the per-replica / per-cohort rollup.

    A replica is one writer process (keyed by its shard — the unit the
    elastic-fleet router scales); cohorts use the SAME cohort label the
    serve tier stamps on ``request`` records (the key the router joins
    against ``autoscale_signal``).  Lock-free by construction: folding
    reads an already-merged list; the REPL's ``stats --fleet`` line
    re-merges on demand and never touches writer state.
    """

    def __init__(self):
        self.replicas: dict = {}
        self.cohorts: dict = {}
        self.pool_tasks = 0
        self.traces: set = set()
        self.worst_burn = None
        self.slo_alerts = 0
        self.autoscale_last = None

    def add(self, rec) -> None:
        event = rec.get("event")
        shard = rec.get("shard") or "?"
        rep = self.replicas.get(shard)
        if rep is None:
            rep = self.replicas[shard] = {
                "shard": shard,
                "pid": _shard_pid(shard),
                "records": 0,
                "requests": 0,
                "ok": 0,
                "pool_tasks": 0,
                "walls": [],
            }
        rep["records"] += 1
        if isinstance(rec.get("trace_id"), str):
            self.traces.add(rec["trace_id"])
        if event == "pool_task":
            rep["pool_tasks"] += 1
            self.pool_tasks += 1
        elif event == "slo_alert":
            self.slo_alerts += 1
        elif event == "autoscale_signal":
            self.autoscale_last = {
                "replicas": rec.get("replicas"),
                "recommended": rec.get("recommended"),
                "reason": rec.get("reason"),
            }
        elif event == "slo_report":
            burn = rec.get("worst_burn")
            if isinstance(burn, (int, float)) and (
                self.worst_burn is None or burn > self.worst_burn
            ):
                self.worst_burn = burn
        elif event == "request":
            rep["requests"] += 1
            status = rec.get("status")
            cohort = rec.get("cohort") or "?"
            grp = self.cohorts.get(cohort)
            if grp is None:
                grp = self.cohorts[cohort] = {
                    "cohort": cohort,
                    "requests": 0,
                    "counts": {},
                    "tenants": set(),
                    "walls": [],
                }
            grp["requests"] += 1
            grp["counts"][status] = grp["counts"].get(status, 0) + 1
            if rec.get("tenant"):
                grp["tenants"].add(rec["tenant"])
            wall = rec.get("wall_s")
            if status == "ok" and isinstance(wall, (int, float)):
                rep["ok"] += 1
                rep["walls"].append(float(wall))
                grp["walls"].append(float(wall))

    def record(self) -> dict:
        """The versioned ``fleet_summary`` record (the router-facing
        contract, registered in ``analysis/contracts.py``)."""
        replicas = []
        for shard in sorted(self.replicas):
            rep = dict(self.replicas[shard])
            walls = sorted(rep.pop("walls"))
            rep["wall_p50_s"] = _quantile(walls, 0.5)
            rep["wall_p99_s"] = _quantile(walls, 0.99)
            replicas.append(rep)
        cohorts = []
        for label in sorted(self.cohorts):
            grp = dict(self.cohorts[label])
            walls = sorted(grp.pop("walls"))
            grp["tenants"] = len(grp["tenants"])
            grp["wall_p50_s"] = _quantile(walls, 0.5)
            grp["wall_p99_s"] = _quantile(walls, 0.99)
            cohorts.append(grp)
        return {
            "event": "fleet_summary",
            "v": _metrics.SCHEMA_VERSION,
            "replicas": replicas,
            "cohorts": cohorts,
            "requests": sum(g["requests"] for g in cohorts),
            "pool_tasks": self.pool_tasks,
            "traces": len(self.traces),
            "worst_burn": self.worst_burn,
            "slo_alerts": self.slo_alerts,
            "autoscale_last": self.autoscale_last,
        }


def fleet_summary(records) -> dict:
    """Fold an already-merged stream into one ``fleet_summary`` record."""
    acc = FleetSummary()
    for rec in records:
        acc.add(rec)
    return acc.record()


def summary_line(summary: dict) -> str:
    """The one-line ``stats --fleet`` rendering of a summary record."""
    walls = [
        r["wall_p99_s"]
        for r in summary.get("replicas", [])
        if r.get("wall_p99_s") is not None
    ]
    p99 = max(walls) if walls else None
    burn = summary.get("worst_burn")
    return (
        f"fleet replicas={len(summary.get('replicas', []))} "
        f"cohorts={len(summary.get('cohorts', []))} "
        f"requests={summary.get('requests')} "
        f"pool_tasks={summary.get('pool_tasks')} "
        f"traces={summary.get('traces')} "
        f"p99_s={p99 if p99 is not None else '-'} "
        f"worst_burn={burn if burn is not None else '-'}"
    )


def assemble_fleet(path: str) -> dict:
    """Merge a sink directory and assemble everything: the summary, one
    ``request_trace`` per traced request, and the determinism digest."""
    records = merge_shards(path)
    traces = [
        assemble_request_trace(records, request_id=rid)
        for rid in request_ids(records)
    ]
    return {
        "records": len(records),
        "shards": [name for name, _ in list_shards(path)],
        "digest": merge_digest(records),
        "summary": fleet_summary(records),
        "request_traces": [t for t in traces if t is not None],
    }


def _main(argv) -> int:
    """``python -m ba_tpu.obs.fleet DIR`` — the jax-free CI validation
    entry: merge twice (pinning byte-identity), assemble every request
    trace, and fail on any unparented span or broken attribution."""
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.split("\n\n")[0])
        print("usage: python -m ba_tpu.obs.fleet SINK_DIR")
        return 2
    path = argv[0]
    first = merge_shards(path)
    second = merge_shards(path)
    deterministic = merge_digest(first) == merge_digest(second)
    assembled = assemble_fleet(path)
    bad = [
        t for t in assembled["request_traces"]
        if t["unparented"] or not t["within_tol"]
    ]
    print(
        json.dumps(
            {
                "shards": len(assembled["shards"]),
                "records": assembled["records"],
                "request_traces": len(assembled["request_traces"]),
                "merge_deterministic": deterministic,
                "all_spans_parented": not any(
                    t["unparented"] for t in assembled["request_traces"]
                ),
                "critical_path_within_tol": all(
                    t["within_tol"] for t in assembled["request_traces"]
                ),
                "digest": assembled["digest"],
            }
        )
    )
    if not deterministic or bad or not assembled["request_traces"]:
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main(sys.argv[1:]))
