"""Flight recorder: one correlated ledger per campaign run (ISSUE 9).

After PRs 7+8 a supervised, mesh-sharded campaign tells its story across
FIVE uncorrelated artifacts — the span trace, the metrics JSONL stream,
the supervisor's ``recovery``/``fault_injected`` records, the carry
checkpoints (+ rows sidecars), and the recompile ledger — and an
operator joining them by hand has nothing to join ON.  This module adds
the join key and the join:

- **run_id** — every campaign run gets one: ``BA_TPU_RUN_ID`` pins it
  (deterministic by fiat — CI and chaos drills set it), otherwise it is
  DERIVED (sha256 over the campaign's key material/rounds/scenario — the
  same identity the supervisor fingerprints), so a killed process's
  successor re-derives the SAME id and the two processes' records read
  as one run.  While a run scope is active the JSONL sink stamps
  ``run_id`` on every record (``utils/metrics.py``), the tracer stamps
  it on every span/instant, the engine writes it into checkpoint
  ``__meta__`` headers, and the cross-run compile ledger rides it on its
  stored rows.
- **run_scope** — the ownership discipline: ``pipeline_sweep`` and
  ``supervised_sweep`` both open a scope, but scopes NEST (the
  supervisor's attempts inherit its id), and only the OUTERMOST owner
  assembles and emits the ``flight_summary`` record at the end.
- **FlightLog / assemble_flight** — the post-hoc join: parse the JSONL
  stream, select one run's records, dedup replayed dispatch windows
  (recoveries re-dispatch from the resume point — the assembled
  timeline must cover every round exactly once), and emit ONE versioned
  ``{"event": "flight_summary", "v": 1}`` record: dispatch→retire→
  checkpoint→recovery causality, per-shard byte/layout provenance
  (ISSUE 8's ``shard_layout``), and recompile attribution by named
  axis.  ``scripts/obs_report.py --flight`` renders it.

Pure stdlib, jax-free, numpy-free: the assembler must run anywhere the
JSONL was copied to (checkpoint/sidecar CONTENT never enters the
summary — their ``scenario_checkpoint`` records carry path, bytes and
shard_layout, which is the provenance an operator correlates on).
Host-tier by lint contract: ba-lint BA301 proves ``obs/flight.py``
never imports through ``ba_tpu.core``/``ba_tpu.ops``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import re

from ba_tpu.utils import metrics as _metrics

RUN_ID_ENV = "BA_TPU_RUN_ID"
# Conservative shape so run ids survive filenames, Prometheus labels and
# shell quoting: leading alnum, then alnum/._:- up to 64 chars total.
# NOTE a pinned BA_TPU_RUN_ID applies to EVERY campaign in the process:
# the assembler dedups dispatch windows by round grid, so two different
# campaigns sharing one pinned id overlay each other's windows — pin
# per campaign (a chaos drill, a CI leg), let derivation handle
# sessions that run several.
RUN_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:-]{0,63}$")

FLIGHT_SUMMARY_VERSION = 1

# Record families that carry a run_id whenever a scope is active — the
# families `scripts/check_metrics_schema.py` validates the key's
# presence/shape on.  (`metrics_snapshot` and friends are stamped too
# when in scope, but only these are BY CONSTRUCTION always emitted from
# inside a campaign's run scope.)
RUN_SCOPED_EVENTS = frozenset(
    {
        "flight_span",
        "scenario_checkpoint",
        "recovery",
        "fault_injected",
        "health_snapshot",
        "flight_summary",
        # The adversary search family (ISSUE 15): every hunt runs
        # inside its own run scope, so these always carry the id.
        "search_generation",
        "search_found",
        "search_minimized",
        "search_checkpoint",
        # The host-crypto pool family (ISSUE 16): the sign-ahead lane
        # stamps an explicit id (active scope, else its own derived
        # key-set identity), so the record always carries one.
        "sign_pool",
        # The SLO family (ISSUE 17): the engine stamps an explicit id
        # (env pin > active scope > its own policy-fingerprint
        # derivation), so every report/alert/signal is joinable.
        "slo_report",
        "slo_alert",
        "autoscale_signal",
    }
)


def valid_run_id(run_id) -> bool:
    return isinstance(run_id, str) and bool(RUN_ID_RE.match(run_id))


def derive_run_id(*material) -> str:
    """``run-<sha256[:16]>`` over the campaign identity material.

    Deterministic: the same (key bytes, rounds, scenario content) —
    whatever the caller feeds — derives the same id in every process,
    which is what lets a killed campaign's auto-resumed successor join
    its predecessor's ledger without any handshake.  ``bytes`` material
    hashes raw; everything else hashes its ``str()``.
    """
    h = hashlib.sha256()
    for m in material:
        h.update(m if isinstance(m, bytes) else str(m).encode())
        h.update(b"\x00")
    return "run-" + h.hexdigest()[:16]


def resolve_run_id(
    *material, inherited: str | None = None, material_fn=None
) -> str:
    """The run id a campaign should use, by precedence:

    1. ``BA_TPU_RUN_ID`` (validated; a malformed value is refused loudly
       — a silently sanitized id would break the operator's own joins);
    2. an already-active scope's id (nested campaigns inherit);
    3. ``inherited`` — the id a resume checkpoint's header carries
       (continuity across a process boundary even when the successor
       cannot re-derive, e.g. an explicit ``resume=path`` entry);
    4. :func:`derive_run_id` over ``material`` plus ``material_fn()``.

    ``material_fn`` (a zero-arg callable returning an iterable) defers
    EXPENSIVE identity material — key fetches, scenario plane hashing —
    to the one precedence branch that needs it: a supervised retry
    attempt (whose derivation always loses to the supervisor's active
    scope) must not re-hash megabytes of event planes per recovery.
    """
    env = os.environ.get(RUN_ID_ENV)
    if env:
        if not valid_run_id(env):
            raise ValueError(
                f"{RUN_ID_ENV}={env!r} is not a valid run id "
                f"(want {RUN_ID_RE.pattern})"
            )
        return env
    active = _metrics.active_run_id()
    if active is not None:
        return active
    if inherited is not None and valid_run_id(inherited):
        return inherited
    if material_fn is not None:
        material = material + tuple(material_fn())
    return derive_run_id(*material)


class RunScope:
    """What :func:`run_scope` yields: the effective ``run_id`` and
    whether THIS scope owns it (``owner`` — the outermost scope; owners
    emit the flight summary, inheritors must not)."""

    __slots__ = ("run_id", "owner")

    def __init__(self, run_id: str, owner: bool):
        self.run_id = run_id
        self.owner = owner


@contextlib.contextmanager
def run_scope(run_id: str):
    """Activate ``run_id`` for the dynamic extent of the block.

    Nesting inherits: when a scope is already active the inner block
    keeps the OUTER id (the supervisor's id wins over its attempts'),
    and ``owner`` is False so exactly one ``flight_summary`` is emitted
    per run.  Always restores on exit, exception or not — a leaked run
    id would stamp unrelated later records.
    """
    active = _metrics.active_run_id()
    if active is not None:
        yield RunScope(active, owner=False)
        return
    _metrics.set_run_id(run_id)
    try:
        yield RunScope(run_id, owner=True)
    finally:
        _metrics.set_run_id(None)


# -- the assembler ------------------------------------------------------------


def _parse_jsonl(path: str, needle: str | None = None):
    """Parsed records, optionally pre-filtered by a raw substring test
    BEFORE json.loads — a shared long-session stream is re-read at the
    end of every owner-scoped campaign, and skipping other runs' lines
    at string speed keeps that linear scan cheap (matched lines still
    go through the real parser and the run-id field check)."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or (needle is not None and needle not in line):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a torn tail line from a killed writer
            if isinstance(rec, dict):
                yield rec


class FlightLog:
    """One run's records, joined.

    Feed records via :meth:`add` (or let :func:`assemble_flight` read a
    JSONL file), then :meth:`summary` builds the versioned
    ``flight_summary``.  Joining rules:

    - **dispatch windows** (``flight_span`` records, one per retire)
      key by their round window's ``lo``; a replayed window after a
      recovery (same lo grid — resume points are dispatch boundaries)
      REPLACES the original, and an OOM-degraded replay's finer grid
      simply chains, so the assembled timeline covers every round
      exactly once (``contiguous`` says whether it does);
    - **checkpoints** key by round cursor (a re-written checkpoint after
      a replay is the same durable point — last write wins);
    - **recompiles** dedup by (fn, changed-axes) — the attribution, not
      the repetition, is the signal;
    - **recoveries** and **faults** are each distinct events and are
      kept in order.
    """

    def __init__(self, run_id: str | None = None):
        self.run_id = run_id
        self._windows: dict = {}  # lo -> window dict (last wins)
        self._checkpoints: dict = {}  # round -> record (last wins)
        self._recoveries: list = []
        self._faults: list = []
        self._recompiles: dict = {}  # (fn, changed json) -> record
        self._health: list = []
        self._events: dict = {}  # event name -> count (this run's records)
        self._last_per_shard: dict = {}

    def add(self, rec: dict) -> bool:
        """Fold one record in.  Returns True when the record belonged to
        this run (matching — or, for a log holding one anonymous run,
        missing — run_id); summaries themselves are never folded."""
        event = rec.get("event")
        if event == "flight_summary":
            return False
        rid = rec.get("run_id")
        if self.run_id is not None and rid is not None and rid != self.run_id:
            return False
        if self.run_id is None and rid is not None:
            self.run_id = rid
        self._events[event] = self._events.get(event, 0) + 1
        if event == "flight_span":
            lo = rec.get("lo")
            if isinstance(lo, int):
                self._windows[lo] = {
                    "lo": lo,
                    "hi": rec.get("hi"),
                    "dispatch": rec.get("dispatch"),
                    "latency_s": rec.get("latency_s"),
                    "lag_s": rec.get("lag_s"),
                    "ts": rec.get("ts"),
                }
        elif event == "scenario_checkpoint":
            rnd = rec.get("round")
            if isinstance(rnd, int):
                self._checkpoints[rnd] = {
                    "round": rnd,
                    "path": rec.get("path"),
                    "bytes": rec.get("bytes"),
                    "shard_layout": rec.get("shard_layout"),
                    "ts": rec.get("ts"),
                }
        elif event == "recovery":
            self._recoveries.append(
                {
                    k: rec.get(k)
                    for k in (
                        "fault", "action", "attempt", "from_round",
                        "lost_rounds", "error", "ts",
                    )
                }
            )
        elif event == "fault_injected":
            self._faults.append(
                {
                    k: rec.get(k)
                    for k in ("plan", "kind", "phase", "round", "ts")
                }
            )
        elif event == "recompile":
            changed = rec.get("changed")
            key = (rec.get("fn"), json.dumps(changed, sort_keys=True))
            self._recompiles.setdefault(
                key,
                {
                    "fn": rec.get("fn"),
                    "changed": changed,
                    "cross_process": rec.get("cross_process"),
                    "ts": rec.get("ts"),
                },
            )
        elif event == "health_snapshot":
            self._health.append(rec)
        elif event == "metrics_snapshot":
            shards = rec.get("metrics", {})
            for g in (
                "pipeline_shards",
                "pipeline_carry_bytes_per_shard",
                "scenario_plane_bytes_per_shard",
            ):
                snap = shards.get(g)
                if isinstance(snap, dict) and "value" in snap:
                    self._last_per_shard[g] = snap["value"]
        return True

    def _chain(self):
        """Sorted window chain + the contiguity verdict: the chained
        windows must cover [first lo, last hi) without a gap."""
        windows = sorted(self._windows.values(), key=lambda w: w["lo"])
        contiguous = bool(windows)
        pos = windows[0]["lo"] if windows else 0
        for w in windows:
            if w["lo"] != pos or not isinstance(w["hi"], int):
                contiguous = False
                break
            pos = w["hi"]
        return windows, contiguous, pos

    def summary(self) -> dict:
        windows, contiguous, end = self._chain()
        checkpoints = [
            self._checkpoints[r] for r in sorted(self._checkpoints)
        ]
        # Shard provenance: the newest checkpoint's layout is the
        # authoritative writing layout; the per-shard byte gauges ride
        # from the last metrics/health snapshot seen.
        layout = checkpoints[-1]["shard_layout"] if checkpoints else None
        lat = [
            w["latency_s"] for w in windows
            if isinstance(w.get("latency_s"), (int, float))
        ]
        timeline = sorted(
            [{"kind": "dispatch_window", **w} for w in windows]
            + [{"kind": "checkpoint", **c} for c in checkpoints]
            + [{"kind": "recovery", **r} for r in self._recoveries]
            + [
                # The injected fault's own "kind" (transient/fatal/...)
                # must not clobber the timeline entry kind.
                {
                    "kind": "fault",
                    "injected": f.get("kind"),
                    "phase": f.get("phase"),
                    "round": f.get("round"),
                    "plan": f.get("plan"),
                    "ts": f.get("ts"),
                }
                for f in self._faults
            ]
            + [{"kind": "recompile", **r} for r in self._recompiles.values()],
            key=lambda e: (
                e["ts"] if isinstance(e.get("ts"), (int, float)) else 0.0
            ),
        )
        return {
            "event": "flight_summary",
            "v": FLIGHT_SUMMARY_VERSION,
            "run_id": self.run_id,
            "rounds": [windows[0]["lo"], end] if windows else None,
            "contiguous": contiguous,
            "windows": len(windows),
            "checkpoints": checkpoints,
            "recoveries": self._recoveries,
            "faults": self._faults,
            "recompiles": list(self._recompiles.values()),
            "health_snapshots": len(self._health),
            "last_health": self._health[-1] if self._health else None,
            "shard_layout": layout,
            "per_shard": self._last_per_shard or None,
            "dispatch_latency_max_s": max(lat) if lat else None,
            "events": dict(sorted(self._events.items())),
            "timeline": timeline,
        }


def assemble_flight(jsonl_path: str, run_id: str | None = None):
    """Join one run's records out of a JSONL stream into a
    ``flight_summary`` dict (None when the file holds nothing for the
    run).  ``run_id=None`` selects the stream's LAST-seen run id — the
    run an operator tailing the file is looking at."""
    if run_id is None:
        for rec in _parse_jsonl(jsonl_path, needle='"run_id"'):
            rid = rec.get("run_id")
            if rid is not None and rec.get("event") != "flight_summary":
                run_id = rid  # keep scanning: last wins
    log = FlightLog(run_id)
    matched = 0
    # With a known run id, only that run's lines pay a json parse (the
    # id is a quoted value on every stamped record); an anonymous log
    # (no stamped records anywhere) parses in full.
    needle = f'"{run_id}"' if run_id is not None else None
    for rec in _parse_jsonl(jsonl_path, needle=needle):
        if log.add(rec):
            matched += 1
    if not matched:
        return None
    return log.summary()


def emit_flight_summary(sink=None, run_id: str | None = None):
    """Assemble the active sink's file-backed stream and append the
    ``flight_summary`` record to it — the scope OWNER's end-of-run
    duty.  A disabled or stderr-backed sink has no stream to join
    (nothing to read back), so this quietly returns None; recording a
    flight means pointing ``BA_TPU_METRICS`` (or ``bench --obs``) at a
    file.
    """
    sink = sink or _metrics.default_sink()
    # Sink-dir mode (ISSUE 19): the process's stream is its own SHARD,
    # not the directory — file_path() resolves it (None until the lazy
    # open, in which case nothing was ever written to assemble).
    if hasattr(sink, "file_path"):
        target = sink.file_path()
    else:
        target = getattr(sink, "target", None)
        if target == "-":
            target = None
    if not target or not os.path.exists(target):
        return None
    summary = assemble_flight(target, run_id=run_id)
    if summary is not None:
        sink.emit(summary)
    return summary
