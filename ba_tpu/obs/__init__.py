"""ba_tpu.obs — the unified observability layer.

Three parts, layered bottom-up (docs/DESIGN.md §8):

- **sink** (``ba_tpu.utils.metrics``): the versioned JSON-lines event
  stream — one record per event, ``BA_TPU_METRICS=<path|->`` enables.
- **registry** (``obs.registry``): typed counters / gauges /
  log-bucketed histograms aggregating in memory; snapshots into the sink
  as ``{"event": "metrics_snapshot", "v": 1, ...}`` and dumps
  Prometheus-style text on demand (REPL ``stats``; ``bench.py --obs``).
- **tracer** (``obs.trace``): thread-safe monotonic ring-buffer span
  tracing with Chrome trace-event export (Perfetto /
  ``chrome://tracing``), ``BA_TPU_TRACE`` enables.

Everything here is HOST-side and jax-free: spans and emissions must
never appear inside jitted or scanned bodies (``scripts/ci.sh`` lints
``ba_tpu/core`` and ``ba_tpu/ops`` for exactly that), and with both env
vars unset the layer writes no files and grows no buffers — the
overhead-guard tests in tests/test_obs.py pin it.
"""

from ba_tpu.obs import instrument, registry, trace
from ba_tpu.obs.instrument import (
    compile_or_dispatch_span,
    first_call,
    reset_first_calls,
    timed_span,
)
from ba_tpu.obs.registry import MetricsRegistry, default_registry
from ba_tpu.obs.trace import Tracer, default_tracer, instant, span

__all__ = [
    "MetricsRegistry",
    "Tracer",
    "compile_or_dispatch_span",
    "default_registry",
    "default_tracer",
    "first_call",
    "instant",
    "instrument",
    "registry",
    "reset_first_calls",
    "span",
    "timed_span",
    "trace",
]
