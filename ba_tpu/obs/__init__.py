"""ba_tpu.obs — the unified observability layer.

Three parts, layered bottom-up (docs/DESIGN.md §8):

- **sink** (``ba_tpu.utils.metrics``): the versioned JSON-lines event
  stream — one record per event, ``BA_TPU_METRICS=<path|->`` enables.
- **registry** (``obs.registry``): typed counters / gauges /
  log-bucketed histograms aggregating in memory; snapshots into the sink
  as ``{"event": "metrics_snapshot", "v": 1, ...}`` and dumps
  Prometheus-style text on demand (REPL ``stats``; ``bench.py --obs``).
- **tracer** (``obs.trace``): thread-safe monotonic ring-buffer span
  tracing with Chrome trace-event export (Perfetto /
  ``chrome://tracing``), ``BA_TPU_TRACE`` enables.
- **device tier** (``obs.xla``, docs/DESIGN.md §8): XLA artifact
  introspection (``compiled_artifact`` records with flops / bytes /
  donation-alias evidence, ``BA_TPU_HLO`` dumps), the recompile
  explainer (``obs.instrument.classify_compile`` → ``recompile``
  records), and the ``jax.profiler`` capture hook (``BA_TPU_XPROF``).
- **flight recorder** (``obs.flight``, ISSUE 9): one ``run_id`` per
  campaign run (``BA_TPU_RUN_ID`` pins; derivation is deterministic)
  threaded through every record/span/checkpoint-header/ledger-row,
  and the ``flight_summary`` assembler joining them into one
  correlated timeline.
- **health sampler** (``obs.health``, ISSUE 9): lock-free periodic
  sampling of the registry into a ``health_*`` gauge family, derived
  live metrics (rounds/s, retire-lag p50/p99, watchdog margin,
  per-shard imbalance) and ``health_snapshot`` records
  (``pipeline_sweep(health_every=)``; REPL ``stats --live``).
- **SLO engine** (``obs.slo``, ISSUE 17): streaming per-phase latency
  attribution and per-(cohort, tenant) error budgets over the request
  record stream; ``slo_report`` / ``slo_alert`` / ``autoscale_signal``
  records ride the health sampler's cadence (``BA_TPU_SLO`` installs a
  policy on the serving front-end).
- **fleet aggregation** (``obs.fleet``, ISSUE 19): cross-process causal
  tracing — (trace_id, span_id, parent_id) contexts flow through serve
  batches, sign-pool pipes and supervisor resumes; each process writes
  its own sink shard (``BA_TPU_METRICS=dir/``) with a ``clock_anchor``;
  ``obs.fleet`` merges shards, aligns clocks, and assembles per-request
  ``request_trace`` span trees plus the ``fleet_summary`` rollup
  (``scripts/obs_report.py --fleet``; REPL ``stats --fleet``).

Everything MODULE-LEVEL here is HOST-side and jax-free (``obs.xla``
imports jax only inside its opt-in functions): spans and emissions must
never appear inside jitted or scanned bodies (ba-lint BA301 checks the
``ba_tpu/core``/``ba_tpu/ops`` closure for exactly that), and with the
``BA_TPU_*`` env vars unset the layer writes no files, grows no
buffers, and triggers no extra compiles — the overhead-guard tests in
tests/test_obs.py and tests/test_obs_xla.py pin it.
"""

from ba_tpu.obs import (
    aotcache,
    flight,
    health,
    instrument,
    registry,
    trace,
    xla,
)
from ba_tpu.obs.instrument import (
    classify_compile,
    compile_or_dispatch_span,
    configure_compile_ledger,
    first_call,
    reset_first_calls,
    timed_span,
)
from ba_tpu.obs.registry import MetricsRegistry, default_registry
from ba_tpu.obs.trace import Tracer, default_tracer, instant, span


def __getattr__(name):
    # obs.slo loads lazily so its ``python -m ba_tpu.obs.slo`` CLI runs
    # without runpy's found-in-sys.modules warning (the package would
    # otherwise import the submodule before runpy executes it as
    # __main__).  Everything else stays eager.
    if name in ("slo", "fleet"):
        import importlib

        return importlib.import_module(f"ba_tpu.obs.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "MetricsRegistry",
    "Tracer",
    "aotcache",
    "classify_compile",
    "compile_or_dispatch_span",
    "configure_compile_ledger",
    "default_registry",
    "default_tracer",
    "first_call",
    "fleet",
    "flight",
    "health",
    "instant",
    "instrument",
    "registry",
    "reset_first_calls",
    "slo",
    "span",
    "timed_span",
    "trace",
    "xla",
]
