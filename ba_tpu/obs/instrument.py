"""Instrumentation glue: compile-vs-dispatch classification and shared
metric names.

jit hides compilation inside the first call of each (program, static
args, shapes) combination — there is no portable "was that a cache hit?"
callback, and through the persistent XLA cache
(``utils/platform.enable_compilation_cache``, ``BA_TPU_COMPILE_CACHE``)
a "compile" may be a disk read.  What IS observable, cheaply and
everywhere, is *first-call timing*: the first dispatch of a given static
key pays trace + compile (or cache load), every later one is a cached
dispatch.  ``first_call(key)`` is that classifier — a process-wide seen
set — and the callers (``parallel/pipeline.py``,
``runtime/backends.py``) name the surrounding span ``compile`` or
``dispatch`` accordingly and feed ``compile_time_s`` on the first hit.
With the persistent cache enabled the ``compile`` spans shrink to cache
loads, which is exactly the effect the cache A/B wants to see in the
trace.

Canonical metric names (so dashboards/tests never chase spellings):

- ``compile_time_s``               histogram, first-call latencies
- ``pipeline_dispatch_latency_s``  histogram, submit → retire per dispatch
- ``pipeline_retire_lag_s``        histogram, time blocked in the retire fetch
- ``pipeline_depth_occupancy``     histogram, in-flight dispatches (base=1)
- ``pipeline_dispatches_total`` / ``pipeline_retires_total``  counters
- ``round_wall_s``                 histogram, interactive round wall time
- ``host_sign_s``                  histogram, host signing batches
- ``elections_total`` / ``failover_kills_total``  counters
- ``recompiles_total``             counter, explained re-specializations
- ``compile_cache_enabled``        gauge, 0/1
- ``xla_introspect_s``             histogram, AOT artifact-harvest cost
- ``xla_<fn>_flops`` / ``_bytes_accessed`` / ``_temp_bytes`` /
  ``_alias_bytes``                 gauges, per-program cost/memory
  (``obs/xla.py`` artifact introspection)

The **recompile explainer** (ISSUE 4) extends ``first_call``: callers
that pass a NAMED ``axes`` signature (shapes/dtypes/capacity/depth/
static args as a dict) get more than a compile/dispatch phase — when a
function that already compiled once compiles AGAIN, the explainer diffs
the new signature against the previous one and emits a ``recompile``
instant plus a versioned ``{"event": "recompile", "v": 1, "fn": ...,
"changed": {axis: [old, new]}}`` JSONL record naming exactly the axis
that forced the re-specialization.  ``runtime/backends.py``'s
per-capacity re-specialization becomes attributable ("capacity: 4 ->
8") instead of a mysterious second ``compile`` span.
"""

from __future__ import annotations

import contextlib
import threading
import time

_seen: set = set()
_seen_lock = threading.Lock()
_last_axes: dict = {}  # fn name -> axes dict of its most recent compile


def first_call(key) -> bool:
    """True exactly once per hashable ``key`` per process.

    The compile-vs-cached-dispatch classifier: key on the static
    arguments + input shapes that force a fresh jit specialization.
    """
    with _seen_lock:
        if key in _seen:
            return False
        _seen.add(key)
        return True


def _freeze(value):
    """A hashable, order-stable form of an axes value (dicts/lists from
    callers become tuples; everything else is already hashable)."""
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    return value


def classify_compile(fn: str, axes: dict):
    """``(first_call, changed)`` for one named compile signature.

    ``first_call`` is True exactly once per (fn, axes) — the same
    classification :func:`first_call` gives, keyed on the caller's named
    signature instead of an opaque tuple.  ``changed`` is non-None only
    on a RE-compile (fn seen before under a different signature): a
    ``{axis: [previous, new]}`` diff against the function's most recent
    compile, the explainer's payload.
    """
    key = (fn, _freeze(axes))
    with _seen_lock:
        if key in _seen:
            return False, None
        _seen.add(key)
        prev = _last_axes.get(fn)
        _last_axes[fn] = dict(axes)
    if prev is None:
        return True, None
    changed = {
        k: [prev.get(k), axes[k]]
        for k in axes
        if prev.get(k) != axes.get(k)
    }
    return True, changed or None


def reset_first_calls() -> None:
    """Forget all seen keys and signatures (tests that pin ``compile``
    span / ``recompile`` record emission)."""
    with _seen_lock:
        _seen.clear()
        _last_axes.clear()


class TimedBox:
    """Yielded by ``timed_span``; ``elapsed_s`` is set when the span
    closes, for callers that also need the scalar (JSONL records)."""

    __slots__ = ("elapsed_s",)

    def __init__(self):
        self.elapsed_s = None


@contextlib.contextmanager
def timed_span(name: str, histogram=None, **attrs):
    """One clock window feeding BOTH a span and a latency histogram.

    ``histogram`` is a registry ``Histogram`` or a metric name resolved
    on the default registry (None = span only).  The single spelling for
    every span-plus-histogram site (host signing, interactive rounds,
    pipeline retires), so the two windows can never drift apart.
    """
    from ba_tpu.obs import registry, trace

    if isinstance(histogram, str):
        histogram = registry.default_registry().histogram(histogram)
    box = TimedBox()
    t0 = time.perf_counter()
    try:
        with trace.default_tracer().span(name, **attrs):
            yield box
    finally:
        box.elapsed_s = time.perf_counter() - t0
        if histogram is not None:
            histogram.record(box.elapsed_s)


@contextlib.contextmanager
def compile_or_dispatch_span(key, axes=None, **attrs):
    """Span a jitted call as ``compile`` (first call of ``key``) or
    ``dispatch`` (cached), yielding the chosen phase name.

    The single spelling of the classification for every instrumented jit
    site (``parallel/pipeline.py``, ``runtime/backends.py``): first hits
    additionally record their latency into the ``compile_time_s``
    histogram.  The span measures host-side time only — for an async
    dispatch that is trace + compile (or persistent-cache load) on the
    first call and just the enqueue afterwards.

    ``axes`` opts into the recompile explainer: a dict naming the static
    signature (shapes, capacity, depth, flags...).  Classification then
    keys on ``(key's function name, axes)`` and a re-specialization of a
    previously-compiled function emits the ``recompile`` instant +
    JSONL record with the per-axis diff (module docstring).
    """
    from ba_tpu.obs import registry, trace

    if axes is None:
        phase = "compile" if first_call(key) else "dispatch"
        changed = None
        fn = None
    else:
        fn = key[0] if isinstance(key, tuple) and key else str(key)
        first, changed = classify_compile(fn, axes)
        phase = "compile" if first else "dispatch"
    t0 = time.perf_counter()
    with trace.default_tracer().span(phase, **attrs):
        yield phase
    if phase == "compile":
        registry.default_registry().histogram("compile_time_s").record(
            time.perf_counter() - t0
        )
        if changed:
            _emit_recompile(fn, axes, changed)


def _emit_recompile(fn: str, axes: dict, changed: dict) -> None:
    """One ``recompile`` instant + versioned JSONL record naming the
    axis/axes whose change forced the re-specialization."""
    from ba_tpu.obs import registry, trace
    from ba_tpu.utils import metrics

    registry.default_registry().counter("recompiles_total").inc()
    trace.default_tracer().instant(
        "recompile", fn=fn, changed=",".join(sorted(changed))
    )
    metrics.emit(
        {
            "event": "recompile",
            "v": metrics.SCHEMA_VERSION,
            "fn": fn,
            "changed": changed,
            "axes": dict(axes),
        }
    )


def report_compile_cache(path: str | None) -> None:
    """Record the persistent-cache decision (called by
    ``utils/platform.enable_compilation_cache``): gauge 0/1 plus an
    instant trace marker carrying the directory when enabled."""
    from ba_tpu.obs import registry, trace

    registry.default_registry().gauge("compile_cache_enabled").set(
        0 if path is None else 1
    )
    if path is not None:
        trace.default_tracer().instant("compile_cache_enabled", dir=path)
