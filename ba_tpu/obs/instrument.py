"""Instrumentation glue: compile-vs-dispatch classification and shared
metric names.

jit hides compilation inside the first call of each (program, static
args, shapes) combination — there is no portable "was that a cache hit?"
callback, and through the persistent XLA cache
(``utils/platform.enable_compilation_cache``, ``BA_TPU_COMPILE_CACHE``)
a "compile" may be a disk read.  What IS observable, cheaply and
everywhere, is *first-call timing*: the first dispatch of a given static
key pays trace + compile (or cache load), every later one is a cached
dispatch.  ``first_call(key)`` is that classifier — a process-wide seen
set — and the callers (``parallel/pipeline.py``,
``runtime/backends.py``) name the surrounding span ``compile`` or
``dispatch`` accordingly and feed ``compile_time_s`` on the first hit.
With the persistent cache enabled the ``compile`` spans shrink to cache
loads, which is exactly the effect the cache A/B wants to see in the
trace.

Canonical metric names (so dashboards/tests never chase spellings):

- ``compile_time_s``               histogram, first-call latencies
- ``pipeline_dispatch_latency_s``  histogram, submit → retire per dispatch
- ``pipeline_retire_lag_s``        histogram, time blocked in the retire fetch
- ``pipeline_depth_occupancy``     histogram, in-flight dispatches (base=1)
- ``pipeline_dispatches_total`` / ``pipeline_retires_total``  counters
- ``round_wall_s``                 histogram, interactive round wall time
- ``host_sign_s``                  histogram, host signing batches
- ``elections_total`` / ``failover_kills_total``  counters
- ``recompiles_total``             counter, explained re-specializations
- ``compile_cache_enabled``        gauge, 0/1
- ``xla_introspect_s``             histogram, AOT artifact-harvest cost
- ``xla_<fn>_flops`` / ``_bytes_accessed`` / ``_temp_bytes`` /
  ``_alias_bytes``                 gauges, per-program cost/memory
  (``obs/xla.py`` artifact introspection)
- ``pipeline_shards``              gauge, mesh data-axis device count
  of the last sweep (1 = single device)
- ``pipeline_carry_bytes_per_shard`` / ``scenario_plane_bytes_per_shard``
  gauges, ONE device's share of the donated carry / staged event chunk
  (the ISSUE 8 weak-scaling denominators; sharded leaves count by
  their local shard, replicated leaves in full)

The **recompile explainer** (ISSUE 4) extends ``first_call``: callers
that pass a NAMED ``axes`` signature (shapes/dtypes/capacity/depth/
static args as a dict) get more than a compile/dispatch phase — when a
function that already compiled once compiles AGAIN, the explainer diffs
the new signature against the previous one and emits a ``recompile``
instant plus a versioned ``{"event": "recompile", "v": 1, "fn": ...,
"changed": {axis: [old, new]}}`` JSONL record naming exactly the axis
that forced the re-specialization.  ``runtime/backends.py``'s
per-capacity re-specialization becomes attributable ("capacity: 4 ->
8") instead of a mysterious second ``compile`` span.

The **cross-run ledger** (ISSUE 6) closes the explainer's blind spot:
the first compile of a session had nothing to diff against, so "why did
a warm persistent cache still compile?" went unexplained.  When the
persistent XLA cache is on, ``utils/platform.enable_compilation_cache``
calls :func:`configure_compile_ledger` with a JSON file NEXT TO the
cache (``<cache_dir>/ba_tpu_axes_ledger.json``) plus process-constant
environment axes (jax/jaxlib versions).  Each fn's most recent compile
signature (axes ∪ env) is written through to the ledger, and a
first-compile-of-the-session whose signature differs from the PREVIOUS
process's emits a ``recompile`` record with ``"cross_process": true``
— "recompiled because jaxlib_version changed" is now a row, not a
mystery.  No cache, no ledger (``BA_TPU_COMPILE_LEDGER=0`` also
disables it; the test suite does, so ledger state never leaks between
test processes).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

_seen: set = set()
_seen_lock = threading.Lock()
_last_axes: dict = {}  # fn name -> axes dict of its most recent compile

# Cross-run ledger state (configure_compile_ledger).  _ledger_prev holds
# the PREVIOUS process's per-fn signature LISTS — every specialization
# that process compiled, not just the last one, so a fn that legitimately
# compiles at capacity 4 then 8 every session does not read as a
# cross-process change each time (read once at configure); _ledger_cur
# accumulates this process's, and the file always holds the merge — fns
# this process never compiled keep their old rows.
_ledger_lock = threading.Lock()
_ledger_path: str | None = None
_ledger_env: dict = {}
_ledger_prev: dict = {}
_ledger_cur: dict = {}


def first_call(key) -> bool:
    """True exactly once per hashable ``key`` per process.

    The compile-vs-cached-dispatch classifier: key on the static
    arguments + input shapes that force a fresh jit specialization.
    """
    with _seen_lock:
        if key in _seen:
            return False
        _seen.add(key)
        return True


def _freeze(value):
    """A hashable, order-stable form of an axes value (dicts/lists from
    callers become tuples; everything else is already hashable)."""
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    return value


def configure_compile_ledger(path: str | None, env_axes: dict | None = None):
    """Point the cross-run ledger at ``path`` (None disables).

    Loads the previous process's per-fn signatures from ``path`` when it
    exists (unreadable/corrupt files start fresh — the ledger is
    forensics, never a correctness dependency).  ``env_axes`` are
    process-constant axes (jax/jaxlib versions) merged into every
    stored signature, so a toolchain bump shows up as the changed axis.
    """
    global _ledger_path, _ledger_env, _ledger_prev, _ledger_cur
    with _ledger_lock:
        _ledger_path = path or None
        _ledger_env = dict(env_axes or {})
        _ledger_prev, _ledger_cur = {}, {}
        if path:
            _ledger_prev = _read_ledger_file(path)


def _read_ledger_file(path: str) -> dict:
    """Parse a ledger file into ``{fn: [signature, ...]}`` — unreadable
    / corrupt / wrong-version files read as empty (the ledger is
    forensics, never a correctness dependency)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
        if doc.get("v") == 1 and isinstance(doc.get("fns"), dict):
            return {
                fn: sigs
                for fn, sigs in doc["fns"].items()
                if isinstance(sigs, list)
                and all(isinstance(s, dict) for s in sigs)
            }
    except (OSError, ValueError):
        pass
    return {}


def ledger_signatures() -> dict:
    """``{fn: [signature, ...]}`` — the cross-run ledger's accumulated
    compile signatures: the previous processes' union plus this
    session's, each row still carrying its env axes (and ``run_id``
    rider).  The warmup pass (ISSUE 11) replays this set through the
    executable cache so a restarted service pre-compiles exactly the
    specializations real traffic reached before.  Empty when no ledger
    is configured (``BA_TPU_COMPILE_LEDGER=0``, or no persistent cache).
    """
    with _ledger_lock:
        if _ledger_path is None:
            return {}
        fns = {f: [dict(s) for s in sigs] for f, sigs in _ledger_prev.items()}
        for f, cur in _ledger_cur.items():
            rows = fns.setdefault(f, [])
            rows.extend(dict(s) for s in cur if not _sig_in(s, rows))
        return fns


def ledger_env_axes() -> dict:
    """The configured process-constant env axes (jax/jaxlib versions) —
    what :func:`ledger_signatures` rows must match to be reproducible by
    THIS process's toolchain (the warmup replay filter)."""
    with _ledger_lock:
        return dict(_ledger_env)


def note_ledger(fn: str, axes: dict) -> None:
    """Store one compile signature into the cross-run ledger WITHOUT
    touching the jit first-call classifier (ISSUE 11).

    The executable cache records its AOT compilations here so the next
    process's warmup replays them — but an AOT ``.compile()`` never
    populates jit's executable cache, so marking the signature ``seen``
    (what :func:`classify_compile` does) would make a LATER jit dispatch
    of the same signature read as a cached ``dispatch`` while silently
    paying a real request-path compile.  The ledger row and the
    classifier mark are separate concerns; this writes only the former.
    No-op when no ledger is configured."""
    with _ledger_lock:
        if _ledger_path is None:
            return
        _ledger_store_locked(fn, {**axes, **_ledger_env})


def _sig_core(sig: dict) -> dict:
    """A ledger row minus its ``run_id`` rider — the comparable compile
    signature.  The rider is provenance (which campaign's first compile
    stored the row, ISSUE 9), never identity: comparing WITH it would
    make every new run re-store — and mis-diff — rows whose axes never
    changed."""
    return {k: v for k, v in sig.items() if k != "run_id"}


def _sig_in(sig: dict, rows) -> bool:
    core = _sig_core(sig)
    return any(_sig_core(r) == core for r in rows)


def _ledger_store_locked(fn: str, signature: dict) -> None:
    """Append ``signature`` to the fn's session list and write through
    (atomic rewrite; one small JSON per compile — compiles are rare and
    already slow).  CALLER HOLDS ``_ledger_lock`` — signature
    construction and the store must share one acquisition, or a
    concurrent ``configure_compile_ledger`` (REPL re-init) between them
    would write an old generation's env axes into the new ledger file.

    The file holds the UNION of the previous process's
    list and this session's, in first-compile order: a session that dies
    before replaying every specialization must not shrink the ledger, or
    the next full session would read the missing tail as a cross-process
    change.  Signatures a toolchain bump obsoletes linger, harmlessly —
    their env axes can never match again.

    CONCURRENT processes sharing one cache dir (the default outside the
    test suite) each rewrite the whole file, so the on-disk rows are
    re-read and merged under the lock right before the replace: a
    configure-time snapshot alone would let process B's first write
    erase every row A stored since B started — and the next session
    would then mis-report A's specializations as cross-process
    recompiles.  The read→replace window is still racy, but it is
    microseconds per rare compile, not the life of the session."""
    global _ledger_path
    from ba_tpu.utils import metrics as _metrics

    row_sig = dict(signature)
    rid = _metrics.active_run_id()
    if rid is not None:
        # Run provenance (ISSUE 9): the campaign whose first compile of
        # this signature stored the row.  A rider, not an axis — every
        # membership/diff comparison strips it (_sig_core).
        row_sig["run_id"] = rid
    sigs = _ledger_cur.setdefault(fn, [])
    if not _sig_in(row_sig, sigs):
        sigs.append(row_sig)
    fns = {f: list(s) for f, s in _ledger_prev.items()}
    for f, disk in _read_ledger_file(_ledger_path).items():
        row = fns.setdefault(f, [])
        row.extend(s for s in disk if not _sig_in(s, row))
    for f, cur in _ledger_cur.items():
        row = fns.setdefault(f, [])
        row.extend(s for s in cur if not _sig_in(s, row))
    doc = {"v": 1, "fns": fns}
    tmp = f"{_ledger_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, _ledger_path)
    except OSError:
        # Forensics only: an unwritable ledger dir silently turns
        # the feature off rather than failing a compile.
        _ledger_path = None
        try:
            os.remove(tmp)
        except OSError:
            pass


def classify_compile(fn: str, axes: dict):
    """``(first_call, changed, cross_process)`` for one named compile
    signature.

    ``first_call`` is True exactly once per (fn, axes) — the same
    classification :func:`first_call` gives, keyed on the caller's named
    signature instead of an opaque tuple.  ``changed`` is non-None only
    on an EXPLAINED compile: an in-process re-specialization (fn seen
    before under a different signature) or — with the cross-run ledger
    configured — a first-compile-of-the-session whose signature matches
    NONE of the previous process's specializations of the fn, in which
    case ``cross_process`` is True (a fn that recompiles at the same
    several capacities every session stays silent).  Either way it is a
    ``{axis: [previous, new]}`` diff — against the fn's most recent
    compile in-process, against the previous process's last-compiled
    signature cross-process — the explainer's payload.
    """
    key = (fn, _freeze(axes))
    with _seen_lock:
        if key in _seen:
            return False, None, False
        _seen.add(key)
        prev = _last_axes.get(fn)
        _last_axes[fn] = dict(axes)
    # Signature construction, the prior snapshot, AND the store share
    # one lock acquisition: a concurrent configure_compile_ledger (REPL
    # re-init) swaps path/env/prev together under the lock, and mixing
    # generations — or storing a signature built from the old env into
    # the newly configured file — would emit a spurious cross-process
    # diff (or drop a real one).
    with _ledger_lock:
        ledgered = _ledger_path is not None
        prior = _ledger_prev.get(fn) if ledgered else None
        if ledgered:
            signature = {**axes, **_ledger_env}
            _ledger_store_locked(fn, signature)
    if prev is None:
        if ledgered and prior and not _sig_in(signature, prior):
            # Diff against the CLOSEST prior signature (fewest differing
            # axes; most recent wins ties), not blindly prior[-1]: a fn
            # the previous process compiled at capacities 4 and 8 that
            # recompiles at capacity 4 after a toolchain bump should
            # read "jaxlib changed", not "capacity 8 -> 4 and jaxlib
            # changed" — naming an axis that did not force anything
            # defeats the explainer.
            def diff_against(baseline):
                return {
                    k: [baseline.get(k), signature.get(k)]
                    for k in {*baseline, *signature}
                    if baseline.get(k) != signature.get(k)
                }

            changed = min(  # reversed: min keeps the first, i.e. newest
                (diff_against(_sig_core(b)) for b in reversed(prior)),
                key=len,
            )
            if changed:
                return True, changed, True
        return True, None, False
    changed = {
        k: [prev.get(k), axes[k]]
        for k in axes
        if prev.get(k) != axes.get(k)
    }
    return True, changed or None, False


def reset_first_calls() -> None:
    """Forget all seen keys and signatures (tests that pin ``compile``
    span / ``recompile`` record emission)."""
    with _seen_lock:
        _seen.clear()
        _last_axes.clear()


class TimedBox:
    """Yielded by ``timed_span``; ``elapsed_s`` is set when the span
    closes, for callers that also need the scalar (JSONL records)."""

    __slots__ = ("elapsed_s",)

    def __init__(self):
        self.elapsed_s = None


@contextlib.contextmanager
def timed_span(name: str, histogram=None, **attrs):
    """One clock window feeding BOTH a span and a latency histogram.

    ``histogram`` is a registry ``Histogram`` or a metric name resolved
    on the default registry (None = span only).  The single spelling for
    every span-plus-histogram site (host signing, interactive rounds,
    pipeline retires), so the two windows can never drift apart.
    """
    from ba_tpu.obs import registry, trace

    if isinstance(histogram, str):
        histogram = registry.default_registry().histogram(histogram)
    box = TimedBox()
    t0 = time.perf_counter()
    try:
        with trace.default_tracer().span(name, **attrs):
            yield box
    finally:
        box.elapsed_s = time.perf_counter() - t0
        if histogram is not None:
            histogram.record(box.elapsed_s)


@contextlib.contextmanager
def compile_or_dispatch_span(key, axes=None, **attrs):
    """Span a jitted call as ``compile`` (first call of ``key``) or
    ``dispatch`` (cached), yielding the chosen phase name.

    The single spelling of the classification for every instrumented jit
    site (``parallel/pipeline.py``, ``runtime/backends.py``): first hits
    additionally record their latency into the ``compile_time_s``
    histogram.  The span measures host-side time only — for an async
    dispatch that is trace + compile (or persistent-cache load) on the
    first call and just the enqueue afterwards.

    ``axes`` opts into the recompile explainer: a dict naming the static
    signature (shapes, capacity, depth, flags...).  Classification then
    keys on ``(key's function name, axes)`` and a re-specialization of a
    previously-compiled function emits the ``recompile`` instant +
    JSONL record with the per-axis diff (module docstring).
    """
    from ba_tpu.obs import registry, trace

    if axes is None:
        phase = "compile" if first_call(key) else "dispatch"
        changed = None
        fn = None
        cross = False
    else:
        fn = key[0] if isinstance(key, tuple) and key else str(key)
        first, changed, cross = classify_compile(fn, axes)
        phase = "compile" if first else "dispatch"
    t0 = time.perf_counter()
    with trace.default_tracer().span(phase, **attrs):
        yield phase
    if phase == "compile":
        registry.default_registry().histogram("compile_time_s").record(
            time.perf_counter() - t0
        )
        if changed:
            _emit_recompile(fn, axes, changed, cross)


def _emit_recompile(
    fn: str, axes: dict, changed: dict, cross_process: bool = False
) -> None:
    """One ``recompile`` instant + versioned JSONL record naming the
    axis/axes whose change forced the re-specialization.
    ``cross_process`` marks ledger-explained first-compiles of the
    session (diffed against the previous process, ISSUE 6)."""
    from ba_tpu.obs import registry, trace
    from ba_tpu.utils import metrics

    registry.default_registry().counter("recompiles_total").inc()
    trace.default_tracer().instant(
        "recompile",
        fn=fn,
        changed=",".join(sorted(changed)),
        cross_process=cross_process,
    )
    metrics.emit(
        {
            "event": "recompile",
            "v": metrics.SCHEMA_VERSION,
            "fn": fn,
            "changed": changed,
            "axes": dict(axes),
            "cross_process": cross_process,
        }
    )


def report_compile_cache(path: str | None) -> None:
    """Record the persistent-cache decision (called by
    ``utils/platform.enable_compilation_cache``): gauge 0/1 plus an
    instant trace marker carrying the directory when enabled."""
    from ba_tpu.obs import registry, trace

    registry.default_registry().gauge("compile_cache_enabled").set(
        0 if path is None else 1
    )
    if path is not None:
        trace.default_tracer().instant("compile_cache_enabled", dir=path)
