"""Instrumentation glue: compile-vs-dispatch classification and shared
metric names.

jit hides compilation inside the first call of each (program, static
args, shapes) combination — there is no portable "was that a cache hit?"
callback, and through the persistent XLA cache
(``utils/platform.enable_compilation_cache``, ``BA_TPU_COMPILE_CACHE``)
a "compile" may be a disk read.  What IS observable, cheaply and
everywhere, is *first-call timing*: the first dispatch of a given static
key pays trace + compile (or cache load), every later one is a cached
dispatch.  ``first_call(key)`` is that classifier — a process-wide seen
set — and the callers (``parallel/pipeline.py``,
``runtime/backends.py``) name the surrounding span ``compile`` or
``dispatch`` accordingly and feed ``compile_time_s`` on the first hit.
With the persistent cache enabled the ``compile`` spans shrink to cache
loads, which is exactly the effect the cache A/B wants to see in the
trace.

Canonical metric names (so dashboards/tests never chase spellings):

- ``compile_time_s``               histogram, first-call latencies
- ``pipeline_dispatch_latency_s``  histogram, submit → retire per dispatch
- ``pipeline_retire_lag_s``        histogram, time blocked in the retire fetch
- ``pipeline_depth_occupancy``     histogram, in-flight dispatches (base=1)
- ``pipeline_dispatches_total`` / ``pipeline_retires_total``  counters
- ``round_wall_s``                 histogram, interactive round wall time
- ``host_sign_s``                  histogram, host signing batches
- ``elections_total`` / ``failover_kills_total``  counters
- ``compile_cache_enabled``        gauge, 0/1
"""

from __future__ import annotations

import contextlib
import threading
import time

_seen: set = set()
_seen_lock = threading.Lock()


def first_call(key) -> bool:
    """True exactly once per hashable ``key`` per process.

    The compile-vs-cached-dispatch classifier: key on the static
    arguments + input shapes that force a fresh jit specialization.
    """
    with _seen_lock:
        if key in _seen:
            return False
        _seen.add(key)
        return True


def reset_first_calls() -> None:
    """Forget all seen keys (tests that pin ``compile`` span emission)."""
    with _seen_lock:
        _seen.clear()


class TimedBox:
    """Yielded by ``timed_span``; ``elapsed_s`` is set when the span
    closes, for callers that also need the scalar (JSONL records)."""

    __slots__ = ("elapsed_s",)

    def __init__(self):
        self.elapsed_s = None


@contextlib.contextmanager
def timed_span(name: str, histogram=None, **attrs):
    """One clock window feeding BOTH a span and a latency histogram.

    ``histogram`` is a registry ``Histogram`` or a metric name resolved
    on the default registry (None = span only).  The single spelling for
    every span-plus-histogram site (host signing, interactive rounds,
    pipeline retires), so the two windows can never drift apart.
    """
    from ba_tpu.obs import registry, trace

    if isinstance(histogram, str):
        histogram = registry.default_registry().histogram(histogram)
    box = TimedBox()
    t0 = time.perf_counter()
    try:
        with trace.default_tracer().span(name, **attrs):
            yield box
    finally:
        box.elapsed_s = time.perf_counter() - t0
        if histogram is not None:
            histogram.record(box.elapsed_s)


@contextlib.contextmanager
def compile_or_dispatch_span(key, **attrs):
    """Span a jitted call as ``compile`` (first call of ``key``) or
    ``dispatch`` (cached), yielding the chosen phase name.

    The single spelling of the classification for every instrumented jit
    site (``parallel/pipeline.py``, ``runtime/backends.py``): first hits
    additionally record their latency into the ``compile_time_s``
    histogram.  The span measures host-side time only — for an async
    dispatch that is trace + compile (or persistent-cache load) on the
    first call and just the enqueue afterwards.
    """
    from ba_tpu.obs import registry, trace

    phase = "compile" if first_call(key) else "dispatch"
    t0 = time.perf_counter()
    with trace.default_tracer().span(phase, **attrs):
        yield phase
    if phase == "compile":
        registry.default_registry().histogram("compile_time_s").record(
            time.perf_counter() - t0
        )


def report_compile_cache(path: str | None) -> None:
    """Record the persistent-cache decision (called by
    ``utils/platform.enable_compilation_cache``): gauge 0/1 plus an
    instant trace marker carrying the directory when enabled."""
    from ba_tpu.obs import registry, trace

    registry.default_registry().gauge("compile_cache_enabled").set(
        0 if path is None else 1
    )
    if path is not None:
        trace.default_tracer().instant("compile_cache_enabled", dir=path)
