"""Batched GF(2^255 - 19) arithmetic in int32 lanes.

TPUs have no 64-bit integer units, so a field element is 22 signed 12-bit
limbs in int32 lanes: value = sum(limb[i] * 2**(12*i)), shape [..., 22].
The bounds work out exactly for int32:

- normalized limbs are in [0, 4096); add/sub leave limbs in (-8192, 8192)
  without carrying;
- schoolbook multiply of two such values is a 43-limb convolution whose
  terms are at most 22 * 8191^2 < 1.48e9 < 2^31 — no overflow;
- the convolution is one [.., 484] x [484, 43] matmul against a static 0/1
  anti-diagonal matrix, so the hot op is a single fused dot per field mul
  instead of an unrolled 484-term scalar loop (compiler-friendly: the trace
  stays tiny and XLA tiles the dot).

Reduction folds limbs >= 22 back with 2^264 = 19 * 2^9 (mod p); carries use
arithmetic shifts so negative intermediates (from sub) flow through without
a borrow pass.  Exponentiation (inverse, sqrt) is a lax.scan over exponent
bits — compiled once, no data-dependent Python control flow.

No counterpart exists in the reference (/root/reference/ba.py has no
crypto); this implements the BASELINE.json north-star's batched Ed25519.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

BITS = 12
LIMBS = 22
MASK = (1 << BITS) - 1
P_INT = 2**255 - 19
# 2^(12*22) = 2^264 = 2^9 * 2^255 ≡ 19 * 2^9 (mod p)
FOLD = 19 << (BITS * LIMBS - 255)

# Static anti-diagonal scatter matrix: conv[k] = sum_{i+j=k} a[i]*b[j].
_CONV = np.zeros((LIMBS * LIMBS, 2 * LIMBS - 1), np.int32)
for _i in range(LIMBS):
    for _j in range(LIMBS):
        _CONV[_i * LIMBS + _j, _i + _j] = 1


def _np_limbs(v: int) -> np.ndarray:
    out = np.zeros(LIMBS, np.int32)
    for i in range(LIMBS):
        out[i] = v & MASK
        v >>= BITS
    assert v == 0
    return out


def constant(v: int) -> jnp.ndarray:
    """Static field constant as a [LIMBS] limb vector."""
    return jnp.asarray(_np_limbs(v % P_INT))


def zeros(shape) -> jnp.ndarray:
    return jnp.zeros((*shape, LIMBS), jnp.int32)


def _fold_pass(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass on [..., 22]: every limb's carry moves up one
    limb in a single vector shift; limb 21's carry wraps to limb 0 * FOLD.

    Arithmetic (floor) shifts make this exact for negative limbs: for any
    int32 v, v == (v >> 12) * 4096 + (v & 4095), so the remainder is always
    in [0, 4096) and negative values ride the (possibly negative) carries.
    """
    c = x >> BITS
    r = x - (c << BITS)
    up = jnp.concatenate([c[..., -1:] * FOLD, c[..., :-1]], axis=-1)
    return r + up


def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce [..., LIMBS] to multiply-safe "carried" form.

    Contract (stress-tested in tests/test_crypto.py): for inputs whose
    limbs are bounded by ~4.4e7 (a folded convolution; lazy add/sub values
    are far smaller), five parallel passes settle limbs 1..21 into
    (-16, 4097) and limb 0 into (-9728, 13824) — the wrap-around fold can
    leave one FOLD-sized surplus (or deficit for negative values).  One
    lazy add/sub of two carried values then keeps |limb 0| < 27652 and the
    rest below 8192, so the schoolbook convolution of two such operands
    peaks below 1.9e9 — inside int32.  Exact normalization only happens in
    canonical().
    """
    for _ in range(5):
        x = _fold_pass(x)
    return x


def _reduce_wide(c: jnp.ndarray) -> jnp.ndarray:
    """Reduce a 43-limb convolution (|terms| <= ~1.8e9) to carried form."""
    # Two growing no-fold passes tame the raw sums so that the fold
    # products below stay inside int32: after them limbs sit in [0, 4096)
    # except for carry residue at positions 43 (< 4200) and 44 (< 100).
    w = c
    for _ in range(2):
        cr = w >> BITS
        r = w - (cr << BITS)
        w = jnp.concatenate([r, jnp.zeros_like(r[..., :1])], axis=-1)
        w = w.at[..., 1:].add(cr)
    # Positions 22..43 fold to 0..21 via 2^264 ≡ 19*2^9; position 44 is
    # 2^(12*44) = (2^264)^2 * 2^(12*0)... folded twice: 19^2 * 2^18 =
    # 361 * 2^6 at limb 1.  Peak addend ~4.1e7 — int32-safe.
    lo = w[..., :LIMBS] + w[..., LIMBS : 2 * LIMBS] * FOLD
    lo = lo.at[..., 1].add(w[..., 2 * LIMBS] * (361 << 6))
    return carry(lo)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply: [..., 22] x [..., 22] -> [..., 22] normalized."""
    a, b = jnp.broadcast_arrays(a, b)
    outer = a[..., :, None] * b[..., None, :]
    flat = outer.reshape(*outer.shape[:-2], LIMBS * LIMBS)
    conv = jnp.matmul(flat, jnp.asarray(_CONV))
    return _reduce_wide(conv)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy add: limbs may leave [0, 4096) but stay multiply-safe."""
    return a + b


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy subtract: limbs may go negative; carry()/mul() handle it."""
    return a - b


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small positive int.

    Safe bound: ``a`` may be in carried form, whose limbs reach ~13824
    (see ``carry``'s input contract), so ``k * 13824`` must stay within
    carry()'s ~4.4e7 input bound — i.e. k <= ~3000.  Asserted statically;
    only tiny k (2) is used today.
    """
    assert 0 < k <= 3000, f"mul_small: k={k} exceeds carry()'s input bound"
    return carry(a * k)


def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a**e for a static exponent, as a lax.scan over e's bits (LSB first)."""
    nbits = e.bit_length()
    bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], jnp.int32)
    one = jnp.broadcast_to(constant(1), a.shape)

    def step(state, bit):
        result, base = state
        result = jnp.where((bit == 1)[..., None], mul(result, base), result)
        return (result, square(base)), None

    (result, _), _ = jax.lax.scan(step, (one, carry(a)), bits)
    return result


def inv(a: jnp.ndarray) -> jnp.ndarray:
    return pow_const(a, P_INT - 2)


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to the canonical representative in [0, p).

    Input may be any multiply-safe lazy value (even negative); output limbs
    are the unique encoding of the value in [0, p), every limb in [0, 4096).
    """
    # Carried form encodes a value in (-2^20, 2^264); +16p clears the
    # negative edge without leaving 22 limbs.
    a = carry(carry(a) + jnp.asarray(_np_limbs(16 * P_INT)))
    # Squash bits 256+ : 2^256 ≡ 38 (mod p).  Two rounds bring the value
    # under 2^256 + small; a third pass settles limb 0's surplus.
    for _ in range(3):
        top = a[..., LIMBS - 1] >> 4
        a = a.at[..., LIMBS - 1].add(-(top << 4))
        a = a.at[..., 0].add(top * 38)
        a = _fold_pass(a)
    # Value now in [0, 2p + small): subtract p while >= p, at most 3 times.
    p_limbs = jnp.asarray(_np_limbs(P_INT))
    for _ in range(3):
        diff = a - p_limbs
        # diff >= 0 iff the borrow chain's final carry is >= 0.
        borrow = jnp.zeros_like(diff[..., 0])
        limbs = []
        for i in range(LIMBS):
            v = diff[..., i] + borrow
            limbs.append(v & MASK)
            borrow = v >> BITS
        ge = borrow >= 0
        reduced = jnp.stack(limbs, axis=-1)
        a = jnp.where(ge[..., None], reduced, a)
    # Exact final chain: the value is in [0, p) with nonnegative limbs that
    # may individually touch 4096; one sequential pass normalizes bitwise
    # (canonical() is rare — equality tests and byte encoding only).
    c = jnp.zeros_like(a[..., 0])
    limbs = []
    for i in range(LIMBS):
        v = a[..., i] + c
        limbs.append(v & MASK)
        c = v >> BITS
    return jnp.stack(limbs, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Canonical equality: [...] bool."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


# -- byte/bit conversions (little-endian, RFC 8032 layout) -------------------


def from_bytes(by: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., 32] little-endian -> limbs [..., 22] (top bit included;
    callers mask bit 255 themselves where the encoding steals it)."""
    bits = bytes_to_bits(by)  # [..., 256]
    pad = jnp.zeros((*bits.shape[:-1], BITS * LIMBS - 256), bits.dtype)
    bits = jnp.concatenate([bits, pad], axis=-1)
    grouped = bits.reshape(*bits.shape[:-1], LIMBS, BITS).astype(jnp.int32)
    weights = jnp.asarray([1 << i for i in range(BITS)], jnp.int32)
    return jnp.einsum("...lb,b->...l", grouped, weights)


def to_bytes(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical little-endian encoding: limbs [..., 22] -> uint8 [..., 32]."""
    a = canonical(a)
    shifts = jnp.arange(BITS, dtype=jnp.int32)
    bits = (a[..., :, None] >> shifts) & 1  # [..., 22, 12]
    bits = bits.reshape(*a.shape[:-1], BITS * LIMBS)[..., :256]
    return bits_to_bytes(bits)


def bytes_to_bits(by: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., n] -> bits [..., 8n], little-endian within each byte."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (by[..., :, None] >> shifts) & 1
    return bits.reshape(*by.shape[:-1], by.shape[-1] * 8).astype(jnp.int32)


def bits_to_bytes(bits: jnp.ndarray) -> jnp.ndarray:
    grouped = bits.reshape(*bits.shape[:-1], bits.shape[-1] // 8, 8)
    weights = jnp.asarray([1 << i for i in range(8)], jnp.int32)
    return jnp.einsum("...nb,b->...n", grouped, weights).astype(jnp.uint8)
