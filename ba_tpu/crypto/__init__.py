"""Crypto subsystem: batched Ed25519 for signed Byzantine agreement.

The reference's oral messages (plain strings over RPC, /root/reference/
ba.py:39-57) carry no authentication; BASELINE.json's north star upgrades
them to SM(m) *signed* messages with batched Ed25519.  Layers:

- ``oracle``  — pure-Python ground truth (RFC 8032), host-side signing.
- ``sha512``  — batched SHA-512 as uint32-pair tensor ops.
- ``field``   — batched GF(2^255-19) in int32 limbs.
- ``ed25519`` — batched verification, one jittable program.
- ``signed``  — the SM(m) bridge: host-sign round-1 orders, device-verify
  the batch, feed the validity mask into the relay rounds.
"""

from ba_tpu.crypto import field, oracle, sha512, signed
from ba_tpu.crypto.ed25519 import compress, decompress, verify
from ba_tpu.crypto.signed import signed_sm_agreement, verify_received

__all__ = [
    "field",
    "oracle",
    "sha512",
    "signed",
    "compress",
    "decompress",
    "verify",
    "signed_sm_agreement",
    "verify_received",
]
