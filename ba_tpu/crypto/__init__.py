"""Crypto subsystem: batched Ed25519 for signed Byzantine agreement.

The reference's oral messages (plain strings over RPC, /root/reference/
ba.py:39-57) carry no authentication; BASELINE.json's north star upgrades
them to SM(m) *signed* messages with batched Ed25519.  Layers:

- ``oracle``  — pure-Python ground truth (RFC 8032), host-side signing.
- ``sha512``  — batched SHA-512 as uint32-pair tensor ops.
- ``field``   — batched GF(2^255-19) in int32 limbs.
- ``ed25519`` — batched verification, one jittable program.
- ``signed``  — the SM(m) bridge: host-sign round-1 orders, device-verify
  the batch, feed the validity mask into the relay rounds.
- ``pool``    — host-tier signing/verify worker pool + signature-table
  cache (ISSUE 16): jax-free BY CONTRACT, so pool worker processes never
  pay a jax import.

The package import is LAZY (PEP 562): ``ed25519``/``sha512``/``field``
pull jax at module import, and the host tier (``ba_tpu.crypto.pool``
workers, the serving front-end's plan construction) must be able to
``import ba_tpu.crypto.pool`` without paying — or even having — jax.
Attribute access resolves submodules and the re-exported names on first
touch; ``from ba_tpu.crypto import signed`` works as before.
"""

import importlib

_SUBMODULES = ("ed25519", "field", "oracle", "pool", "sha512", "signed")
# name -> (submodule, attr) for the re-exported convenience names.
_REEXPORTS = {
    "compress": ("ed25519", "compress"),
    "decompress": ("ed25519", "decompress"),
    "verify": ("ed25519", "verify"),
    "signed_sm_agreement": ("signed", "signed_sm_agreement"),
    "verify_received": ("signed", "verify_received"),
}

__all__ = list(_SUBMODULES) + list(_REEXPORTS)


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"{__name__}.{name}")
    if name in _REEXPORTS:
        mod, attr = _REEXPORTS[name]
        return getattr(importlib.import_module(f"{__name__}.{mod}"), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
