"""Batched reduction of 512-bit scalars mod L (the Ed25519 group order).

Verification needs ``h = SHA-512(R || A || M)`` as a *scalar* multiplier of
A.  Round 1 of this framework fed the full 512-bit digest to the ladder
("256 extra steps beat implementing mod-L"), which made the double-scalar
ladder 512 steps long.  This module makes the opposite trade: reducing h
mod L on device costs a handful of small convolutions (~60 vector ops on
<=51-limb axes), and in exchange the [h]A ladder halves to 256 steps —
the single hottest loop of the whole crypto path (ba_tpu/ops/ladder.py).
Reducing mod L is also what ref10/libsodium-style implementations do, so
the accept set matches standard verifiers even for adversarial keys whose
torsion component would otherwise see ``h`` and ``h mod L`` differently.

Representation: little-endian 8-bit limbs in int32 lanes (a *different*
radix from ba_tpu.crypto.field's 12-bit mod-p limbs — this is mod-L integer
arithmetic, not field arithmetic).  8-bit limbs keep every convolution term
comfortably inside int32: the largest fold below peaks at ~2.1e6.

Algorithm (all shapes static, fully jittable):

    L = 2^252 + delta,  delta < 2^125,  so  2^256 === -16*delta  (mod L)

    three folds at the 2^256 limb boundary shrink 512 -> ~258 bits, then
    one exact fold at 2^252 plus a single conditional subtract lands in
    [0, L).  Bounds are tracked limb-wise in each step's comment.

The reference (/root/reference/ba.py) has no crypto; this backs the signed
SM(m) north star (BASELINE.json config #3).  Differential contract:
``int.from_bytes(reduce_mod_l(h), 'little') == int.from_bytes(h) % L``
for every input — tested against Python bigints in tests/test_crypto.py.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ba_tpu.crypto.oracle import L

DELTA = L - 2**252  # 125 bits
C16 = 16 * DELTA  # 2^256 mod-L fold constant, 129 bits

# Static anti-diagonal scatter matrix for the 32x16-limb schoolbook
# product (mul_mod_l): conv[k] = sum_{i+j=k} a[i] * z[j].  Same trick as
# ba_tpu.crypto.field._CONV, sized for scalar x 128-bit-scalar.
_CONV_32x16 = np.zeros((32 * 16, 47), np.int32)
for _i in range(32):
    for _j in range(16):
        _CONV_32x16[_i * 16 + _j, _i + _j] = 1

# Full 32x32-limb variant for the device signer's k * a (both 256-bit).
_CONV_32x32 = np.zeros((32 * 32, 63), np.int32)
for _i in range(32):
    for _j in range(32):
        _CONV_32x32[_i * 32 + _j, _i + _j] = 1


def _const_limbs(v: int, n: int) -> np.ndarray:
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = v & 0xFF
        v >>= 8
    assert v == 0, "constant does not fit"
    return out


_C16 = _const_limbs(C16, 17)
_DELTA = _const_limbs(DELTA, 16)
_L32 = _const_limbs(L, 32)


def _mul_const(a: jnp.ndarray, c: np.ndarray) -> jnp.ndarray:
    """[..., n] int32 times a static limb constant -> [..., n+m-1]."""
    n, m = a.shape[-1], len(c)
    out = jnp.zeros((*a.shape[:-1], n + m - 1), jnp.int32)
    for j, cj in enumerate(c):
        if cj:
            out = out.at[..., j : j + n].add(a * int(cj))
    return out


def _carry(v: jnp.ndarray, passes: int, extra: int) -> jnp.ndarray:
    """Parallel signed base-256 carry passes (value-preserving, no wrap).

    ``extra`` fresh top limbs give transient carries headroom; callers size
    it so the top limb can never carry out (asserted by the bit bounds in
    reduce_mod_l's comments — inputs here peak at ~2.1e6 per limb, so three
    passes settle limbs into [-1, 256] with carries shrinking 256x each
    pass: 2.1e6 -> 8.2e3 -> 33 -> 1).
    """
    if extra:
        pad = jnp.zeros((*v.shape[:-1], extra), jnp.int32)
        v = jnp.concatenate([v, pad], axis=-1)
    zero1 = jnp.zeros((*v.shape[:-1], 1), jnp.int32)
    for _ in range(passes):
        c = v >> 8  # arithmetic shift: exact floor for negatives
        r = v - (c << 8)
        v = r + jnp.concatenate([zero1, c[..., :-1]], axis=-1)
    return v


def _exact_chain(v: jnp.ndarray) -> jnp.ndarray:
    """Sequential exact carry chain: signed limbs encoding a NON-NEGATIVE
    value that fits the limb count -> canonical base-256 limbs in [0, 256).
    Trace-time Python loop over a static <=40-limb axis."""
    c = jnp.zeros(v.shape[:-1], jnp.int32)
    outs = []
    for i in range(v.shape[-1]):
        x = v[..., i] + c
        outs.append(x & 0xFF)
        c = x >> 8
    return jnp.stack(outs, axis=-1)


def _fold_256(v: jnp.ndarray, keep: int) -> jnp.ndarray:
    """One 2^256-boundary fold: v === v[:32] - v[32:] * C16 (mod L)."""
    lo, hi = v[..., :32], v[..., 32:]
    prod = _mul_const(hi, _C16)
    n = max(32, prod.shape[-1], keep)
    lo = jnp.pad(lo, [(0, 0)] * (lo.ndim - 1) + [(0, n - 32)])
    prod = jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(0, n - prod.shape[-1])])
    return lo - prod


def reduce_mod_l(h_bytes: jnp.ndarray) -> jnp.ndarray:
    """Batched ``h mod L``: uint8 [..., 64] little-endian -> uint8 [..., 32].

    Fully static-shape jnp; safe under jit/vmap.  See module docstring for
    the fold plan; per-step bounds:
    """
    v = h_bytes.astype(jnp.int32)  # 64 limbs in [0, 256); value < 2^512
    # Fold 1: hi has 32 limbs -> conv terms <= 32*255*255 ~ 2.08e6 (int32-
    # safe); value lands in (-2^385, 2^257).
    v = _fold_256(v, keep=48)
    v = _carry(v, passes=3, extra=3)  # 51 limbs, each in [-1, 256]
    # Fold 2: hi is 19 limbs (|value| < 2^130); terms <= 19*256*255 ~ 1.24e6;
    # value lands in (-2^259, 2^257 + 2^259).
    v = _fold_256(v, keep=35)
    v = _carry(v, passes=3, extra=2)  # 37 limbs, each in [-1, 256]
    # Fold 3: hi is 5 limbs (|value| < 18); value lands in (-2^135, 2^257).
    v = _fold_256(v, keep=33)
    v = _carry(v, passes=2, extra=1)  # 34 limbs
    # Make non-negative: + L (> 2^135) keeps value < 2^257 + L < 2^258.
    v = v.at[..., :32].add(jnp.asarray(_L32))
    v = _carry(v, passes=2, extra=1)
    v = _exact_chain(v)  # canonical limbs, value in (0, 2^258)
    # Exact fold at 2^252: hi < 64, so hi*delta < 2^131.
    hi = (v[..., 31] >> 4) + v[..., 32] * 16 + v[..., 33] * (16 * 256)
    lo = v[..., :32].at[..., 31].set(v[..., 31] & 0xF)
    prod = _mul_const(hi[..., None], _DELTA)  # 16 limbs, terms <= 64*255
    v = lo.at[..., :16].add(-prod)  # value in (-2^131, 2^252)
    # + L once -> (0, 2L); then one conditional subtract of L -> [0, L).
    v = v + jnp.asarray(_L32)
    # Value < 2L < 2^254 fits 32 limbs; the extra limb only absorbs the
    # parallel passes' transient carries and is provably 0 after the chain.
    v = _exact_chain(_carry(v, passes=2, extra=1))[..., :32]
    borrow = jnp.zeros(v.shape[:-1], jnp.int32)
    diffs = []
    for i in range(32):
        x = v[..., i] - int(_L32[i]) + borrow
        diffs.append(x & 0xFF)
        borrow = x >> 8
    ge = borrow >= 0  # no final borrow <=> v >= L
    diff = jnp.stack(diffs, axis=-1)
    v = jnp.where(ge[..., None], diff, v)
    return v.astype(jnp.uint8)


def _bytes_from_signed_limbs(
    v: jnp.ndarray, total: int, extra: int = 2
) -> jnp.ndarray:
    """Signed int32 limbs of a NON-NEGATIVE value -> canonical uint8
    [..., total] (zero-padded).  Carries are settled with parallel passes
    then one exact chain; ``total`` must cover the value's byte length and
    ``extra`` must give the settled value's top limbs room — the value must
    fit ``8 * (v.shape[-1] + extra)`` bits, else the top carry is silently
    dropped (callers size ``extra`` from their static bounds)."""
    v = _carry(v, passes=3, extra=extra)
    v = _exact_chain(v)
    pad = total - v.shape[-1]
    if pad > 0:
        v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(0, pad)])
    return v[..., :total].astype(jnp.uint8)


def mul_mod_l(a_bytes: jnp.ndarray, z_bytes: jnp.ndarray) -> jnp.ndarray:
    """Batched ``(a * z) mod L``: a uint8 [..., 32], z uint8 [..., 16]
    little-endian -> uint8 [..., 32].

    The random-linear-combination batch verifier needs per-lane products
    of 256-bit scalars (reduced hashes h_i) with 128-bit random
    coefficients z_i.  Schoolbook convolution in 8-bit limbs (terms <=
    16 * 255^2 ~ 1.04e6 — int32-safe), settled to canonical base-256
    limbs (value < 2^384 -> 48 bytes), then reduced through the same
    ``reduce_mod_l`` fold chain the verifier already trusts (its 64-byte
    input covers 2^512 > 2^384).  Differential contract: equals
    ``(int(a) * int(z)) % L`` on Python bigints (tests/test_crypto.py).
    """
    a = a_bytes.astype(jnp.int32)
    z = z_bytes.astype(jnp.int32)
    outer = a[..., :, None] * z[..., None, :]
    flat = outer.reshape(*outer.shape[:-2], 32 * 16)
    conv = jnp.matmul(flat, jnp.asarray(_CONV_32x16))  # [..., 47]
    return reduce_mod_l(_bytes_from_signed_limbs(conv, 64))


def muladd_bytes(
    k_bytes: jnp.ndarray, a_bytes: jnp.ndarray, r_bytes: jnp.ndarray
) -> jnp.ndarray:
    """Batched ``k * a + r`` settled to canonical bytes: k, a, r uint8
    [..., 32] little-endian -> uint8 [..., 64] (UNREDUCED — the value is
    < 2^508 + 2^256, which the 64-byte ``reduce_mod_l`` input covers).

    The device signer's S-side arithmetic (ed25519.sign): S = (r + k*a)
    mod L with k the challenge scalar, a the clamped secret scalar
    (< 2^255), r the per-signature nonce.  Split from the mod-L reduction
    so callers pick the reduction substrate (``reduce_mod_l`` here, the
    ops/modl.py Pallas kernel on TPU).  Schoolbook terms peak at
    32 * 255^2 + 255 ~ 2.08e6 — int32-safe; the settled value fits 64
    bytes with the default 2-limb carry headroom (63 + 2 limbs = 520
    bits > 509).  Differential contract in tests/test_crypto.py.
    """
    k = k_bytes.astype(jnp.int32)
    a = a_bytes.astype(jnp.int32)
    outer = k[..., :, None] * a[..., None, :]
    flat = outer.reshape(*outer.shape[:-2], 32 * 32)
    conv = jnp.matmul(flat, jnp.asarray(_CONV_32x32))  # [..., 63]
    conv = conv.at[..., :32].add(r_bytes.astype(jnp.int32))
    return _bytes_from_signed_limbs(conv, 64)


def sum_mod_l(v_bytes: jnp.ndarray, axis: int = -2) -> jnp.ndarray:
    """Batched ``sum mod L`` over ``axis``: uint8 [..., G, 32] -> [..., 32].

    Exact for G <= ~8.4M (G * 255 < 2^31 keeps limb-wise int32 sums
    exact; asserted below from the static shape).  The settled sum is
    < G * L < 2^(253 + 23), so the carry headroom passed to
    ``_bytes_from_signed_limbs`` is sized from the static G — the fixed
    default (2 extra limbs = 34 bytes) only covers G <= ~2^20, beyond
    which the top carry would be silently dropped (ADVICE r4 medium;
    test_sum_mod_l_above_default_headroom pins the large-G case).  The
    64-byte ``reduce_mod_l`` input covers the result either way.
    """
    G = v_bytes.shape[axis]
    assert G * 255 < 2**31, f"G={G} overflows int32 limb sums (G > ~8.4M)"
    # Capacity: value < G * L < 2^(252 + bitlen(G)); limbs hold 8 bits each.
    extra = max(2, (252 + G.bit_length() + 7) // 8 + 1 - 32)
    s = v_bytes.astype(jnp.int32).sum(axis=axis)
    return reduce_mod_l(_bytes_from_signed_limbs(s, 64, extra=extra))
