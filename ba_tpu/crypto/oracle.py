"""Pure-Python Ed25519 oracle: the correctness anchor for the TPU kernels.

The reference (`/root/reference/ba.py`) has no signatures at all — its "oral
messages" are plain strings over RPC.  BASELINE.json's north star adds
SM(m)-style *signed* messages with batched Ed25519, so this module provides
the ground-truth implementation (Python bigints + hashlib SHA-512, RFC 8032
semantics) that the batched JAX/Pallas kernels and the native C++ path are
differentially tested against.  It is also the host-side signer used to
prepare message fixtures; the hot batched verify runs on device.

Deliberately slow and obvious — correctness only.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19  # field prime
L = 2**252 + 27742317777372353535851937790883648493  # group order


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


D = (-121665 * _inv(121666)) % P  # Edwards d
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)


def _xrecover(y: int) -> int:
    """Recover even x with x^2 = (y^2-1)/(d y^2+1); RFC 8032 section 5.1.3."""
    xx = (y * y - 1) * _inv(D * y * y + 1) % P
    x = pow(xx, (P + 3) // 8, P)
    if (x * x - xx) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - xx) % P != 0:
        raise ValueError("not a square: point not on curve")
    if x % 2 != 0:
        x = P - x
    return x


B_Y = 4 * _inv(5) % P
B_X = _xrecover(B_Y)
BASE = (B_X, B_Y)


def edwards_add(p: tuple, q: tuple) -> tuple:
    x1, y1 = p
    x2, y2 = q
    k = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * _inv(1 + k) % P
    y3 = (y1 * y2 + x1 * x2) * _inv(1 - k) % P
    return (x3, y3)


def scalarmult(p: tuple, e: int) -> tuple:
    q = (0, 1)
    while e > 0:
        if e & 1:
            q = edwards_add(q, p)
        p = edwards_add(p, p)
        e >>= 1
    return q


def encode_point(p: tuple) -> bytes:
    x, y = p
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def decode_point(s: bytes) -> tuple:
    y_full = int.from_bytes(s, "little")
    sign = y_full >> 255
    y = y_full & ((1 << 255) - 1)
    if y >= P:
        raise ValueError("y out of range")
    x = _xrecover(y)
    if x == 0 and sign == 1:
        # RFC 8032 5.1.3 step 4: the only square root of 0 is 0, whose
        # encoding must carry sign bit 0 (P - 0 would be non-canonical).
        raise ValueError("non-canonical x=0 encoding")
    if x & 1 != sign:
        x = P - x
    return (x, y)


def _hint(m: bytes) -> int:
    return int.from_bytes(hashlib.sha512(m).digest(), "little")


def _clamp(h32: bytes) -> int:
    a = int.from_bytes(h32, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def publickey(sk: bytes) -> bytes:
    """32-byte public key from a 32-byte secret seed (RFC 8032 5.1.5)."""
    h = hashlib.sha512(sk).digest()
    a = _clamp(h[:32])
    return encode_point(scalarmult(BASE, a))


def sign(sk: bytes, pk: bytes, msg: bytes) -> bytes:
    """64-byte signature R || S (RFC 8032 5.1.6)."""
    h = hashlib.sha512(sk).digest()
    a = _clamp(h[:32])
    r = _hint(h[32:] + msg)
    R = scalarmult(BASE, r)
    r_enc = encode_point(R)
    s = (r + _hint(r_enc + pk + msg) * a) % L
    return r_enc + s.to_bytes(32, "little")


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Check [S]B == R + [h]A (RFC 8032 5.1.7, no cofactor multiplication —
    the same equation the batched device kernel evaluates).

    h is reduced mod L before the multiply, matching ref10/libsodium (and
    the device path, ba_tpu.crypto.scalar.reduce_mod_l).  For honest keys
    the reduction is invisible — A and R have order L — it only pins down
    the accept set for adversarial points with a torsion component."""
    if len(sig) != 64 or len(pk) != 32:
        return False
    try:
        R = decode_point(sig[:32])
        A = decode_point(pk)
    except ValueError:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    h = _hint(sig[:32] + pk + msg) % L
    left = scalarmult(BASE, s)
    right = edwards_add(R, scalarmult(A, h))
    return left == right


def secret_from_seed(seed: bytes) -> bytes:
    """Deterministic 32-byte secret key from an arbitrary seed — the single
    derivation shared by :func:`keypair` and the fast host signer
    (ba_tpu.crypto.signed.commander_keys)."""
    return hashlib.sha512(b"ba_tpu-key:" + seed).digest()[:32]


def keypair(seed: bytes) -> tuple[bytes, bytes]:
    """Deterministic (sk, pk) so fixtures are reproducible from small
    integer seeds."""
    sk = secret_from_seed(seed)
    return sk, publickey(sk)
