"""Batched SHA-512 as a JAX tensor program (uint32 lane pairs).

Ed25519 needs SHA-512 twice per signature (key expansion / the challenge
scalar h = H(R || A || M)); verifying thousands of SM(m) messages on device
means hashing thousands of 96-byte inputs per round.  TPUs have no 64-bit
integer lanes, so every 64-bit word lives as an (hi, lo) pair of uint32
lanes and the whole compression function vectorises over the batch axis —
80 rounds of pure VPU element-wise ops, no data-dependent control flow.

Message length is static (shapes must be static under jit); the padding
layout is precomputed in Python per length.  Round constants and initial
state are derived at import from their definitions (cube/square roots of
the first primes) and asserted against the published values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _iroot(n: int, k: int) -> int:
    """Floor integer k-th root by Newton iteration on Python ints."""
    if n == 0:
        return 0
    x = 1 << ((n.bit_length() + k - 1) // k)
    while True:
        y = ((k - 1) * x + n // x ** (k - 1)) // k
        if y >= x:
            return x
        x = y


def _frac_root_bits(p: int, k: int) -> int:
    """First 64 bits of the fractional part of p**(1/k)."""
    root = _iroot(p << (64 * k), k)
    return root & ((1 << 64) - 1)


def _primes(count: int) -> list[int]:
    out, c = [], 2
    while len(out) < count:
        if all(c % q for q in out if q * q <= c):
            out.append(c)
        c += 1
    return out


_P80 = _primes(80)
K64 = [_frac_root_bits(p, 3) for p in _P80]
H64 = [_frac_root_bits(p, 2) for p in _P80[:8]]
assert K64[0] == 0x428A2F98D728AE22 and K64[79] == 0x6C44198C4A475817
assert H64[0] == 0x6A09E667F3BCC908 and H64[7] == 0x5BE0CD19137E2179

_KH = np.array([k >> 32 for k in K64], np.uint32)
_KL = np.array([k & 0xFFFFFFFF for k in K64], np.uint32)
_IH = np.array([h >> 32 for h in H64], np.uint32)
_IL = np.array([h & 0xFFFFFFFF for h in H64], np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _add64_many(*pairs):
    h, l = pairs[0]
    for ph, pl in pairs[1:]:
        h, l = _add64(h, l, ph, pl)
    return h, l


def _rotr64(h, l, n: int):
    n %= 64
    if n == 0:
        return h, l
    if n == 32:
        return l, h
    if n < 32:
        return (
            (h >> n) | (l << (32 - n)),
            (l >> n) | (h << (32 - n)),
        )
    m = n - 32
    return (
        (l >> m) | (h << (32 - m)),
        (h >> m) | (l << (32 - m)),
    )


def _shr64(h, l, n: int):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _xor3(a, b, c):
    return a ^ b ^ c


def _big_sigma0(h, l):
    r1 = _rotr64(h, l, 28)
    r2 = _rotr64(h, l, 34)
    r3 = _rotr64(h, l, 39)
    return _xor3(r1[0], r2[0], r3[0]), _xor3(r1[1], r2[1], r3[1])


def _big_sigma1(h, l):
    r1 = _rotr64(h, l, 14)
    r2 = _rotr64(h, l, 18)
    r3 = _rotr64(h, l, 41)
    return _xor3(r1[0], r2[0], r3[0]), _xor3(r1[1], r2[1], r3[1])


def _small_sigma0(h, l):
    r1 = _rotr64(h, l, 1)
    r2 = _rotr64(h, l, 8)
    r3 = _shr64(h, l, 7)
    return _xor3(r1[0], r2[0], r3[0]), _xor3(r1[1], r2[1], r3[1])


def _small_sigma1(h, l):
    r1 = _rotr64(h, l, 19)
    r2 = _rotr64(h, l, 61)
    r3 = _shr64(h, l, 6)
    return _xor3(r1[0], r2[0], r3[0]), _xor3(r1[1], r2[1], r3[1])


def _compress(state, wh, wl):
    """One 1024-bit block: state is a list of 8 (h, l) pairs; wh/wl are
    [B, 16] uint32 big-endian words of the block.

    The 80 rounds run as one lax.scan (body ~50 vector ops) instead of an
    unrolled trace — XLA's optimization time is superlinear in module size
    and an unrolled SHA-512 alone stalls the CPU backend for minutes.  The
    message schedule W is computed in the same scan with a 16-word sliding
    window in the carry: for t < 16 the word comes from the block (selected
    by a static per-step flag), afterwards from the sigma recurrence.
    """
    B = wh.shape[0]
    zeros = jnp.zeros((80 - 16, B), jnp.uint32)
    in_h = jnp.concatenate([jnp.moveaxis(wh, 0, 1), zeros])  # [80, B]
    in_l = jnp.concatenate([jnp.moveaxis(wl, 0, 1), zeros])
    is_input = (jnp.arange(80) < 16).astype(jnp.uint32)
    xs = (jnp.asarray(_KH), jnp.asarray(_KL), in_h, in_l, is_input)

    init_regs = tuple(
        jnp.broadcast_to(part, (B,)) for pair in state for part in pair
    )
    init_win = (jnp.zeros((16, B), jnp.uint32), jnp.zeros((16, B), jnp.uint32))

    def step(carry, x):
        regs, (win_h, win_l) = carry
        kh, kl, ih, il, flag = x
        s0 = _small_sigma0(win_h[1], win_l[1])  # W[t-15]
        s1 = _small_sigma1(win_h[14], win_l[14])  # W[t-2]
        sh, sl = _add64_many(s1, (win_h[9], win_l[9]), s0, (win_h[0], win_l[0]))
        use_in = flag == 1
        wth = jnp.where(use_in, ih, sh)
        wtl = jnp.where(use_in, il, sl)

        ah, al, bh, bl, ch, cl, dh, dl, eh, el, fh, fl, gh, gl, hh, hl = regs
        S1 = _big_sigma1(eh, el)
        chh = (eh & fh) ^ (~eh & gh)
        chl = (el & fl) ^ (~el & gl)
        t1 = _add64_many((hh, hl), S1, (chh, chl), (kh, kl), (wth, wtl))
        S0 = _big_sigma0(ah, al)
        majh = (ah & bh) ^ (ah & ch) ^ (bh & ch)
        majl = (al & bl) ^ (al & cl) ^ (bl & cl)
        t2 = _add64(S0[0], S0[1], majh, majl)
        neh, nel = _add64(dh, dl, t1[0], t1[1])
        nah, nal = _add64(t1[0], t1[1], t2[0], t2[1])
        new_regs = (nah, nal, ah, al, bh, bl, ch, cl, neh, nel, eh, el, fh, fl, gh, gl)
        new_win = (
            jnp.concatenate([win_h[1:], wth[None]]),
            jnp.concatenate([win_l[1:], wtl[None]]),
        )
        return (new_regs, new_win), None

    (regs, _), _ = jax.lax.scan(step, (init_regs, init_win), xs)
    new = [(regs[2 * i], regs[2 * i + 1]) for i in range(8)]
    return [
        _add64(sh, sl, nh, nl) for (sh, sl), (nh, nl) in zip(state, new)
    ]


def _pad_layout(nbytes: int) -> tuple[int, np.ndarray]:
    """(n_blocks, tail) for a message of static length nbytes: tail is the
    padding bytes appended (0x80, zeros, 128-bit big-endian bit length)."""
    pad_len = (112 - (nbytes + 1)) % 128
    tail = np.zeros(1 + pad_len + 16, np.uint8)
    tail[0] = 0x80
    bitlen = nbytes * 8
    tail[-16:] = np.frombuffer(bitlen.to_bytes(16, "big"), np.uint8)
    total = nbytes + len(tail)
    assert total % 128 == 0
    return total // 128, tail


def _message_words(msg: jnp.ndarray):
    """Pad + split a static-length message batch into big-endian word
    halves: uint8 [B, L] -> (wh, wl) [B, n_blocks, 16] uint32, n_blocks."""
    B, nbytes = msg.shape
    n_blocks, tail = _pad_layout(nbytes)
    padded = jnp.concatenate(
        [msg.astype(jnp.uint8), jnp.broadcast_to(jnp.asarray(tail), (B, len(tail)))],
        axis=1,
    )
    # Big-endian uint32 words: [B, n_blocks, 32 words of 4 bytes].
    by = padded.reshape(B, n_blocks * 32, 4).astype(jnp.uint32)
    words = (by[..., 0] << 24) | (by[..., 1] << 16) | (by[..., 2] << 8) | by[..., 3]
    words = words.reshape(B, n_blocks, 16, 2)
    return words[..., 0], words[..., 1], n_blocks


def sha512_mod_l(msg: jnp.ndarray) -> jnp.ndarray:
    """Batched ``SHA-512(msg) mod L``: uint8 [B, L] -> uint8 [B, 32].

    The scalar-derivation composite both verification (h = H(R||A||M))
    and signing (r = H(prefix||M)) need: on TPU it is ONE fused Mosaic
    kernel (ops/sha512_kernel.sha512_blocks_mod_l — the digest bytes
    never leave registers on their way into the mod-L fold chain); the
    jnp fallback composes the two stages, so the accept set is identical
    on every platform.
    """
    from ba_tpu.utils.platform import use_pallas

    if use_pallas():
        from ba_tpu.ops.sha512_kernel import sha512_blocks_mod_l

        wh, wl, n_blocks = _message_words(msg)
        return sha512_blocks_mod_l(wh, wl, n_blocks)
    from ba_tpu.crypto.scalar import reduce_mod_l

    return reduce_mod_l(sha512(msg))


def sha512(msg: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-512: uint8 [B, L] -> uint8 [B, 64].  L is static."""
    B = msg.shape[0]
    wh, wl, n_blocks = _message_words(msg)

    from ba_tpu.utils.platform import use_pallas

    if use_pallas():
        # One Mosaic kernel per call: 80 unrolled rounds, window shifts as
        # register renaming (ba_tpu.ops.sha512_kernel shares these round
        # functions, so the math exists once).
        from ba_tpu.ops.sha512_kernel import sha512_blocks

        words16 = sha512_blocks(wh, wl, n_blocks)  # [B, 16] (hi, lo) pairs
    else:
        state = [
            (
                jnp.broadcast_to(jnp.uint32(int(_IH[i])), (B,)),
                jnp.broadcast_to(jnp.uint32(int(_IL[i])), (B,)),
            )
            for i in range(8)
        ]
        for blk in range(n_blocks):
            state = _compress(state, wh[:, blk], wl[:, blk])
        words16 = jnp.stack(
            [part for pair in state for part in pair], axis=1
        )

    out = []
    for i in range(16):
        word = words16[:, i]
        out.extend(
            [
                (word >> 24) & 0xFF,
                (word >> 16) & 0xFF,
                (word >> 8) & 0xFF,
                word & 0xFF,
            ]
        )
    return jnp.stack(out, axis=1).astype(jnp.uint8)
