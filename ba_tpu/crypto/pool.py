"""Host-crypto pool + signature-table cache: the ISSUE 16 tentpole.

PR 14's sign-ahead lane moved signing/verify off the signed megastep's
critical path, but ONE host core still did all the work —
``BENCH_signed_r14.json``'s sweep leg reads 0.998x because the lane's
overlap slot saturates at ~11k verifies/s/core.  This module breaks
that wall twice over:

- :class:`SignPool` — N worker PROCESSES (subprocess + length-prefixed
  pickle pipes, not ``multiprocessing`` — no ``__main__`` re-import
  hazard under pytest, full lifecycle control) that shard
  ``sign_round_tables`` / ``verify_host_exact`` work.  Sharding is
  DETERMINISTIC and output-invariant: work splits into contiguous
  index ranges, results reassemble BY INDEX, and every unit's bytes
  depend only on its own inputs (Ed25519 is deterministic), so worker
  count, shard order and completion order can never affect a single
  output byte.  A dead worker (broken pipe, EOF, timeout) degrades
  that shard to the in-process path, is counted
  (:attr:`SignPool.degraded`), and never wedges a dispatch.
- :class:`SigTableCache` — a bounded, bytes-keyed LRU over per-round
  signature tables AND their host verdict planes.  Deterministic
  Ed25519 over round-bound messages means identical
  ``(key-set, instance, round, value)`` claims re-sign identical bytes
  across cohorts and repeated campaigns: a warm hit skips sign AND
  verify, bit-exactly, which is where repeat signed serving traffic
  stops paying host crypto at all.

jax-free BY CONTRACT: workers import exactly this module (plus
``ba_tpu.crypto.signed``'s host tier), so a pool never pays — or even
needs — a jax install.  ``tests/test_sign_pool.py`` pins the import
with a subprocess.

Env dials:

- ``BA_TPU_SIGN_POOL`` — worker count.  Unset/``auto`` derives from
  ``os.cpu_count() - 1`` (capped at 8); ``0`` keeps the in-process
  path (and is what a 1-core host derives).
- ``BA_TPU_SIGN_CACHE`` — cache capacity in round-table entries
  (default 256); ``0`` disables.
- ``BA_TPU_SIGN_CACHE_BYTES`` — cache byte budget (default 128 MiB);
  the LRU evicts on whichever bound trips first.
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import struct
import subprocess
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

from ba_tpu.utils import metrics as _metrics

_LEN = struct.Struct("<Q")

# Generous by design: the timeout exists to keep a HUNG worker from
# wedging a dispatch forever, not to police slow shards — a worker that
# trips it is killed and its shard re-runs in-process.
_DEFAULT_TIMEOUT_S = 120.0


def pool_size_from_env() -> int:
    """Worker count from ``BA_TPU_SIGN_POOL``: explicit int, or the
    ``os.cpu_count()``-derived default (cores minus the one the lane
    itself occupies, capped at 8 — more workers than cores only adds
    scheduler churn).  ``0`` keeps the in-process path."""
    env = os.environ.get("BA_TPU_SIGN_POOL", "").strip().lower()
    if env in ("", "auto"):
        return max(0, min(8, (os.cpu_count() or 1) - 1))
    n = int(env)
    if n < 0:
        raise ValueError(f"BA_TPU_SIGN_POOL must be >= 0, got {env!r}")
    return n


def _send(fh, obj) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    fh.write(_LEN.pack(len(blob)))
    fh.write(blob)
    fh.flush()


def _read_exact(fh, size: int) -> bytes:
    """Read exactly ``size`` bytes (raw pipes may return short reads)."""
    buf = io.BytesIO()
    remaining = size
    while remaining:
        chunk = fh.read(min(remaining, 1 << 20))
        if not chunk:
            raise EOFError("pool worker closed its pipe mid-frame")
        buf.write(chunk)
        remaining -= len(chunk)
    return buf.getvalue()


def _recv(fh):
    (size,) = _LEN.unpack(_read_exact(fh, _LEN.size))
    return pickle.loads(_read_exact(fh, size))


def _worker_main() -> None:  # pragma: no cover - runs in the workers
    """Worker process entry: a blocking task loop over stdin/stdout.

    Tasks arrive as length-prefixed pickles; each reply is written
    before the next task is read (ONE outstanding task per worker —
    the pipe-deadlock-free discipline the parent enforces too).  Keys
    derive worker-side from the (seed, batch) identity — deterministic
    ``commander_keys``, so no key material crosses the pipe — and are
    cached per key-set for the worker's lifetime.
    """
    from ba_tpu.crypto import signed as _signed

    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    keysets: dict = {}

    def keys_for(seed: int, batch: int, n_values: int):
        ident = (seed, batch, n_values)
        if ident not in keysets:
            sks, pks = _signed.commander_keys(batch, seed)
            keysets[ident] = (pks,) + _signed.key_table_arrays(
                sks, pks, n_values
            )
        return keysets[ident]

    while True:
        try:
            task = _recv(stdin)
        except EOFError:
            return
        kind = task[0]
        if kind == "exit":
            return
        t0 = time.perf_counter()
        rows = 0
        traceparent = None
        try:
            if kind == "sign":
                seed, batch, n_values, base, rounds = task[1:6]
                # Optional trailing traceparent (ISSUE 19): the staging
                # window's causal position rode the pickle pipe; absent
                # on tasks from older parents (length-gated, never
                # positional breakage).
                traceparent = task[6] if len(task) > 6 else None
                pks, sk_rep, pk_rep = keys_for(seed, batch, n_values)
                sigs = np.empty(
                    (len(rounds), batch, n_values, 64), np.uint8
                )
                for i, r in enumerate(rounds):
                    msgs = _signed._round_table_msgs(
                        batch, r, n_values, base
                    )
                    sigs[i] = _signed.sign_table_msgs_arrays(
                        sk_rep, pk_rep, msgs
                    )
                rows = len(rounds)
                reply = ("ok", sigs)
            elif kind == "verify":
                pks, msgs, sigs = task[1:4]
                traceparent = task[4] if len(task) > 4 else None
                rows = int(msgs.shape[0])
                reply = ("ok", _signed.verify_host_exact(pks, msgs, sigs))
            else:
                reply = ("err", f"unknown task kind {kind!r}")
        except Exception as exc:  # noqa: BLE001 - worker must answer
            reply = ("err", f"{type(exc).__name__}: {exc}")
        wall_s = time.perf_counter() - t0
        _send(stdout, reply)
        if reply[0] == "ok" and _metrics.default_sink().enabled:
            # One pool_task span per completed task, into this worker's
            # OWN shard (the parent only forwards a sink-dir target) —
            # emitted AFTER the reply so telemetry never sits on the
            # parent's read path.  The span parents under the staging
            # window's position; the codec lives in utils/metrics so no
            # obs import widens the worker's jax-free closure.
            rec = {
                "event": "pool_task",
                "v": _metrics.SCHEMA_VERSION,
                "kind": kind,
                "rows": rows,
                "wall_s": round(wall_s, 6),
                "t_perf": round(t0, 6),
            }
            parsed = _metrics.parse_traceparent(traceparent)
            if parsed is not None:
                rec["trace_id"] = parsed[0]
                rec["span_id"] = _metrics.new_span_id()
                rec["parent_id"] = parsed[1]
            _metrics.emit(rec)


class _Worker:
    __slots__ = ("proc", "alive")

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc
        self.alive = True


class SignPool:
    """N signing/verify worker processes with deterministic sharding.

    The degradation ladder (never wedge, never change bytes):

    1. healthy worker — shard runs in its process;
    2. dead/hung worker (broken pipe, EOF, reply timeout, ``err``
       reply) — the worker is killed and retired, :attr:`degraded`
       counts the event, and the shard re-runs IN-PROCESS via the same
       jax-free bodies the worker would have called;
    3. every worker dead — the pool behaves as the in-process path
       (workers == 0) for the rest of its life.

    Because sign/verify are per-row deterministic and shards reassemble
    by index, every rung produces identical bytes.
    """

    def __init__(
        self, workers: int | None = None, *, timeout_s: float | None = None
    ):
        if workers is None:
            workers = pool_size_from_env()
        if workers < 0:
            raise ValueError(f"workers={workers} must be >= 0")
        self.requested = workers
        self.degraded = 0
        self.pool_s = 0.0
        self.shards = 0
        self._lock = threading.Lock()
        self._timeout_s = (
            float(os.environ.get("BA_TPU_SIGN_POOL_TIMEOUT_S", "0"))
            or _DEFAULT_TIMEOUT_S
            if timeout_s is None
            else timeout_s
        )
        self._workers: list[_Worker] = []
        for _ in range(workers):
            self._workers.append(_Worker(self._spawn()))

    # -- lifecycle ----------------------------------------------------------

    def _spawn(self) -> subprocess.Popen:
        env = dict(os.environ)
        # Workers are computation, not observation: strip the telemetry
        # sinks so a worker never double-emits into the parent's stream,
        # and pin the package path so an uninstalled checkout resolves.
        # EXCEPT (ISSUE 19) a sink-DIRECTORY target: there each process
        # appends to its OWN <pid>.<token>.jsonl shard, so the worker
        # keeps (or inherits — the parent may have configured the sink
        # programmatically, not via env) the dir target, opens its own
        # shard (clock anchor first), and its pool_task spans join the
        # fleet merge instead of vanishing.
        for k in ("BA_TPU_METRICS", "BA_TPU_TRACE"):
            env.pop(k, None)
        live_target = _metrics.default_sink().target
        if _metrics.is_dir_target(live_target):
            env["BA_TPU_METRICS"] = live_target
        elif _metrics.is_dir_target(os.environ.get("BA_TPU_METRICS")):
            env["BA_TPU_METRICS"] = os.environ["BA_TPU_METRICS"]
        import ba_tpu

        pkg_root = os.path.dirname(os.path.dirname(ba_tpu.__file__))
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        return subprocess.Popen(
            [
                sys.executable,
                "-c",
                "from ba_tpu.crypto.pool import _worker_main; _worker_main()",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            # Unbuffered pipes on the PARENT side: the reply `select`
            # polls the raw fd, and a buffered reader's read-ahead
            # would strand a frame in Python-side memory the fd poll
            # can't see.
            bufsize=0,
            env=env,
        )

    @property
    def workers(self) -> int:
        """Live worker count (dead workers retire permanently)."""
        return sum(1 for w in self._workers if w.alive)

    def close(self) -> None:
        """Drain: ask every live worker to exit, then reap (kill on
        timeout).  Idempotent; the pool is in-process-only afterward."""
        for w in self._workers:
            if not w.alive:
                continue
            try:
                _send(w.proc.stdin, ("exit",))
                w.proc.stdin.close()
            except (BrokenPipeError, OSError, ValueError):
                pass
            w.alive = False
        for w in self._workers:
            try:
                w.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait()

    def __del__(self):  # pragma: no cover - GC-order dependent
        try:
            self.close()
        except Exception:
            pass

    # -- the deterministic shard round-trip ---------------------------------

    def _kill(self, w: _Worker) -> None:
        w.alive = False
        self.degraded += 1
        try:
            w.proc.kill()
        except OSError:
            pass

    def _round_trip(self, assignments, fallback):
        """One task per live worker, write-all then read-all (a worker
        never holds more than one outstanding task, so neither side can
        block on a full pipe).  ``assignments`` is ``[(worker, task,
        shard_args)]``; any failure degrades that shard to
        ``fallback(shard_args)``.  Returns results in assignment
        order."""
        t0 = time.perf_counter()
        sent = []
        for w, task, shard_args in assignments:
            ok = False
            if w is not None and w.alive:
                try:
                    _send(w.proc.stdin, task)
                    ok = True
                except (BrokenPipeError, OSError, ValueError):
                    self._kill(w)
            sent.append((w, ok, shard_args))
        results = []
        deadline = time.perf_counter() + self._timeout_s
        for w, ok, shard_args in sent:
            reply = None
            if ok:
                try:
                    if hasattr(w.proc.stdout, "fileno"):
                        import selectors

                        sel = selectors.DefaultSelector()
                        sel.register(w.proc.stdout, selectors.EVENT_READ)
                        budget = max(0.0, deadline - time.perf_counter())
                        if not sel.select(timeout=budget):
                            raise TimeoutError("pool worker reply timeout")
                        sel.close()
                    reply = _recv(w.proc.stdout)
                except (EOFError, OSError, TimeoutError, ValueError):
                    self._kill(w)
                    reply = None
            if reply is not None and reply[0] == "ok":
                results.append(reply[1])
            else:
                if reply is not None:  # structured worker error
                    self._kill(w)
                results.append(fallback(shard_args))
        with self._lock:
            self.pool_s += time.perf_counter() - t0
            self.shards += len(assignments)
        return results

    def _live(self) -> list[_Worker]:
        return [w for w in self._workers if w.alive]

    @staticmethod
    def _split(n: int, parts: int) -> list[tuple[int, int]]:
        """Contiguous index ranges covering [0, n): shard boundaries
        depend only on (n, parts), never on scheduling."""
        parts = max(1, min(parts, n))
        step, extra = divmod(n, parts)
        spans, lo = [], 0
        for i in range(parts):
            hi = lo + step + (1 if i < extra else 0)
            spans.append((lo, hi))
            lo = hi
        return spans

    def sign_rounds(
        self,
        seed: int,
        batch: int,
        n_values: int,
        base: int,
        rounds: list[int],
        fallback,
        traceparent: str | None = None,
    ) -> np.ndarray:
        """Shard ``rounds`` across the workers -> sigs uint8
        [len(rounds), batch, n_values, 64], reassembled by round index.
        ``fallback(rounds_slice)`` is the in-process body (degradation
        rung 2).  ``traceparent`` (ISSUE 19) rides each task so the
        workers' pool_task spans parent under the staging window."""
        live = self._live()
        if not rounds:
            return np.empty((0, batch, n_values, 64), np.uint8)
        if not live:
            return fallback(rounds)
        spans = self._split(len(rounds), len(live))
        assignments = [
            (
                live[i],
                ("sign", seed, batch, n_values, base, rounds[lo:hi],
                 traceparent),
                rounds[lo:hi],
            )
            for i, (lo, hi) in enumerate(spans)
        ]
        parts = self._round_trip(
            assignments, lambda rs: np.asarray(fallback(rs), np.uint8)
        )
        return np.concatenate([np.asarray(p, np.uint8) for p in parts])

    def verify_rows(
        self, pks: np.ndarray, msgs: np.ndarray, sigs: np.ndarray,
        traceparent: str | None = None,
    ) -> np.ndarray:
        """Shard a flattened [N, ...] verify across the workers ->
        bool [N, n] verdicts, reassembled by row index.  Degraded
        shards re-verify in-process via the same host body.
        ``traceparent`` rides each task exactly as in sign_rounds."""
        from ba_tpu.crypto.signed import verify_host_exact

        pks = np.ascontiguousarray(pks, np.uint8)
        msgs = np.ascontiguousarray(msgs, np.uint8)
        sigs = np.ascontiguousarray(sigs, np.uint8)
        live = self._live()
        if not live:
            return verify_host_exact(pks, msgs, sigs)
        spans = self._split(msgs.shape[0], len(live))
        assignments = [
            (
                live[i],
                ("verify", pks[lo:hi], msgs[lo:hi], sigs[lo:hi],
                 traceparent),
                (lo, hi),
            )
            for i, (lo, hi) in enumerate(spans)
        ]
        parts = self._round_trip(
            assignments,
            lambda span: verify_host_exact(
                pks[span[0] : span[1]],
                msgs[span[0] : span[1]],
                sigs[span[0] : span[1]],
            ),
        )
        return np.concatenate([np.asarray(p, np.bool_) for p in parts])


class SigTableCache:
    """Bounded bytes-keyed LRU over per-round signature tables.

    One entry = one round's ``(sigs [B, V, 64], host verdicts [B, V]
    or None)`` under a key hashed over the PUBLIC inputs that determine
    them — the key-set's pk table and the round's message table bytes
    (which bind instance base, round index and values).  Ed25519
    determinism is the correctness argument: same pks + same message
    bytes re-sign to the same signature bytes and re-verify to the same
    verdicts, so a hit is bit-identical to a recompute by construction.

    Verdict planes are cached only when they were derived ON HOST
    (native verify route / pool) — a device-verify platform caches
    signatures alone and ``ok=None`` tells the lane to still dispatch
    its verify.

    Double-bounded: ``max_entries`` entries AND ``max_bytes`` of table
    payload, LRU-evicted on whichever trips first.  Thread-safe (the
    serving front-end's dispatcher and a campaign thread may share the
    process default).
    """

    def __init__(self, max_entries: int = 256, max_bytes: int = 128 << 20):
        if max_entries < 1:
            raise ValueError(f"max_entries={max_entries} must be >= 1")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.nbytes = 0
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()

    @staticmethod
    def round_key(pks: np.ndarray, msgs: np.ndarray) -> bytes:
        """The cache key grammar: sha256 over ``pks`` bytes || ``msgs``
        bytes (shapes ride along to split any theoretical concat
        ambiguity).  Everything that determines the output is in the
        hash; nothing else is."""
        h = hashlib.sha256()
        h.update(repr(pks.shape).encode())
        h.update(np.ascontiguousarray(pks).tobytes())
        h.update(repr(msgs.shape).encode())
        h.update(np.ascontiguousarray(msgs).tobytes())
        return h.digest()

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: bytes):
        """-> (sigs, ok_or_None) or None; a hit refreshes LRU order."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def put(self, key: bytes, sigs: np.ndarray, ok: np.ndarray | None):
        with self._lock:
            if key in self._entries:
                old = self._entries.pop(key)
                self.nbytes -= old[0].nbytes + (
                    old[1].nbytes if old[1] is not None else 0
                )
            self._entries[key] = (sigs, ok)
            self.nbytes += sigs.nbytes + (ok.nbytes if ok is not None else 0)
            while self._entries and (
                len(self._entries) > self.max_entries
                or self.nbytes > self.max_bytes
            ):
                _, (esigs, eok) = self._entries.popitem(last=False)
                self.nbytes -= esigs.nbytes + (
                    eok.nbytes if eok is not None else 0
                )
                self.evictions += 1


# -- process defaults (lifecycle owned by the serving front-end) ------------

_default_pool: SignPool | None = None
_default_pool_made = False
_default_cache: SigTableCache | None = None
_default_cache_made = False
_defaults_lock = threading.Lock()


def default_pool() -> SignPool | None:
    """The process-wide pool per ``BA_TPU_SIGN_POOL`` (None when the
    env derives 0 workers — the in-process path).  Lazily created on
    first use; ``AgreementService.open()`` creates it eagerly and
    ``stop()`` drains it (the service owns the lifecycle)."""
    global _default_pool, _default_pool_made
    with _defaults_lock:
        if not _default_pool_made:
            n = pool_size_from_env()
            _default_pool = SignPool(n) if n else None
            _default_pool_made = True
        return _default_pool


def default_cache() -> SigTableCache | None:
    """The process-wide signature-table cache per ``BA_TPU_SIGN_CACHE``
    (None when disabled with ``=0``)."""
    global _default_cache, _default_cache_made
    with _defaults_lock:
        if not _default_cache_made:
            env = os.environ.get("BA_TPU_SIGN_CACHE", "").strip().lower()
            cap = 256 if env in ("", "auto") else int(env)
            if cap < 0:
                raise ValueError(
                    f"BA_TPU_SIGN_CACHE must be >= 0, got {env!r}"
                )
            max_bytes = int(
                os.environ.get("BA_TPU_SIGN_CACHE_BYTES", str(128 << 20))
            )
            _default_cache = (
                SigTableCache(cap, max_bytes) if cap else None
            )
            _default_cache_made = True
        return _default_cache


def close_default_pool() -> None:
    """Drain just the default pool (the cache keeps its warm entries)
    — ``AgreementService.stop()``'s half of the lifecycle it owns.  A
    later ``default_pool()`` re-derives from the env."""
    global _default_pool, _default_pool_made
    with _defaults_lock:
        if _default_pool is not None:
            _default_pool.close()
        _default_pool = None
        _default_pool_made = False


def shutdown_defaults() -> None:
    """Drain the default pool and drop both defaults (they re-derive
    from the env on next use) — the service's ``stop()`` hook, and the
    reset seam tests/bench legs use between env reconfigurations."""
    global _default_pool, _default_pool_made
    global _default_cache, _default_cache_made
    with _defaults_lock:
        if _default_pool is not None:
            _default_pool.close()
        _default_pool = None
        _default_pool_made = False
        _default_cache = None
        _default_cache_made = False
