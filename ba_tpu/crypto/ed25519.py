"""Batched Ed25519 verification as one jittable JAX program.

The hot op of the SM(m) signed-message protocol (BASELINE.json config #3):
thousands of independent signature checks per agreement round, vectorised
over the batch axis.  The curve lives in extended twisted-Edwards
coordinates (X : Y : Z : T), where the a=-1 / d-nonsquare addition law is
*complete* — one branch-free formula for add and double, which is exactly
what SIMD lanes and XLA want (no data-dependent control flow anywhere;
scalar multiplication is a lax.scan over scalar bits with a select).

Verification checks the RFC 8032 equation without cofactor multiplication,

    [S]B == R + [h]A,   h = SHA-512(R || A || M),

matching the pure-Python oracle (ba_tpu.crypto.oracle) bit for bit; the
oracle and RFC 8032 test vectors are the differential tests.  The 512-bit h
is used as a scalar directly — no mod-L reduction is needed for
correctness, and 256 extra ladder steps beat implementing Barrett mod-L on
the device.

The reference (/root/reference/ba.py) has no signatures; this module is the
north-star addition that makes oral messages *signed* messages.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ba_tpu.crypto import field as F
from ba_tpu.crypto.oracle import B_X, B_Y, D, L, P, SQRT_M1
from ba_tpu.crypto.sha512 import sha512


from ba_tpu.utils.platform import use_pallas as _use_pallas  # shared flag

# -- constants ----------------------------------------------------------------

_D = F.constant(D)
_D2 = F.constant(2 * D % P)
_SQRT_M1 = F.constant(SQRT_M1)
_ONE = F.constant(1)
_BASE = (
    F.constant(B_X),
    F.constant(B_Y),
    F.constant(1),
    F.constant(B_X * B_Y % P),
)

Point = tuple  # (X, Y, Z, T) limb tensors, shapes [..., 22]


def identity(shape) -> Point:
    z = F.zeros(shape)
    one = jnp.broadcast_to(_ONE, (*shape, F.LIMBS))
    return (z, one, one, z)


def base_point(shape) -> Point:
    return tuple(jnp.broadcast_to(c, (*shape, F.LIMBS)) for c in _BASE)


def point_add(p: Point, q: Point) -> Point:
    """Complete unified addition (add-2008-hwcd-3, a=-1): 8 muls + 1 small.

    Valid for doubling too; inputs must be carry()-normalized (every mul
    output is), operands formed as one lazy add/sub of normalized values.
    """
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = F.mul(F.sub(y1, x1), F.sub(y2, x2))
    b = F.mul(F.add(y1, x1), F.add(y2, x2))
    c = F.mul(F.mul(t1, t2), _D2)
    d = F.mul_small(F.mul(z1, z2), 2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_select(mask: jnp.ndarray, p: Point, q: Point) -> Point:
    """Per-batch-element select: mask [...] bool -> p where True else q."""
    m = mask[..., None]
    return tuple(jnp.where(m, a, b) for a, b in zip(p, q))


def scalar_mult(point: Point, bits: jnp.ndarray) -> Point:
    """[k]P via double-and-add-always: bits [..., nbits] int32, LSB first.

    One lax.scan over the bit axis — 2 complete additions per step, a
    select instead of a branch.  nbits is static (256 for S, 512 for h).
    """
    nbits = bits.shape[-1]
    bits_t = jnp.moveaxis(bits, -1, 0)  # [nbits, ...]

    def step(state, bit):
        acc, q = state
        acc = point_select(bit == 1, point_add(acc, q), acc)
        return (acc, point_add(q, q)), None

    init = (identity(bits.shape[:-1]), point)
    (acc, _), _ = jax.lax.scan(step, init, bits_t, length=nbits)
    return acc


def scalar_mult_base(bits: jnp.ndarray) -> Point:
    return scalar_mult(base_point(bits.shape[:-1]), bits)


def point_eq(p: Point, q: Point) -> jnp.ndarray:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return F.eq(F.mul(x1, z2), F.mul(x2, z1)) & F.eq(F.mul(y1, z2), F.mul(y2, z1))


def compress(p: Point) -> jnp.ndarray:
    """Point -> 32-byte encoding (y with the sign of x in the top bit)."""
    x, y, z, _ = p
    zi = F.inv(z)
    xa = F.canonical(F.mul(x, zi))
    ya = F.canonical(F.mul(y, zi))
    by = F.to_bytes(ya)
    sign = (xa[..., 0] & 1).astype(jnp.uint8)
    return by.at[..., 31].add(sign << 7)


def _lt_const(by: jnp.ndarray, bound: int) -> jnp.ndarray:
    """Little-endian uint8 [..., 32] < bound, lexicographic from the top."""
    bnd = np.frombuffer(bound.to_bytes(32, "little"), np.uint8)
    lt = jnp.zeros(by.shape[:-1], bool)
    eq_so_far = jnp.ones(by.shape[:-1], bool)
    for i in range(31, -1, -1):
        bi = by[..., i].astype(jnp.int32)
        c = int(bnd[i])
        lt = lt | (eq_so_far & (bi < c))
        eq_so_far = eq_so_far & (bi == c)
    return lt


def decompress(by: jnp.ndarray) -> tuple[Point, jnp.ndarray]:
    """32-byte encodings [..., 32] -> (Point, valid mask).

    RFC 8032 5.1.3: y from the low 255 bits (rejected unless y < p), x
    from x^2 = (y^2-1)/(d y^2+1) via the (p+3)/8 exponent trick, sqrt(-1)
    correction, sign-bit choice; x == 0 with sign 1 is invalid.  On an
    invalid mask lane the returned coordinates are garbage — callers must
    gate on the mask (verify() does).
    """
    sign = (by[..., 31] >> 7).astype(jnp.int32)
    masked = by.at[..., 31].set(by[..., 31] & 0x7F)
    ok = _lt_const(masked, P)
    y = F.from_bytes(masked)
    yy = F.square(y)
    u = F.sub(yy, jnp.broadcast_to(_ONE, yy.shape))
    v = F.carry(F.add(F.mul(yy, _D), jnp.broadcast_to(_ONE, yy.shape)))
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    if _use_pallas():
        from ba_tpu.ops.powchain import pow_planes

        uv7 = F.mul(u, v7)  # kernel tiling is 2-D; keep [...] batch dims
        flat = uv7.reshape(-1, F.LIMBS)
        t = pow_planes(flat, (P - 5) // 8).reshape(uv7.shape)
    else:
        t = F.pow_const(F.mul(u, v7), (P - 5) // 8)
    x = F.mul(F.mul(u, v3), t)
    vxx = F.mul(v, F.square(x))
    root1 = F.eq(vxx, u)
    root2 = F.eq(vxx, F.sub(F.zeros(u.shape[:-1]), u))
    x = jnp.where(root2[..., None], F.mul(x, _SQRT_M1), x)
    ok = ok & (root1 | root2)
    xc = F.canonical(x)
    x_zero = F.is_zero(xc)
    ok = ok & ~(x_zero & (sign == 1))
    flip = (xc[..., 0] & 1) != sign
    xc = jnp.where(flip[..., None], F.canonical(F.sub(F.zeros(xc.shape[:-1]), xc)), xc)
    one = jnp.broadcast_to(_ONE, y.shape)
    return (xc, y, one, F.mul(xc, y)), ok


def verify(pk: jnp.ndarray, msg: jnp.ndarray, sig: jnp.ndarray) -> jnp.ndarray:
    """Batched verify: pk [B, 32], msg [B, L] (L static), sig [B, 64] uint8
    -> bool [B].  Semantics identical to oracle.verify per lane.

    Graph-size trick: A and R decompress in one 2B call, and [S]B / [h]A
    run as one 2B double-and-add scan over 512 bits (S zero-padded) —
    halving the compiled program versus four separate subgraphs, which
    matters because XLA optimization time grows superlinearly in module
    size.
    """
    B = pk.shape[0]
    r_enc = sig[..., :32]
    s_enc = sig[..., 32:]
    pts, oks = decompress(jnp.concatenate([pk, r_enc], axis=0))
    a_pt = tuple(c[:B] for c in pts)
    r_pt = tuple(c[B:] for c in pts)
    ok_a, ok_r = oks[:B], oks[B:]
    ok_s = _lt_const(s_enc, L)
    h_bytes = sha512(jnp.concatenate([r_enc, pk, msg], axis=-1))
    h_bits = F.bytes_to_bits(h_bytes)  # [B, 512]
    s_bits = F.bytes_to_bits(s_enc)  # [B, 256]
    s_bits = jnp.concatenate([s_bits, jnp.zeros_like(s_bits)], axis=-1)
    bits = jnp.concatenate([s_bits, h_bits], axis=0)  # [2B, 512]
    points = tuple(
        jnp.concatenate([b, a], axis=0)
        for b, a in zip(base_point((B,)), a_pt)
    )
    if _use_pallas():
        from ba_tpu.ops.ladder import scalar_mult as pallas_scalar_mult

        prods = pallas_scalar_mult(points, bits)
    else:
        prods = scalar_mult(points, bits)
    left = tuple(c[:B] for c in prods)
    ha = tuple(c[B:] for c in prods)
    right = point_add(r_pt, ha)
    return ok_a & ok_r & ok_s & point_eq(left, right)
