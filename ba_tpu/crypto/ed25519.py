"""Batched Ed25519 verification as one jittable JAX program.

The hot op of the SM(m) signed-message protocol (BASELINE.json config #3):
thousands of independent signature checks per agreement round, vectorised
over the batch axis.  The curve lives in extended twisted-Edwards
coordinates (X : Y : Z : T), where the a=-1 / d-nonsquare addition law is
*complete* — one branch-free formula for add and double, which is exactly
what SIMD lanes and XLA want (no data-dependent control flow anywhere;
scalar multiplication is a lax.scan over scalar bits with a select).

Verification checks the RFC 8032 equation without cofactor multiplication,

    [S]B == R + [h]A,   h = SHA-512(R || A || M) mod L,

matching the pure-Python oracle (ba_tpu.crypto.oracle) bit for bit; the
oracle and RFC 8032 test vectors are the differential tests.  The two
scalar multiplies are deliberately asymmetric:

- [h]A must ladder (A varies per lane), but h is first reduced mod L on
  device (ba_tpu.crypto.scalar) so the ladder is 256 steps, not 512;
- [S]B never ladders at all: B is a compile-time constant, so [S]B is 64
  table lookups into precomputed 4-bit windows (j * 16^w) B plus 64
  complete additions — ~8x fewer point ops than a 256-step ladder.

Round 1 ran both products through one joint 512-bit ladder over 2B lanes;
this layout does ~4x less point arithmetic per signature.

The reference (/root/reference/ba.py) has no signatures; this module is the
north-star addition that makes oral messages *signed* messages.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ba_tpu.crypto import field as F
from ba_tpu.crypto.oracle import B_X, B_Y, D, L, P, SQRT_M1
from ba_tpu.crypto.scalar import reduce_mod_l
from ba_tpu.crypto.sha512 import sha512, sha512_mod_l


from ba_tpu.utils.platform import use_pallas as _use_pallas  # shared flag

# -- constants ----------------------------------------------------------------

_D = F.constant(D)
_D2 = F.constant(2 * D % P)
_SQRT_M1 = F.constant(SQRT_M1)
_ONE = F.constant(1)
_BASE = (
    F.constant(B_X),
    F.constant(B_Y),
    F.constant(1),
    F.constant(B_X * B_Y % P),
)

Point = tuple  # (X, Y, Z, T) limb tensors, shapes [..., 22]


def identity(shape) -> Point:
    z = F.zeros(shape)
    one = jnp.broadcast_to(_ONE, (*shape, F.LIMBS))
    return (z, one, one, z)


def base_point(shape) -> Point:
    return tuple(jnp.broadcast_to(c, (*shape, F.LIMBS)) for c in _BASE)


def point_add(p: Point, q: Point) -> Point:
    """Complete unified addition (add-2008-hwcd-3, a=-1): 8 muls + 1 small.

    Valid for doubling too; inputs must be carry()-normalized (every mul
    output is), operands formed as one lazy add/sub of normalized values.
    """
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = F.mul(F.sub(y1, x1), F.sub(y2, x2))
    b = F.mul(F.add(y1, x1), F.add(y2, x2))
    c = F.mul(F.mul(t1, t2), _D2)
    d = F.mul_small(F.mul(z1, z2), 2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_select(mask: jnp.ndarray, p: Point, q: Point) -> Point:
    """Per-batch-element select: mask [...] bool -> p where True else q."""
    m = mask[..., None]
    return tuple(jnp.where(m, a, b) for a, b in zip(p, q))


def scalar_mult(point: Point, bits: jnp.ndarray) -> Point:
    """[k]P via double-and-add-always: bits [..., nbits] int32, LSB first.

    One lax.scan over the bit axis — 2 complete additions per step, a
    select instead of a branch.  nbits is static (256 for S, 512 for h).
    """
    nbits = bits.shape[-1]
    bits_t = jnp.moveaxis(bits, -1, 0)  # [nbits, ...]

    def step(state, bit):
        acc, q = state
        acc = point_select(bit == 1, point_add(acc, q), acc)
        return (acc, point_add(q, q)), None

    init = (identity(bits.shape[:-1]), point)
    (acc, _), _ = jax.lax.scan(step, init, bits_t, length=nbits)
    return acc


def scalar_mult_base(bits: jnp.ndarray) -> Point:
    return scalar_mult(base_point(bits.shape[:-1]), bits)


@functools.lru_cache(maxsize=None)
def _base_table() -> np.ndarray:
    """Fixed-base window table: [64, 16, 4, 22] int32, T[w, j] = [j*16^w]B
    in affine-extended limbs (Z=1, T=XY).  Built once per process with the
    oracle's affine adds (~1k adds); row j=0 is the identity, which the
    complete addition formula absorbs without a branch."""
    from ba_tpu.crypto import oracle

    table = np.zeros((64, 16, 4, F.LIMBS), np.int32)
    step = oracle.BASE
    for w in range(64):
        pt = (0, 1)
        for j in range(16):
            x, y = pt
            table[w, j, 0] = F._np_limbs(x)
            table[w, j, 1] = F._np_limbs(y)
            table[w, j, 2] = F._np_limbs(1)
            table[w, j, 3] = F._np_limbs(x * y % P)
            if j < 15:
                pt = oracle.edwards_add(pt, step)
        step = oracle.edwards_add(pt, step)  # [16^(w+1)]B from [15*16^w]B
    return table


@functools.lru_cache(maxsize=None)
def _base_table_int8() -> tuple:
    """The window table split into 6-bit int8 halves [64, 16, 88]:
    limb = lo + (hi << 6).  One-hot x table einsums over int8 are exact
    and run on the MXU's native int8 path — the fastest way to gather
    the 64 window points directly into plane-major layout (measured r2:
    half the latency of gather + layout-transpose at 64k lanes).

    Returns NUMPY arrays: this cache is shared across jit traces, so it
    must never hold tracer-lifted device constants (callers jnp.asarray
    at the use site)."""
    t = _base_table().reshape(64, 16, 4 * F.LIMBS)
    return ((t & 63).astype(np.int8), (t >> 6).astype(np.int8))


def fixed_base_mult(s_enc: jnp.ndarray) -> Point:
    """[S]B from the 32-byte little-endian scalar encoding [..., 32] uint8.

    4-bit windows: S = sum_w digit_w * 16^w, so [S]B folds 64 gathered
    table points with complete additions — no doublings, no ladder.  On
    TPU the gather is two int8 one-hot MXU einsums writing plane-major
    entries, folded by the 63-add VMEM tree kernel (ba_tpu.ops.treeadd);
    the jnp fallback scans the 64 additions.
    """
    lo = (s_enc & 0xF).astype(jnp.int32)
    hi = (s_enc >> 4).astype(jnp.int32)
    digits = jnp.stack([lo, hi], axis=-1).reshape(*s_enc.shape[:-1], 64)
    if _use_pallas() and s_enc.ndim == 2:
        from ba_tpu.ops.ladder import TILE
        from ba_tpu.ops.treeadd import fold64_planes

        B = s_enc.shape[0]
        batch_pad = -(-B // TILE) * TILE
        dig = jnp.pad(digits, ((0, batch_pad - B), (0, 0)))
        oh = (dig[..., None] == jnp.arange(16, dtype=jnp.int32)).astype(jnp.int8)
        tab_lo, tab_hi = (jnp.asarray(t) for t in _base_table_int8())
        e_lo = jnp.einsum(
            "bwj,wjp->wpb", oh, tab_lo, preferred_element_type=jnp.int32
        )
        e_hi = jnp.einsum(
            "bwj,wjp->wpb", oh, tab_hi, preferred_element_type=jnp.int32
        )
        ent = (e_lo + (e_hi << 6)).reshape(
            64, 4, F.LIMBS, batch_pad // 128, 128
        )
        return fold64_planes([ent[:, c] for c in range(4)], B)

    table = jnp.asarray(_base_table())  # [64, 16, 4, 22] (jnp fallback only)

    def step(acc, wt):
        tab, dig = wt  # [16, 4, 22], [...]
        entry = tuple(jnp.take(tab[:, c], dig, axis=0) for c in range(4))
        return point_add(acc, entry), None

    digits_t = jnp.moveaxis(digits, -1, 0)  # [64, ...]
    acc, _ = jax.lax.scan(step, identity(s_enc.shape[:-1]), (table, digits_t))
    return acc


def point_eq(p: Point, q: Point) -> jnp.ndarray:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return F.eq(F.mul(x1, z2), F.mul(x2, z1)) & F.eq(F.mul(y1, z2), F.mul(y2, z1))


def compress(p: Point) -> jnp.ndarray:
    """Point -> 32-byte encoding (y with the sign of x in the top bit).

    The modular inverse of Z dominates (one Fermat exponentiation per
    lane); on the Pallas path it runs the p-2 addition-chain kernel
    (ops/powchain.inv_chain: 254 squarings + 13 muls, VMEM-resident) —
    the hot piece of the device signer's R encoding.
    """
    x, y, z, _ = p
    if _use_pallas() and z.ndim == 2:
        from ba_tpu.ops.powchain import pow_planes

        zi = pow_planes(z, P - 2)
    else:
        zi = F.inv(z)
    xa = F.canonical(F.mul(x, zi))
    ya = F.canonical(F.mul(y, zi))
    by = F.to_bytes(ya)
    sign = (xa[..., 0] & 1).astype(jnp.uint8)
    return by.at[..., 31].add(sign << 7)


def _lt_const(by: jnp.ndarray, bound: int) -> jnp.ndarray:
    """Little-endian uint8 [..., 32] < bound, lexicographic from the top."""
    bnd = np.frombuffer(bound.to_bytes(32, "little"), np.uint8)
    lt = jnp.zeros(by.shape[:-1], bool)
    eq_so_far = jnp.ones(by.shape[:-1], bool)
    for i in range(31, -1, -1):
        bi = by[..., i].astype(jnp.int32)
        c = int(bnd[i])
        lt = lt | (eq_so_far & (bi < c))
        eq_so_far = eq_so_far & (bi == c)
    return lt


def decompress(by: jnp.ndarray) -> tuple[Point, jnp.ndarray]:
    """32-byte encodings [..., 32] -> (Point, valid mask).

    RFC 8032 5.1.3: y from the low 255 bits (rejected unless y < p), x
    from x^2 = (y^2-1)/(d y^2+1) via the (p+3)/8 exponent trick, sqrt(-1)
    correction, sign-bit choice; x == 0 with sign 1 is invalid.  On an
    invalid mask lane the returned coordinates are garbage — callers must
    gate on the mask (verify() does).
    """
    sign = (by[..., 31] >> 7).astype(jnp.int32)
    masked = by.at[..., 31].set(by[..., 31] & 0x7F)
    ok = _lt_const(masked, P)
    y = F.from_bytes(masked)
    if _use_pallas() and by.ndim == 2:
        # The whole field chain (incl. the (p-5)/8 addition chain) in one
        # VMEM program; only the root choice stays here.
        from ba_tpu.ops.decompress import decompress_core

        x, x_alt, vxx, u = decompress_core(y)
    else:
        yy = F.square(y)
        u = F.sub(yy, jnp.broadcast_to(_ONE, yy.shape))
        v = F.carry(F.add(F.mul(yy, _D), jnp.broadcast_to(_ONE, yy.shape)))
        v3 = F.mul(F.square(v), v)
        v7 = F.mul(F.square(v3), v)
        t = F.pow_const(F.mul(u, v7), (P - 5) // 8)
        x = F.mul(F.mul(u, v3), t)
        x_alt = F.mul(x, _SQRT_M1)
        vxx = F.mul(v, F.square(x))
    root1 = F.eq(vxx, u)
    root2 = F.eq(vxx, F.sub(F.zeros(u.shape[:-1]), u))
    x = jnp.where(root2[..., None], x_alt, x)
    ok = ok & (root1 | root2)
    xc = F.canonical(x)
    x_zero = F.is_zero(xc)
    ok = ok & ~(x_zero & (sign == 1))
    flip = (xc[..., 0] & 1) != sign
    xc = jnp.where(flip[..., None], F.canonical(F.sub(F.zeros(xc.shape[:-1]), xc)), xc)
    one = jnp.broadcast_to(_ONE, y.shape)
    return (xc, y, one, F.mul(xc, y)), ok


def batch_point_sum(point: Point) -> Point:
    """Sum a batch of points over the leading axis -> a 1-lane Point.

    Log-depth halving tree of complete additions (identity-padded to the
    next power of two), so the whole reduction costs ~B point adds total —
    amortized ~9 field muls per lane, negligible next to any ladder.
    """
    B = point[0].shape[0]
    size = 1 << max(1, (B - 1).bit_length())
    if size != B:
        ident = identity((size - B,))
        point = tuple(
            jnp.concatenate([c, i], axis=0) for c, i in zip(point, ident)
        )
    while size > 1:
        half = size // 2
        point = point_add(
            tuple(c[:half] for c in point),
            tuple(c[half:] for c in point),
        )
        size = half
    return point


def verify_rlc(
    pk: jnp.ndarray,
    msg: jnp.ndarray,
    sig: jnp.ndarray,
    z: jnp.ndarray,
    pk_group: int = 1,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Random-linear-combination BATCH verification of B signatures.

    Checks the single combined equation

        [sum_i z_i S_i mod L] B  ==  sum_i [z_i] R_i  +  sum_j [W_j] A_j,
        W_j = sum_{i in group j} z_i h_i mod L,

    with caller-supplied random 128-bit coefficients z [B, 16] uint8,
    and the COMPARISON COFACTORED: both sides are multiplied by 8 (three
    doublings) before the equality, so every small-order (torsion)
    component — from a malleated R, a torsion-carrying public key, or
    the mod-L-reduced W_j — is annihilated deterministically.  This is
    the standard batch-Ed25519 convention.  If every signature is valid
    the equation holds identically; if any has a defect
    d_i = S_i B - R_i - h_i A_i with a PRIME-ORDER component, the check
    fails except with probability ~2^-128 over z (the RLC soundness
    argument).  Consequence, stated plainly: a signer can craft
    R' = rB + T with T small-order so that the signature fails the
    cofactorless per-signature ``verify`` but passes this cofactored
    batch check; the divergence is one-sided (per-signature-accept
    implies batch-accept for every lane, so batch-reject always means
    some lane is per-signature-invalid), affects only the signer's OWN
    malleated signatures (unforgeability of other messages is untouched
    — the binding of commander to claimed value stands either way), and
    is pinned by test_verify_rlc_cofactored_accepts_torsion_malleated_sig.

    NOT a per-signature verdict: returns ``(batch_ok, enc_ok)`` where
    batch_ok is a scalar bool ("all B valid") and enc_ok [B] flags the
    per-lane encoding checks (point/scalar range) that are exact either
    way.  Callers needing the per-lane mask after a reject fall back to
    ``verify`` (crypto/signed.verify_received does).

    Why it is faster than B independent verifies: the per-lane ladder
    shrinks from 256-bit [h]A to 128-bit [z]R (~halving the hot loop), the
    per-lane 63-add fixed-base [S]B disappears into ONE combined
    fixed-base multiply, and A only ladders once per KEY — ``pk_group``
    consecutive lanes share a public key (2 table sigs per commander, n
    broadcast copies per cluster: crypto/signed.py), so the [W]A work
    divides by the group size.  Lanes whose encodings fail are excluded
    from the combination by zeroing z_i ([0]P folds to the identity), so
    one garbage lane cannot mask the others' verdict.
    """
    from ba_tpu.crypto.scalar import mul_mod_l, sum_mod_l

    B = pk.shape[0]
    assert B % pk_group == 0, (B, pk_group)
    K = B // pk_group
    r_enc = sig[..., :32]
    s_enc = sig[..., 32:]
    pk_u = pk[:: pk_group]  # unique keys, group-major layout
    pts, oks = decompress(jnp.concatenate([pk_u, r_enc], axis=0))
    a_pt = tuple(c[:K] for c in pts)
    r_pt = tuple(c[K:] for c in pts)
    ok_a, ok_r = oks[:K], oks[K:]
    ok_s = _lt_const(s_enc, L)
    enc_ok = jnp.repeat(ok_a, pk_group, axis=0) & ok_r & ok_s
    z = jnp.where(enc_ok[:, None], z, 0).astype(jnp.uint8)

    if _use_pallas():
        from ba_tpu.ops.ladder import window_mult

        _mult = window_mult
    else:
        _mult = scalar_mult
    h = sha512_mod_l(jnp.concatenate([r_enc, pk, msg], axis=-1))  # [B, 32]
    w = sum_mod_l(mul_mod_l(h, z).reshape(K, pk_group, 32))  # [K, 32]
    c = sum_mod_l(mul_mod_l(s_enc, z))  # combined S coefficient [32]

    zr = batch_point_sum(_mult(r_pt, F.bytes_to_bits(z)))
    wa = batch_point_sum(_mult(a_pt, F.bytes_to_bits(w)))
    left = fixed_base_mult(c[None, :])
    right = point_add(zr, wa)
    for _ in range(3):  # cofactor-clear: [8]P on both single-lane points
        left = point_add(left, left)
        right = point_add(right, right)
    batch_ok = point_eq(left, right)[0] & jnp.all(enc_ok)
    return batch_ok, enc_ok


def clamp_scalar(h32: jnp.ndarray) -> jnp.ndarray:
    """RFC 8032 5.1.5 clamp of the low digest half -> the secret scalar a:
    clear the 3 low bits (cofactor), clear bit 255, set bit 254."""
    a = h32.at[..., 0].set(h32[..., 0] & 0xF8)
    return a.at[..., 31].set((h32[..., 31] & 0x3F) | 0x40)


def sign(sk: jnp.ndarray, pk: jnp.ndarray, msg: jnp.ndarray) -> jnp.ndarray:
    """Batched Ed25519 SIGNING on device: sk [B, 32], pk [B, 32],
    msg [B, L] (L static) uint8 -> sig [B, 64] uint8, byte-identical to
    ``oracle.sign`` per lane (Ed25519 is deterministic; pinned by
    tests/test_crypto.py's differential).

    RFC 8032 5.1.6 with every stage batched on the accelerator — the
    sign-side half of the north star's "batched Ed25519 sign/verify
    kernel" obligation (SURVEY.md section 2.3; the reference signs
    nothing, /root/reference/ba.py:39-57, so this is blueprint-driven):

    - key expansion + nonce + challenge are three ``sha512`` calls (the
      80-round Mosaic kernel on TPU, ops/sha512_kernel.py);
    - r and h reduce mod L on device (ops/modl.py kernel);
    - R = [r]B is the SAME fixed-base window path verification uses
      (one-hot int8 MXU einsums + the 63-add VMEM fold, ``fixed_base_mult``)
      — no ladder anywhere: signing is fixed-base only;
    - R's encoding inverts Z via the p-2 addition-chain kernel
      (``compress`` -> ops/powchain.inv_chain);
    - S = (r + h*a) mod L is one 32x32-limb MXU convolution
      (scalar.muladd_bytes) + a mod-L reduction.

    The oracle feeds the unreduced 512-bit nonce to [r]B; reducing r mod
    L first yields the same point (B generates the prime-order subgroup)
    and the same S (arithmetic mod L), hence the same bytes.
    """
    from ba_tpu.crypto.scalar import muladd_bytes

    if _use_pallas():
        from ba_tpu.ops.modl import reduce_mod_l_planes as _modl
    else:
        _modl = reduce_mod_l
    h1 = sha512(sk)
    a = clamp_scalar(h1[..., :32])
    prefix = h1[..., 32:]
    r = sha512_mod_l(jnp.concatenate([prefix, msg], axis=-1))
    r_enc = compress(fixed_base_mult(r))
    k = sha512_mod_l(jnp.concatenate([r_enc, pk, msg], axis=-1))
    s = _modl(muladd_bytes(k, a, r))
    return jnp.concatenate([r_enc, s], axis=-1)


def verify(pk: jnp.ndarray, msg: jnp.ndarray, sig: jnp.ndarray) -> jnp.ndarray:
    """Batched verify: pk [B, 32], msg [B, L] (L static), sig [B, 64] uint8
    -> bool [B].  Semantics identical to oracle.verify per lane.

    A and R decompress in one 2B call (halving that subgraph); the point
    products split asymmetrically — [h]A ladders over the mod-L-reduced
    256-bit h (B lanes), [S]B comes from the fixed-base window table.
    """
    B = pk.shape[0]
    r_enc = sig[..., :32]
    s_enc = sig[..., 32:]
    pts, oks = decompress(jnp.concatenate([pk, r_enc], axis=0))
    a_pt = tuple(c[:B] for c in pts)
    r_pt = tuple(c[B:] for c in pts)
    ok_a, ok_r = oks[:B], oks[B:]
    ok_s = _lt_const(s_enc, L)
    left = fixed_base_mult(s_enc)
    h_bits = F.bytes_to_bits(
        sha512_mod_l(jnp.concatenate([r_enc, pk, msg], axis=-1))
    )  # [B, 256]
    if _use_pallas():
        # Fused tail (r5): h = H(R||A||M) mod L in one sha+modl kernel,
        # then [h]A + the completion add + the projective equality in one
        # window kernel — the two non-ladder stages VERDICT r4 flagged
        # (mod_l 569 ns/sig, finish_add_eq 584 ns/sig standalone) stop
        # existing as dispatches.
        from ba_tpu.ops.ladder import window_verify

        return ok_a & ok_r & ok_s & window_verify(a_pt, h_bits, r_pt, left)
    ha = scalar_mult(a_pt, h_bits)
    right = point_add(r_pt, ha)
    return ok_a & ok_r & ok_s & point_eq(left, right)
