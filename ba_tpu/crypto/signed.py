"""Signed orders: Ed25519-authenticated round-1 broadcast for SM(m).

The bridge between the host signer and the device verifier — the missing
half of the reference's trust model.  The reference's oral messages are
plain strings over RPC (ba.py:39-57): any general can lie about what the
commander said.  SM(m) removes that power with signatures; here the
commander signs each *value* it utters ("commander of instance b says v"),
recipients verify in one batched Ed25519 device call, and the resulting
[B, n] validity mask feeds ``sm_round(sig_valid=...)`` so unauthenticated
values never enter any general's V-set.

Split of labor:

- Signing is host-side (``ba_tpu.crypto.oracle``, pure Python): commanders
  are few (one per instance) and sign at most two distinct values each —
  per-instance memoization makes this O(B) scalar mults, off the hot path.
- Verification is device-side (``ba_tpu.crypto.ed25519.verify``): B x n
  checks per round, the batched hot op (BASELINE config #3).

Message encoding (MSG_LEN bytes, static for the SHA-512 kernel):
``b"BAv1" || instance u32 LE || value u8 || zero pad``.  Binding the
instance id prevents cross-instance replay inside a batch; the value is
the signed claim itself.
"""

from __future__ import annotations

import numpy as np

from ba_tpu.crypto import oracle

MSG_LEN = 16
_MAGIC = b"BAv1"

_verify_jit = None  # lazily-created jitted ed25519.verify (shared cache)


def commander_keys(batch: int, seed: int = 0) -> tuple[list[bytes], np.ndarray]:
    """Deterministic per-instance commander keypairs.

    Returns (secret keys as a list of 32-byte strings, public keys as a
    uint8 [B, 32] array ready for the device verifier).
    """
    sks, pks = [], []
    for b in range(batch):
        sk, pk = oracle.keypair(f"{seed}:{b}".encode())
        sks.append(sk)
        pks.append(np.frombuffer(pk, np.uint8))
    return sks, np.stack(pks)


def order_message(instance: int, value: int) -> bytes:
    """The signed claim: "commander of ``instance`` says ``value``"."""
    body = _MAGIC + int(instance).to_bytes(4, "little") + bytes([value & 0xFF])
    return body.ljust(MSG_LEN, b"\0")


def sign_received(
    sks: list[bytes],
    pks: np.ndarray,
    received: np.ndarray,
    corrupt: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Sign the round-1 values: received [B, n] int -> (msgs, sigs) uint8.

    Each (b, i) entry is the commander-of-b-signed message for the value
    general i received; a commander signs each distinct value once
    (deterministic Ed25519), so equivocation = two honestly-signed
    contradictory claims — exactly the paper's faulty-commander power.

    ``corrupt`` (optional [B, n] bool) flips a signature byte on marked
    entries, modelling transmission/forgery faults the verifier must
    reject.

    Returns msgs uint8 [B, n, MSG_LEN] and sigs uint8 [B, n, 64].
    """
    B, n = received.shape
    msgs = np.zeros((B, n, MSG_LEN), np.uint8)
    sigs = np.zeros((B, n, 64), np.uint8)
    for b in range(B):
        pk = pks[b].tobytes()
        cache: dict[int, tuple[bytes, bytes]] = {}
        for i in range(n):
            v = int(received[b, i])
            if v not in cache:
                msg = order_message(b, v)
                cache[v] = (msg, oracle.sign(sks[b], pk, msg))
            msg, sig = cache[v]
            msgs[b, i] = np.frombuffer(msg, np.uint8)
            sigs[b, i] = np.frombuffer(sig, np.uint8)
    if corrupt is not None:
        sigs = sigs.copy()
        sigs[..., 0] ^= np.where(corrupt, np.uint8(0xFF), np.uint8(0))
    return msgs, sigs


def verify_received(pks, msgs, sigs):
    """Batched device verification: -> [B, n] bool sig-validity mask.

    pks [B, 32], msgs [B, n, MSG_LEN], sigs [B, n, 64] (uint8, any
    array-like).  Flattens to one [B*n] ``ed25519.verify`` call — the hot
    batched kernel — and reshapes back.
    """
    import jax
    import jax.numpy as jnp

    from ba_tpu.crypto.ed25519 import verify

    global _verify_jit
    if _verify_jit is None:
        _verify_jit = jax.jit(verify)
    pks = jnp.asarray(pks, jnp.uint8)
    msgs = jnp.asarray(msgs, jnp.uint8)
    sigs = jnp.asarray(sigs, jnp.uint8)
    B, n = msgs.shape[:2]
    pk_bn = jnp.broadcast_to(pks[:, None, :], (B, n, 32)).reshape(B * n, 32)
    ok = _verify_jit(pk_bn, msgs.reshape(B * n, -1), sigs.reshape(B * n, 64))
    return ok.reshape(B, n)


def sign_round1(key, state, seed: int = 0, corrupt: np.ndarray | None = None):
    """The shared sign-then-verify preamble of every signed agreement.

    Runs the round-1 broadcast, signs each uttered value host-side, and
    verifies the batch on device.  Returns ``(relay_key, received,
    sig_valid)`` ready for any SM relay path (unsharded or node-sharded).
    """
    import jax.random as jr

    from ba_tpu.core.om import round1_broadcast

    k1, k2 = jr.split(key)
    received = round1_broadcast(k1, state)
    sks, pks = commander_keys(state.batch, seed)
    msgs, sigs = sign_received(sks, pks, np.asarray(received), corrupt)
    sig_valid = verify_received(pks, msgs, sigs)
    return k2, received, sig_valid


def signed_sm_agreement(
    key,
    state,
    m: int,
    withhold=None,
    corrupt: np.ndarray | None = None,
    seed: int = 0,
    collapsed: bool = False,
):
    """End-to-end signed SM(m): sign -> verify on device -> relay -> quorum.

    The full signed upgrade of the reference's ``actual-order`` hot path
    (ba.py:376-399): round-1 broadcast with commander equivocation
    (ba.py:268-273 semantics), host Ed25519 signing of each uttered value,
    batched device verification, and m relay rounds gated on the validity
    mask.  Returns the ``om1_agreement``-shaped dict plus ``sig_valid``.
    """
    from ba_tpu.core.sm import sm_agreement

    k2, received, sig_valid = sign_round1(key, state, seed, corrupt)
    out = sm_agreement(k2, state, m, withhold, sig_valid, received, collapsed)
    out["sig_valid"] = sig_valid
    return out


def signed_sm_agreement_sharded(
    mesh,
    key,
    state,
    m: int,
    corrupt: np.ndarray | None = None,
    seed: int = 0,
    collapsed: bool = True,
):
    """Signed SM(m) across a device mesh: the n=1024-scale signed path.

    Same sign -> verify -> relay -> quorum pipeline as
    ``signed_sm_agreement``, but the relay and quorum run node-sharded
    (``ba_tpu.parallel.sm_parallel.sm_node_sharded``): instances shard over
    "data", the n generals of each cluster over "node".
    """
    from ba_tpu.parallel.sm_parallel import sm_node_sharded

    k2, received, sig_valid = sign_round1(key, state, seed, corrupt)
    out = sm_node_sharded(
        mesh, k2, state, m,
        received=received, sig_valid=sig_valid, collapsed=collapsed,
    )
    out["sig_valid"] = sig_valid
    return out
