"""Signed orders: Ed25519-authenticated round-1 broadcast for SM(m).

The bridge between the host signer and the device verifier — the missing
half of the reference's trust model.  The reference's oral messages are
plain strings over RPC (ba.py:39-57): any general can lie about what the
commander said.  SM(m) removes that power with signatures; here the
commander signs each *value* it utters ("commander of instance b says v"),
recipients verify in one batched Ed25519 device call, and the resulting
[B, n] validity mask feeds ``sm_round(sig_valid=...)`` so unauthenticated
values never enter any general's V-set.

Split of labor:

- Signing is host-side: commanders are few (one per instance) and sign at
  most two distinct values each, so signing is O(B) signs off the hot
  path.  Batch signing prefers the framework's own C++ library
  (``ba_tpu.native``, one OpenMP'd C call per batch — ~44k signs/s/core
  vs ~10k through per-call ``cryptography``); per-call signing uses the
  baked-in ``cryptography`` wheel when importable; the pure-Python
  ``ba_tpu.crypto.oracle`` is the universal fallback and ground truth.
  Ed25519 is deterministic, so all three produce identical bytes
  (tests/test_sm.py and tests/test_native.py pin this).
- Verification is device-side (``ba_tpu.crypto.ed25519.verify``): B x n
  checks per round, the batched hot op (BASELINE config #3).  For
  sweep-scale work the per-(instance, value) signature tables let the
  verifier check each distinct signature once ([B, 2]) and gather the
  [B, n] validity mask, instead of re-verifying n identical copies.

Message encoding (MSG_LEN bytes, static for the SHA-512 kernel):
``b"BAv1" || instance u32 LE || value u8 || zero pad``.  Binding the
instance id prevents cross-instance replay inside a batch; the value is
the signed claim itself.
"""

from __future__ import annotations

import os

import numpy as np

from ba_tpu import obs
from ba_tpu.crypto import oracle

try:  # native Ed25519 (baked-in wheel); oracle is the fallback + oracle
    from cryptography.hazmat.primitives import serialization as _ser
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _NativeSK,
    )

    _HAVE_NATIVE = True
except ImportError:  # pragma: no cover - cryptography is baked into the image
    _HAVE_NATIVE = False

MSG_LEN = 16
_MAGIC = b"BAv1"
# Round-bound claims (the sign-ahead lane, ISSUE 14) carry their own
# domain separator: a "says v" table signature can never satisfy a
# "says v in round r" verifier or vice versa, whatever the pad bytes.
_MAGIC_ROUND = b"BAr1"

_verify_jit = None  # lazily-created jitted ed25519.verify (shared cache)
_verify_rlc_jit = None  # lazily-created jitted ed25519.verify_rlc


def host_publickey(sk: bytes) -> bytes:
    """RFC 8032 public key, native-accelerated when available."""
    if _HAVE_NATIVE:
        return (
            _NativeSK.from_private_bytes(sk)
            .public_key()
            .public_bytes(_ser.Encoding.Raw, _ser.PublicFormat.Raw)
        )
    return oracle.publickey(sk)


def host_sign(sk: bytes, pk: bytes, msg: bytes) -> bytes:
    """RFC 8032 signature, native-accelerated when available.

    Deterministic, so the native path and ``oracle.sign`` are
    byte-identical (pinned by test_host_signer_matches_oracle).
    """
    if _HAVE_NATIVE:
        return _NativeSK.from_private_bytes(sk).sign(msg)
    return oracle.sign(sk, pk, msg)


def _native_or_none():
    """The ba_tpu.native C++ library, or None (no compiler / disabled)."""
    from ba_tpu import native

    return native if native.available() else None


def commander_keys(batch: int, seed: int = 0) -> tuple[list[bytes], np.ndarray]:
    """Deterministic per-instance commander keypairs.

    Returns (secret keys as a list of 32-byte strings, public keys as a
    uint8 [B, 32] array ready for the device verifier).  The sk derivation
    matches ``oracle.keypair`` exactly; pk computation uses the C++ batch
    path when available, else the per-call native signer.
    """
    sks = [oracle.secret_from_seed(f"{seed}:{b}".encode()) for b in range(batch)]
    nat = _native_or_none()
    if nat is not None:
        sk_arr = np.stack([np.frombuffer(s, np.uint8) for s in sks])
        return sks, nat.publickey_batch(sk_arr)
    return sks, np.stack(
        [np.frombuffer(host_publickey(sk), np.uint8) for sk in sks]
    )


def order_message(instance: int, value: int) -> bytes:
    """The signed claim: "commander of ``instance`` says ``value``"."""
    body = _MAGIC + int(instance).to_bytes(4, "little") + bytes([value & 0xFF])
    return body.ljust(MSG_LEN, b"\0")


def sign_value_tables(
    sks: list[bytes], pks: np.ndarray, n_values: int = 2, base: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Per-(instance, value) signature tables: ``n_values`` signs per commander.

    A commander utters at most ``n_values`` distinct claims and Ed25519 is
    deterministic, so every signature the protocol can ever carry lives in
    these tables: msgs uint8 [B, V, MSG_LEN], sigs uint8 [B, V, 64].
    Equivocation = two honestly-signed contradictory claims — exactly the
    paper's faulty-commander power.

    ``base`` offsets the instance ids bound into the messages: row b signs
    claims for instance ``base + b``.  Chunked setups
    (``setup_signed_tables_overlapped``) MUST pass their chunk offset here
    — a chunk signed with local ids would re-bind instances 0..chunk-1 and
    void the anti-cross-instance-replay binding (module docstring).
    """
    B = len(sks)
    msgs = _value_table_msgs(B, n_values, base)
    return msgs, _sign_table_msgs(sks, pks, msgs)


def key_table_arrays(
    sks: list[bytes], pks: np.ndarray, n_values: int
) -> tuple[np.ndarray, np.ndarray]:
    """The per-signature-row key arrays ``sign_table_msgs_arrays`` wants
    — sk/pk uint8 [B*V, 32], each key repeated once per value column.

    These are INVARIANT for a fixed key-set: ``SignAheadLane`` hoists
    them to construction (ISSUE 16 small fix) instead of re-deriving
    them inside every window's signing call, where the np.frombuffer
    stack over B secret keys was a measurable per-round host cost.
    """
    sk_arr = np.stack([np.frombuffer(s, np.uint8) for s in sks])
    return (
        np.repeat(sk_arr, n_values, axis=0),
        np.repeat(np.asarray(pks, np.uint8), n_values, axis=0),
    )


def sign_table_msgs_arrays(
    sk_rep: np.ndarray, pk_rep: np.ndarray, msgs: np.ndarray
) -> np.ndarray:
    """Host-sign a [N, V, MSG_LEN] message table with PRECOMPUTED key
    arrays (``key_table_arrays``, possibly np.tile'd over a window of
    rounds) -> sigs uint8 [N, V, 64].

    jax-free BY CONTRACT: this is the signing body pool worker
    processes call (``ba_tpu.crypto.pool``), so it must never touch the
    device tier.  Native C++ batch path when available, per-call signer
    otherwise; Ed25519 determinism makes both byte-identical.
    """
    N, n_values = msgs.shape[:2]
    with obs.timed_span("host_sign", "host_sign_s", batch=N, values=n_values):
        nat = _native_or_none()
        if nat is not None:
            sigs = nat.sign_batch(
                sk_rep, pk_rep, msgs.reshape(N * n_values, MSG_LEN)
            ).reshape(N, n_values, 64)
        else:
            sigs = np.zeros((N, n_values, 64), np.uint8)
            flat_sk = sk_rep.reshape(N * n_values, 32)
            flat_pk = pk_rep.reshape(N * n_values, 32)
            for i in range(N):
                for v in range(n_values):
                    row = i * n_values + v
                    sigs[i, v] = np.frombuffer(
                        host_sign(
                            flat_sk[row].tobytes(),
                            flat_pk[row].tobytes(),
                            msgs[i, v].tobytes(),
                        ),
                        np.uint8,
                    )
    obs.default_registry().counter("host_signs_total").inc(N * n_values)
    return sigs


def _sign_table_msgs(sks: list[bytes], pks: np.ndarray, msgs: np.ndarray) -> np.ndarray:
    """Host-sign a [B, V, MSG_LEN] message table -> sigs uint8 [B, V, 64].

    The one signing body behind :func:`sign_value_tables` and the
    round-bound :func:`sign_round_tables` (sign-ahead lane, ISSUE 14):
    builds the repeated key arrays per call and delegates to
    :func:`sign_table_msgs_arrays` — callers with an invariant key-set
    (the lane) hoist :func:`key_table_arrays` and call the arrays body
    directly (ISSUE 16).
    """
    n_values = msgs.shape[1]
    sk_rep, pk_rep = key_table_arrays(sks, pks, n_values)
    return sign_table_msgs_arrays(sk_rep, pk_rep, msgs)


def round_message(instance: int, round_index: int, value: int) -> bytes:
    """The round-bound claim: "commander of ``instance`` says ``value``
    in round ``round_index``" (sign-ahead lane, ISSUE 14).

    Binding the round next to the instance id closes the cross-ROUND
    replay a multi-round signed protocol would otherwise admit (a round
    r signature re-presented at round r' != r verifies under the
    round-free encoding); the distinct magic keeps the two table
    grammars mutually unverifiable.
    """
    body = (
        _MAGIC_ROUND
        + int(instance).to_bytes(4, "little")
        + int(round_index).to_bytes(4, "little")
        + bytes([value & 0xFF])
    )
    return body.ljust(MSG_LEN, b"\0")


def _round_table_msgs(
    B: int, round_index: int, n_values: int, base: int
) -> np.ndarray:
    """Vectorized :func:`round_message` over the [B, V] table grid —
    byte-identical to the per-call encoder (pinned by
    tests/test_signed_pipeline.py) at O(1) numpy ops, the
    :func:`_value_table_msgs` discipline."""
    msgs = np.zeros((B, n_values, MSG_LEN), np.uint8)
    msgs[:, :, 0:4] = np.frombuffer(_MAGIC_ROUND, np.uint8)
    msgs[:, :, 4:8] = (
        np.arange(base, base + B, dtype="<u4").view(np.uint8).reshape(B, 1, 4)
    )
    msgs[:, :, 8:12] = np.frombuffer(
        np.uint32(round_index).tobytes(), np.uint8
    )
    msgs[:, :, 12] = np.arange(n_values, dtype=np.uint8)[None, :]
    return msgs


def sign_round_tables(
    sks: list[bytes],
    pks: np.ndarray,
    round_index: int,
    n_values: int = 2,
    base: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-(instance, value) signature tables for ONE round: the unit of
    work the sign-ahead host lane (``ba_tpu.parallel.signing``) prepares
    for rounds d+1..d+depth while dispatches d-depth..d are in flight.

    Same shapes and signing substrate as :func:`sign_value_tables`
    (msgs uint8 [B, V, MSG_LEN], sigs uint8 [B, V, 64]); the messages
    bind (instance, ROUND, value) via :func:`round_message`, so each
    round's tables are distinct bytes under the same commander keys —
    Ed25519 determinism makes a round-free per-round table a no-op
    recomputation, and the round binding is what makes per-round
    signing a real protocol obligation rather than busywork.
    """
    B = len(sks)
    msgs = _round_table_msgs(B, round_index, n_values, base)
    return msgs, _sign_table_msgs(sks, pks, msgs)


def _value_table_msgs(B: int, n_values: int, base: int) -> np.ndarray:
    """Vectorized order_message over the table grid: byte-identical to the
    per-call encoder (pinned by test_sign_value_tables_match_order_message)
    but O(1) numpy ops instead of 2B Python calls — at sweep scale the
    loop was a measurable slice of the signing setup."""
    msgs = np.zeros((B, n_values, MSG_LEN), np.uint8)
    msgs[:, :, 0:4] = np.frombuffer(_MAGIC, np.uint8)
    msgs[:, :, 4:8] = (
        np.arange(base, base + B, dtype="<u4").view(np.uint8).reshape(B, 1, 4)
    )
    msgs[:, :, 8] = np.arange(n_values, dtype=np.uint8)[None, :]
    return msgs


_sign_jit = None  # lazily-created jitted ed25519.sign (shared cache)


def sign_value_tables_device(
    sks: list[bytes], pks: np.ndarray, n_values: int = 2, base: int = 0
):
    """``sign_value_tables`` with the signing itself ON THE DEVICE: the
    sign-side half of the north star's batched-kernel obligation
    (ba_tpu.crypto.ed25519.sign — SHA-512, mod-L, fixed-base [r]B and the
    inv-chain compress all run as TPU kernels).

    Returns ``(msgs, sigs)`` where msgs is host numpy uint8
    [B, V, MSG_LEN] and sigs is a DEVICE array uint8 [B, V, 64] — the
    dispatch returns on ACK (tunnel semantics), so callers overlap
    downstream device work (the table verify) for free and fetch sigs
    once at drain time (``setup_signed_tables_overlapped`` does).  Bytes
    are identical to the host/oracle tables (Ed25519 determinism; pinned
    by test_setup_device_sign_matches_host).
    """
    import jax
    import jax.numpy as jnp

    from ba_tpu.crypto import ed25519

    global _sign_jit
    if _sign_jit is None:
        _sign_jit = jax.jit(ed25519.sign)
    B = len(sks)
    msgs = _value_table_msgs(B, n_values, base)
    sk_arr = np.repeat(
        np.stack([np.frombuffer(s, np.uint8) for s in sks]), n_values, axis=0
    )
    pk_arr = np.repeat(np.asarray(pks, np.uint8), n_values, axis=0)
    with obs.span("device_sign_dispatch", batch=B, values=n_values):
        # Host-side dispatch cost only: the sign program executes
        # asynchronously and drains at the caller's fetch.
        sigs = _sign_jit(
            jnp.asarray(sk_arr),
            jnp.asarray(pk_arr),
            jnp.asarray(msgs.reshape(B * n_values, MSG_LEN)),
        )
    return msgs, sigs.reshape(B, n_values, 64)


def sign_received(
    sks: list[bytes],
    pks: np.ndarray,
    received: np.ndarray,
    corrupt: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Sign the round-1 values: received [B, n] int -> (msgs, sigs) uint8.

    Each (b, i) entry is the commander-of-b-signed message for the value
    general i received, gathered from the ``sign_value_tables`` (a
    commander signs each distinct value once).

    ``corrupt`` (optional [B, n] bool) flips a signature byte on marked
    entries, modelling transmission/forgery faults the verifier must
    reject.

    Returns msgs uint8 [B, n, MSG_LEN] and sigs uint8 [B, n, 64].
    """
    B, n = received.shape
    received = np.asarray(received).astype(np.int64)
    assert received.min() >= 0 and received.max() <= 1, "round-1 values are 0/1"
    msgs_t, sigs_t = sign_value_tables(sks, pks)
    rows = np.arange(B)[:, None]
    msgs = msgs_t[rows, received]  # [B, n, MSG_LEN]
    sigs = sigs_t[rows, received]  # [B, n, 64]
    if corrupt is not None:
        sigs = sigs.copy()
        sigs[..., 0] ^= np.where(corrupt, np.uint8(0xFF), np.uint8(0))
    return msgs, sigs


def _verify_chunk() -> int:
    """Max signatures per ed25519.verify dispatch.

    The jnp ladder's live intermediates spill past ~4k lanes and
    throughput collapses superlinearly (r2, like-for-like timings: ~25x
    slower per signature at 20480 lanes than at 4096); the Pallas kernel
    set (ba_tpu.ops) has no such cliff and keeps scaling through
    64k-signature chunks (~270-360k verifies/s, host-fetch-timed r2),
    where the fixed dispatch cost amortizes.
    """
    env = os.environ.get("BA_TPU_VERIFY_CHUNK")
    if env:
        chunk = int(env)
        if chunk <= 0:
            raise ValueError(f"BA_TPU_VERIFY_CHUNK must be positive, got {env!r}")
        return chunk
    from ba_tpu.crypto.ed25519 import _use_pallas

    return 65536 if _use_pallas() else 4096


def verify_received(pks, msgs, sigs):
    """Batched device verification: -> [B, n] bool sig-validity mask.

    pks [B, 32], msgs [B, n, MSG_LEN], sigs [B, n, 64] (uint8, any
    array-like).  Flattens to [B*n] and dispatches ``ed25519.verify`` in
    chunk-sized pieces (padding the tail so one compiled kernel serves
    every call), then reshapes back; see ``_verify_chunk`` for sizing.

    ``BA_TPU_VERIFY_RLC=1`` routes through the random-linear-combination
    BATCH check first (``verify_received_rlc``: one cofactored combined
    equation, ~2x same-window when all signatures are valid — the hot
    path) with this exact per-signature path as the fallback on reject;
    see verify_received_rlc's docstring for the one documented
    cofactored-acceptance divergence.  Default off: exact cofactorless
    per-signature semantics.

    On the CPU backend the jnp ladder is pathologically slow (~0.3k/s;
    the Pallas kernels are TPU-only), so there the batch routes through
    the C++ library instead (~12k/s/core, byte-identical accept set) —
    ``BA_TPU_VERIFY_NATIVE=0`` forces the jnp path, ``=1`` forces native
    everywhere.
    """
    if os.environ.get("BA_TPU_VERIFY_RLC", "0") == "1":
        return verify_received_rlc(pks, msgs, sigs)
    return _verify_received_exact(pks, msgs, sigs)


def _verify_received_exact(pks, msgs, sigs):
    """The per-signature body of ``verify_received`` (also the RLC
    fallback — calling it directly sidesteps the env knob so the two
    can never recurse)."""
    import jax
    import jax.numpy as jnp

    from ba_tpu.crypto.ed25519 import verify

    mode = os.environ.get("BA_TPU_VERIFY_NATIVE", "auto")
    use_native = (
        mode == "1"
        or (mode == "auto" and jax.devices()[0].platform == "cpu")
    )
    if use_native:
        nat = _native_or_none()
        if nat is None and mode == "1":
            raise RuntimeError(
                "BA_TPU_VERIFY_NATIVE=1 but the native library is "
                "unavailable (no compiler?)"
            )
        if nat is not None:
            pks_np = np.asarray(pks, np.uint8)
            msgs_np = np.asarray(msgs, np.uint8)
            sigs_np = np.asarray(sigs, np.uint8)
            B, n = msgs_np.shape[:2]
            pk_bn = np.repeat(pks_np, n, axis=0)
            ok = nat.verify_batch(
                pk_bn, msgs_np.reshape(B * n, -1), sigs_np.reshape(B * n, 64)
            )
            return jnp.asarray(ok.reshape(B, n))

    global _verify_jit
    if _verify_jit is None:
        _verify_jit = jax.jit(verify)
    pks = jnp.asarray(pks, jnp.uint8)
    msgs = jnp.asarray(msgs, jnp.uint8)
    sigs = jnp.asarray(sigs, jnp.uint8)
    B, n = msgs.shape[:2]
    total = B * n
    pk_bn = jnp.broadcast_to(pks[:, None, :], (B, n, 32)).reshape(total, 32)
    msgs = msgs.reshape(total, -1)
    sigs = sigs.reshape(total, 64)
    chunk = _verify_chunk()
    if total <= chunk:
        return _verify_jit(pk_bn, msgs, sigs).reshape(B, n)
    pad = (-total) % chunk
    if pad:
        pk_bn = jnp.concatenate([pk_bn, jnp.tile(pk_bn[:1], (pad, 1))])
        msgs = jnp.concatenate([msgs, jnp.tile(msgs[:1], (pad, 1))])
        sigs = jnp.concatenate([sigs, jnp.tile(sigs[:1], (pad, 1))])
    oks = [
        _verify_jit(
            pk_bn[o : o + chunk],
            msgs[o : o + chunk],
            sigs[o : o + chunk],
        )
        for o in range(0, total + pad, chunk)
    ]
    return jnp.concatenate(oks)[:total].reshape(B, n)


def host_verify_route() -> bool:
    """True when :func:`_verify_received_exact` would route this
    process's verifies through the HOST (native C++ batch verifier)
    rather than a device dispatch — the condition under which the
    sign-ahead lane may keep verdicts in host numpy (and hence cache /
    pool-shard them, ISSUE 16) without changing a single code path's
    bytes.  Imports jax for the platform probe, so this is lane-side
    only; pool workers never call it.
    """
    mode = os.environ.get("BA_TPU_VERIFY_NATIVE", "auto")
    if mode == "1":
        return True
    if mode != "auto":
        return False
    import jax

    return (
        jax.devices()[0].platform == "cpu" and _native_or_none() is not None
    )


def verify_host_exact(pks, msgs, sigs) -> np.ndarray:
    """Exact per-signature verification ON HOST -> bool [B, n] numpy.

    jax-free BY CONTRACT: the verify body pool worker processes call
    (``ba_tpu.crypto.pool``), and the lane's own CPU leg at coalesced
    sizes.  Byte-identical verdicts to ``_verify_received_exact``'s
    native branch (it IS that branch, minus the device wrap); the
    per-call ``cryptography``/oracle ladder is the no-compiler
    fallback, verdict-identical by RFC 8032 (tests pin it).
    """
    pks_np = np.asarray(pks, np.uint8)
    msgs_np = np.asarray(msgs, np.uint8)
    sigs_np = np.asarray(sigs, np.uint8)
    B, n = msgs_np.shape[:2]
    nat = _native_or_none()
    if nat is not None:
        pk_bn = np.repeat(pks_np, n, axis=0)
        return nat.verify_batch(
            pk_bn, msgs_np.reshape(B * n, -1), sigs_np.reshape(B * n, 64)
        ).reshape(B, n)
    ok = np.zeros((B, n), np.bool_)
    for b in range(B):
        pk = pks_np[b].tobytes()
        for i in range(n):
            msg = msgs_np[b, i].tobytes()
            sig = sigs_np[b, i].tobytes()
            if _HAVE_NATIVE:
                from cryptography.exceptions import InvalidSignature
                from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                    Ed25519PublicKey,
                )

                try:
                    Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
                    ok[b, i] = True
                except (InvalidSignature, ValueError):
                    ok[b, i] = False
            else:
                ok[b, i] = oracle.verify(pk, msg, sig)
    return ok


def fresh_rlc_coeffs(total: int) -> np.ndarray:
    """Unpredictable 128-bit RLC coefficients, one per lane: uint8
    [total, 16] from OS entropy.  Batch-verification soundness needs z
    unknown to whoever chose the signatures, so these are drawn fresh
    per call — never derived from the batch contents or a fixed seed.
    (Cofactor clearing is verify_rlc's job — it multiplies the final
    comparison by 8 — so z needs no structure beyond uniformity.)"""
    import secrets

    return np.frombuffer(secrets.token_bytes(total * 16), np.uint8).reshape(
        total, 16
    )


def rlc_batch_ok(pks, msgs, sigs):
    """Dispatch the chunked RLC batch check and return the DEVICE scalar
    verdict ("every signature valid") WITHOUT fetching it.

    The overlap primitive behind both RLC routes: dispatches return on
    ACK (tunnel semantics), so callers queue the check behind other
    device work and fetch the verdict once at drain time
    (``setup_signed_tables_overlapped`` under ``BA_TPU_VERIFY_RLC=1``);
    ``verify_received_rlc`` is the blocking wrapper.

    Chunking (ADVICE r4): large batches pad to a fixed multiple of the
    per-dispatch chunk (a multiple of ``n`` so the pk-group layout
    survives), so one compiled program serves every production-scale
    call instead of a monolithic kernel per (B, n) shape; calls SMALLER
    than a chunk dispatch at their own lane count — same policy as
    ``_verify_received_exact``, because padding a 20-lane call to the
    64k production chunk would multiply its cost ~3000x, not cap it.
    Padding replicates the leading pk-group: replicated-valid lanes fold
    to the identity defect (no effect), replicated-invalid lanes keep a
    nonzero defect (still reject, and a reject only ever routes to the
    exact fallback) — so padding never flips a verdict that matters.
    """
    import jax
    import jax.numpy as jnp

    from ba_tpu.crypto.ed25519 import verify_rlc

    global _verify_rlc_jit
    if _verify_rlc_jit is None:
        _verify_rlc_jit = jax.jit(
            verify_rlc, static_argnames="pk_group"
        )
    pks = jnp.asarray(pks, jnp.uint8)
    msgs = jnp.asarray(msgs, jnp.uint8)
    sigs = jnp.asarray(sigs, jnp.uint8)
    B, n = msgs.shape[:2]
    total = B * n
    pk_bn = jnp.broadcast_to(pks[:, None, :], (B, n, 32)).reshape(total, 32)
    msgs_f = msgs.reshape(total, -1)
    sigs_f = sigs.reshape(total, 64)
    chunk = min(max(n, (_verify_chunk() // n) * n), total)
    pad = (-total) % chunk
    if pad:
        reps = pad // n  # pad whole pk-groups to keep group-major layout
        pk_bn = jnp.concatenate([pk_bn, jnp.tile(pk_bn[:n], (reps, 1))])
        msgs_f = jnp.concatenate([msgs_f, jnp.tile(msgs_f[:n], (reps, 1))])
        sigs_f = jnp.concatenate([sigs_f, jnp.tile(sigs_f[:n], (reps, 1))])
    z = jnp.asarray(fresh_rlc_coeffs(total + pad))
    oks = [
        _verify_rlc_jit(
            pk_bn[o : o + chunk],
            msgs_f[o : o + chunk],
            sigs_f[o : o + chunk],
            z[o : o + chunk],
            pk_group=n,
        )[0]
        for o in range(0, total + pad, chunk)
    ]
    return oks[0] if len(oks) == 1 else jnp.stack(oks).all()


def verify_received_rlc(pks, msgs, sigs):
    """Batched verification via the random-linear-combination check, with
    an exact per-signature fallback on reject: -> [B, n] bool mask.

    The common case of every hot path is all-valid signatures (honest
    commanders sign correctly; the adversary model corrupts *values*, not
    usually encodings), and there ``ed25519.verify_rlc`` replaces B*n
    independent verifies with one combined equation per chunk at roughly
    half the per-lane ladder work and no per-lane fixed-base multiply
    (the [W]A ladders also collapse n-fold because each instance's n
    copies share a commander key).  On a reject — any invalid signature —
    the exact per-signature ``verify_received`` runs and its mask is
    returned; only the (rare) mixed-validity case pays both dispatches.
    Soundness: a batch containing a signature with a prime-order defect
    passes the combined check with probability ~2^-125 over the fresh
    coefficients.

    DOCUMENTED divergences from the per-signature path (see
    ed25519.verify_rlc's contract for why neither weakens the
    commander-to-value binding):

    - the batch check is cofactored (the batch-Ed25519 standard), so a
      signer's own torsion-malleated signature — R deliberately offset
      by a small-order point — is accepted here but rejected by the
      cofactorless per-lane path;
    - consequently RLC-mode acceptance of such a signature is
      BATCH-DEPENDENT (ADVICE r4): in an all-otherwise-valid batch the
      cofactored check accepts it, but if ANY other lane is invalid the
      batch rejects and the cofactorless fallback rejects the malleated
      lane too.  The divergence stays one-sided either way (only ever
      *extra* accepts of a signer's own malleated encoding, never a
      forgery), and only RLC mode exhibits the batch dependence.

    Callers that need strict cofactorless semantics must use
    ``verify_received`` directly.
    """
    import jax.numpy as jnp

    B, n = np.shape(msgs)[:2]
    if bool(rlc_batch_ok(pks, msgs, sigs)):
        return jnp.ones((B, n), bool)
    return _verify_received_exact(pks, msgs, sigs)


def sign_on_device() -> bool:
    """Resolve the BA_TPU_SIGN_DEVICE knob: 1 forces the TPU signer, 0
    forces host signing, default "auto" signs on-device exactly when the
    Pallas kernels are live AND the platform really is TPU.  Auto is safe
    because SETUP_AB_r5 measured setup total_s parity (device 0.4196 s vs
    best host 0.4197 s at batch 10240) with host sign_s 13x lower; on CPU
    backends the host signer stays the right substrate — which is why
    auto checks the actual platform, not just ``use_pallas()``:
    ``BA_TPU_PALLAS=1`` on CPU (the interpret-mode test configuration)
    must NOT silently flip the signing default to the emulated device
    path (ADVICE r5).  Forcing ``BA_TPU_SIGN_DEVICE=1`` still wins for
    callers who want interpret-mode device signing deliberately."""
    env = os.environ.get("BA_TPU_SIGN_DEVICE", "auto")
    if env in ("0", "1"):
        return env == "1"
    from ba_tpu.utils.platform import use_pallas

    if not use_pallas():
        return False
    import jax

    return jax.devices()[0].platform == "tpu"


def setup_signed_tables_overlapped(
    batch: int,
    seed: int = 0,
    chunks: int = 2,
):
    """Key-set setup with host signing OVERLAPPED against device verify.

    The sweep north star's one-time setup used to be strictly sequential:
    sign all 2*batch table signatures on the host, then upload + verify
    them on device — so the wall clock paid sign_time + verify_time
    (BENCH_r03: 0.33 s + 0.19 s for batch=10240).  Device dispatches on
    this backend return on ACK (the queue drains only at a host fetch), so
    chunking the batch lets chunk c's upload+verify execute on the chip
    while the host is already signing chunk c+1: the wall clock tends to
    max(sign, verify) + one chunk's drain instead of the sum.

    Each chunk is the same shape, so the verify kernel compiles once (at
    the chunk's own lane count — no padding to the 64k production chunk);
    callers warm that shape off the clock with ``warm_signed_tables``.

    ``BA_TPU_SIGN_DEVICE`` moves the signing itself onto the TPU
    (``sign_value_tables_device``): each chunk's sign program queues
    behind the previous chunk's verify, the host loop only builds
    messages and dispatches, and everything drains at the final fetch —
    host CPU leaves the critical path entirely (the r4 measurement that
    motivated this: host sign_s 0.29-0.31 s was the dominant setup cost,
    SETUP_AB_r4.json).  Default "auto" signs on-device exactly when the
    Pallas kernels are live (real TPU): SETUP_AB_r5 measured total_s
    parity with the best host mode (0.4196 vs 0.4197 s, batch 10240) —
    host sign_s drops 13x (0.21 -> 0.016 s) and the device drain absorbs
    it, so offloading costs nothing and frees the host.  ``1``/``0``
    force; host CPU remains the right substrate when the backend is CPU
    jax (the kernels would run in slow interpret/emulated form).

    Returns ``(sks, pks, msgs_t, sigs_t, ok, timings)`` where timings has
    ``keys_s`` (keygen), ``sign_s`` (host signing work: with device
    signing this is just message-building + dispatch), ``drain_s`` (wall
    time from last dispatch to verified mask + signature bytes on host —
    the un-overlapped residual), and ``total_s`` (whole setup wall
    clock).
    """
    import time

    import jax
    import jax.numpy as jnp

    if not 1 <= chunks <= batch:
        raise ValueError(f"chunks={chunks} out of range for batch={batch}")
    device_sign = sign_on_device()
    # RLC table-verify (BA_TPU_VERIFY_RLC=1) is DEFERRED-FETCH here: each
    # chunk dispatches its combined check without fetching the verdict
    # (rlc_batch_ok returns a device scalar), so the overlap with signing
    # survives; ALL verdicts fetch in one drain, and only a rejecting
    # chunk — impossible for self-signed tables, so never on this path in
    # production — pays the exact per-signature fallback.  r4 excluded
    # RLC from setup because the old wrapper's accept/fallback decision
    # was a blocking fetch per chunk that serialized the loop (VERDICT r4
    # item 3a); splitting dispatch from fetch dissolves that objection.
    rlc = os.environ.get("BA_TPU_VERIFY_RLC", "0") == "1"
    t_start = time.perf_counter()
    sks, pks = commander_keys(batch, seed)
    t_keys = time.perf_counter() - t_start
    per = -(-batch // chunks)
    sign_s = 0.0
    msgs_parts, sigs_parts, oks, deferred = [], [], [], []
    for lo in range(0, batch, per):
        hi = min(batch, lo + per)
        t0 = time.perf_counter()
        if device_sign:
            m_c, s_c = sign_value_tables_device(sks[lo:hi], pks[lo:hi], base=lo)
        else:
            m_c, s_c = sign_value_tables(sks[lo:hi], pks[lo:hi], base=lo)
        sign_s += time.perf_counter() - t0
        msgs_parts.append(m_c)
        sigs_parts.append(s_c)
        pk_c = pks[lo:hi]
        if hi - lo < per:  # pad the tail chunk so every dispatch shares
            pad = per - (hi - lo)  # one compiled shape (warmed off-clock)
            xp = jnp if device_sign else np
            pk_c = np.concatenate([pk_c, np.tile(pk_c[:1], (pad, 1))])
            m_c = np.concatenate([m_c, np.tile(m_c[:1], (pad, 1, 1))])
            s_c = xp.concatenate([s_c, xp.tile(s_c[:1], (pad, 1, 1))])
        if rlc:
            deferred.append((rlc_batch_ok(pk_c, m_c, s_c), pk_c, m_c, s_c))
        else:
            oks.append(_verify_received_exact(pk_c, m_c, s_c)[: hi - lo])
    t_signed = time.perf_counter()
    with obs.span("signed_setup_drain", batch=batch, chunks=chunks):
        if rlc:
            flags = jax.device_get([d[0] for d in deferred])  # ONE drain fetch
            for flag, (_, pk_c, m_c, s_c) in zip(flags, deferred):
                keep = min(per, batch - per * len(oks))
                if flag:
                    oks.append(jnp.ones((keep, m_c.shape[1]), bool))
                else:  # rare: an invalid table signature slipped in
                    oks.append(_verify_received_exact(pk_c, m_c, s_c)[:keep])
        ok = jnp.concatenate(oks) if len(oks) > 1 else oks[0]
        jax.device_get(ok)  # host fetch: genuinely drain the verify queue
        if device_sign:  # signature bytes live on device until fetched
            sigs_parts = [np.asarray(s) for s in sigs_parts]
    t_end = time.perf_counter()
    msgs_t = np.concatenate(msgs_parts)
    sigs_t = np.concatenate(sigs_parts)
    timings = {
        "keys_s": t_keys,
        "sign_s": sign_s,
        "drain_s": t_end - t_signed,
        "total_s": t_end - t_start,
        "chunks": len(oks),
        "device_sign": device_sign,
    }
    return sks, pks, msgs_t, sigs_t, ok, timings


def warm_signed_tables(batch: int, chunks: int = 4) -> None:
    """Compile/warm the chunk-shaped verify program off the clock.

    Same chunk shape as ``setup_signed_tables_overlapped`` will dispatch,
    content from a throwaway key-set (the tunnel backend memoizes only
    byte-identical repeats, and real setups use different keys/content).
    """
    per = -(-batch // chunks)
    sks, pks = commander_keys(per, seed=987654321)
    if sign_on_device():
        m_c, s_c = sign_value_tables_device(sks, pks)  # warm the signer too
    else:
        m_c, s_c = sign_value_tables(sks, pks)
    import jax

    if os.environ.get("BA_TPU_VERIFY_RLC", "0") == "1":
        # Warm the program the setup will actually dispatch (the deferred
        # RLC route); the exact program stays warm too — it is the
        # fallback on reject.
        jax.device_get(rlc_batch_ok(pks, m_c, s_c))
    jax.device_get(_verify_received_exact(pks, m_c, s_c))


def sig_valid_from_tables(ok, received):
    """Gather the [B, n] validity mask from per-value verdicts ok [B, V].

    The dedup counterpart of ``verify_received``: every general of instance
    b holds one of b's (at most V) table signatures, so checking the tables
    once covers all n copies — O(B*V) verifies instead of O(B*n).

    The V=2 case is a broadcast select, NOT ``take_along_axis``: fused into
    the agreement program, the gather lowers to a serialized scatter/gather
    on TPU (~350x slower than the whole relay; measured r2), while the
    select fuses cleanly.
    """
    import jax.numpy as jnp

    ok = jnp.asarray(ok)
    received = jnp.asarray(received)
    if ok.shape[1] == 2:
        return jnp.where(received == 1, ok[:, 1:2], ok[:, 0:1])
    return jnp.take_along_axis(ok, received.astype(jnp.int32), axis=1)


def sign_round1(
    key,
    state,
    seed: int = 0,
    corrupt: np.ndarray | None = None,
    dedup_verify: bool = False,
):
    """The shared sign-then-verify preamble of every signed agreement.

    Runs the round-1 broadcast, signs each uttered value host-side, and
    verifies the batch on device.  Returns ``(relay_key, received,
    sig_valid)`` ready for any SM relay path (unsharded or node-sharded).

    ``dedup_verify`` verifies each distinct (instance, value) signature
    once and gathers the mask (``sig_valid_from_tables``) — the
    sweep-scale path; per-copy ``corrupt`` faults need the full verify.
    """
    import jax.random as jr

    from ba_tpu.core.om import round1_broadcast

    k1, k2 = jr.split(key)
    received = round1_broadcast(k1, state)
    sks, pks = commander_keys(state.batch, seed)
    if dedup_verify:
        assert corrupt is None, "per-copy corruption needs the full verify"
        msgs_t, sigs_t = sign_value_tables(sks, pks)
        ok = verify_received(pks, msgs_t, sigs_t)  # [B, V]
        sig_valid = sig_valid_from_tables(ok, np.asarray(received))
    else:
        msgs, sigs = sign_received(sks, pks, np.asarray(received), corrupt)
        sig_valid = verify_received(pks, msgs, sigs)
    return k2, received, sig_valid


def signed_sm_agreement(
    key,
    state,
    m: int,
    withhold=None,
    corrupt: np.ndarray | None = None,
    seed: int = 0,
    collapsed: bool = False,
):
    """End-to-end signed SM(m): sign -> verify on device -> relay -> quorum.

    The full signed upgrade of the reference's ``actual-order`` hot path
    (ba.py:376-399): round-1 broadcast with commander equivocation
    (ba.py:268-273 semantics), host Ed25519 signing of each uttered value,
    batched device verification, and m relay rounds gated on the validity
    mask.  Returns the ``om1_agreement``-shaped dict plus ``sig_valid``.
    """
    from ba_tpu.core.sm import sm_agreement

    k2, received, sig_valid = sign_round1(key, state, seed, corrupt)
    out = sm_agreement(k2, state, m, withhold, sig_valid, received, collapsed)
    out["sig_valid"] = sig_valid
    return out


def signed_sm_agreement_sharded(
    mesh,
    key,
    state,
    m: int,
    corrupt: np.ndarray | None = None,
    seed: int = 0,
    collapsed: bool = True,
):
    """Signed SM(m) across a device mesh: the n=1024-scale signed path.

    Same sign -> verify -> relay -> quorum pipeline as
    ``signed_sm_agreement``, but the relay and quorum run node-sharded
    (``ba_tpu.parallel.sm_parallel.sm_node_sharded``): instances shard over
    "data", the n generals of each cluster over "node".
    """
    from ba_tpu.parallel.sm_parallel import sm_node_sharded

    k2, received, sig_valid = sign_round1(key, state, seed, corrupt)
    out = sm_node_sharded(
        mesh, k2, state, m,
        received=received, sig_valid=sig_valid, collapsed=collapsed,
    )
    out["sig_valid"] = sig_valid
    return out
