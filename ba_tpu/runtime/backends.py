"""Execution backends for the interactive cluster.

Two interchangeable engines behind ``Cluster``:

- :class:`JaxBackend` — the TPU path.  Pads the roster to a power-of-two
  capacity (so elastic ``g-add``/``g-kill`` reuses compiled programs instead
  of recompiling per membership change) and runs the jitted batched core
  with B=1.  The same core scales to thousands of instances in
  ``ba_tpu.parallel``.
- :class:`PyBackend` — a deliberately boring sequential-Python oracle with
  the exact reference semantics (ba.py:159-195, 258-285), used for
  differential testing of the tensorised core and for running the REPL
  without JAX at all.

Both draw faults from seeded RNG (the reference uses ``random.randint`` per
RPC call, ba.py:44-49, 268-273 — unseeded; we make it reproducible).
"""

from __future__ import annotations

import itertools
import random

from ba_tpu import obs
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED


class PyBackend:
    """Sequential oracle: one cluster, plain loops, stdlib RNG only."""

    def run_round(self, generals, leader_idx, order_code, seed):
        rng = random.Random(seed)
        n = len(generals)
        alive = [g.alive for g in generals]
        faulty = [g.faulty for g in generals]

        # Round 1: push. A faulty leader flips a coin per recipient
        # (equivocation, ba.py:268-273); the leader keeps the true order.
        received = []
        for i in range(n):
            if i == leader_idx or not faulty[leader_idx]:
                received.append(order_code)
            else:
                received.append(rng.randint(0, 1))

        # Round 2: pull. Each lieutenant tallies its own received command
        # plus every other alive non-primary general's answer; faulty
        # responders coin-flip per query (ba.py:159-186, 44-49).
        majorities = []
        for i in range(n):
            if i == leader_idx:
                majorities.append(order_code)  # ba.py:284-285 (Q1)
                continue
            if not alive[i]:
                majorities.append(UNDEFINED)
                continue
            n_attack = n_retreat = 0
            for j in range(n):
                if j == leader_idx or not alive[j]:
                    continue
                if j == i:
                    vote = received[i]
                elif faulty[j]:
                    vote = rng.randint(0, 1)
                else:
                    vote = received[j]
                if vote == ATTACK:
                    n_attack += 1
                else:
                    n_retreat += 1
            if n_attack > n_retreat:
                majorities.append(ATTACK)
            elif n_retreat > n_attack:
                majorities.append(RETREAT)
            else:
                majorities.append(UNDEFINED)
        return majorities


_INSTANCE_IDS = itertools.count()


class JaxBackend:
    """The batched TPU core behind a B=1 interactive facade.

    ``protocol`` selects the agreement engine: ``"om"`` (oral messages —
    OM(1) for m == 1, the EIG tree otherwise) or ``"sm"`` (signed
    messages, the Lamport-Shostak-Pease SM(m) upgrade).  ``signed=True``
    (sm only) runs the full Ed25519 pipeline per round: host-sign the
    commander's uttered values, verify the batch on device, gate the
    relay rounds on the validity mask (ba_tpu.crypto.signed).
    """

    def __init__(
        self,
        platform: str | None = None,
        m: int = 1,
        protocol: str = "om",
        signed: bool = False,
    ):
        import jax

        from ba_tpu.utils.platform import enable_compilation_cache

        if platform:
            jax.config.update("jax_platforms", platform)
        # Persistent XLA cache: interactive sessions stop re-paying the
        # compiles a previous session already did (REPL and cluster both
        # construct their jitted programs through this backend).
        enable_compilation_cache()
        if protocol not in ("om", "sm"):
            raise ValueError(f"unknown protocol {protocol!r}")
        if signed and protocol != "sm":
            raise ValueError("signed=True requires protocol='sm'")
        self._jax = jax
        # Monotonic instance tag for compile-vs-dispatch classification
        # (id() could be recycled after GC and misclassify a fresh
        # instance's first compile as a cached dispatch).
        self._obs_instance = next(_INSTANCE_IDS)
        self.m = m
        self.protocol = protocol
        self.signed = signed
        self._compiled = None  # jitted step (jit re-specializes per capacity)
        self._signed_compiled = None  # (jitted r1, jitted post-sign) pair
        self._keys = None  # cached (sks, pks) for the B=1 commander
        self._majorities_fn = None  # jitted last-round majority recompute
        self._signed_maj_fn = None  # signed twin of _majorities_fn
        self._sign_lane = None  # cached sign-ahead lane (B=1 commander)
        self._round_keys_fn = None  # jitted on-device key derivation

    @staticmethod
    def _capacity(n: int) -> int:
        cap = 4
        while cap < n:
            cap *= 2
        return cap

    def _fn(self):
        if self._compiled is None:
            import jax

            from ba_tpu.core.eig import eig_round
            from ba_tpu.core.om import om1_round
            from ba_tpu.core.sm import sm_round

            m = self.m
            protocol = self.protocol

            def step(key, state):
                if protocol == "sm":
                    return sm_round(key, state, m)
                if m == 1:
                    return om1_round(key, state)
                # max_liars stays at its safe n-1 default: faulty flags
                # change interactively (g-state) under one compiled step,
                # so no tighter static cap exists here — and interactive
                # n is tens, where the extra popcount words are noise.
                return eig_round(key, state, m)

            self._compiled = jax.jit(step)
        return self._compiled

    def _make_state(self, generals, leader_idx, order_code):
        import jax.numpy as jnp
        import numpy as np

        from ba_tpu.core.state import SimState
        from ba_tpu.core.types import COMMAND_DTYPE

        cap = self._capacity(len(generals))
        # Stage on host, transfer once — per-element .at[].set() would
        # dispatch O(n) device scatters per interactive round.
        faulty = np.zeros((1, cap), np.bool_)
        alive = np.zeros((1, cap), np.bool_)
        ids = np.zeros((1, cap), np.int32)
        for i, g in enumerate(generals):
            faulty[0, i] = g.faulty
            alive[0, i] = g.alive
            ids[0, i] = g.id
        return SimState(
            order=jnp.full((1,), order_code, COMMAND_DTYPE),
            leader=jnp.full((1,), leader_idx, jnp.int32),
            faulty=jnp.asarray(faulty),
            alive=jnp.asarray(alive),
            ids=jnp.asarray(ids),
        )

    def _signed_fns(self):
        """Jitted (round-1 broadcast, post-sign SM) pair.

        The host Ed25519 signer sits between the two device programs, so
        the signed path is split there; jax.jit re-specializes each per
        roster capacity on its own.
        """
        if self._signed_compiled is None:
            import jax

            from ba_tpu.core.om import round1_broadcast
            from ba_tpu.core.sm import sm_round

            m = self.m

            def post(key, state, sig_valid, received):
                return sm_round(
                    key, state, m, sig_valid=sig_valid, received=received
                )

            self._signed_compiled = (jax.jit(round1_broadcast), jax.jit(post))
        return self._signed_compiled

    def _run_signed(self, state, seed):
        import jax.random as jr
        import numpy as np

        from ba_tpu.crypto.signed import (
            commander_keys,
            sign_received,
            verify_received,
        )

        if self._keys is None:
            self._keys = commander_keys(1, seed=0)
        sks, pks = self._keys
        r1, post = self._signed_fns()
        k1, k2 = jr.split(jr.key(seed))
        received = r1(k1, state)
        msgs, sigs = sign_received(sks, pks, np.asarray(received))
        sig_valid = verify_received(pks, msgs, sigs)
        return post(k2, state, sig_valid, received)

    def run_round(self, generals, leader_idx, order_code, seed):
        import jax.random as jr
        import numpy as np

        n = len(generals)
        state = self._make_state(generals, leader_idx, order_code)
        if self.signed:
            # Not compile/dispatch-classified: the signed round
            # synchronously host-signs and verifies between two device
            # programs, so its wall time is NOT dispatch latency — the
            # sign/verify internals carry their own host_sign /
            # device_sign_dispatch spans (crypto/signed.py).
            with obs.span("signed_round", n=n, m=self.m):
                maj = self._run_signed(state, seed)
        else:
            # First call at a fresh roster capacity pays trace + compile
            # (or a persistent-cache load, BA_TPU_COMPILE_CACHE); later
            # calls are cached dispatches — obs.compile_or_dispatch_span
            # names the span and feeds first-call latency into
            # compile_time_s.  The NAMED axes feed the recompile
            # explainer: when an elastic g-add crosses a power-of-two
            # boundary and this step re-specializes, the emitted
            # `recompile` record names "capacity" (e.g. 4 -> 8) instead
            # of leaving a mysterious second compile span.  The instance
            # tag rides the axes because the jit cache is per-instance
            # (self._compiled): a second backend at equal statics
            # re-pays the compile and must re-classify.
            axes = {
                "instance": self._obs_instance,
                "protocol": self.protocol,
                "m": self.m,
                "capacity": self._capacity(n),
            }
            k = jr.key(seed)
            with obs.compile_or_dispatch_span(
                "jax_backend_step", axes=axes, n=n, protocol=self.protocol
            ) as phase:
                maj = self._fn()(k, state)
            if phase == "compile" and obs.xla.enabled():
                # Device-tier artifact for the interactive step:
                # cost/memory analysis of this capacity's program
                # (obs/xla.py; abstract shapes only — nothing here is
                # donated, so the concrete args are still live).  After
                # the span so the extra AOT compile never inflates the
                # canonical compile_time_s.
                obs.xla.introspect(
                    self._fn(), "jax_backend_step", (k, state), axes=axes
                )
        # ONE host fetch for the whole row: int(v) per element costs a
        # ~50-100 ms tunnel round-trip per general (measured r3: the REPL
        # round dropped ~4x when this loop stopped fetching elementwise).
        return [int(v) for v in np.asarray(maj[0, :n])]

    def run_rounds(
        self, generals, leader_idx, order_code, seed, rounds,
        host_work=None, executables=None, engine=None,
    ):
        """``rounds`` agreement rounds through the pipelined sweep engine.

        Oral-message protocols ride the plain megasteps; ``signed=True``
        SM(m) rides the SIGNED megastep behind the sign-ahead host lane
        (ISSUE 14): per-round signature tables prepared in the engine's
        host_work overlap slot while depth-k dispatches are in flight —
        the host round-trip that used to force the per-round
        ``_run_signed`` fallback is gone.  Unsigned SM still falls back
        (returns None): its relay has no pipelined path yet.

        Returns ``(majorities_last, decision_codes, stats)`` — the last
        round's per-roster-general majorities (for the REPL's per-general
        block), each round's device quorum decision code, and the engine's
        dispatch stats — or None when the protocol cannot be pipelined.

        ``executables`` (ISSUE 11, opt-in) is an
        ``obs.aotcache.ExecutableCache`` consulted before each dispatch —
        the campaign-side mirror of the serving dispatcher's warm path.
        """
        import os

        import jax
        import jax.random as jr
        import numpy as np

        if self.protocol != "om" and not self.signed:
            # Explicitly asking the kernel engine (ISSUE 13) to run a
            # path that cannot be pipelined at all deserves a loud
            # error, not the silent sequential fallback: the caller
            # expressed an engine expectation the fallback would betray.
            if engine in ("pallas", "interpret"):
                raise ValueError(
                    f"engine={engine!r} unsupported: "
                    f"protocol={self.protocol!r} unsigned has no "
                    f"pipelined path"
                )
            return None

        from ba_tpu.parallel.pipeline import (
            fresh_copy,
            make_key_schedule,
            pipeline_sweep,
            round_keys,
        )
        from ba_tpu.parallel.sweep import agreement_step

        n = len(generals)
        key = jr.key(seed)
        state = self._make_state(generals, leader_idx, order_code)
        # The engine donates its input state; keep a live copy for the
        # last-round majority recompute below.
        state_copy = fresh_copy(state)
        depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
        per_dispatch = min(
            rounds, int(os.environ.get("BA_TPU_PIPELINE_ROUNDS", 8))
        )
        out = pipeline_sweep(
            key,
            state,
            rounds,
            m=self.m,
            depth=depth,
            rounds_per_dispatch=per_dispatch,
            collect_decisions=True,
            with_counters=True,
            signed=self.signed,
            host_work=host_work,
            executables=executables,
            engine=engine,
        )
        # Per-general block for the LAST round: recompute it from the same
        # key schedule (counter = rounds - 1).  Bit-exact with what the
        # pipeline executed — the schedule's determinism contract — at the
        # cost of one extra B=1 dispatch, which keeps majority collection
        # out of the engine's steady-state outputs.
        if self._round_keys_fn is None:
            # Cached like _majorities_fn: a fresh jax.jit wrapper per call
            # would retrace (and recompile, seconds on the tunnel) every
            # run-rounds invocation.
            self._round_keys_fn = jax.jit(round_keys, static_argnums=1)
        keys_last = self._round_keys_fn(make_key_schedule(key, rounds - 1), 1)
        if self.signed:
            # The signed block recomputes through the SAME lane grammar
            # the engine staged: the last round's table verdicts gate
            # the recomputed broadcast exactly as they did in-scan.
            from ba_tpu.crypto.signed import _verify_received_exact
            from ba_tpu.parallel.signing import SignAheadLane
            from ba_tpu.parallel.sweep import signed_agreement_step

            if self._sign_lane is None:
                self._sign_lane = SignAheadLane(1, seed=0)
            if self._signed_maj_fn is None:
                m = self.m
                self._signed_maj_fn = jax.jit(
                    lambda keys, st, ok: signed_agreement_step(
                        keys, st, ok, m=m
                    )["majorities"]
                )
            msgs, sigs = self._sign_lane.round_tables(rounds - 1)
            # Exact per-signature semantics, like the lane's staging
            # (the RLC knob's batch-dependent verdicts never reach the
            # signed round tables).
            ok = _verify_received_exact(self._sign_lane.pks, msgs, sigs)
            maj = self._signed_maj_fn(keys_last, state_copy, ok)
        else:
            if self._majorities_fn is None:
                self._majorities_fn = jax.jit(
                    lambda keys, st: agreement_step(keys, st, m=self.m)[
                        "majorities"
                    ]
                )
            maj = self._majorities_fn(keys_last, state_copy)
        majorities = [int(v) for v in np.asarray(maj[0, :n])]
        decisions = [int(v) for v in out["decisions"][:, 0]]
        # The on-device agreement counters ride the stats block (they
        # were drained inside the engine's existing retire fetches).
        stats = dict(out["stats"], counters=out["counters"])
        return majorities, decisions, stats

    def run_scenario(
        self,
        generals,
        leader_idx,
        order_code,
        seed,
        spec,
        checkpoint_every=None,
        checkpoint_path=None,
        checkpoint_keep_last=None,
        supervise=False,
        fault_plan=None,
        mesh=None,
        health_every=None,
        executables=None,
        engine=None,
    ):
        """A declarative scenario campaign on the B=1 interactive cluster.

        Compiles the spec against the ROSTER's ids at the padded roster
        capacity (unknown ids raise eagerly, matching ``g-kill``'s
        silent-ignore being a roster-layer decision, not a device one),
        then drives the pipelined mutating engine
        (``pipeline_sweep(scenario=...)``): kills, revives, fault flips,
        strategy assignment and lowest-alive-id re-election all run on
        device, depth-k dispatches in flight.  The lowering is SPARSE
        (ISSUE 6): host plane memory stays O(chunk) however long the
        campaign runs, so an interactive ``scenario`` command can replay
        a million-round churn soak without the roster process caring.
        ``checkpoint_every``/``checkpoint_path`` thread straight into
        the engine's carry checkpoints (resume via
        ``pipeline_sweep(resume=...)`` against the same roster);
        ``checkpoint_keep_last`` prunes a ``{round}``-templated family
        to its N newest members.  Oral-message protocols only, exactly
        like ``run_rounds`` — returns None for sm/signed.

        ``supervise=True`` (ISSUE 7) runs the campaign under the
        resilient execution supervisor
        (``runtime/supervisor.supervised_sweep``): watchdogged retires,
        transient retry with backoff, automatic resume from the newest
        valid checkpoint, OOM degradation — same results dict, plus the
        ``supervisor`` stats block (attempts/retries/recoveries/...)
        folded into ``stats``.  ``fault_plan`` (a
        ``runtime.chaos.FaultPlan`` or a live ``ChaosInjector``) injects
        deterministic faults for drills and tests; it requires
        ``supervise=True`` — injecting faults with nobody to catch them
        would just kill the campaign.

        ``mesh`` (ISSUE 8) threads straight into the engine's
        mesh-sharded scan core (``pipeline_sweep(mesh=)``).  NOTE the
        interactive facade is B=1, so the mesh's "data" axis must be 1
        — a larger axis raises the engine's clear divisibility error
        (batched multi-chip campaigns call ``scenario_sweep(mesh=)``
        directly); the parameter exists so the one campaign surface is
        dial-for-dial complete and the REPL can exercise the sharded
        path.

        Returns a dict: ``decisions`` (per-round quorum codes),
        ``leaders`` (per-round roster indices), ``counters``
        (SCENARIO_COUNTER_NAMES incl. IC1/IC2 verdicts), ``stats``,
        and the final ``alive``/``faulty`` rows for the roster update.
        """
        import os

        import jax.random as jr
        import numpy as np

        if self.protocol != "om" or self.signed:
            return None
        if fault_plan is not None and not supervise:
            raise ValueError("fault_plan requires supervise=True")

        from ba_tpu.parallel.pipeline import fresh_copy, pipeline_sweep
        from ba_tpu.scenario.compile import compile_scenario

        n = len(generals)
        cap = self._capacity(n)
        ids = np.zeros(cap, np.int64)
        for i, g in enumerate(generals):
            ids[i] = g.id
        block = compile_scenario(
            spec, batch=1, capacity=cap, ids=ids, sparse=True
        )
        # fresh_copy is LOAD-BEARING, not defensive: _make_state stages
        # numpy and jnp.asarray may ZERO-COPY it on CPU — donating a
        # buffer that aliases live host memory makes the returned
        # (aliased) final_state nondeterministically garbage, which this
        # path is the first to actually read back (run_rounds only
        # consumes the retire outputs).  The copy puts a real device
        # buffer into the donation thread.
        state = fresh_copy(self._make_state(generals, leader_idx, order_code))
        depth = int(os.environ.get("BA_TPU_PIPELINE_DEPTH", 2))
        per_dispatch = min(
            spec.rounds, int(os.environ.get("BA_TPU_PIPELINE_ROUNDS", 8))
        )
        # ONE kwargs dict for both arms: supervised and unsupervised
        # campaigns must stay dial-for-dial identical — a future engine
        # dial added to one arm only would silently diverge them.
        kwargs = dict(
            m=self.m,
            depth=depth,
            rounds_per_dispatch=per_dispatch,
            collect_decisions=True,
            scenario=block,
            checkpoint_every=checkpoint_every,
            checkpoint_path=checkpoint_path,
            checkpoint_keep_last=checkpoint_keep_last,
            mesh=mesh,
            health_every=health_every,
            executables=executables,
            engine=engine,
        )
        if supervise:
            from ba_tpu.runtime.supervisor import supervised_sweep

            out = supervised_sweep(
                jr.key(seed), state, spec.rounds,
                chaos=fault_plan, **kwargs,
            )
        else:
            out = pipeline_sweep(jr.key(seed), state, spec.rounds, **kwargs)
        final = out["final_state"]
        stats = out["stats"]
        if supervise:
            stats = dict(stats, supervisor=out["supervisor"])
            if out["supervisor"]["history_start"] != 0:
                # The per-round consumers below (decision tally,
                # leaders) assume row 0 is campaign round 0.  A resume
                # whose prior checkpoints carry no usable rows history
                # (e.g. written by an UNSUPERVISED run — no sidecars)
                # assembles only the tail; printing a fractional tally
                # as the full campaign would be silently wrong output.
                raise ValueError(
                    f"supervised resume assembled only rounds "
                    f"[{out['supervisor']['history_start']}, "
                    f"{spec.rounds}) — the prior checkpoints at "
                    f"{checkpoint_path!r} have no rows-history "
                    f"sidecars (written unsupervised?); rerun with a "
                    f"fresh checkpoint_path, or resume unsupervised"
                )
        # ONE fetch per row, as in run_round (elementwise fetches pay a
        # tunnel round-trip per element).
        return {
            "decisions": [int(v) for v in out["decisions"][:, 0]],
            "leaders": [int(v) for v in out["leaders"][:, 0]],
            "counters": out["counters"],
            "stats": stats,
            "alive": [bool(v) for v in np.asarray(final.alive[0, :n])],
            "faulty": [bool(v) for v in np.asarray(final.faulty[0, :n])],
        }

    def run_search(self, generals, seed, space=None, **kwargs):
        """An adversary hunt sized to THIS cluster's shape (ISSUE 15).

        The search is roster-independent — every candidate campaign
        starts from the canonical all-honest state — but the default
        :class:`~ba_tpu.search.generate.SearchSpace` takes its capacity
        from the padded roster width, so the REPL ``search`` command
        hunts adversaries for clusters like the one on screen.  An
        explicit ``space`` (a SearchSpace or its dict form) overrides
        everything; ``kwargs`` thread straight into
        :func:`ba_tpu.search.loop.hunt` (generations, objective,
        export_dir, checkpoint_path, mesh, engine, ...).  Oral-message
        protocols only, like ``run_scenario`` — returns None for
        sm/signed.
        """
        if self.protocol != "om" or self.signed:
            return None
        from ba_tpu.search.generate import SearchSpace
        from ba_tpu.search.loop import hunt

        if space is None:
            cap = self._capacity(len(generals))
            space = SearchSpace(
                rounds=8,
                capacity=cap,
                population=32,
                events_min=2,
                events_max=6,
            )
        return hunt(space, seed=seed, **kwargs)
