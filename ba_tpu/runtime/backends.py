"""Execution backends for the interactive cluster.

Two interchangeable engines behind ``Cluster``:

- :class:`JaxBackend` — the TPU path.  Pads the roster to a power-of-two
  capacity (so elastic ``g-add``/``g-kill`` reuses compiled programs instead
  of recompiling per membership change) and runs the jitted batched core
  with B=1.  The same core scales to thousands of instances in
  ``ba_tpu.parallel``.
- :class:`PyBackend` — a deliberately boring sequential-Python oracle with
  the exact reference semantics (ba.py:159-195, 258-285), used for
  differential testing of the tensorised core and for running the REPL
  without JAX at all.

Both draw faults from seeded RNG (the reference uses ``random.randint`` per
RPC call, ba.py:44-49, 268-273 — unseeded; we make it reproducible).
"""

from __future__ import annotations

import random

from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED


class PyBackend:
    """Sequential oracle: one cluster, plain loops, stdlib RNG only."""

    def run_round(self, generals, leader_idx, order_code, seed):
        rng = random.Random(seed)
        n = len(generals)
        alive = [g.alive for g in generals]
        faulty = [g.faulty for g in generals]

        # Round 1: push. A faulty leader flips a coin per recipient
        # (equivocation, ba.py:268-273); the leader keeps the true order.
        received = []
        for i in range(n):
            if i == leader_idx or not faulty[leader_idx]:
                received.append(order_code)
            else:
                received.append(rng.randint(0, 1))

        # Round 2: pull. Each lieutenant tallies its own received command
        # plus every other alive non-primary general's answer; faulty
        # responders coin-flip per query (ba.py:159-186, 44-49).
        majorities = []
        for i in range(n):
            if i == leader_idx:
                majorities.append(order_code)  # ba.py:284-285 (Q1)
                continue
            if not alive[i]:
                majorities.append(UNDEFINED)
                continue
            n_attack = n_retreat = 0
            for j in range(n):
                if j == leader_idx or not alive[j]:
                    continue
                if j == i:
                    vote = received[i]
                elif faulty[j]:
                    vote = rng.randint(0, 1)
                else:
                    vote = received[j]
                if vote == ATTACK:
                    n_attack += 1
                else:
                    n_retreat += 1
            if n_attack > n_retreat:
                majorities.append(ATTACK)
            elif n_retreat > n_attack:
                majorities.append(RETREAT)
            else:
                majorities.append(UNDEFINED)
        return majorities


class JaxBackend:
    """The batched TPU core behind a B=1 interactive facade."""

    def __init__(self, platform: str | None = None, m: int = 1):
        import jax

        if platform:
            jax.config.update("jax_platforms", platform)
        self._jax = jax
        self.m = m
        self._compiled = {}  # capacity -> jitted fn

    @staticmethod
    def _capacity(n: int) -> int:
        cap = 4
        while cap < n:
            cap *= 2
        return cap

    def _fn(self, capacity: int):
        if capacity not in self._compiled:
            import jax

            from ba_tpu.core.eig import eig_round
            from ba_tpu.core.om import om1_round

            m = self.m

            def step(key, state):
                if m == 1:
                    return om1_round(key, state)
                return eig_round(key, state, m)

            self._compiled[capacity] = jax.jit(step)
        return self._compiled[capacity]

    def run_round(self, generals, leader_idx, order_code, seed):
        import jax.numpy as jnp
        import jax.random as jr
        import numpy as np

        from ba_tpu.core.state import SimState
        from ba_tpu.core.types import COMMAND_DTYPE

        n = len(generals)
        cap = self._capacity(n)
        # Stage on host, transfer once — per-element .at[].set() would
        # dispatch O(n) device scatters per interactive round.
        faulty = np.zeros((1, cap), np.bool_)
        alive = np.zeros((1, cap), np.bool_)
        ids = np.zeros((1, cap), np.int32)
        for i, g in enumerate(generals):
            faulty[0, i] = g.faulty
            alive[0, i] = g.alive
            ids[0, i] = g.id
        state = SimState(
            order=jnp.full((1,), order_code, COMMAND_DTYPE),
            leader=jnp.full((1,), leader_idx, jnp.int32),
            faulty=jnp.asarray(faulty),
            alive=jnp.asarray(alive),
            ids=jnp.asarray(ids),
        )
        maj = self._fn(cap)(jr.key(seed), state)
        return [int(v) for v in maj[0, :n]]
